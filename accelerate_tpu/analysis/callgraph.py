"""Module indexing and traced-region discovery for jaxlint.

jaxlint's unit of analysis is not "the file" but **the traced region**: the
set of functions reachable from a ``jax.jit`` / ``pjit`` / ``shard_map``
wrap point. Rules R1/R2/R5 only fire inside that region (a ``float()`` on a
host-side numpy batch is fine; the same call on a tracer inside the jitted
step is a device→host sync). This module builds everything the rules need:

- :class:`ModuleIndex` — one parsed file: imports, every ``def`` (however
  nested) as a :class:`FunctionInfo`, raw source lines.
- :class:`PackageIndex` — all scanned modules plus name resolution: local
  defs, module globals, ``from x import y``, ``self.method`` — best-effort
  and static, the same trade every import-light linter makes.
- :func:`discover_traced` — finds jit wrap points (decorator form, call
  form, ``functools.partial`` form, and one level of builder indirection:
  ``step = build(); jit(step)`` follows ``build``'s ``return`` of a nested
  def), then BFSes the call graph to mark every reachable function traced.

Pure stdlib ``ast`` — importing this module must never import jax or any
scanned code (linting runs on machines with no TPU and in CI sandboxes).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Optional

#: call targets that open a traced region, matched on the dotted tail.
#: Bare names match when the module imports them (from jax / jax_compat);
#: attribute forms must be rooted in a jax-ish base (``jax.jit``,
#: ``jax.experimental.pjit.pjit``) so ``scheduler.jit`` can't false-positive.
JIT_TAILS = {"jit", "pjit", "shard_map"}
_JIT_BASES = {"jax", "jax.experimental.pjit", "jax.experimental.shard_map", "pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class JitSpec:
    """One jit/pjit/shard_map wrap point and the argnums that matter."""

    kind: str  # "jit" | "pjit" | "shard_map"
    node: ast.Call  # the wrap call itself (or decorator call)
    donate_argnums: Optional[tuple] = None
    donate_argnames: Optional[tuple] = None
    static_argnums: Optional[tuple] = None
    static_argnames: Optional[tuple] = None

    @property
    def donates(self) -> bool:
        return bool(self.donate_argnums) or bool(self.donate_argnames)


@dataclass
class FunctionInfo:
    """One ``def``/``lambda`` anywhere in a module."""

    qualname: str  # "Class.method" / "outer.<locals>.inner"
    module: str  # dotted module name
    path: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    class_name: Optional[str] = None
    parent: Optional[str] = None  # enclosing function qualname
    local_defs: "dict[str, str]" = field(default_factory=dict)  # name -> child qualname
    jit_specs: "list[JitSpec]" = field(default_factory=list)  # wraps applied to THIS fn
    returned_local_defs: "list[str]" = field(default_factory=list)  # builder pattern
    _own_nodes: Optional[list] = field(default=None, repr=False, compare=False)

    @property
    def key(self) -> tuple:
        return (self.module, self.qualname)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def lineno(self) -> int:
        return getattr(self.node, "lineno", 1)

    def param_names(self) -> "list[str]":
        a = self.node.args
        names = [p.arg for p in getattr(a, "posonlyargs", [])] + [p.arg for p in a.args]
        if a.vararg:
            names.append(a.vararg.arg)
        names += [p.arg for p in a.kwonlyargs]
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def positional_params(self) -> "list[str]":
        a = self.node.args
        return [p.arg for p in getattr(a, "posonlyargs", [])] + [p.arg for p in a.args]


class _ModuleVisitor(ast.NodeVisitor):
    """Single pass that records imports, functions (at any depth), module
    globals, and ``global``-reassigned names."""

    def __init__(self, index: "ModuleIndex"):
        self.index = index
        self._stack: "list[FunctionInfo]" = []
        self._class_stack: "list[str]" = []

    # -- imports -------------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.index.imports[alias.asname or alias.name.split(".")[0]] = alias.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:  # relative: resolve against this module's dotted name
            parts = self.index.modname.split(".")
            # level 1 == current package: for a plain module that strips the
            # module's own leaf name; a package __init__ (modname IS the
            # package) keeps all its parts
            drop = node.level - 1 if self.index.is_package else node.level
            anchor = parts[: len(parts) - drop] if drop else parts
            base = ".".join(anchor + ([base] if base else []))
        for alias in node.names:
            if alias.name == "*":
                continue
            self.index.imports[alias.asname or alias.name] = (
                f"{base}.{alias.name}" if base else alias.name
            )
        self.generic_visit(node)

    # -- defs ----------------------------------------------------------------
    def _enter_function(self, node, name: str) -> FunctionInfo:
        if self._stack:
            parent = self._stack[-1]
            qual = f"{parent.qualname}.<locals>.{name}"
        elif self._class_stack:
            qual = ".".join(self._class_stack + [name])
            parent = None
        else:
            qual, parent = name, None
        info = FunctionInfo(
            qualname=qual,
            module=self.index.modname,
            path=self.index.path,
            node=node,
            class_name=self._class_stack[-1] if self._class_stack else None,
            parent=parent.qualname if parent else None,
        )
        if parent is not None:
            parent.local_defs[name] = qual
        elif not self._class_stack:
            self.index.top_defs[name] = qual
        self.index.functions[qual] = info
        return info

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._function(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._function(node, node.name)

    def _function(self, node, name: str) -> None:
        info = self._enter_function(node, name)
        for deco in node.decorator_list:
            spec = parse_jit_expr(deco, self.index)
            if spec is not None:
                info.jit_specs.append(spec)
        self._stack.append(info)
        for child in node.body:
            self.visit(child)
        self._stack.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        name = f"<lambda:{node.lineno}>"
        info = self._enter_function(node, name)
        self.index.lambdas[id(node)] = info
        self._stack.append(info)
        self.visit(node.body)
        self._stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        for child in node.body:
            self.visit(child)
        self._class_stack.pop()

    # -- module globals ------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        if not self._stack and not self._class_stack:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.index.module_globals[tgt.id] = node.value
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        if self._stack:
            self.index.global_writes.update(node.names)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        # builder pattern: ``def build(): def step(..): ...; return step``
        if self._stack and node.value is not None:
            fn = self._stack[-1]
            for name in _returned_names(node.value):
                if name in fn.local_defs:
                    fn.returned_local_defs.append(fn.local_defs[name])
        self.generic_visit(node)


def _returned_names(value: ast.AST) -> "list[str]":
    """Names a ``return`` statement may hand back (bare name, tuple, or a
    jit-wrap of a name)."""
    out: list[str] = []
    if isinstance(value, ast.Name):
        out.append(value.id)
    elif isinstance(value, ast.Tuple):
        for elt in value.elts:
            out.extend(_returned_names(elt))
    elif isinstance(value, ast.Call) and value.args:
        # return jax.jit(step) / return shard_map(step, ...)
        if isinstance(value.args[0], ast.Name):
            out.append(value.args[0].id)
    return out


@dataclass
class ModuleIndex:
    """Everything jaxlint knows about one parsed file."""

    path: str
    modname: str
    tree: ast.Module
    source_lines: "list[str]"
    is_package: bool = False  # an __init__.py: modname names the package itself
    imports: "dict[str, str]" = field(default_factory=dict)
    functions: "dict[str, FunctionInfo]" = field(default_factory=dict)
    top_defs: "dict[str, str]" = field(default_factory=dict)
    lambdas: "dict[int, FunctionInfo]" = field(default_factory=dict)
    module_globals: "dict[str, ast.AST]" = field(default_factory=dict)
    global_writes: "set[str]" = field(default_factory=set)

    @classmethod
    def parse(cls, path: str, modname: str, source: str) -> "ModuleIndex":
        tree = ast.parse(source, filename=path)
        index = cls(
            path=path,
            modname=modname,
            tree=tree,
            source_lines=source.splitlines(),
            is_package=os.path.basename(path) == "__init__.py",
        )
        _ModuleVisitor(index).visit(tree)
        return index

    def line(self, lineno: int) -> str:
        try:
            return self.source_lines[lineno - 1].strip()
        except IndexError:
            return ""


def _tuple_int_kwarg(call: ast.Call, name: str) -> Optional[tuple]:
    for kw in call.keywords:
        if kw.arg != name:
            continue
        v = kw.value
        if isinstance(v, ast.Constant):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            vals = []
            for elt in v.elts:
                if isinstance(elt, ast.Constant):
                    vals.append(elt.value)
            # non-constant elements (donate_argnums=(A, B)) must still read
            # as configured — pad with the "?" sentinel per unreadable slot
            return tuple(vals) + ("?",) * (len(v.elts) - len(vals))
        if isinstance(v, ast.IfExp):  # donate_argnums=(0, 1) if donate else ()
            for arm in (v.body, v.orelse):
                got = None
                if isinstance(arm, (ast.Tuple, ast.List)) and arm.elts:
                    got = tuple(
                        e.value for e in arm.elts if isinstance(e, ast.Constant)
                    )
                elif isinstance(arm, ast.Constant) and arm.value != ():
                    got = (arm.value,)
                if got:
                    return got  # conservatively: "donation is configured"
        # present but not statically readable (a variable, a computed
        # tuple): the "?" sentinel keeps the kwarg truthy — JitSpec.donates
        # must not read configured donation as absent — while every
        # per-argnum check skips it (they only accept ints)
        return ("?",)
    return None


def _is_jit_name(name: str, index: ModuleIndex) -> bool:
    """Does ``name`` (dotted) denote jit/pjit/shard_map here?"""
    tail = name.rsplit(".", 1)[-1]
    if tail not in JIT_TAILS:
        return False
    if "." in name:
        base = name.rsplit(".", 1)[0]
        resolved = index.imports.get(base.split(".")[0], base.split(".")[0])
        full_base = base.replace(base.split(".")[0], resolved, 1)
        return full_base in _JIT_BASES or full_base.startswith("jax.")
    # bare name: accept when imported from a jax-ish or compat module
    target = index.imports.get(name, "")
    return (
        target.startswith("jax")
        or target.endswith(f"jax_compat.{tail}")
        or target.endswith(f".{tail}")  # from ..utils.jax_compat import shard_map
        and ("jax" in target or "compat" in target)
    )


def parse_jit_expr(node: ast.AST, index: ModuleIndex) -> Optional[JitSpec]:
    """Recognize a jit wrap expression: ``jax.jit``, ``jax.jit(...)``,
    ``partial(jax.jit, ...)``, ``functools.partial(jax.jit, ...)`` — used
    both for decorators and for call-form wraps."""
    if isinstance(node, (ast.Name, ast.Attribute)):
        name = dotted(node)
        if name and _is_jit_name(name, index):
            fake = ast.Call(func=node, args=[], keywords=[])
            ast.copy_location(fake, node)
            return JitSpec(kind=name.rsplit(".", 1)[-1], node=fake)
        return None
    if not isinstance(node, ast.Call):
        return None
    fname = dotted(node.func)
    if fname in _PARTIAL_NAMES and node.args:
        inner = dotted(node.args[0])
        if inner and _is_jit_name(inner, index):
            return JitSpec(
                kind=inner.rsplit(".", 1)[-1],
                node=node,
                donate_argnums=_tuple_int_kwarg(node, "donate_argnums"),
                donate_argnames=_tuple_int_kwarg(node, "donate_argnames"),
                static_argnums=_tuple_int_kwarg(node, "static_argnums"),
                static_argnames=_tuple_int_kwarg(node, "static_argnames"),
            )
        return None
    if fname and _is_jit_name(fname, index):
        return JitSpec(
            kind=fname.rsplit(".", 1)[-1],
            node=node,
            donate_argnums=_tuple_int_kwarg(node, "donate_argnums"),
            donate_argnames=_tuple_int_kwarg(node, "donate_argnames"),
            static_argnums=_tuple_int_kwarg(node, "static_argnums"),
            static_argnames=_tuple_int_kwarg(node, "static_argnames"),
        )
    return None


@dataclass
class JitSite:
    """A call-form wrap point: ``jax.jit(fn, ...)`` / ``shard_map(fn, ..)``
    with the wrapped function resolved when possible. R3 analyzes these."""

    spec: JitSpec
    module: ModuleIndex
    enclosing: Optional[FunctionInfo]  # function containing the wrap call
    target: Optional[FunctionInfo]  # the wrapped function, if resolved
    bound_names: "list[str]" = field(default_factory=list)  # x = jax.jit(f)


class PackageIndex:
    """All scanned modules + cross-module name resolution."""

    def __init__(self) -> None:
        self.modules: "dict[str, ModuleIndex]" = {}
        self.errors: "list[tuple[str, str]]" = []  # (path, message)

    def add_file(self, path: str, modname: str) -> Optional[ModuleIndex]:
        # same-named files outside packages (scripts/, fixtures/) must not
        # shadow each other — every scanned file gets its own index entry
        base, n = modname, 2
        while modname in self.modules:
            modname = f"{base}#{n}"
            n += 1
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            index = ModuleIndex.parse(path, modname, source)
        except (OSError, SyntaxError, ValueError) as exc:
            self.errors.append((path, f"{type(exc).__name__}: {exc}"))
            return None
        self.modules[modname] = index
        return index

    # -- resolution ----------------------------------------------------------
    def resolve_call(
        self, name: str, module: ModuleIndex, scope: Optional[FunctionInfo]
    ) -> Optional[FunctionInfo]:
        """Resolve a (possibly dotted) called name to a FunctionInfo."""
        if name.startswith("self.") or name.startswith("cls."):
            method = name.split(".", 1)[1]
            if scope is not None and scope.class_name and "." not in method:
                return module.functions.get(f"{scope.class_name}.{method}")
            return None
        if "." not in name:
            # enclosing local defs, innermost first
            fn = scope
            while fn is not None:
                if name in fn.local_defs:
                    return module.functions.get(fn.local_defs[name])
                fn = module.functions.get(fn.parent) if fn.parent else None
            if name in module.top_defs:
                return module.functions.get(module.top_defs[name])
            target = module.imports.get(name)
            if target and "." in target:
                mod, leaf = target.rsplit(".", 1)
                other = self.modules.get(mod)
                if other and leaf in other.top_defs:
                    return other.functions.get(other.top_defs[leaf])
            return None
        base, leaf = name.rsplit(".", 1)
        if "." in base:
            return None  # a.b.c(): too deep to chase statically
        target_mod = module.imports.get(base)
        other = self.modules.get(target_mod) if target_mod else None
        if other and leaf in other.top_defs:
            return other.functions.get(other.top_defs[leaf])
        return None

    def all_functions(self):
        for module in self.modules.values():
            yield from module.functions.values()


# ---------------------------------------------------------------------------
# traced-region discovery


@dataclass
class TracedRegion:
    """Output of :func:`discover_traced`."""

    traced: "dict[tuple, FunctionInfo]"  # key -> fn reachable from a wrap point
    roots: "dict[tuple, JitSpec]"  # directly-wrapped functions
    sites: "list[JitSite]"  # call-form wrap points (R3's input)

    def is_traced(self, fn: FunctionInfo) -> bool:
        return fn.key in self.traced

    def spec_for(self, fn: FunctionInfo) -> Optional[JitSpec]:
        return self.roots.get(fn.key)


def _calls_in(fn: FunctionInfo):
    """Call nodes lexically inside ``fn``, not descending into nested defs
    (those are their own FunctionInfos)."""
    return (n for n in iter_own_nodes(fn) if isinstance(n, ast.Call))


def iter_own_nodes(fn: FunctionInfo):
    """Every AST node lexically owned by ``fn`` (nested defs excluded), in
    pre-order — the traversal surface rules use. Cached per function: every
    rule walks every traced function, and recomputing the nested-def set
    per walk dominated the engine's runtime."""
    if fn._own_nodes is not None:
        return fn._own_nodes
    out: list = []
    stack = [fn.node]
    first = True
    while stack:
        node = stack.pop()
        if not first and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        first = False
        out.append(node)
        stack.extend(reversed(list(ast.iter_child_nodes(node))))
    fn._own_nodes = out
    return out


def _resolve_wrapped(
    arg: ast.AST,
    pkg: PackageIndex,
    module: ModuleIndex,
    scope: Optional[FunctionInfo],
    local_values: "dict[str, ast.AST]",
) -> Optional[FunctionInfo]:
    """What function does the first argument of ``jax.jit(<arg>)`` denote?"""
    if isinstance(arg, ast.Lambda):
        return module.lambdas.get(id(arg))
    if isinstance(arg, ast.Call):
        # jax.jit(partial(f, ...)) → f
        fname = dotted(arg.func)
        if fname in _PARTIAL_NAMES and arg.args:
            return _resolve_wrapped(arg.args[0], pkg, module, scope, local_values)
        # jax.jit(build_step(...)) → the nested def build_step returns
        if fname:
            built = pkg.resolve_call(fname, module, scope)
            if built is not None and built.returned_local_defs:
                return module.functions.get(built.returned_local_defs[0])
        return None
    name = dotted(arg)
    if name is None:
        return None
    direct = pkg.resolve_call(name, module, scope)
    if direct is not None:
        return direct
    # one level of value-chasing: step = build(...); jax.jit(step)
    if "." not in name and name in local_values:
        return _resolve_wrapped(local_values[name], pkg, module, scope, local_values)
    return None


def discover_traced(pkg: PackageIndex) -> TracedRegion:
    """Find every wrap point, resolve targets, BFS the call graph."""
    roots: "dict[tuple, JitSpec]" = {}
    sites: "list[JitSite]" = []

    for module in pkg.modules.values():
        # decorator-form roots were collected during parsing
        for fn in module.functions.values():
            for spec in fn.jit_specs:
                roots.setdefault(fn.key, spec)
        # call-form wrap points: jax.jit(f, ...) anywhere in the module
        for scope_fn in [None] + list(module.functions.values()):
            nodes = list(
                iter_own_nodes(scope_fn)
                if scope_fn is not None
                else _module_level_nodes(module)
            )
            # pass 1 — first-assignment value map, so ``step = build(...);
            # step = jax.jit(step)`` resolves ``step`` through the builder
            # (the self-wrap assignment maps the name to the wrapped expr,
            # not to the wrap itself), plus assign-targets per wrap call
            local_values: "dict[str, ast.AST]" = {}
            bound_by_call: "dict[int, list[str]]" = {}
            for node in nodes:
                if not isinstance(node, ast.Assign):
                    continue
                value = node.value
                spec = (
                    parse_jit_expr(value, module)
                    if isinstance(value, ast.Call)
                    else None
                )
                targets = [dotted(t) for t in node.targets]
                targets = [t for t in targets if t]
                if spec is not None:
                    bound_by_call[id(value)] = targets
                    if getattr(value, "args", None):
                        value = value.args[0]  # name denotes the wrapped fn
                for t in targets:
                    if "." not in t and t not in local_values:
                        local_values[t] = value
            # pass 2 — the wrap sites themselves
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                spec = parse_jit_expr(node, module)
                if spec is None or not node.args:
                    continue
                target = _resolve_wrapped(
                    node.args[0], pkg, module, scope_fn, local_values
                )
                site = JitSite(
                    spec=spec,
                    module=module,
                    enclosing=scope_fn,
                    target=target,
                    bound_names=bound_by_call.get(id(node), []),
                )
                sites.append(site)
                if target is not None:
                    roots.setdefault(target.key, spec)

    # BFS reachability over resolvable calls
    traced: "dict[tuple, FunctionInfo]" = {}
    frontier: "list[FunctionInfo]" = []
    for module in pkg.modules.values():
        for fn in module.functions.values():
            if fn.key in roots:
                frontier.append(fn)
    while frontier:
        fn = frontier.pop()
        if fn.key in traced:
            continue
        traced[fn.key] = fn
        module = pkg.modules[fn.module]
        for call in _calls_in(fn):
            name = dotted(call.func)
            if name is None:
                continue
            callee = pkg.resolve_call(name, module, fn)
            if callee is not None and callee.key not in traced:
                frontier.append(callee)

    return TracedRegion(traced=traced, roots=roots, sites=sites)


def _module_level_nodes(module: ModuleIndex):
    """Module-level statements only — every function body (top-level or
    nested) belongs to its own FunctionInfo and is pruned, including the
    def statement itself."""
    fn_nodes = {id(f.node) for f in module.functions.values()}

    def _walk(node):
        if id(node) in fn_nodes:
            return
        yield node
        for child in ast.iter_child_nodes(node):
            yield from _walk(child)

    for stmt in module.tree.body:
        yield from _walk(stmt)


# ---------------------------------------------------------------------------
# file discovery


def modname_for(path: str) -> str:
    """Dotted module name: walk up while __init__.py exists."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    parent = os.path.dirname(path)
    while os.path.exists(os.path.join(parent, "__init__.py")):
        parts.append(os.path.basename(parent))
        parent = os.path.dirname(parent)
    if parts[0] == "__init__":
        parts = parts[1:] or parts
    return ".".join(reversed(parts))


def collect_py_files(paths: "list[str]") -> "list[str]":
    files: "list[str]" = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            files.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if d != "__pycache__" and not d.startswith(".")
                )
                for name in sorted(names):
                    if name.endswith(".py"):
                        files.append(os.path.join(root, name))
    return files


def build_package_index(paths: "list[str]") -> PackageIndex:
    pkg = PackageIndex()
    for path in collect_py_files(paths):
        pkg.add_file(path, modname_for(path))
    return pkg
