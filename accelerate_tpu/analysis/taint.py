"""Three-valued intra-function taint for traced code.

Inside a jitted function a value is one of:

- ``TRACED`` — a tracer. Python control flow on it (``if``/``while``/
  ``bool()``/``float()``) forces a device→host sync at best and a
  ``ConcretizationTypeError`` at worst → R1's input.
- ``SHAPE`` — trace-time static but *shape-derived* (``x.shape``, ``len(x)``,
  ``x.ndim``). Branching on it is legal and silent — and recompiles the whole
  program for every new shape → R2's input.
- ``STATIC`` — ordinary Python (config flags, mesh names, constants).

The lattice join is ``TRACED > SHAPE > STATIC``; any mixed expression takes
the worst class of its parts. The walk is a single forward pass over the
statement list (no fixpoint): assignments propagate classes to names, and
loop-carried reassignment to a *weaker* class is rare enough in real step
functions that the precision trade is worth the simplicity — this is a
linter, not a verifier.

Heuristics for the seed class of each parameter live in
:func:`initial_params`: positional args of a jitted function are tracers
unless named in the jit spec's ``static_argnums``/``static_argnames`` or
shaped like configuration (``self``, ``config``, ``*_fn``, or any constant
default — str/bool/None and also numbers, so ``group_size=2048``-style
knobs read as static). Helpers *reachable from* a root get the same
treatment — their array-ish params (``params``, ``batch``, ``x``…) stay
TRACED, their config-ish params don't fire false R1s.
"""

from __future__ import annotations

import ast
import enum
from typing import Optional

from .callgraph import FunctionInfo, JitSpec, dotted


class Cls(enum.IntEnum):
    STATIC = 0
    SHAPE = 1
    TRACED = 2


def join(*classes: Cls) -> Cls:
    return max(classes, default=Cls.STATIC)


#: parameter names that denote configuration, not arrays, in helpers the
#: call graph reaches (for jit *roots* the spec's static_argnums wins).
STATIC_PARAM_NAMES = {
    "self",
    "cls",
    "config",
    "cfg",
    "mesh",
    "axis",
    "axis_name",
    "axis_names",
    "spec",
    "specs",
    "sharding",
    "shardings",
    "policy",
    "mode",
    "name",
    "dtype",
    "shape",
    "num_heads",
    "block_size",
    "eps",
    "optimizer",
    "tx",
}

#: dotted-call prefixes whose results are tracers when called in traced code
_TRACED_CALL_PREFIXES = (
    "jnp.",
    "jax.numpy.",
    "lax.",
    "jax.lax.",
    "jax.nn.",
    "jax.random.",
    "jax.tree.",
    "jax.tree_util.",
    "optax.",
)

#: calls that always produce trace-time-static values
_STATIC_CALLS = {
    "len",
    "isinstance",
    "hasattr",
    "getattr",
    "type",
    "id",
    "range",
    "enumerate",
    "zip",
    "str",
    "repr",
    "format",
}

#: attribute tails on a traced value that yield shape-derived statics.
#: ``dtype`` is deliberately absent: jit keys its cache on dtype anyway, so
#: a dtype branch specializes without *adding* compiles (unlike shape
#: branches, which defeat padding/bucketing).
_SHAPE_ATTRS = {"shape", "ndim", "size", "nbytes"}

#: attributes of a traced value that are plain trace-time objects (not
#: tracers, not shape-derived): branching on them is benign specialization
_STATIC_ATTRS = {"dtype", "sharding", "device", "weak_type", "aval"}


def _param_default_is_configy(fn: FunctionInfo, name: str) -> bool:
    """A constant default (str/bool/None/int/float) marks a param as
    configuration, not an array. Numeric defaults are a judged trade: they
    make ``group_size=2048``-style knobs static (correct in every case this
    repo has) at the price of missing a host sync on a scalar passed as a
    traced array through a numeric-default param — spell those as arrays
    with no default to keep them traced."""
    a = fn.node.args
    pos = [p.arg for p in getattr(a, "posonlyargs", [])] + [p.arg for p in a.args]
    defaults = list(a.defaults)
    # defaults align with the tail of positional params
    for p, d in zip(pos[len(pos) - len(defaults):], defaults):
        if p == name and isinstance(d, ast.Constant):
            if d.value is None or isinstance(d.value, (bool, str, int, float)):
                return True
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if p.arg == name and isinstance(d, ast.Constant):
            if d.value is None or isinstance(d.value, (bool, str, int, float)):
                return True
    return False


def initial_params(fn: FunctionInfo, spec: Optional[JitSpec]) -> "dict[str, Cls]":
    """Seed classes for a function's parameters."""
    out: "dict[str, Cls]" = {}
    static_idx = set(spec.static_argnums or ()) if spec else set()
    static_names = set(spec.static_argnames or ()) if spec else set()
    positional = fn.positional_params()
    for i, name in enumerate(positional):
        if (
            i in static_idx
            or name in static_names
            or name in STATIC_PARAM_NAMES
            or name.endswith("_fn")
            or name.endswith("_fns")
            or _param_default_is_configy(fn, name)
        ):
            out[name] = Cls.STATIC
        else:
            out[name] = Cls.TRACED
    for name in fn.param_names():
        if name not in out:
            out[name] = (
                Cls.STATIC
                if (
                    name in static_names
                    or name in STATIC_PARAM_NAMES
                    or name.endswith("_fn")
                    or name.endswith("_fns")
                    or _param_default_is_configy(fn, name)
                )
                else Cls.TRACED
            )
    return out


class Taint:
    """Forward-pass classifier for one function body."""

    def __init__(self, fn: FunctionInfo, spec: Optional[JitSpec] = None):
        self.fn = fn
        self.names: "dict[str, Cls]" = initial_params(fn, spec)

    # -- expression classification -------------------------------------------
    def classify(self, node: Optional[ast.AST]) -> Cls:
        if node is None:
            return Cls.STATIC
        if isinstance(node, ast.Constant):
            return Cls.STATIC
        if isinstance(node, ast.Name):
            return self.names.get(node.id, Cls.STATIC)
        if isinstance(node, ast.Attribute):
            base = self.classify(node.value)
            if node.attr in _SHAPE_ATTRS:
                return Cls.SHAPE if base == Cls.TRACED else base
            if node.attr in _STATIC_ATTRS:
                return Cls.STATIC
            # attribute on a traced pytree (batch["x"] spelled batch.x) stays
            # traced; attributes on statics stay static
            return base
        if isinstance(node, ast.Subscript):
            base = self.classify(node.value)
            if base == Cls.SHAPE:
                return Cls.SHAPE  # x.shape[0]
            return base
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return join(*(self.classify(e) for e in node.elts))
        if isinstance(node, ast.Dict):
            return join(
                *(self.classify(v) for v in node.values),
                *(self.classify(k) for k in node.keys if k is not None),
            )
        if isinstance(node, (ast.BinOp,)):
            return join(self.classify(node.left), self.classify(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.classify(node.operand)
        if isinstance(node, ast.BoolOp):
            return join(*(self.classify(v) for v in node.values))
        if isinstance(node, ast.Compare):
            # identity checks (`aux is not None`) resolve at trace time —
            # the *object* is known even when its value is a tracer
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return Cls.STATIC
            return join(
                self.classify(node.left), *(self.classify(c) for c in node.comparators)
            )
        if isinstance(node, ast.IfExp):
            return join(self.classify(node.body), self.classify(node.orelse))
        if isinstance(node, ast.Call):
            return self._classify_call(node)
        if isinstance(node, ast.Starred):
            return self.classify(node.value)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return self.classify(node.elt)
        if isinstance(node, ast.DictComp):
            return join(self.classify(node.key), self.classify(node.value))
        if isinstance(node, ast.JoinedStr):
            return Cls.STATIC
        if isinstance(node, ast.Lambda):
            return Cls.STATIC
        # unknown expression kinds: assume static (under-flagging beats noise)
        return Cls.STATIC

    def _classify_call(self, node: ast.Call) -> Cls:
        name = dotted(node.func) or ""
        tail = name.rsplit(".", 1)[-1]
        # pytree/dict structure is trace-time static: iterating params.items()
        # (or .keys()/.values()) is ordinary python over static structure
        if isinstance(node.func, ast.Attribute) and tail in {
            "items",
            "keys",
            "values",
        }:
            return Cls.STATIC
        if name in _STATIC_CALLS:
            if name == "len" and self.classify(node.args[0] if node.args else None) == Cls.TRACED:
                return Cls.SHAPE  # len(traced) is static but shape-derived
            return Cls.STATIC
        if name in {"int", "float", "bool", "complex"}:
            arg = self.classify(node.args[0]) if node.args else Cls.STATIC
            # int(x.shape[0]) → shape-derived static; int(tracer) is R1's
            # job to flag, but the *value* it would produce is host-side
            return Cls.SHAPE if arg in (Cls.SHAPE, Cls.TRACED) else Cls.STATIC
        for prefix in _TRACED_CALL_PREFIXES:
            if name.startswith(prefix):
                return Cls.TRACED
        if name.endswith(".astype") or name.endswith(".reshape") or name.endswith(
            ".sum"
        ) or name.endswith(".mean") or name.endswith(".max") or name.endswith(".min"):
            return self.classify(node.func.value) if isinstance(
                node.func, ast.Attribute
            ) else Cls.TRACED
        # method on a traced receiver keeps the receiver's class
        if isinstance(node.func, ast.Attribute):
            return self.classify(node.func.value)
        # unknown free function: propagate the worst argument class — a helper
        # fed a tracer almost always returns one
        return join(
            *(self.classify(a) for a in node.args),
            *(self.classify(k.value) for k in node.keywords),
        )

    # -- statement effects ---------------------------------------------------
    def assign(self, target: ast.AST, cls: Cls) -> None:
        if isinstance(target, ast.Name):
            self.names[target.id] = cls
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.assign(elt, cls)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, cls)
        # attribute/subscript targets don't bind names

    def visit_statement(self, node: ast.AST) -> None:
        """Update name classes for one statement (callers walk in source
        order via :func:`callgraph.iter_own_nodes`)."""
        if isinstance(node, ast.Assign):
            cls = self.classify(node.value)
            for tgt in node.targets:
                self.assign(tgt, cls)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            self.assign(node.target, self.classify(node.value))
        elif isinstance(node, ast.AugAssign):
            cls = join(self.classify(node.target), self.classify(node.value))
            self.assign(node.target, cls)
        elif isinstance(node, ast.For):
            self.assign(node.target, self.classify(node.iter))
        elif isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, self.classify(item.context_expr))
        elif isinstance(node, ast.comprehension):
            self.assign(node.target, self.classify(node.iter))
