"""Inline suppression comments.

Two spellings, matching the linter convention the repo already follows for
noqa-style tools:

- ``# jaxlint: disable=R1`` (or ``disable=R1,R3``) at the end of the
  flagged line suppresses those rules **on that line only**;
- ``# jaxlint: disable`` with no rule list suppresses every rule on the
  line;
- ``# jaxlint: skip-file`` within the first ten lines of a file suppresses
  the whole file (generated code, vendored fixtures).

A suppression is an *audited* exception: the finding still appears in the
report (counted under "suppressed"), it just doesn't fail the run. This is
deliberately different from the baseline (:mod:`.baseline`), which exists
to ratchet down pre-existing debt without an in-source annotation.
"""

from __future__ import annotations

import re
from typing import Iterable

from .findings import Finding

_DISABLE_RE = re.compile(r"#\s*jaxlint:\s*disable(?:=(?P<rules>[A-Za-z0-9,\s]+))?")
_SKIP_FILE_RE = re.compile(r"#\s*jaxlint:\s*skip-file")
_SKIP_FILE_WINDOW = 10


def parse_line_suppressions(source_lines: "list[str]") -> "dict[int, set]":
    """1-based line -> set of suppressed rule ids ({"*"} = all rules)."""
    out: "dict[int, set]" = {}
    for i, line in enumerate(source_lines, start=1):
        m = _DISABLE_RE.search(line)
        if not m:
            continue
        rules = m.group("rules")
        if rules is None:
            out[i] = {"*"}
        else:
            out[i] = {r.strip().upper() for r in rules.split(",") if r.strip()}
    return out


def file_is_skipped(source_lines: "list[str]") -> bool:
    return any(
        _SKIP_FILE_RE.search(line)
        for line in source_lines[:_SKIP_FILE_WINDOW]
    )


def apply_suppressions(
    findings: Iterable[Finding],
    suppressions_by_path: "dict[str, dict[int, set]]",
    skipped_paths: "set[str]",
) -> None:
    """Mark findings covered by an inline comment (in place)."""
    for f in findings:
        if f.path in skipped_paths:
            f.suppressed = True
            continue
        rules = suppressions_by_path.get(f.path, {}).get(f.line)
        if rules and ("*" in rules or f.rule in rules):
            f.suppressed = True
