"""The jaxlint engine: parse → discover traced region → run rules →
suppressions → baseline.

``run_lint`` is the single entry point the CLI, the tests, and the
telemetry doctor all call. It never imports the code it analyzes — pure
``ast`` over source text — so linting is safe on machines with no jax
backend and costs tens of milliseconds for this whole repo.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from . import baseline as baseline_mod
from . import suppressions as suppress_mod
from .callgraph import build_package_index, discover_traced
from .findings import Finding, summarize
from .rules import RuleContext, load_all_rules


@dataclass
class LintResult:
    """Everything a caller needs: all findings (annotated), run stats, and
    the pass/fail verdict."""

    findings: "list[Finding]" = field(default_factory=list)
    stats: dict = field(default_factory=dict)
    baseline_path: Optional[str] = None

    @property
    def new_findings(self) -> "list[Finding]":
        return [f for f in self.findings if f.is_new]

    @property
    def ok(self) -> bool:
        return not self.new_findings and not self.stats.get("parse_errors")

    def summary(self) -> dict:
        return summarize(self.findings)


def _lint_root(paths: "list[str]") -> str:
    """Findings carry paths relative to the common root of the linted
    paths' parent — which for ``lint accelerate_tpu/`` from the repo root
    means repo-relative paths, matching the baseline file."""
    first = os.path.abspath(paths[0]) if paths else os.getcwd()
    if os.path.isfile(first):
        first = os.path.dirname(first)
    return os.path.dirname(first) or first


def run_lint(
    paths: "list[str]",
    rules: Optional["list[str]"] = None,
    baseline_path: Optional[str] = None,
    use_baseline: bool = True,
    root: Optional[str] = None,
) -> LintResult:
    """Lint ``paths`` (files or directories).

    ``rules`` restricts to a subset (e.g. ``["R1", "R4"]``); ``baseline_path``
    overrides baseline discovery; ``use_baseline=False`` reports everything
    as new (the fixture-corpus mode the tests use).
    """
    # resolve the baseline FIRST: when one is in play, finding paths must be
    # relative to ITS directory so `lint accelerate_tpu/state.py` and
    # `lint accelerate_tpu/` fingerprint the same file identically
    resolved_baseline = baseline_path
    if resolved_baseline is None and use_baseline:
        resolved_baseline = baseline_mod.discover_baseline(paths)
    if root is None and use_baseline and resolved_baseline:
        root = os.path.dirname(os.path.abspath(resolved_baseline))
    root = root or _lint_root(paths)
    pkg = build_package_index(paths)
    region = discover_traced(pkg)
    ctx = RuleContext(pkg, region, root)

    registry = load_all_rules()
    if rules:
        unknown = [r for r in rules if r.upper() not in registry]
        if unknown:
            raise ValueError(
                f"unknown rule id(s) {unknown} — known: {sorted(registry)}"
            )
        selected = [registry[r.upper()] for r in rules]
    else:
        selected = list(registry.values())

    findings: "list[Finding]" = []
    for rule in selected:
        findings.extend(rule.check(ctx))

    # inline suppressions (path keys are lint-root-relative, like findings)
    suppressions_by_path: "dict[str, dict[int, set]]" = {}
    skipped_paths: "set[str]" = set()
    for module in pkg.modules.values():
        rel = os.path.relpath(module.path, root)
        suppressions_by_path[rel] = suppress_mod.parse_line_suppressions(
            module.source_lines
        )
        if suppress_mod.file_is_skipped(module.source_lines):
            skipped_paths.add(rel)
    suppress_mod.apply_suppressions(findings, suppressions_by_path, skipped_paths)

    # baseline
    if use_baseline and resolved_baseline and os.path.exists(resolved_baseline):
        baseline_mod.apply_baseline(
            findings, baseline_mod.load_baseline(resolved_baseline)
        )

    stats = {
        "files": len(pkg.modules),
        "traced_functions": len(region.traced),
        "jit_roots": len(region.roots),
        "jit_sites": len(region.sites),
        "parse_errors": list(pkg.errors),
        "rules": [r.id for r in selected],
    }
    return LintResult(
        findings=findings, stats=stats, baseline_path=resolved_baseline
    )
