"""R2 — recompile hazards.

The static counterpart of the step profiler's jit-cache-miss detector
(telemetry PR 2): everything here compiles *fine* and then recompiles — or
unrolls — in production, which on a TPU pod means minutes of XLA time per
occurrence (the bench round 2 recompile storms).

Flags:

- **shape-derived Python branches** in traced code (``if x.shape[0] > 128:``)
  — legal at trace time, silently specializes the program per shape;
- **python loops over traced arrays** — unroll into the HLO and re-unroll
  (recompile) for every new length;
- **unhashable static args** at jit call boundaries (list/dict/set literal
  passed at a ``static_argnums`` position raises at best, retraces at worst);
- **per-iteration-varying static args** (the static arg is the loop
  variable: one recompile per iteration);
- **closures over mutable globals** — the traced function bakes the value at
  trace time; later mutation is invisible (stale constant) or, when the
  cache key sees it, a retrace per mutation.
"""

from __future__ import annotations

import ast

from ..callgraph import dotted, iter_own_nodes
from ..findings import Severity
from ..taint import Cls, Taint
from . import Rule, RuleContext, register

_MUTABLE_CALLS = {"dict", "list", "set", "defaultdict", "OrderedDict", "deque"}


def _loop_targets(scope_node: ast.AST, call: ast.Call) -> "set[str]":
    """Names bound by ``for`` loops lexically enclosing ``call``."""
    targets: "set[str]" = set()

    def _contains(node: ast.AST) -> bool:
        return any(n is call for n in ast.walk(node))

    def _descend(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if not _contains(child):
                continue
            if isinstance(child, ast.For) and any(
                _contains(s) for s in child.body + child.orelse
            ):
                targets.update(
                    n.id for n in ast.walk(child.target) if isinstance(n, ast.Name)
                )
            _descend(child)
            return  # the call lives in exactly one child subtree

    _descend(scope_node)
    return targets


def check(ctx: RuleContext) -> list:
    findings = []

    # -- traced-region hazards ------------------------------------------------
    for fn in ctx.region.traced.values():
        module = ctx.pkg.modules[fn.module]
        taint = Taint(fn, ctx.region.spec_for(fn))
        local_names = set(fn.param_names())
        for node in iter_own_nodes(fn):
            taint.visit_statement(node)
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in tgts:
                    local_names.update(
                        n.id for n in ast.walk(t) if isinstance(n, ast.Name)
                    )
            elif isinstance(node, (ast.For,)):
                local_names.update(
                    n.id for n in ast.walk(node.target) if isinstance(n, ast.Name)
                )
                if taint.classify(node.iter) == Cls.TRACED:
                    findings.append(
                        ctx.finding(
                            "R2",
                            Severity.WARNING,
                            module,
                            node,
                            "python loop over a traced array unrolls into the "
                            "program and recompiles per length — use lax.scan "
                            "/ lax.fori_loop",
                            fn=fn,
                        )
                    )
            if isinstance(node, (ast.If, ast.While)):
                if taint.classify(node.test) == Cls.SHAPE:
                    findings.append(
                        ctx.finding(
                            "R2",
                            Severity.WARNING,
                            module,
                            node,
                            "branch on a shape-derived value specializes the "
                            "compiled program per shape — pad/bucket shapes "
                            "or lift the branch out of the traced region",
                            fn=fn,
                        )
                    )
        # closure over a mutable module global (ALL_CAPS constants exempt
        # unless something rebinds them through ``global``)
        for node in iter_own_nodes(fn):
            if not isinstance(node, ast.Name) or not isinstance(node.ctx, ast.Load):
                continue
            name = node.id
            if name in local_names or name in module.imports:
                continue
            if name.isupper() and name not in module.global_writes:
                continue
            value = module.module_globals.get(name)
            mutable_literal = isinstance(value, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(value, ast.Call)
                and (dotted(value.func) or "").rsplit(".", 1)[-1] in _MUTABLE_CALLS
            )
            if name in module.global_writes or (value is not None and mutable_literal):
                findings.append(
                    ctx.finding(
                        "R2",
                        Severity.WARNING,
                        module,
                        node,
                        f"traced function closes over mutable module global "
                        f"`{name}` — its value is baked at trace time (stale "
                        "after mutation) or forces a retrace; pass it as an "
                        "argument",
                        fn=fn,
                    )
                )
                local_names.add(name)  # one finding per name per function

    # -- jit call-boundary hazards -------------------------------------------
    for call, spec, module, scope in ctx.jit_call_sites():
        static_idx = spec.static_argnums or ()
        if not static_idx:
            continue
        for i in static_idx:
            if not isinstance(i, int) or i >= len(call.args):
                continue
            arg = call.args[i]
            if isinstance(arg, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)):
                findings.append(
                    ctx.finding(
                        "R2",
                        Severity.ERROR,
                        module,
                        arg,
                        f"unhashable static argument (argnum {i}) at a jit "
                        "call site — static args key the compile cache and "
                        "must be hashable (use a tuple / frozen dataclass)",
                        fn=scope,
                    )
                )
            elif isinstance(arg, ast.Name):
                # module-level call sites use the module tree as the loop
                # ancestry (a top-level benchmark loop recompiles the same)
                scope_node = scope.node if scope is not None else module.tree
                if arg.id in _loop_targets(scope_node, call):
                    findings.append(
                        ctx.finding(
                            "R2",
                            Severity.WARNING,
                            module,
                            arg,
                            f"static argument (argnum {i}) is the enclosing "
                            "loop variable — one recompile per iteration; "
                            "trace it or hoist the loop inside the jit",
                            fn=scope,
                        )
                    )
    return findings


register(
    Rule(
        id="R2",
        name="recompile-hazard",
        severity=Severity.WARNING,
        description=(
            "Code that compiles once in the demo and recompiles per shape/"
            "iteration in production: shape-derived branches, unrolling loops "
            "over tracers, unhashable or loop-varying static args, closures "
            "over mutable globals."
        ),
        check=check,
    )
)
