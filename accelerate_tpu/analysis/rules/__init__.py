"""jaxlint rule registry.

A rule is a pure function ``check(ctx) -> list[Finding]`` plus metadata,
registered at import time. Every rule descends from a bug this repo actually
shipped or autopsied (see ``docs/static_analysis.md`` for the lineage):

- **R1** host-sync in traced code — the retrace/stall class the telemetry
  step profiler can only *report* after it burns device time.
- **R2** recompile hazards — the jit-cache-miss storms of bench round 2.
- **R3** donation bugs — the PR 3 schedule-free optimizer state aliasing a
  donated param buffer.
- **R4** rank-divergent collectives — the r04 evidence-free hang: a
  collective reached by only some ranks deadlocks the fleet.
- **R5** nondeterminism in traced code — trace-time values baked into the
  compiled program that differ per run/rank.
- **R6** accumulator precision — a bare ``dot_general`` in kernel code
  accumulates in the operand dtype (bf16/fp8), discarding the MXU's f32
  accumulator; the drift only surfaces at scale (the ISSUE 20 kernels).

``RuleContext`` carries the package index and traced region, plus the
cross-rule helpers (jit call sites, collective-containment fixpoint) that
several rules need, computed once.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Optional

from ..callgraph import (
    FunctionInfo,
    JitSite,
    ModuleIndex,
    PackageIndex,
    TracedRegion,
    _module_level_nodes,
    dotted,
    iter_own_nodes,
)
from ..findings import Finding, Severity


@dataclass
class Rule:
    id: str
    name: str
    severity: Severity
    description: str
    check: Callable  # (RuleContext) -> list[Finding]


RULES: "dict[str, Rule]" = {}


def register(rule: Rule) -> Rule:
    RULES[rule.id] = rule
    return rule


class RuleContext:
    """Shared state for one lint run."""

    def __init__(self, pkg: PackageIndex, region: TracedRegion, root: str):
        self.pkg = pkg
        self.region = region
        self.root = root
        self._call_sites: Optional[list] = None
        self._collective_fns: Optional[set] = None

    # -- finding construction ------------------------------------------------
    def finding(
        self,
        rule: str,
        severity: Severity,
        module: ModuleIndex,
        node: ast.AST,
        message: str,
        fn: Optional[FunctionInfo] = None,
        **extra,
    ) -> Finding:
        import os

        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        path = os.path.relpath(module.path, self.root)
        return Finding(
            rule=rule,
            severity=severity,
            path=path,
            line=line,
            col=col,
            message=message,
            symbol=fn.qualname if fn is not None else "",
            line_content=module.line(line),
            extra=extra,
        )

    # -- shared analyses -----------------------------------------------------
    def jit_call_sites(self) -> "list[tuple]":
        """Call sites *of* jitted functions: ``(call, spec, module, scope)``.

        Covers calls to decorator-jitted defs, to names a call-form wrap was
        bound to (``step = jax.jit(f); ... step(...)``), and to attribute
        bindings (``self._train_step = jax.jit(f); self._train_step(...)``).
        R2 (varying/unhashable static args) and R3 (donation at the call
        boundary) both consume this.
        """
        if self._call_sites is not None:
            return self._call_sites
        sites: "list[tuple]" = []
        # name -> spec maps, per module (call-form bindings are module-local)
        bound: "dict[str, dict[str, JitSite]]" = {}
        for site in self.region.sites:
            per = bound.setdefault(site.module.modname, {})
            for name in site.bound_names:
                per[name] = site
        for module in self.pkg.modules.values():
            per = bound.get(module.modname, {})
            # scope None = module level: a top-level `step(x, [4, 8])` is as
            # much a call site as one inside a function
            for scope in [None] + list(module.functions.values()):
                nodes = (
                    iter_own_nodes(scope)
                    if scope is not None
                    else _module_level_nodes(module)
                )
                for node in nodes:
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted(node.func)
                    if name is None:
                        continue
                    if name in per:
                        sites.append((node, per[name].spec, module, scope))
                        continue
                    callee = self.pkg.resolve_call(name, module, scope)
                    if callee is not None:
                        spec = self.region.roots.get(callee.key)
                        # only decorator-form roots are jitted under their
                        # own name; for call-form wraps (`step = jax.jit(f)`)
                        # a direct `f(...)` is an EAGER call that donates
                        # nothing — the jitted spelling is the bound name,
                        # matched above
                        if spec is not None and callee.jit_specs:
                            sites.append((node, spec, module, scope))
        self._call_sites = sites
        return sites

    def collective_functions(self) -> "set[tuple]":
        """Keys of scanned functions that (transitively) issue a host-level
        collective — the fixpoint R4 walks rank-conditionals against."""
        if self._collective_fns is not None:
            return self._collective_fns
        contains: "set[tuple]" = set()
        # one AST pass: per-function resolved callees + direct-collective seed
        callees: "dict[tuple, set]" = {}
        for module in self.pkg.modules.values():
            for fn in module.functions.values():
                keys = set()
                for node in iter_own_nodes(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    if call_is_collective(node):
                        contains.add(fn.key)
                        continue
                    name = dotted(node.func)
                    if name is not None:
                        callee = self.pkg.resolve_call(name, module, fn)
                        if callee is not None:
                            keys.add(callee.key)
                callees[fn.key] = keys
        # propagate caller-ward to fixpoint over the precomputed edges
        changed = True
        while changed:
            changed = False
            for key, callee_keys in callees.items():
                if key not in contains and callee_keys & contains:
                    contains.add(key)
                    changed = True
        self._collective_fns = contains
        return contains


#: host-level collective entry points (``utils/operations.py`` and the
#: jax_compat/multihost wrappers) — every one of these deadlocks when only a
#: subset of ranks reaches it.
COLLECTIVE_NAMES = {
    "gather",
    "gather_object",
    "gather_for_metrics",
    "broadcast",
    "broadcast_object_list",
    "broadcast_one_to_all",
    "reduce",
    "pad_across_processes",
    "process_allgather",
    "sync_global_devices",
    "wait_for_everyone",
    "barrier",
    "all_gather",
    "all_reduce",
}


def call_is_collective(node: ast.Call) -> Optional[str]:
    name = dotted(node.func)
    if name is None:
        return None
    tail = name.rsplit(".", 1)[-1]
    return tail if tail in COLLECTIVE_NAMES else None


#: names whose truthiness differs across ranks — branching on one of these
#: and then issuing a collective is the R4 deadlock shape.
RANK_MARKERS = {
    "is_main_process",
    "is_local_main_process",
    "is_last_process",
    "process_index",
    "local_process_index",
    "rank",
    "local_rank",
    "node_rank",
    "global_rank",
}


def test_is_rank_divergent(node: ast.AST) -> bool:
    """Does this expression's value depend on the process identity?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in RANK_MARKERS:
            return True
        if isinstance(sub, ast.Name) and sub.id in RANK_MARKERS:
            return True
        if isinstance(sub, ast.Call):
            name = dotted(sub.func) or ""
            if name.rsplit(".", 1)[-1] in {"process_index", "process_count"}:
                return True
    return False


def load_all_rules() -> "dict[str, Rule]":
    """Import every rule module (registration is an import side effect)."""
    from . import (  # noqa: F401
        collectives,
        donation,
        host_sync,
        nondeterminism,
        precision,
        recompile,
    )

    return RULES
