"""R5 — nondeterminism baked into traced code.

Anything evaluated at *trace time* becomes a constant in the compiled
program. ``time.time()`` inside a jitted step isn't a clock — it's the
timestamp of the first trace, forever. ``random.random()`` is one draw,
frozen. Worse on SPMD: each rank traces independently, so each rank bakes a
*different* constant — silent cross-rank divergence that surfaces hundreds
of steps later as a loss mismatch (or, when the value feeds a shape or a
sharding spec, as the R4 deadlock class).

Flags, inside the traced region:

- ``time.*`` / ``datetime.now`` calls;
- python ``random.*`` / ``np.random.*`` / ``os.urandom`` / ``uuid.*``
  (``jax.random`` with explicit keys is the deterministic spelling and is
  never flagged);
- iteration over a ``set`` — order is unspecified and varies per process
  (hash randomization), so any structure built from it diverges per rank.

Set iteration is additionally flagged in *sharding-spec-shaped* functions
(name mentions shard/spec/partition) even outside traced code: an
unordered axis assignment diverging across ranks is how a mesh disagrees
with itself.
"""

from __future__ import annotations

import ast

from ..callgraph import dotted, iter_own_nodes
from ..findings import Severity
from . import Rule, RuleContext, register

_TIME_CALLS = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.time_ns",
    "time.monotonic_ns",
    "datetime.now",
    "datetime.utcnow",
    "datetime.datetime.now",
}
_ENTROPY_PREFIXES = ("random.", "np.random.", "numpy.random.")
_ENTROPY_CALLS = {"os.urandom", "uuid.uuid4", "uuid.uuid1", "secrets.token_bytes"}


def _is_entropy_call(name: str) -> bool:
    if name in _ENTROPY_CALLS or name in _TIME_CALLS:
        return True
    for prefix in _ENTROPY_PREFIXES:
        if name.startswith(prefix):
            return True
    return False


def _is_set_iter(node: ast.For) -> bool:
    it = node.iter
    if isinstance(it, ast.Set):
        return True
    if isinstance(it, ast.Call):
        return (dotted(it.func) or "") == "set"
    return False


def check(ctx: RuleContext) -> list:
    findings = []
    for fn in ctx.region.traced.values():
        module = ctx.pkg.modules[fn.module]
        for node in iter_own_nodes(fn):
            if isinstance(node, ast.Call):
                name = dotted(node.func) or ""
                if _is_entropy_call(name):
                    findings.append(
                        ctx.finding(
                            "R5",
                            Severity.WARNING,
                            module,
                            node,
                            f"`{name}()` in traced code is evaluated once at "
                            "trace time and baked into the program — each "
                            "rank bakes a different constant; use jax.random "
                            "with an explicit key (or pass the value in as "
                            "an argument)",
                            fn=fn,
                        )
                    )
            elif isinstance(node, ast.For) and _is_set_iter(node):
                findings.append(
                    ctx.finding(
                        "R5",
                        Severity.WARNING,
                        module,
                        node,
                        "iteration over a set in traced code — order is "
                        "unspecified and varies per process, so the traced "
                        "program differs per rank; sort it",
                        fn=fn,
                    )
                )
    # sharding-spec builders: set-iteration order becomes the mesh layout
    traced_keys = set(ctx.region.traced)
    for module in ctx.pkg.modules.values():
        for fn in module.functions.values():
            if fn.key in traced_keys:
                continue
            lowered = fn.name.lower()
            if not any(h in lowered for h in ("shard", "spec", "partition")):
                continue
            for node in iter_own_nodes(fn):
                if isinstance(node, ast.For) and _is_set_iter(node):
                    findings.append(
                        ctx.finding(
                            "R5",
                            Severity.WARNING,
                            module,
                            node,
                            "iteration over a set while building sharding "
                            "specs — unordered axis assignment can differ "
                            "across ranks; sort it",
                            fn=fn,
                        )
                    )
    return findings


register(
    Rule(
        id="R5",
        name="nondeterminism-in-traced-code",
        severity=Severity.WARNING,
        description=(
            "time.*/random.*/np.random/set-iteration inside traced code — "
            "values baked at trace time that differ per run and per rank."
        ),
        check=check,
    )
)
