"""R3 — buffer-donation hazards at jit boundaries.

Descends directly from the PR 3 schedule-free optimizer bug: the optimizer
init copied state leaves that *aliased* the param buffers (schedule-free's
``z`` iterate), so a train step with ``donate_argnums=(0, 1)`` donated one
physical buffer twice — ``INTERNAL: ... buffer donated twice`` on TPU, or
silent corruption where the runtime doesn't check.

Three shapes, all at the *call site* of a donated jit function (where the
alias is visible), plus one at the wrap point:

- **missing donation** (wrap point): a jitted step that returns updated
  versions of its large-state params (``return params, opt_state, …``)
  without ``donate_argnums`` holds two copies of the model live across the
  update — 2× params of HBM wasted. Warning, not error: sometimes the caller
  really does need the old state.
- **aliased donation**: an argument at a donated position shares a buffer
  (via plain-name assignment or container-literal membership) with another
  argument of the same call.
- **use-after-donate**: the donated name is read after the call without
  being rebound by it.
- **donate-in-loop**: the call sits in a loop and the donated name is not
  rebound by the call's own assignment — the second iteration passes a
  deleted buffer.
"""

from __future__ import annotations

import ast

from ..callgraph import iter_own_nodes
from ..findings import Severity
from . import Rule, RuleContext, register

#: param names that denote the large, update-in-place state of a train step
LARGE_STATE_NAMES = {
    "params",
    "opt_state",
    "state",
    "grads",
    "model",
    "weights",
    "variables",
    "master_params",
    "kv_cache",
    "cache",
}


def _alias_roots(name: str, aliases: "dict[str, set]") -> "set[str]":
    return aliases.get(name, set()) | {name}


def _build_aliases(scope_node: ast.AST) -> "dict[str, set]":
    """Name → set of names it may share buffers with, from plain-name
    assignments (``z = params``) and container-literal membership
    (``opt_state = {"z": z}``). One forward pass, lexical order."""
    aliases: "dict[str, set]" = {}
    for node in ast.walk(scope_node):
        if not isinstance(node, ast.Assign):
            continue
        sources: "set[str]" = set()
        value = node.value
        if isinstance(value, ast.Name):
            sources |= _alias_roots(value.id, aliases)
        elif isinstance(value, (ast.Tuple, ast.List, ast.Dict, ast.Set)):
            elts = value.values if isinstance(value, ast.Dict) else value.elts
            for elt in elts:
                if isinstance(elt, ast.Name):
                    sources |= _alias_roots(elt.id, aliases)
        if not sources:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                aliases.setdefault(tgt.id, set()).update(sources)
    return aliases


def _arg_names(arg: ast.AST, aliases: "dict[str, set]") -> "set[str]":
    """Buffer roots an argument expression may carry."""
    if isinstance(arg, ast.Name):
        return _alias_roots(arg.id, aliases)
    if isinstance(arg, (ast.Tuple, ast.List, ast.Set)):
        out: "set[str]" = set()
        for elt in arg.elts:
            out |= _arg_names(elt, aliases)
        return out
    if isinstance(arg, ast.Dict):
        out = set()
        for v in arg.values:
            out |= _arg_names(v, aliases)
        return out
    return set()


def _stores_after(scope_node: ast.AST, name: str, after_line: int) -> "list[int]":
    return sorted(
        n.lineno
        for n in ast.walk(scope_node)
        if isinstance(n, ast.Name)
        and isinstance(n.ctx, (ast.Store,))
        and n.id == name
        and n.lineno >= after_line
    )


def _loads_between(scope_node, name, lo, hi) -> "list[int]":
    return sorted(
        n.lineno
        for n in ast.walk(scope_node)
        if isinstance(n, ast.Name)
        and isinstance(n.ctx, ast.Load)
        and n.id == name
        and lo < n.lineno <= hi
    )


def _call_in_loop(scope_node: ast.AST, call: ast.Call) -> bool:
    def _contains(node):
        return any(n is call for n in ast.walk(node))

    def _descend(node) -> bool:
        for child in ast.iter_child_nodes(node):
            if not _contains(child):
                continue
            if isinstance(child, (ast.For, ast.While)) and any(
                _contains(s) for s in child.body + child.orelse
            ):
                return True
            return _descend(child)
        return False

    return _descend(scope_node)


def _assignment_rebinds(scope_node: ast.AST, call: ast.Call, name: str) -> bool:
    """Is ``call`` the value of an assignment whose targets rebind ``name``?
    (``params, opt_state, m = step(params, opt_state, batch)``)"""
    for node in ast.walk(scope_node):
        if isinstance(node, ast.Assign) and (
            node.value is call
            or (
                isinstance(node.value, (ast.Tuple,))
                and any(e is call for e in node.value.elts)
            )
        ):
            for tgt in node.targets:
                if any(
                    isinstance(n, ast.Name) and n.id == name
                    for n in ast.walk(tgt)
                ):
                    return True
    return False


def check(ctx: RuleContext) -> list:
    findings = []

    # -- wrap points: large-state step without donation ----------------------
    seen_wraps = set()
    for key, spec in ctx.region.roots.items():
        if spec.kind not in ("jit", "pjit") or spec.donates:
            continue
        fn = ctx.region.traced.get(key)
        if fn is None or key in seen_wraps:
            continue
        seen_wraps.add(key)
        params = set(fn.positional_params())
        large = params & LARGE_STATE_NAMES
        if not large:
            continue
        # only a step that RETURNS updated versions of those params is an
        # update-in-place candidate (eval/forward steps keep their inputs)
        # top-level returned names only — a param used *inside* the returned
        # expression (``return eval_fn(params, batch)``) is not an update
        returned: "set[str]" = set()
        for node in iter_own_nodes(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                elts = (
                    node.value.elts
                    if isinstance(node.value, ast.Tuple)
                    else [node.value]
                )
                for e in elts:
                    if isinstance(e, ast.Name):
                        returned.add(e.id)
                        # ``return new_params, ...`` is an update of ``params``
                        for prefix in ("new_", "next_", "updated_"):
                            if e.id.startswith(prefix):
                                returned.add(e.id[len(prefix):])
        updated = large & returned
        if not updated:
            continue
        module = ctx.pkg.modules[fn.module]
        names = ", ".join(sorted(updated))
        findings.append(
            ctx.finding(
                "R3",
                Severity.WARNING,
                module,
                spec.node if spec.node.lineno else fn.node,
                f"jitted step returns updated `{names}` without "
                "donate_argnums — the old and new state are both live across "
                "the update (2x state HBM); donate the input buffers",
                fn=fn,
            )
        )

    # -- call sites of donated functions -------------------------------------
    for call, spec, module, scope in ctx.jit_call_sites():
        if not spec.donates:
            continue
        donated_idx = [
            i for i in (spec.donate_argnums or ()) if isinstance(i, int)
        ]
        if not donated_idx:
            continue
        # module-level call sites (scope None) use the module tree as the
        # alias/use-after-donate scope — a script-level donated call is the
        # same bug as one inside a function
        scope_node = scope.node if scope is not None else module.tree
        aliases = _build_aliases(scope_node)
        donated: "dict[int, set]" = {}
        for i in donated_idx:
            if i < len(call.args):
                donated[i] = _arg_names(call.args[i], aliases)
        for i, dnames in donated.items():
            if not dnames:
                continue
            # (a) the same buffer appears in another argument of this call
            for j, arg in enumerate(call.args):
                if j == i:
                    continue
                other = _arg_names(arg, aliases)
                shared = dnames & other
                if shared:
                    what = ", ".join(sorted(shared))
                    also_donated = j in donated
                    findings.append(
                        ctx.finding(
                            "R3",
                            Severity.ERROR,
                            module,
                            call,
                            f"donated argument {i} shares buffer(s) `{what}` "
                            f"with argument {j}"
                            + (
                                " (also donated — double donation)"
                                if also_donated
                                else " — the donated buffer is still aliased "
                                "by a live reference"
                            )
                            + "; copy the aliased leaves before the call",
                            fn=scope,
                        )
                    )
            # (b)/(c): use-after-donate and donate-in-loop, on the directly
            # passed name (alias tracking would over-flag here)
            if not isinstance(call.args[i], ast.Name):
                continue
            name = call.args[i].id
            rebound = _assignment_rebinds(scope_node, call, name)
            in_loop = _call_in_loop(scope_node, call)
            # the load window opens after the call's LAST line — a wrapped
            # call's own continuation-line arguments are not post-call reads
            call_end = getattr(call, "end_lineno", None) or call.lineno
            if in_loop and not rebound:
                findings.append(
                    ctx.finding(
                        "R3",
                        Severity.ERROR,
                        module,
                        call,
                        f"`{name}` is donated inside a loop but never rebound "
                        "from the call result — the next iteration passes a "
                        "deleted buffer",
                        fn=scope,
                    )
                )
            elif not rebound:
                stores = _stores_after(scope_node, name, call_end + 1)
                horizon = stores[0] if stores else 10**9
                loads = _loads_between(scope_node, name, call_end, horizon)
                if loads:
                    findings.append(
                        ctx.finding(
                            "R3",
                            Severity.ERROR,
                            module,
                            call,
                            f"`{name}` is read at line {loads[0]} after being "
                            "donated here — donated buffers are deleted by "
                            "the call",
                            fn=scope,
                        )
                    )
    return findings


register(
    Rule(
        id="R3",
        name="donation-hazard",
        severity=Severity.ERROR,
        description=(
            "Buffer-donation bugs at jit boundaries: large-state steps "
            "without donate_argnums, donated buffers aliased by other live "
            "references (the PR 3 schedule-free bug), use-after-donate, "
            "donation inside loops without rebinding."
        ),
        check=check,
    )
)
