"""R6 — accumulator precision at explicit kernel matmuls.

Born with the in-tree flash-attention and fp8 kernels (ISSUE 20): a bare
``lax.dot_general`` on bf16/fp8 operands accumulates in the *operand* dtype
unless ``preferred_element_type`` says otherwise. On the MXU that is the
difference between a f32 accumulator (free — the systolic array carries
one anyway) and a silently quantized partial sum: online-softmax
renormalization and fp8 dequantization both amplify that rounding into
visible loss drift, and the failure only shows at scale, never in a tiny
parity test. Every hand-written ``dot_general`` in a kernel module must
pin its accumulator.

Flags ``dot_general`` calls missing ``preferred_element_type`` when the
call is (a) inside a traced function — the jit region is where operand
dtypes go bf16/fp8 — or (b) anywhere in an ``ops/`` module, where Pallas
kernel bodies live (kernel fns are called by ``pallas_call``, not wrapped
by ``jax.jit``, so traced-region discovery cannot see them).

Operator matmuls (``a @ b``, ``jnp.einsum``) are *not* flagged: policy for
those lives in ``jax.default_matmul_precision``; this rule is about the
explicit-``dot_general`` spelling that kernels use precisely because they
need the accumulator pinned.
"""

from __future__ import annotations

import ast
import os

from ..callgraph import _module_level_nodes, dotted, iter_own_nodes
from ..findings import Severity
from . import Rule, RuleContext, register

_MSG = (
    "dot_general without preferred_element_type accumulates in the operand "
    "dtype — on bf16/fp8 inputs the MXU's f32 accumulator is discarded; "
    "pass preferred_element_type=jnp.float32"
)


def _is_dot_general(node: ast.Call) -> bool:
    name = dotted(node.func)
    return name is not None and name.rsplit(".", 1)[-1] == "dot_general"


def _has_accum_dtype(node: ast.Call) -> bool:
    return any(kw.arg == "preferred_element_type" for kw in node.keywords)


def check(ctx: RuleContext) -> list:
    findings = []
    seen = set()  # (path, line): traced fns in ops/ would double-report

    def flag(module, node, fn):
        key = (module.path, getattr(node, "lineno", 0))
        if key in seen:
            return
        seen.add(key)
        findings.append(
            ctx.finding("R6", Severity.WARNING, module, node, _MSG, fn=fn)
        )

    for fn in ctx.region.traced.values():
        module = ctx.pkg.modules[fn.module]
        for node in iter_own_nodes(fn):
            if isinstance(node, ast.Call) and _is_dot_general(node) and not _has_accum_dtype(node):
                flag(module, node, fn)

    for module in ctx.pkg.modules.values():
        if "ops" not in os.path.normpath(module.path).split(os.sep):
            continue
        for scope in [None] + list(module.functions.values()):
            nodes = (
                iter_own_nodes(scope) if scope is not None
                else _module_level_nodes(module)
            )
            for node in nodes:
                if isinstance(node, ast.Call) and _is_dot_general(node) and not _has_accum_dtype(node):
                    flag(module, node, scope)
    return findings


register(
    Rule(
        id="R6",
        name="accumulator-precision",
        severity=Severity.WARNING,
        description=(
            "Explicit dot_general calls in kernel code (traced regions and "
            "ops/ modules) must pin their accumulator via "
            "preferred_element_type — bf16/fp8 operands otherwise accumulate "
            "in the operand dtype and the rounding only surfaces at scale."
        ),
        check=check,
    )
)
