"""R4 — rank-divergent collectives.

Descends from the r04 bench hang: a collective reached by only a subset of
ranks blocks forever, with zero evidence of *which* call site diverged. The
PR 4 watchdog can autopsy that hang (it names the collective each stalled
rank is blocked in); this rule refuses to ship it.

A call site is flagged when a collective — or a function that transitively
issues one (:meth:`RuleContext.collective_functions`) — is reachable only
under a rank-dependent condition:

- ``if is_main_process: gather(...)`` (directly, or via a helper);
- ``gather(x) if is_main_process else None`` / ``is_main and gather(x)``;
- an early return guarded by rank identity (``if not is_main: return``)
  followed by a collective later in the function — the subtlest shape, and
  exactly how real checkpoint/logging code deadlocks.

Symmetric branches are clean: when the ``if`` and ``else`` arms issue the
same multiset of collective ops, every rank participates (a source-rank
*argument* like ``broadcast_one_to_all(x, is_source=rank == 0)`` is the
correct spelling and never matches this rule).
"""

from __future__ import annotations

import ast
from typing import Optional

from ..callgraph import FunctionInfo, ModuleIndex, dotted
from ..findings import Severity
from . import (
    Rule,
    RuleContext,
    call_is_collective,
    register,
    test_is_rank_divergent,
)


def _collective_calls(
    ctx: RuleContext, module: ModuleIndex, scope: Optional[FunctionInfo], node: ast.AST
) -> "list[tuple[ast.Call, str]]":
    """Collective call sites lexically under ``node`` (not descending into
    nested defs — a def under a conditional runs only when *called*)."""
    out: "list[tuple[ast.Call, str]]" = []

    def _visit(n: ast.AST) -> None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(n, ast.Call):
            op = call_is_collective(n)
            if op is not None:
                out.append((n, op))
            else:
                name = dotted(n.func)
                if name is not None:
                    callee = ctx.pkg.resolve_call(name, module, scope)
                    if (
                        callee is not None
                        and callee.key in ctx.collective_functions()
                    ):
                        out.append((n, f"{name} -> collective"))
        for child in ast.iter_child_nodes(n):
            _visit(child)

    _visit(node)
    return out


def _branch_ops(calls: "list[tuple[ast.Call, str]]") -> "tuple[str, ...]":
    # ORDER-SENSITIVE: `if main: gather(); reduce() else: reduce(); gather()`
    # has equal op multisets and still deadlocks (main's gather meets the
    # other ranks' reduce) — only an identical sequence is symmetric
    return tuple(op for _, op in calls)


def _arm_op_signature(
    ctx: RuleContext,
    module: ModuleIndex,
    scope: Optional[FunctionInfo],
    stmts: "list[ast.stmt]",
) -> "tuple[str, ...]":
    """Op sequence of one arm for the symmetry comparison, with collectives
    nested under FURTHER conditions inside the arm marked ``op?`` — a
    sometimes-executed gather is not symmetric with an unconditional one
    (``if main: (if step % 100 == 0: gather()) else: gather()`` deadlocks
    on 99 of 100 steps)."""
    ops: "list[str]" = []

    def _visit(node: ast.AST, cond: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            for call, op in _collective_calls(ctx, module, scope, node):
                if call is node:
                    ops.append(f"{op}?" if cond else op)
                    break
        child_cond = cond or isinstance(
            node, (ast.If, ast.While, ast.For, ast.AsyncFor, ast.IfExp, ast.BoolOp)
        )
        for child in ast.iter_child_nodes(node):
            _visit(child, child_cond)

    for stmt in stmts:
        _visit(stmt, False)
    return tuple(ops)


def _flatten_arms(stmt: ast.If) -> "list[list[ast.stmt]]":
    """``if/elif/elif/else`` as a flat list of arm bodies. A chain with no
    final ``else`` contributes an empty arm — ranks matching no condition
    execute nothing, which is exactly what the symmetry check must see.

    Only RANK-DIVERGENT elif tests are flattened into arms: ``elif
    process_index == 1`` partitions the ranks, but an ``elif step % 100``
    (AST-identical to ``else: if step % 100:``) is ordinary control flow
    every remaining rank evaluates alike — it stays inside its arm, where
    :func:`_arm_op_signature`'s ``?`` marking compares it structurally."""
    arms: "list[list[ast.stmt]]" = [stmt.body]
    orelse = stmt.orelse
    while (
        len(orelse) == 1
        and isinstance(orelse[0], ast.If)
        and test_is_rank_divergent(orelse[0].test)
    ):
        arms.append(orelse[0].body)
        orelse = orelse[0].orelse
    arms.append(orelse)  # the final else (possibly empty)
    return arms


def _ends_in_exit(body: "list[ast.stmt]") -> bool:
    if not body:
        return False
    last = body[-1]
    return isinstance(last, (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _check_scope(
    ctx: RuleContext,
    module: ModuleIndex,
    scope: Optional[FunctionInfo],
    body: "list[ast.stmt]",
    findings: list,
    guarded_since: Optional[int] = None,
) -> None:
    """Walk one statement list; ``guarded_since`` carries the line of an
    earlier rank-guarded early-return that filters who executes the rest."""
    for stmt in body:
        # nested defs/classes are their own scopes (separate FunctionInfos);
        # a def statement under a guard executes nothing by itself
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        # collectives after a rank-filtered early return
        if guarded_since is not None:
            for call, op in _collective_calls(ctx, module, scope, stmt):
                findings.append(
                    ctx.finding(
                        "R4",
                        Severity.ERROR,
                        module,
                        call,
                        f"collective `{op}` is unreachable for ranks filtered "
                        f"by the rank-guarded early return at line "
                        f"{guarded_since} — the participating ranks deadlock "
                        "waiting for the filtered ones",
                        fn=scope,
                    )
                )
            continue  # already flagged everything below the guard

        if isinstance(stmt, ast.If) and test_is_rank_divergent(stmt.test):
            arm_calls = []
            sequences = set()
            for arm in _flatten_arms(stmt):
                calls = []
                for s in arm:
                    calls.extend(_collective_calls(ctx, module, scope, s))
                arm_calls.append(calls)
                sequences.add(_arm_op_signature(ctx, module, scope, arm))
            if len(sequences) > 1:
                for call, op in [c for calls in arm_calls for c in calls]:
                    findings.append(
                        ctx.finding(
                            "R4",
                            Severity.ERROR,
                            module,
                            call,
                            f"collective `{op}` reached only under a "
                            "rank-dependent condition — ranks that skip it "
                            "deadlock the ones that don't; hoist the "
                            "collective out of the conditional (gate the "
                            "*payload*, not the op)",
                            fn=scope,
                        )
                    )
            if _ends_in_exit(stmt.body) and not stmt.orelse:
                guarded_since = stmt.lineno
            continue

        # ternaries / short-circuits anywhere in this statement; nested
        # defs are pruned (they run only when called — their bodies are
        # walked as their own scopes), lambdas are scanned inline since a
        # rank ternary inside one is almost always invoked in place
        stack = [stmt]
        subs = []
        while stack:
            n = stack.pop()
            subs.append(n)
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                stack.append(child)
        for sub in subs:
            if isinstance(sub, ast.IfExp) and test_is_rank_divergent(sub.test):
                for arm in (sub.body, sub.orelse):
                    for call, op in _collective_calls(ctx, module, scope, arm):
                        findings.append(
                            ctx.finding(
                                "R4",
                                Severity.ERROR,
                                module,
                                call,
                                f"collective `{op}` in one arm of a "
                                "rank-dependent conditional expression — "
                                "only some ranks execute it",
                                fn=scope,
                            )
                        )
            elif isinstance(sub, ast.BoolOp):
                values = sub.values
                if any(test_is_rank_divergent(v) for v in values[:-1]):
                    for v in values[1:]:
                        for call, op in _collective_calls(ctx, module, scope, v):
                            findings.append(
                                ctx.finding(
                                    "R4",
                                    Severity.ERROR,
                                    module,
                                    call,
                                    f"collective `{op}` short-circuited "
                                    "behind a rank-dependent condition",
                                    fn=scope,
                                )
                            )

        # recurse into non-rank-divergent compound statements so nested
        # rank conditionals (e.g. inside a try or a data loop) are seen
        for attr in ("body", "orelse", "finalbody"):
            inner = getattr(stmt, attr, None)
            if isinstance(inner, list) and inner and isinstance(inner[0], ast.stmt):
                _check_scope(ctx, module, scope, inner, findings, None)
        for handler in getattr(stmt, "handlers", []) or []:
            _check_scope(ctx, module, scope, handler.body, findings, None)


def check(ctx: RuleContext) -> list:
    findings: list = []
    for module in ctx.pkg.modules.values():
        for fn in module.functions.values():
            node = fn.node
            body = getattr(node, "body", None)
            if isinstance(body, list):
                _check_scope(ctx, module, fn, body, findings)
        _check_scope(
            ctx,
            module,
            None,
            [s for s in module.tree.body],
            findings,
        )
    # module-level walk above re-descends into function bodies via compound
    # statements only when they are plain statements; defs are separate —
    # dedupe anything flagged twice by (path, line, col, message)
    unique: dict = {}
    for f in findings:
        unique.setdefault((f.path, f.line, f.col, f.message), f)
    return list(unique.values())


register(
    Rule(
        id="R4",
        name="rank-divergent-collective",
        severity=Severity.ERROR,
        description=(
            "Collectives reachable by only a subset of ranks: calls under "
            "is_main_process/process_index conditionals, behind rank-guarded "
            "early returns, or in one arm of rank ternaries — the r04 "
            "deadlock class the watchdog can only autopsy."
        ),
        check=check,
    )
)
