"""R1 — host synchronization inside traced code.

Every construct this rule flags forces the runtime to materialize a traced
value on the host: ``.item()`` / ``.tolist()``, ``float()/int()/bool()`` on a
tracer, ``np.asarray`` of a tracer, ``jax.device_get``, Python ``if`` /
``while`` / ``assert`` on a traced value, ``.block_until_ready()``. Inside a
``jax.jit`` region these either raise ``ConcretizationTypeError`` at trace
time or — worse, when the function is *sometimes* run eagerly — silently
serialize the device pipeline (the hidden-sync papercut class of the MLPerf
TPU-pod postmortem, PAPERS.md 1909.09756).

The step profiler sees these as inexplicable gaps between dispatch and
execute *after* TPU time is burned; this rule sees them in the diff.
"""

from __future__ import annotations

import ast

from ..callgraph import dotted, iter_own_nodes
from ..findings import Severity
from ..taint import Cls, Taint
from . import Rule, RuleContext, register

_CAST_SYNCS = {"float", "int", "bool", "complex"}
_METHOD_SYNCS = {"item", "tolist"}
_NP_MATERIALIZE = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


def check(ctx: RuleContext) -> list:
    findings = []
    for fn in ctx.region.traced.values():
        module = ctx.pkg.modules[fn.module]
        taint = Taint(fn, ctx.region.spec_for(fn))
        for node in iter_own_nodes(fn):
            taint.visit_statement(node)

            if isinstance(node, (ast.If, ast.While)):
                if taint.classify(node.test) == Cls.TRACED:
                    kw = "while" if isinstance(node, ast.While) else "if"
                    findings.append(
                        ctx.finding(
                            "R1",
                            Severity.ERROR,
                            module,
                            node,
                            f"python `{kw}` on a traced value — forces a "
                            "device→host sync (ConcretizationTypeError under "
                            "jit); use jnp.where / lax.cond",
                            fn=fn,
                        )
                    )
            elif isinstance(node, ast.IfExp):
                if taint.classify(node.test) == Cls.TRACED:
                    findings.append(
                        ctx.finding(
                            "R1",
                            Severity.ERROR,
                            module,
                            node,
                            "conditional expression on a traced value — use "
                            "jnp.where / lax.select",
                            fn=fn,
                        )
                    )
            elif isinstance(node, ast.Assert):
                if taint.classify(node.test) == Cls.TRACED:
                    findings.append(
                        ctx.finding(
                            "R1",
                            Severity.ERROR,
                            module,
                            node,
                            "assert on a traced value syncs the host; use "
                            "checkify or debug.check",
                            fn=fn,
                        )
                    )
            elif isinstance(node, ast.Call):
                findings.extend(_check_call(ctx, module, fn, taint, node))
    return findings


def _check_call(ctx, module, fn, taint: Taint, node: ast.Call) -> list:
    out = []
    name = dotted(node.func) or ""
    tail = name.rsplit(".", 1)[-1]

    if name in _CAST_SYNCS and node.args:
        if taint.classify(node.args[0]) == Cls.TRACED:
            out.append(
                ctx.finding(
                    "R1",
                    Severity.ERROR,
                    module,
                    node,
                    f"`{name}()` on a traced value pulls it to the host — "
                    "keep it on device (jnp.asarray / astype) or mark the "
                    "argument static",
                    fn=fn,
                )
            )
    elif tail in _METHOD_SYNCS and isinstance(node.func, ast.Attribute):
        if taint.classify(node.func.value) != Cls.STATIC:
            out.append(
                ctx.finding(
                    "R1",
                    Severity.ERROR,
                    module,
                    node,
                    f"`.{tail}()` inside traced code is a device→host sync — "
                    "return the array and materialize outside the jit "
                    "boundary",
                    fn=fn,
                )
            )
    elif name in _NP_MATERIALIZE and node.args:
        if taint.classify(node.args[0]) == Cls.TRACED:
            out.append(
                ctx.finding(
                    "R1",
                    Severity.ERROR,
                    module,
                    node,
                    f"`{name}()` of a traced value materializes it on the "
                    "host — use jnp equivalents inside traced code",
                    fn=fn,
                )
            )
    elif name in {"jax.device_get", "device_get"}:
        out.append(
            ctx.finding(
                "R1",
                Severity.ERROR,
                module,
                node,
                "`jax.device_get` inside traced code is a host sync — move "
                "it outside the jit boundary",
                fn=fn,
            )
        )
    elif tail == "block_until_ready":
        out.append(
            ctx.finding(
                "R1",
                Severity.ERROR,
                module,
                node,
                "`.block_until_ready()` inside traced code stalls dispatch — "
                "it belongs in benchmarks/tests outside the jit boundary",
                fn=fn,
            )
        )
    return out


register(
    Rule(
        id="R1",
        name="host-sync-in-traced-code",
        severity=Severity.ERROR,
        description=(
            "Device→host synchronization inside a jit/pjit/shard_map region: "
            ".item()/.tolist(), float()/int()/bool() on tracers, np.asarray, "
            "jax.device_get, python control flow on traced values."
        ),
        check=check,
    )
)
