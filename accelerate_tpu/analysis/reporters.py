"""Human and JSON renderings of a lint run.

The human reporter is one line per finding (``path:line:col: Rn severity:
message [symbol]``) sorted by location, then a summary line — the format
editors and CI log scrapers already parse for flake8-family tools. The JSON
reporter is the machine surface ``tests/test_analysis.py`` pins a schema
for; its top-level shape is versioned independently of the rule set.
"""

from __future__ import annotations

import json

from .findings import Finding, summarize

JSON_SCHEMA_VERSION = 1


def render_human(findings: "list[Finding]", stats: dict, verbose: bool = False) -> str:
    lines = []
    for f in sorted(findings, key=Finding.sort_key):
        if f.suppressed and not verbose:
            continue
        if f.baselined and not verbose:
            continue
        tag = ""
        if f.suppressed:
            tag = " (suppressed)"
        elif f.baselined:
            tag = " (baselined)"
        sym = f" [{f.symbol}]" if f.symbol else ""
        lines.append(
            f"{f.location()}: {f.rule} {f.severity}: {f.message}{sym}{tag}"
        )
    s = summarize(findings)
    lines.append(
        f"jaxlint: {s['new']} new finding(s) "
        f"({s['errors']} error(s), {s['warnings']} warning(s)), "
        f"{s['baselined']} baselined, {s['suppressed']} suppressed — "
        f"{stats.get('files', 0)} file(s), "
        f"{stats.get('traced_functions', 0)} traced function(s), "
        f"{stats.get('jit_roots', 0)} jit root(s)"
    )
    if s["new"] and s["by_rule"]:
        per = ", ".join(f"{r}: {n}" for r, n in s["by_rule"].items())
        lines.append(f"  by rule: {per}")
    for path, err in stats.get("parse_errors", []):
        lines.append(f"  parse error: {path}: {err}")
    return "\n".join(lines)


def render_json(findings: "list[Finding]", stats: dict) -> str:
    payload = {
        "schema": JSON_SCHEMA_VERSION,
        "summary": summarize(findings),
        "stats": {
            "files": stats.get("files", 0),
            "traced_functions": stats.get("traced_functions", 0),
            "jit_roots": stats.get("jit_roots", 0),
            "parse_errors": [
                {"path": p, "error": e} for p, e in stats.get("parse_errors", [])
            ],
        },
        "findings": [f.to_dict() for f in sorted(findings, key=Finding.sort_key)],
    }
    return json.dumps(payload, indent=2, sort_keys=True)
