"""Finding/severity model for jaxlint.

A :class:`Finding` is one diagnostic at one source location. Findings are
value objects: the engine produces them, suppressions and the baseline
annotate them (``suppressed`` / ``baselined``), and the reporters render
them — nothing downstream mutates the location or message.

The *fingerprint* (rule, relative path, enclosing symbol, stripped source
line) deliberately excludes the line number so a baseline entry survives
unrelated edits above the finding; see :mod:`.baseline`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class Severity(enum.IntEnum):
    """Ordered so reporters can sort worst-first."""

    NOTE = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error" / "warning" in human output
        return self.name.lower()


@dataclass
class Finding:
    """One diagnostic: ``path:line:col: <rule> <severity>: <message>``."""

    rule: str  # "R1".."R5"
    severity: Severity
    path: str  # as scanned (engine relativizes against the lint root)
    line: int
    col: int
    message: str
    symbol: str = ""  # enclosing function qualname ("" at module level)
    line_content: str = ""  # stripped source line, for baseline matching
    suppressed: bool = False  # an inline ``# jaxlint: disable=Rn`` covers it
    baselined: bool = False  # a checked-in baseline entry covers it
    extra: dict = field(default_factory=dict)

    @property
    def fingerprint(self) -> tuple:
        """Line-number-free identity used for baseline matching."""
        return (self.rule, self.path, self.symbol, self.line_content)

    @property
    def is_new(self) -> bool:
        """True when neither a suppression nor the baseline covers it —
        exactly the findings that fail the lint run."""
        return not (self.suppressed or self.baselined)

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> dict:
        out = {
            "rule": self.rule,
            "severity": str(self.severity),
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "symbol": self.symbol,
            "line_content": self.line_content,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }
        if self.extra:
            out["extra"] = self.extra
        return out

    def sort_key(self) -> tuple:
        return (self.path, self.line, self.col, self.rule)


def summarize(findings: "list[Finding]") -> dict:
    """Counts the reporters and the CLI exit code are built from."""
    new = [f for f in findings if f.is_new]
    return {
        "total": len(findings),
        "new": len(new),
        "errors": sum(1 for f in new if f.severity == Severity.ERROR),
        "warnings": sum(1 for f in new if f.severity == Severity.WARNING),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "baselined": sum(1 for f in findings if f.baselined),
        "by_rule": {
            rule: sum(1 for f in new if f.rule == rule)
            for rule in sorted({f.rule for f in new})
        },
    }
