"""Checked-in baseline: pre-existing findings ratchet down, never block.

The shipped ``jaxlint-baseline.json`` lists every finding that existed when
the linter landed and was judged not-worth-fixing-yet. A finding matching a
baseline entry is reported but doesn't fail the run; a finding NOT in the
baseline fails it. Entries are matched by line-number-free fingerprint
(rule, path, enclosing symbol, stripped source line) so edits elsewhere in
a file don't invalidate them — and matching *consumes* entries, so two new
copies of one baselined bug still fail.

``tests/test_repo_hygiene.py`` guards that the file only ever shrinks:
fixing debt removes entries; adding debt means adding an entry, which the
guard rejects. ``--write-baseline`` regenerates the file from the current
findings (sorted, stable) for the shrinking case.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional

from .findings import Finding

BASELINE_FILENAME = "jaxlint-baseline.json"
BASELINE_VERSION = 1


def load_baseline(path: str) -> "list[dict]":
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path}: not a jaxlint baseline (missing 'findings')")
    return list(data["findings"])


def apply_baseline(findings: Iterable[Finding], entries: "list[dict]") -> None:
    """Mark findings covered by baseline entries (in place). Each entry
    covers at most one finding."""
    pool: "dict[tuple, int]" = {}
    for e in entries:
        key = (
            e.get("rule", ""),
            e.get("path", ""),
            e.get("symbol", ""),
            e.get("line_content", ""),
        )
        pool[key] = pool.get(key, 0) + 1
    for f in findings:
        if f.suppressed:
            continue
        left = pool.get(f.fingerprint, 0)
        if left > 0:
            pool[f.fingerprint] = left - 1
            f.baselined = True


def write_baseline(findings: Iterable[Finding], path: str) -> int:
    """Serialize the *unsuppressed* findings as the new baseline."""
    # per-fingerprint multiplicity: duplicate findings on distinct lines
    # with identical text need one entry each to all be covered
    counts: "dict[tuple, int]" = {}
    for f in findings:
        if not f.suppressed:
            counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    out = []
    for fp in sorted(counts):
        rule, fpath, symbol, line_content = fp
        for _ in range(counts[fp]):
            out.append(
                {
                    "rule": rule,
                    "path": fpath,
                    "symbol": symbol,
                    "line_content": line_content,
                }
            )
    payload = {"version": BASELINE_VERSION, "findings": out}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return len(out)


def discover_baseline(paths: "list[str]") -> Optional[str]:
    """Walk up from the first linted path looking for the baseline file —
    so ``python -m accelerate_tpu.analysis lint accelerate_tpu/`` run from
    the repo root finds ``./jaxlint-baseline.json`` without a flag."""
    start = os.path.abspath(paths[0]) if paths else os.getcwd()
    if os.path.isfile(start):
        start = os.path.dirname(start)
    current = start
    for _ in range(12):
        candidate = os.path.join(current, BASELINE_FILENAME)
        if os.path.exists(candidate):
            return candidate
        parent = os.path.dirname(current)
        if parent == current:
            break
        current = parent
    return None
