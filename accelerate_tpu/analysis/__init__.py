"""jaxlint — static analysis for jit-traced JAX code.

An AST-based rule engine that discovers the jit/pjit/shard_map-decorated
functions in a package and the call graph reachable from them, then runs
JAX-aware rules over that **traced region**: host syncs (R1), recompile
hazards (R2), buffer-donation bugs (R3), rank-divergent collectives (R4),
and trace-time nondeterminism (R5). Every rule descends from a bug this
repo shipped or autopsied at runtime; the linter turns those runtime
detectors (telemetry PR 2, forensics PR 4) into preventions.

Entry points::

    python -m accelerate_tpu.analysis lint accelerate_tpu/   # the CLI
    make lint                                                # same, CI-wired

    from accelerate_tpu.analysis import run_lint
    result = run_lint(["accelerate_tpu/"])
    result.ok, result.new_findings

Pure stdlib ``ast`` — linting never imports the analyzed code and never
touches a jax backend. See ``docs/static_analysis.md`` for the rule
catalog, and ``jaxlint-baseline.json`` for the ratcheting baseline.
"""

from .baseline import (
    BASELINE_FILENAME,
    apply_baseline,
    discover_baseline,
    load_baseline,
    write_baseline,
)
from .callgraph import (
    FunctionInfo,
    JitSpec,
    ModuleIndex,
    PackageIndex,
    TracedRegion,
    build_package_index,
    discover_traced,
)
from .engine import LintResult, run_lint
from .findings import Finding, Severity, summarize
from .reporters import JSON_SCHEMA_VERSION, render_human, render_json
from .rules import RULES, Rule, RuleContext, load_all_rules

__all__ = [
    "BASELINE_FILENAME",
    "Finding",
    "FunctionInfo",
    "JitSpec",
    "JSON_SCHEMA_VERSION",
    "LintResult",
    "ModuleIndex",
    "PackageIndex",
    "RULES",
    "Rule",
    "RuleContext",
    "Severity",
    "TracedRegion",
    "apply_baseline",
    "build_package_index",
    "discover_baseline",
    "discover_traced",
    "load_all_rules",
    "load_baseline",
    "render_human",
    "render_json",
    "run_lint",
    "summarize",
    "write_baseline",
]
