"""``python -m accelerate_tpu.analysis`` — the jaxlint CLI.

Subcommands:

- ``lint PATH... [--json] [--rules R1,R4] [--baseline FILE] [--no-baseline]
  [--write-baseline] [--verbose]`` — lint files/dirs; exit 0 iff no *new*
  (unsuppressed, unbaselined) findings and no parse errors.
- ``rules`` — print the rule catalog.

``make lint`` wires ``lint accelerate_tpu/`` into CI; the baseline at the
repo root is discovered automatically.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

from . import baseline as baseline_mod
from .engine import run_lint
from .reporters import render_human, render_json
from .rules import load_all_rules


def main(argv: Optional["list[str]"] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m accelerate_tpu.analysis",
        description="jaxlint: static analysis for jit-traced JAX code "
        "(host syncs, recompile hazards, donation bugs, rank-divergent "
        "collectives, trace-time nondeterminism).",
    )
    sub = parser.add_subparsers(dest="command")

    lint = sub.add_parser("lint", help="lint files or directories")
    lint.add_argument("paths", nargs="+", help="python files or package dirs")
    lint.add_argument("--json", action="store_true", help="machine-readable output")
    lint.add_argument(
        "--rules",
        help="comma-separated subset (e.g. R1,R4); default: all rules",
    )
    lint.add_argument(
        "--baseline",
        help=f"baseline file (default: nearest {baseline_mod.BASELINE_FILENAME} "
        "above the first path)",
    )
    lint.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore any baseline: report every finding as new",
    )
    lint.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    lint.add_argument(
        "--verbose",
        action="store_true",
        help="also print suppressed/baselined findings",
    )

    sub.add_parser("rules", help="print the rule catalog")

    args = parser.parse_args(argv)
    if args.command == "rules":
        for rule in load_all_rules().values():
            print(f"{rule.id}  {rule.name}  [{rule.severity}]")
            print(f"    {rule.description}")
        return 0
    if args.command != "lint":
        parser.print_help()
        return 2

    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
    try:
        result = run_lint(
            args.paths,
            rules=rules,
            baseline_path=args.baseline,
            use_baseline=not args.no_baseline,
        )
    except ValueError as exc:  # e.g. a typo in --rules must not pass vacuously
        print(f"jaxlint: error: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = (
            args.baseline
            or result.baseline_path
            or baseline_mod.BASELINE_FILENAME
        )
        n = baseline_mod.write_baseline(result.findings, path)
        print(f"jaxlint: wrote {n} baseline entr{'y' if n == 1 else 'ies'} to {path}")
        return 0

    if args.json:
        print(render_json(result.findings, result.stats))
    else:
        print(render_human(result.findings, result.stats, verbose=args.verbose))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
