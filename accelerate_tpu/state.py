"""Process/device runtime state singletons.

TPU-native counterpart of the reference's ``state.py``:

- :class:`PartialState` — reference ``state.py:122``: process bootstrap (here
  ``jax.distributed.initialize`` instead of ``torch.distributed.init_process_group``
  ``state.py:243``), rank/world/device info, process-control helpers
  (``wait_for_everyone :376``, ``split_between_processes :424``,
  ``main_process_first :515``, decorators ``:556-712``).
- :class:`AcceleratorState` — reference ``state.py:863``: adds mixed precision and
  parallelism routing; here it owns the device :class:`jax.sharding.Mesh`.
- :class:`GradientState` — reference ``state.py:1225``: gradient-accumulation
  bookkeeping shared between Accelerator, dataloaders, optimizer and scheduler.

All three use the shared-``__dict__`` singleton trick (reference ``state.py:90-119``)
so every instance in the process observes the same state.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from functools import wraps
from typing import Any, Callable, Optional

from .parallelism_config import ParallelismConfig
from .utils.dataclasses import (
    DistributedType,
    GradientAccumulationPlugin,
    MixedPrecisionPolicy,
    PrecisionType,
)
from .utils.environment import parse_flag_from_env


def _jax():
    import jax

    return jax


def do_nothing(*args, **kwargs):
    """reference ``state.py:86`` — the default no-op callback."""
    return None


def is_initialized() -> bool:
    return PartialState._shared_state.get("_initialized", False)


def _forensic_env_int(key: str, default: int) -> int:
    # a malformed launcher env (set-but-blank template var) must not crash the
    # crash handler itself — identity degrades to the default, never raises
    try:
        return int(os.environ.get(key, "") or default)
    except (TypeError, ValueError):
        return default


def process_identity() -> "dict[str, Any]":
    """Rank/host identity for forensic artifacts (flight records, watchdog
    dumps). Safe to call from signal handlers and background threads: when
    :class:`PartialState` is not yet initialized it answers from the launcher
    env protocol instead of booting ``jax.distributed`` (which could itself
    hang — the exact failure being diagnosed)."""
    import socket

    ident: dict[str, Any] = {"pid": os.getpid()}
    try:
        ident["hostname"] = socket.gethostname()
    except OSError:
        ident["hostname"] = "?"
    if is_initialized():
        state = PartialState()
        ident.update(
            process_index=state.process_index,
            num_processes=state.num_processes,
            local_process_index=state.local_process_index,
            backend=state.backend,
            run_id=state.run_id,
        )
        return ident
    ident.update(
        process_index=_forensic_env_int("ACCELERATE_PROCESS_ID", 0),
        num_processes=_forensic_env_int("ACCELERATE_NUM_PROCESSES", 1),
        local_process_index=_forensic_env_int("ACCELERATE_LOCAL_PROCESS_INDEX", 0),
        run_id=os.environ.get("ACCELERATE_RUN_ID"),
    )
    return ident


class PartialState:
    """Singleton holding process topology: how many processes, which one am I,
    which devices are mine. First construction performs multi-host initialization
    when the launcher's env protocol requests it."""

    _shared_state: dict[str, Any] = {}

    def __init__(self, cpu: bool = False, **kwargs: Any):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        jax = _jax()

        if cpu or parse_flag_from_env("ACCELERATE_USE_CPU"):
            jax.config.update("jax_platforms", "cpu")

        # Multi-host bootstrap — the launcher writes ACCELERATE_COORDINATOR_ADDRESS /
        # ACCELERATE_NUM_PROCESSES / ACCELERATE_PROCESS_ID (moral twin of
        # MASTER_ADDR/RANK/WORLD_SIZE, reference utils/launch.py:98-196).
        coordinator = kwargs.pop("coordinator_address", None) or os.environ.get(
            "ACCELERATE_COORDINATOR_ADDRESS"
        )
        from .utils.jax_compat import distributed_is_initialized

        if coordinator and not distributed_is_initialized():
            if "cpu" in str(getattr(jax.config, "jax_platforms", "") or ""):
                # CPU-backend multi-process (tests, dev boxes): collectives
                # need an explicit implementation or the backend refuses them
                from .utils.jax_compat import enable_cpu_multiprocess_collectives

                enable_cpu_multiprocess_collectives()
            init_kwargs = {}
            if kwargs.get("local_device_ids") is not None:
                init_kwargs["local_device_ids"] = kwargs.pop("local_device_ids")
            if kwargs.get("initialization_timeout") is not None:
                timeout = kwargs.pop("initialization_timeout")
                init_kwargs["initialization_timeout"] = (
                    int(timeout.total_seconds()) if hasattr(timeout, "total_seconds") else int(timeout)
                )
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=int(
                    kwargs.pop("num_processes", os.environ.get("ACCELERATE_NUM_PROCESSES", 1))
                ),
                process_id=int(
                    kwargs.pop("process_id", os.environ.get("ACCELERATE_PROCESS_ID", 0))
                ),
                **init_kwargs,
            )

        self.num_processes = jax.process_count()
        self.process_index = jax.process_index()
        # One process per host on TPU-VM → every process is its host's local main.
        # (A LOCAL_RANK-style env override is honored for exotic multi-proc-per-host.)
        self.local_process_index = int(os.environ.get("ACCELERATE_LOCAL_PROCESS_INDEX", 0))
        self.devices = jax.devices()
        self.local_devices = jax.local_devices()
        self.num_devices = len(self.devices)
        self.num_local_devices = len(self.local_devices)
        self.device = self.local_devices[0]
        self.backend = jax.default_backend()
        if self.num_processes > 1:
            self.distributed_type = DistributedType.MULTI_HOST
        elif self.num_devices > 1:
            self.distributed_type = DistributedType.SPMD
        else:
            self.distributed_type = DistributedType.NO
        self.debug = parse_flag_from_env("ACCELERATE_DEBUG_MODE")
        # Run identity (telemetry/tracking correlation): the launcher should
        # set ACCELERATE_RUN_ID so all processes of one run agree; without it
        # a process-local id is generated — exact for single-process runs,
        # per-process otherwise.
        self.run_id = os.environ.get("ACCELERATE_RUN_ID") or f"run-{int(time.time())}-{os.getpid()}"
        self.initialized = True

    # ------------------------------------------------------------------ info --
    def __repr__(self) -> str:
        return (
            f"PartialState(backend={self.backend!r}, distributed_type={self.distributed_type}, "
            f"num_processes={self.num_processes}, process_index={self.process_index}, "
            f"num_devices={self.num_devices})"
        )

    @property
    def initialized(self) -> bool:
        return self._shared_state.get("_initialized", False)

    @initialized.setter
    def initialized(self, value: bool) -> None:
        self._shared_state["_initialized"] = value

    @property
    def use_distributed(self) -> bool:
        return self.num_devices > 1 or self.num_processes > 1

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    # -------------------------------------------------------------- control --
    def wait_for_everyone(self, tag: str = "accelerate_tpu.wait_for_everyone") -> None:
        """Cross-host barrier (reference ``state.py:376``). Under a single process
        this is a no-op; across hosts it syncs via a tiny global collective."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            from .telemetry import flight_recorder as _flight

            _flight.record_collective("barrier", tag)
            multihost_utils.sync_global_devices(tag)

    @contextmanager
    def main_process_first(self):
        """Main process runs the body first, others wait (reference ``state.py:515``)."""
        # sequenced-barrier idiom: every rank enters the "enter" barrier
        # exactly once (non-main before the body, main after), so the
        # schedules match even though each call site is rank-conditional
        if not self.is_main_process:
            self.wait_for_everyone("main_process_first.enter")  # jaxlint: disable=R4
        try:
            yield
        finally:
            if self.is_main_process:
                self.wait_for_everyone("main_process_first.enter")  # jaxlint: disable=R4
            self.wait_for_everyone("main_process_first.exit")

    @contextmanager
    def local_main_process_first(self):
        with self.main_process_first():
            yield

    def on_main_process(self, function: Callable) -> Callable:
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)
            return None

        return wrapper

    def on_local_main_process(self, function: Callable) -> Callable:
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)
            return None

        return wrapper

    def on_last_process(self, function: Callable) -> Callable:
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_last_process:
                return function(*args, **kwargs)
            return None

        return wrapper

    def on_process(self, function: Callable = None, process_index: int = None) -> Callable:
        if function is None:
            return lambda f: self.on_process(f, process_index)

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)
            return None

        return wrapper

    @contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Split a list/tuple/dict/array evenly between processes (reference
        ``state.py:424``). With ``apply_padding`` the last element is repeated so
        every process gets the same count (needed for static shapes)."""
        if self.num_processes == 1:
            yield inputs
            return
        length = len(inputs)
        num = self.num_processes
        base, extra = divmod(length, num)
        if isinstance(inputs, dict):
            results = {}
            for key, value in inputs.items():
                with self.split_between_processes(value, apply_padding) as v:
                    results[key] = v
            yield results
            return
        start = self.process_index * base + min(self.process_index, extra)
        end = start + base + (1 if self.process_index < extra else 0)
        chunk = inputs[start:end]
        if apply_padding and extra != 0:
            target = base + 1
            while len(chunk) < target:
                chunk = list(chunk) + [chunk[-1] if len(chunk) else inputs[-1]]
        yield chunk

    def destroy_process_group(self) -> None:
        from .utils.jax_compat import distributed_is_initialized

        jax = _jax()
        if distributed_is_initialized():
            jax.distributed.shutdown()

    @classmethod
    def _reset_state(cls) -> None:
        """Testing hook (reference ``state.py`` ``_reset_state``)."""
        cls._shared_state.clear()

    def print(self, *args, **kwargs) -> None:
        if self.is_main_process:
            print(*args, **kwargs)


class AcceleratorState:
    """Adds precision + parallelism layout (the mesh) on top of PartialState
    (reference ``state.py:863``)."""

    _shared_state: dict[str, Any] = {}

    def __init__(
        self,
        mixed_precision: Optional[str] = None,
        cpu: bool = False,
        parallelism_config: Optional[ParallelismConfig] = None,
        **kwargs: Any,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            if parallelism_config is not None and parallelism_config != self.parallelism_config:
                raise ValueError(
                    "AcceleratorState already initialized with a different ParallelismConfig; "
                    "call AcceleratorState._reset_state() first (tests) or construct once."
                )
            if (
                mixed_precision is not None
                and PrecisionType(str(mixed_precision)) != self.mixed_precision
            ):
                raise ValueError(
                    f"AcceleratorState already initialized with mixed_precision="
                    f"{self.mixed_precision}; got conflicting {mixed_precision!r}."
                )
            return
        self._partial = PartialState(cpu=cpu, **kwargs)
        if mixed_precision is None:
            mixed_precision = os.environ.get("ACCELERATE_MIXED_PRECISION", "no")
        self.mixed_precision = PrecisionType(str(mixed_precision))
        self.mixed_precision_policy = MixedPrecisionPolicy.from_precision(self.mixed_precision)
        if parallelism_config is None:
            if any(k.startswith("PARALLELISM_CONFIG_") for k in os.environ):
                parallelism_config = ParallelismConfig.from_env()
            else:
                # default: pure DP over all devices
                parallelism_config = ParallelismConfig(dp_replicate_size=self._partial.num_devices)
        self.parallelism_config = parallelism_config
        self.mesh = parallelism_config.build_mesh(self._partial.devices)
        self.initialized = True

    @property
    def initialized(self) -> bool:
        return self._shared_state.get("_initialized", False)

    @initialized.setter
    def initialized(self, value: bool) -> None:
        self._shared_state["_initialized"] = value

    def __getattr__(self, name: str):
        # delegate topology attrs to PartialState
        partial = self.__dict__.get("_partial")
        if partial is not None and hasattr(partial, name):
            return getattr(partial, name)
        raise AttributeError(f"AcceleratorState has no attribute {name!r}")

    def __repr__(self) -> str:
        return (
            f"AcceleratorState(mixed_precision={self.mixed_precision}, "
            f"mesh={self.parallelism_config.describe(self._partial.num_devices)}, "
            f"{self._partial!r})"
        )

    @classmethod
    def _reset_state(cls, reset_partial_state: bool = False) -> None:
        cls._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()


class GradientState:
    """Gradient-accumulation bookkeeping singleton (reference ``state.py:1225``).

    ``sync_gradients`` flags whether the current micro-step is an optimizer-update
    boundary; dataloaders flip ``end_of_dataloader``/``remainder`` so the final
    partial accumulation window still updates (reference ``_set_sync_gradients
    :1318``, ``_add_dataloader :1329``). The XLA ``mark_step`` graph-cut the
    reference performs has no equivalent here: the whole step is one jitted fn.
    """

    _shared_state: dict[str, Any] = {}

    def __init__(self, gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references = []
            self.plugin = gradient_accumulation_plugin or GradientAccumulationPlugin()
            self.num_steps_count = 0
            self.initialized = True
        elif gradient_accumulation_plugin is not None:
            self.plugin = gradient_accumulation_plugin

    @property
    def initialized(self) -> bool:
        return self._shared_state.get("_initialized", False)

    @initialized.setter
    def initialized(self, value: bool) -> None:
        self._shared_state["_initialized"] = value

    @property
    def num_steps(self) -> int:
        return self.plugin.num_steps

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin.adjust_scheduler

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin.sync_with_dataloader

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _set_sync_gradients(self, sync: bool) -> None:
        self.sync_gradients = sync

    def _add_dataloader(self, dataloader) -> None:
        self.active_dataloader = dataloader
        self.dataloader_references.append(dataloader)

    def _remove_dataloader(self, dataloader) -> None:
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1] if self.dataloader_references else None

    def __repr__(self) -> str:
        return (
            f"GradientState(sync_gradients={self.sync_gradients}, num_steps={self.num_steps}, "
            f"end_of_dataloader={self.end_of_dataloader}, remainder={self.remainder})"
        )

    @classmethod
    def _reset_state(cls) -> None:
        cls._shared_state.clear()
