"""Continuous-batching scheduler: admission, slot placement, preemption.

Decisions live here, device work lives in ``engine.py``. The policy is the
in-flight batching loop (Orca/vLLM style):

- **admission** happens at STEP granularity: whenever a batch slot is free
  and the block pool can hold the prompt (plus the configured watermark),
  the next queued request is prefilled and joins the running decode batch —
  no waiting for the current batch to drain;
- **completion/eviction** frees a sequence's blocks immediately and the slot
  is backfilled on the next step;
- **preemption** is the pool's pressure valve: when a running sequence needs
  a block and none is free, the most-recently-admitted OTHER sequence is
  evicted (LIFO — oldest requests keep their progress), its blocks freed and
  the request requeued AT THE FRONT with its prompt + generated tokens
  persisted, so resume re-prefills the full prefix and continues with
  identical output (the preemption parity test proves it).

``continuous=False`` turns the same machinery into the static-batching
baseline for the serving benchmark: admission only happens when the engine
is completely idle (gang admission), and finished sequences' slots are NOT
backfilled until the whole batch drains — the classic waste continuous
batching exists to eliminate.
"""

from __future__ import annotations

import enum
import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..telemetry import metrics as _metrics
from .kv_pager import BlockAllocator, BlockPoolExhausted

__all__ = ["RequestStatus", "Request", "Scheduler", "SchedulingError"]

_rid_counter = itertools.count()


class SchedulingError(RuntimeError):
    """A request that can never be scheduled (e.g. larger than the pool)."""


class RequestStatus(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    PREEMPTED = "preempted"
    FINISHED = "finished"
    REJECTED = "rejected"  # can never run on this engine; see Request.error


@dataclass(eq=False)  # identity equality: requests are stateful handles
class Request:
    """One generation request plus its full persisted progress.

    ``prompt`` + ``generated`` are the request's durable state: eviction
    drops ONLY device blocks, so a preempted request resumes by
    re-prefilling ``prompt + generated`` and keeps decoding — no tokens are
    lost and the continuation is identical to an uninterrupted run.
    """

    prompt: np.ndarray  # int32 [S]
    max_new_tokens: int
    rid: int = field(default_factory=lambda: next(_rid_counter))
    eos_token_id: Optional[int] = None
    rng_seed: int = 0
    arrival_t: float = 0.0

    # runtime state
    status: RequestStatus = RequestStatus.QUEUED
    generated: "list[int]" = field(default_factory=list)
    slot: Optional[int] = None
    preemptions: int = 0
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    error: Optional[str] = None  # set when REJECTED
    # prefix-cache admission state, overwritten on EVERY admission (a resume
    # re-plans against the index as it stands then): how many leading prefix
    # tokens are already cached (the engine prefills only past them), and the
    # pending copy-on-write pair the engine must apply before any write
    cached_tokens: int = 0
    cow_block: "Optional[tuple[int, int]]" = None
    # distributed-tracing state (telemetry/tracing.py): the propagated
    # context (None while tracing is disarmed — every check stays one
    # branch) and this request's accumulated span dicts. The engine fills
    # them; router-owned requests ship the spans back over the replica
    # event stream instead of emitting locally.
    trace: Optional[dict] = None
    trace_spans: "list[dict]" = field(default_factory=list)
    # engine-side PRNGKey cache (pure function of rng_seed)
    _key: Optional[np.ndarray] = field(default=None, repr=False, init=False)
    # open trace spans (closed as the request moves through the engine)
    _span_root: Optional[dict] = field(default=None, repr=False, init=False)
    _span_queue: Optional[dict] = field(default=None, repr=False, init=False)
    _trace_owner: bool = field(default=False, repr=False, init=False)

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {self.max_new_tokens}")

    @property
    def prefix_len(self) -> int:
        """Tokens the model has consumed so far: prompt + generated."""
        return int(self.prompt.size) + len(self.generated)

    @property
    def done(self) -> bool:
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (
            self.eos_token_id is not None
            and bool(self.generated)
            and self.generated[-1] == self.eos_token_id
        )

    def output_ids(self) -> np.ndarray:
        """prompt + generated, the same layout ``greedy_generate`` returns."""
        return np.concatenate([self.prompt, np.asarray(self.generated, np.int32)])


class Scheduler:
    """Admission queue + batch-slot table over one :class:`BlockAllocator`."""

    def __init__(
        self,
        allocator: BlockAllocator,
        max_slots: int,
        *,
        continuous: bool = True,
        admit_watermark_blocks: int = 0,
        max_seq_blocks: Optional[int] = None,
        max_seq_tokens: Optional[int] = None,
        admission_gate=None,
    ):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.allocator = allocator
        # optional predicate over the queue head: False holds the request
        # (and everything behind it — admission stays FIFO) without popping
        # it. The disaggregated DecodeEngine gates on "its handed-off KV
        # blocks have landed"; None keeps the legacy path branch-free.
        self.admission_gate = admission_gate
        self.max_slots = max_slots
        self.continuous = continuous
        # hard per-sequence caps, both enforced at ADMISSION on the worst
        # case (prefix + max_new) so nothing crashes or corrupts mid-decode:
        # - blocks: the engine passes its bucket lattice's widest table;
        # - tokens: the engine passes config.max_seq_len — positions past the
        #   RoPE table would be silently CLAMPED by the cos/sin gathers,
        #   corrupting output with no error.
        self.max_seq_blocks = (
            allocator.usable_blocks if max_seq_blocks is None
            else min(max_seq_blocks, allocator.usable_blocks)
        )
        self.max_seq_tokens = max_seq_tokens
        # admission keeps this many blocks free as decode headroom, so a
        # fresh admission doesn't immediately force a preemption
        self.admit_watermark_blocks = admit_watermark_blocks
        self.queue: "deque[Request]" = deque()
        self.slots: "list[Optional[Request]]" = [None] * max_slots
        self._admission_order: "list[Request]" = []  # oldest first
        self.preemption_count = 0
        #: requests that can NEVER run on this pool (prefix larger than the
        #: whole pool) — rejected at admission instead of wedging the queue
        self.rejected: "list[Request]" = []

    # -- views ---------------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    def running(self) -> "list[Request]":
        return [r for r in self.slots if r is not None]

    def idle(self) -> bool:
        return not self.queue and not self.running()

    # -- admission -----------------------------------------------------------

    def submit(self, request: Request) -> Request:
        request.status = RequestStatus.QUEUED
        self.queue.append(request)
        return request

    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def admissions(self) -> "list[Request]":
        """Pop and place every request admissible RIGHT NOW (the engine
        prefills each). Continuous mode admits whenever a slot + blocks are
        available; static mode only gang-admits into an idle engine."""
        if not self.continuous and self.running():
            return []
        admitted = []
        while self.queue:
            slot = self._free_slot()
            if slot is None:
                break
            req = self.queue[0]
            if self.admission_gate is not None and not self.admission_gate(req):
                break  # gated (e.g. KV handoff not landed): FIFO order holds
            prefix_tokens = req.output_ids()
            # admission charges only UNCACHED blocks: the plan maps the
            # longest cached block-aligned prefix for free, and the watermark
            # compares the fresh-tail cost against free + reclaimable blocks
            # (with caching off the plan degenerates to blocks_for(prefix))
            plan = self.allocator.plan_prefix(prefix_tokens)
            # fresh blocks the tail takes, plus LRU-parked matched blocks this
            # mapping will pin (they count as available today but can't also
            # serve as fresh blocks — without the charge the allocation below
            # could throw on a plan admission just green-lit)
            need = plan.fresh_blocks + plan.lru_pinned
            # worst case the sequence can reach: its current prefix plus every
            # remaining token it may generate
            remaining = max(0, req.max_new_tokens - len(req.generated))
            worst_tokens = req.prefix_len + remaining
            # the block-WIDTH cap charges the full table (shared blocks widen
            # the gather exactly like private ones); only the pool check is
            # prefix-aware
            worst = self.allocator.blocks_for(worst_tokens)
            reason = None
            if self.max_seq_tokens is not None and worst_tokens > self.max_seq_tokens:
                reason = (
                    f"worst case {worst_tokens} tokens (prefix {req.prefix_len} "
                    f"+ up to {remaining} new) exceeds the model's "
                    f"max_seq_len of {self.max_seq_tokens}"
                )
            elif worst > self.max_seq_blocks:
                reason = (
                    f"worst case {worst} block(s) (prefix {req.prefix_len} + "
                    f"up to {remaining} new tokens) exceeds the per-sequence "
                    f"cap of {self.max_seq_blocks}"
                )
            if reason is not None:
                # impossible on this engine no matter what drains: reject it
                # rather than wedging the queue behind it forever, crashing
                # mid-decode, or silently clamping RoPE positions
                self.queue.popleft()
                req.status = RequestStatus.REJECTED
                req.error = "rejected: " + reason
                self.rejected.append(req)
                continue
            if need + self.admit_watermark_blocks > self.allocator.available_blocks:
                break  # pool pressure: let running sequences drain first
            self.queue.popleft()
            alloc = self.allocator.allocate_with_prefix(
                req.rid, prefix_tokens, plan=plan
            )
            req.cached_tokens = alloc.cached_tokens
            req.cow_block = alloc.cow
            req.status = RequestStatus.RUNNING
            req.slot = slot
            self.slots[slot] = req
            self._admission_order.append(req)
            admitted.append(req)
        return admitted

    # -- progress ------------------------------------------------------------

    def grow(self, request: Request, n_tokens: int = 1) -> None:
        """Reserve pool room for the request's next ``n_tokens`` tokens
        (speculative decoding grows by up to k+1 per step), preempting other
        sequences (LIFO) if the pool is dry. Raises :class:`SchedulingError`
        only when the request cannot fit even with every other sequence
        evicted."""
        if n_tokens <= 0:
            return
        while True:
            try:
                self.allocator.append(request.rid, n_tokens)
                return
            except BlockPoolExhausted:
                if not self._preempt_one(exclude=request):
                    raise SchedulingError(
                        f"request {request.rid} exhausted the pool with no "
                        "other sequence left to evict — the pool is smaller "
                        "than one request's worst case"
                    ) from None

    def _preempt_one(self, exclude: Request) -> bool:
        """Evict the most-recently-admitted running request (except
        ``exclude``): free its blocks, requeue it at the FRONT with its
        progress persisted. False when there is no candidate."""
        for req in reversed(self._admission_order):
            if req is exclude or req.status is not RequestStatus.RUNNING:
                continue
            self._release(req)
            req.status = RequestStatus.PREEMPTED
            req.preemptions += 1
            self.preemption_count += 1
            _metrics.inc("accelerate_preemptions_total")
            self.queue.appendleft(req)
            return True
        return False

    def complete(self, request: Request, now: float) -> None:
        self._release(request)
        request.status = RequestStatus.FINISHED
        request.finish_t = now

    def _release(self, request: Request) -> None:
        self.allocator.free(request.rid)
        if request.slot is not None:
            self.slots[request.slot] = None
            request.slot = None
        self._admission_order.remove(request)
