"""Overload control for the serving router: graceful degradation, not wedging.

A router in front of N replicas has exactly three honest answers to more
traffic than the fleet can decode: queue it (bounded — an unbounded queue
converts overload into unbounded latency and OOM), slow the producers down
(token-bucket admission), or say no NOW (shedding, with a distinct
``SHED`` outcome the client can act on). This module implements all three as
one :class:`AdmissionController` the
:class:`~accelerate_tpu.serving.router.ServingRouter` consults on every
submit:

- **token bucket** — admission is charged the request's worst-case token
  cost (prompt + ``max_new_tokens``); the bucket refills at
  ``rate_tokens_per_s`` up to ``burst_tokens``. A request the bucket cannot
  cover is shed with reason ``"rate-limited"`` before it touches a queue.
- **bounded priority queues** — one FIFO per priority class (lower number =
  more important; :data:`PRIORITY_INTERACTIVE` / :data:`PRIORITY_BATCH` are
  the conventional two), bounded by ``max_queue`` TOTAL entries. That bound
  is the router's backpressure: when it is hit, something must be shed.
- **priority shedding** — a newcomer that finds the queue full evicts the
  most recently queued request of a STRICTLY lower priority class (the
  least important, least-progressed work); if nothing below it exists, the
  newcomer itself is shed with reason ``"queue-full"``. Interactive traffic
  therefore displaces batch traffic under overload, never the reverse.

Failover re-queues (a dead replica's in-flight work coming back) bypass the
bucket and the bound via :meth:`AdmissionController.requeue_front` — those
requests already paid admission once, and dropping them would break the
router's no-lost-requests invariant.

The clock is injectable so shed/refill behavior is deterministic in tests.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..telemetry import metrics as _metrics

__all__ = [
    "PRIORITY_INTERACTIVE",
    "PRIORITY_BATCH",
    "TokenBucket",
    "AdmissionVerdict",
    "AdmissionController",
]

#: conventional priority classes (any int works: lower = more important)
PRIORITY_INTERACTIVE = 0
PRIORITY_BATCH = 1


class TokenBucket:
    """Classic token bucket: ``rate_per_s`` refill, ``burst`` cap.

    ``take(n)`` is all-or-nothing — a request is either fully admitted or
    fully shed; partial admission would decode a truncated reply."""

    def __init__(self, rate_per_s: float, burst: float, clock: Callable[[], float] = time.monotonic):
        if rate_per_s <= 0 or burst <= 0:
            raise ValueError(
                f"rate_per_s and burst must be > 0, got {rate_per_s}/{burst}"
            )
        self.rate_per_s = float(rate_per_s)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)  # a fresh bucket starts full
        self._last = clock()

    def _refill(self, now: float) -> None:
        # monotone: a backdated `now` (replayed arrival_t) must not rewind
        # _last — that would re-credit an interval already spent
        if now > self._last:
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate_per_s)
            self._last = now

    def available(self, now: Optional[float] = None) -> float:
        self._refill(self._clock() if now is None else now)
        return self._tokens

    def take(self, n: float, now: Optional[float] = None) -> bool:
        self._refill(self._clock() if now is None else now)
        if n > self._tokens:
            return False
        self._tokens -= n
        return True


@dataclass
class AdmissionVerdict:
    """Outcome of one admission decision. ``evicted`` lists queued requests
    displaced by a higher-priority newcomer — the ROUTER marks them shed (it
    owns request status; the controller only owns the queues)."""

    admitted: bool
    reason: Optional[str] = None  # "rate-limited" | "queue-full" when shed
    evicted: "list" = field(default_factory=list)


class AdmissionController:
    """Bounded priority queues behind an optional token bucket."""

    def __init__(
        self,
        *,
        max_queue: int = 64,
        rate_tokens_per_s: Optional[float] = None,
        burst_tokens: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        self.clock = clock
        self.bucket = (
            TokenBucket(rate_tokens_per_s, burst_tokens or 2 * rate_tokens_per_s, clock)
            if rate_tokens_per_s
            else None
        )
        self._queues: "dict[int, deque]" = {}
        self.shed_count = 0
        self.evicted_count = 0

    # -- views ---------------------------------------------------------------

    @property
    def depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depth_by_priority(self) -> "dict[int, int]":
        return {p: len(q) for p, q in sorted(self._queues.items()) if q}

    def queued(self) -> "list":
        """All queued requests in pop order (priority, then FIFO)."""
        return [r for _, q in sorted(self._queues.items()) for r in q]

    # -- admission -----------------------------------------------------------

    def try_admit(self, request: Any, cost: float, now: Optional[float] = None) -> AdmissionVerdict:
        """Admit ``request`` (anything with a ``priority`` int attribute) at
        worst-case token ``cost``, or shed — possibly by evicting strictly
        lower-priority queued work instead of the newcomer."""
        now = self.clock() if now is None else now
        # probe the bucket first but CHARGE it last: a request shed for a
        # full queue must not also drain rate budget other traffic could use
        if self.bucket is not None and self.bucket.available(now) < cost:
            self.shed_count += 1
            _metrics.inc("accelerate_admission_shed_total", reason="rate-limited")
            return AdmissionVerdict(False, reason="rate-limited")
        evicted = []
        if self.depth >= self.max_queue:
            victim = self._evict_below(request.priority)
            if victim is None:
                self.shed_count += 1
                _metrics.inc("accelerate_admission_shed_total", reason="queue-full")
                return AdmissionVerdict(False, reason="queue-full")
            evicted.append(victim)
            _metrics.inc("accelerate_admission_shed_total", reason="displaced")
        if self.bucket is not None:
            self.bucket.take(cost, now)  # same `now` as the probe: cannot fail
        self._queues.setdefault(request.priority, deque()).append(request)
        if _metrics.is_enabled():
            _metrics.observe("accelerate_admission_queue_depth", self.depth,
                             buckets=_metrics.DEPTH_BUCKETS)
        return AdmissionVerdict(True, evicted=evicted)

    def _evict_below(self, priority: int):
        """Pop the most recently queued request of the LOWEST priority class
        strictly below ``priority`` (highest int). Failover re-queues
        (``retries > 0`` — already admitted AND already decoded on some
        replica) are never victims: shedding one would lose an admitted
        request, the invariant this whole module exists to keep. None when
        nothing evictable is queued — the newcomer must be shed instead."""
        for p in sorted(self._queues, reverse=True):
            if p <= priority:
                break
            q = self._queues[p]
            for i in range(len(q) - 1, -1, -1):  # newest evictable first
                if getattr(q[i], "retries", 0) == 0:
                    victim = q[i]
                    del q[i]
                    self.evicted_count += 1
                    self.shed_count += 1
                    return victim
        return None

    def requeue_front(self, request: Any) -> None:
        """Failover path: put a previously admitted request back at the FRONT
        of its class. No rate charge, no bound — it already paid admission,
        and dropping it would lose a request the router promised to finish."""
        self._queues.setdefault(request.priority, deque()).appendleft(request)

    # -- dispatch side -------------------------------------------------------

    def pop_next(self):
        """Next request to dispatch: highest-priority class first, FIFO
        within a class. None when everything is drained."""
        for p in sorted(self._queues):
            if self._queues[p]:
                return self._queues[p].popleft()
        return None

    def expire(self, now: float) -> "list":
        """Remove and return every queued request whose ``deadline_t`` has
        passed — work that would miss its deadline anyway must not occupy a
        decode slot that live work could use."""
        expired = []
        for q in self._queues.values():
            keep = deque()
            while q:
                r = q.popleft()
                if getattr(r, "deadline_t", None) is not None and r.deadline_t < now:
                    expired.append(r)
                else:
                    keep.append(r)
            q.extend(keep)
        return expired
