"""Bucketed shape compilation for the serving engine.

Continuous batching churns: requests arrive, finish, and get evicted at step
granularity, so the "natural" shapes of an engine step — how many batch slots
are live, how wide the widest block table is, how long the admitted prompt is
— change constantly. Feeding those raw shapes to ``jax.jit`` would recompile
on nearly every admission (seconds to minutes of XLA time each, the classic
cliff jaxlint R2 and the telemetry recompile detector exist to catch).

The fix is a small static lattice: every engine step function compiles ONLY
at shapes drawn from this lattice — batch slots and per-sequence block-table
width rounded UP to the nearest power-of-two bucket (prefill lengths
likewise) — so the jit cache is bounded by ``len(slot_buckets) *
len(block_buckets)`` decode entries plus ``len(prefill_buckets)`` prefill
entries, all warmable up front. Admission/eviction churn after warmup can
never trigger a compile; the zero-recompile regression test and ``make
doctor`` check 12 hold the line.
"""

from __future__ import annotations

from dataclasses import dataclass


def _pow2_buckets(max_value: int, floor: int = 1) -> "tuple[int, ...]":
    """Powers of two from ``floor`` up to and including ``max_value`` (the max
    itself is appended when it is not a power of two, so the lattice always
    covers the configured limit exactly)."""
    if max_value < 1:
        raise ValueError(f"max_value must be >= 1, got {max_value}")
    buckets = []
    b = max(1, floor)
    while b < max_value:
        buckets.append(b)
        b *= 2
    buckets.append(max_value)
    return tuple(buckets)


@dataclass(frozen=True)
class BucketLattice:
    """The static shape lattice the engine compiles over.

    - ``slot_buckets``: decode batch sizes (live batch slots rounded up);
    - ``block_buckets``: per-sequence block-table widths (the gather width of
      the paged attention, rounded up to the widest live sequence's bucket);
    - ``prefill_buckets``: padded prompt lengths for prefill.

    ``bucket_for`` rounds a live value up to the smallest covering bucket and
    refuses values beyond the lattice — an engine misconfiguration must fail
    loudly at admission, not silently compile a 33rd shape.
    """

    slot_buckets: "tuple[int, ...]"
    block_buckets: "tuple[int, ...]"
    prefill_buckets: "tuple[int, ...]"

    def __post_init__(self):
        for name in ("slot_buckets", "block_buckets", "prefill_buckets"):
            buckets = getattr(self, name)
            if not buckets or list(buckets) != sorted(set(buckets)):
                raise ValueError(f"{name} must be non-empty, sorted, unique: {buckets}")

    @classmethod
    def from_limits(
        cls,
        max_slots: int,
        max_blocks_per_seq: int,
        max_prefill_len: int,
        *,
        min_prefill_len: int = 8,
    ) -> "BucketLattice":
        """Power-of-two lattice covering the engine's configured limits."""
        return cls(
            slot_buckets=_pow2_buckets(max_slots),
            block_buckets=_pow2_buckets(max_blocks_per_seq),
            prefill_buckets=_pow2_buckets(max_prefill_len, floor=min_prefill_len),
        )

    @staticmethod
    def bucket_for(value: int, buckets: "tuple[int, ...]") -> int:
        """Smallest bucket >= ``value``; ValueError beyond the lattice."""
        for b in buckets:
            if value <= b:
                return b
        raise ValueError(
            f"value {value} exceeds the bucket lattice (max {buckets[-1]}); "
            "raise the engine limit this lattice was built from"
        )

    def slot_bucket(self, n_slots: int) -> int:
        return self.bucket_for(max(1, n_slots), self.slot_buckets)

    def block_bucket(self, n_blocks: int) -> int:
        return self.bucket_for(max(1, n_blocks), self.block_buckets)

    def prefill_bucket(self, prompt_len: int) -> int:
        return self.bucket_for(max(1, prompt_len), self.prefill_buckets)

    def decode_points(self) -> "list[tuple[int, int]]":
        """All (slot, block) decode compile points, for warmup."""
        return [(s, b) for s in self.slot_buckets for b in self.block_buckets]

    def prefill_points(self) -> "list[tuple[int, int]]":
        """All (prefill_len, block) prefill compile points. The block width of
        a prefill call is always the WIDEST block bucket — one width per
        prompt length keeps the prefill lattice linear in
        ``len(prefill_buckets)`` instead of the cross product (prefill is
        per-request, so the extra gather width costs little, and a
        resumed sequence's table may already be wider than its prompt)."""
        widest = self.block_buckets[-1]
        return [(s, widest) for s in self.prefill_buckets]

    def size(self) -> int:
        """Total compile points (the warmed jit-cache budget)."""
        return len(self.decode_points()) + len(self.prefill_points())

    def warmup_points(self, prefix_cache: bool = False, spec_decode: bool = False) -> int:
        """Total shapes :meth:`~accelerate_tpu.serving.engine.ServingEngine.
        warmup` visits: the lattice, plus the single copy-on-write block-copy
        shape when prefix caching is enabled (the COW copy is one fixed-shape
        program — ``(pool, src, dst)`` scalars — so it adds exactly one point
        and no churn-driven shapes), plus — with speculative decoding on —
        the draft and k-verify families: one draft point and one verify point
        per (slot, block) decode point (the draft is an S=1 step over the
        truncated model; verify is ONE batched S=k+1 step whose static width
        k+1 makes it exactly one extra warmed shape per decode point, not a
        new lattice axis). This is the number the compile-cache hit/miss
        counters and the frozen-jit-cache oracle compare against."""
        extra = 2 * len(self.decode_points()) if spec_decode else 0
        return self.size() + (1 if prefix_cache else 0) + extra
