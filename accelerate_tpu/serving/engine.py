"""The serving engine: continuous batching over a paged KV cache.

One :class:`ServingEngine` owns the device state (params + the paged block
pool) and two compiled step functions:

- **prefill** (per request, batch 1): forward the request's full prefix
  (prompt + any tokens generated before a preemption) in length-bucketed
  chunks — each chunk padded to the smallest covering prefill bucket, so a
  prefix of ANY length stays inside the compiled lattice — writing KV into
  the request's blocks via its block table, and sample the next token from
  the last real position's logits;
- **decode** (batched): one token for every live batch slot in a single
  paged-attention forward at a bucketed (slots, table-width) shape, with
  per-slot positions, per-slot PRNG keys and per-slot fold indices so each
  request's token stream is EXACTLY what a single-stream
  ``generation.greedy_generate`` / ``sample_generate`` call with batch 1
  would produce — batch composition can never leak into a request's output.

Both functions compile only at :class:`~accelerate_tpu.serving.buckets.
BucketLattice` shapes; :meth:`ServingEngine.warmup` pre-compiles every
lattice point so admission/eviction churn after warmup is recompile-free
(guarded by ``tests/test_serving.py`` and ``make doctor`` check 12 via the
telemetry recompile detector).

Multi-chip placement rides the existing generation surface: pass ``mesh``
(params already sharded via ``parallel.sharding``) and the pool is placed by
:func:`~accelerate_tpu.generation.serving_shardings` — KV heads over ``tp``,
the same Megatron decode dataflow as ``generation_shardings``.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..generation import _project_qkv, sample_token_logits, serving_shardings
from ..models.transformer import (
    LlamaConfig,
    draft_config,
    draft_params,
    rms_norm,
    rope_frequencies,
)
from ..ops.flash_attention import paged_attention
from ..telemetry import events as tel
from ..telemetry import goodput as _goodput
from ..telemetry import metrics as _metrics
from ..telemetry import tracing as _tracing
from ..telemetry import watchdog as _watchdog
from .buckets import BucketLattice
from .kv_pager import NULL_BLOCK, BlockAllocator, init_block_pool
from .scheduler import Request, Scheduler

__all__ = ["ServingEngine", "paged_forward"]


def _chaos_inject(point: str, step: int) -> None:
    # lazy import: resilience pulls in the supervisor stack, which serving
    # must not pay for (or cyclically import) at module load
    from ..resilience import chaos as _chaos

    _chaos.maybe_inject(point, step=step)


def _paged_layer_step(layer_params, h, k_pool, v_pool, block_tables, positions,
                      cos, sin, config, block_size):
    """One decoder layer over per-row positions, writing K/V into the paged
    pool (scatter at ``(block_tables[b, pos // block_size], pos %
    block_size)``) — the paged counterpart of ``generation._layer_step``,
    built from the same shared pieces (``_project_qkv``, ``llama_ffn``, the
    masked-attention core) so the math cannot drift."""
    B, S, _ = h.shape
    x = rms_norm(h, layer_params["attn_norm"]["scale"], config.norm_eps)
    q, k, v = _project_qkv(layer_params, x, positions, cos, sin, config)
    W = block_tables.shape[1]
    logical = positions // block_size
    phys = jnp.take_along_axis(block_tables, jnp.minimum(logical, W - 1), axis=1)
    # positions past the table (padded prefill tail) and inactive slots write
    # to the null block — a pad write may never land in a live block
    phys = jnp.where(logical < W, phys, NULL_BLOCK)
    off = positions % block_size
    k_pool = k_pool.at[phys, off].set(k.astype(k_pool.dtype))
    v_pool = v_pool.at[phys, off].set(v.astype(v_pool.dtype))
    attn = paged_attention(q, k_pool, v_pool, block_tables, positions)
    h = h + attn.reshape(B, S, -1) @ layer_params["wo"]["kernel"]
    x = rms_norm(h, layer_params["mlp_norm"]["scale"], config.norm_eps)
    from ..models.transformer import llama_ffn

    capacity_factor = None
    # decode-vs-prefill program split, same two-bucket shape family as the
    # contiguous path (generation._layer_step) — not a per-step retrace
    if config.moe_experts > 0 and S == 1:  # jaxlint: disable=R2
        capacity_factor = max(config.moe_capacity_factor, config.moe_experts / config.moe_top_k)
    y, _ = llama_ffn(layer_params, x, config, capacity_factor=capacity_factor)
    return h + y, k_pool, v_pool


def paged_forward(params, ids, pool, block_tables, positions, config: LlamaConfig,
                  block_size: int):
    """Forward ``ids [B, S]`` at per-row ``positions [B, S]`` against the
    paged pool. Returns ``(logits [B, S, vocab], new_pool)`` — the paged
    counterpart of ``generation._forward_cached``."""
    cos, sin = rope_frequencies(config.head_dim, config.max_seq_len, config.rope_theta)
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    h = params["embed_tokens"]["embedding"][ids]

    def layer(carry, xs):
        h = carry
        layer_params, k_p, v_p = xs
        h, k_p, v_p = _paged_layer_step(
            layer_params, h, k_p, v_p, block_tables, positions, cos, sin,
            config, block_size,
        )
        return h, (k_p, v_p)

    h, (k_new, v_new) = jax.lax.scan(
        layer, h, (params["layers"], pool["k"], pool["v"]),
        unroll=config.unroll_layers,
    )
    h = rms_norm(h, params["final_norm"]["scale"], config.norm_eps)
    if config.tie_embeddings:
        logits = h @ params["embed_tokens"]["embedding"].T
    else:
        logits = h @ params["lm_head"]["kernel"]
    return logits, {"k": k_new, "v": v_new}


class ServingEngine:
    """Continuous-batching serving engine over a paged KV cache.

    ``submit`` enqueues requests; each ``step`` admits what fits (prefill in
    length buckets), decodes one token for every live slot, completes/frees
    finished sequences and backfills their slots. Pool pressure preempts the
    youngest request (progress persisted, resumed later with identical
    output). Sampling knobs are engine-level (compiled into the step
    functions — per-request knobs would multiply the compile lattice);
    ``temperature=0`` is greedy. Emits ``serving`` / ``serving_request``
    telemetry records when telemetry is enabled.

    ``spec_tokens=k`` (with ``draft_layers=n``) turns on speculative
    decoding: a truncated-layer self-draft (the verifier's first n layers +
    its head, sharing params AND the paged pool) proposes k tokens per step
    and one batched S=k+1 verify step accepts the longest prefix matching
    the verifier's own per-slot fold-stream emissions — so the output stream
    stays bitwise-identical to non-speculative decode while a good draft
    collapses up to k+1 tokens into one model step (see
    ``docs/serving.md``).
    """

    def __init__(
        self,
        params,
        config: LlamaConfig,
        *,
        num_blocks: int = 64,
        block_size: int = 16,
        max_slots: int = 4,
        max_prefill_len: Optional[int] = None,
        max_blocks_per_seq: Optional[int] = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        cache_dtype=jnp.bfloat16,
        mesh=None,
        continuous: bool = True,
        admit_watermark_blocks: int = 0,
        lattice: Optional[BucketLattice] = None,
        heartbeat_name: str = "serving_decode",
        compile_cache_dir: Optional[str] = None,
        prefix_cache: bool = True,
        spec_tokens: int = 0,
        draft_layers: Optional[int] = None,
    ):
        self.params = params
        self.config = config
        self.block_size = block_size
        self.max_slots = max_slots
        self.mesh = mesh
        # speculative decoding: a truncated-layer self-draft proposes
        # ``spec_tokens`` tokens per step and ONE batched S=k+1 verify step
        # accepts the longest prefix that matches the verifier's own
        # fold-stream emissions (bitwise-accept — see _spec_decode_batch)
        self.spec_tokens = int(spec_tokens)
        self.draft_layers = draft_layers
        if self.spec_tokens < 0:
            raise ValueError(f"spec_tokens must be >= 0, got {spec_tokens}")
        if self.spec_tokens > 0 and draft_layers is None:
            raise ValueError("spec_tokens > 0 requires draft_layers (the self-draft depth)")
        # watchdog heartbeat source for the decode loop: a hang inside a
        # batched decode produces a stall dump naming this engine (replicas
        # suffix their name so a stuck replica is attributable)
        self.heartbeat_name = heartbeat_name
        self.prefix_cache = prefix_cache
        self.allocator = BlockAllocator(
            num_blocks, block_size, prefix_caching=prefix_cache
        )
        if max_blocks_per_seq is None:
            max_blocks_per_seq = self.allocator.usable_blocks
        max_prefill_len = max_prefill_len or min(
            config.max_seq_len, max_blocks_per_seq * block_size
        )
        if max_prefill_len > max_blocks_per_seq * block_size:
            raise ValueError(
                f"max_prefill_len={max_prefill_len} exceeds "
                f"{max_blocks_per_seq} block(s) x {block_size} slots"
            )
        self.lattice = lattice or BucketLattice.from_limits(
            max_slots, max_blocks_per_seq, max_prefill_len
        )
        self.scheduler = Scheduler(
            self.allocator, max_slots,
            continuous=continuous, admit_watermark_blocks=admit_watermark_blocks,
            # a sequence's block table can never exceed the lattice's widest
            # bucket, and its positions can never exceed the RoPE table —
            # admission rejects worst cases beyond either up front
            max_seq_blocks=self.lattice.block_buckets[-1],
            max_seq_tokens=config.max_seq_len,
        )
        self.pool = init_block_pool(config, num_blocks, block_size, cache_dtype)
        if mesh is not None:
            sharding = serving_shardings(mesh, config)
            self.pool = jax.tree_util.tree_map(
                lambda c: jax.device_put(c, sharding), self.pool
            )

        if temperature == 0.0:
            def select_one(row, key):
                return jnp.argmax(row, axis=-1)
        else:
            def select_one(row, key):
                return sample_token_logits(
                    row[None], key, temperature=temperature, top_k=top_k, top_p=top_p
                )[0]

        def _prefill(params, pool, ids, table, start, last_idx, key, token_idx):
            # one CHUNK of a prefix: ids [1, Sb] holds the tokens at absolute
            # positions start..start+Sb-1 (the host loop feeds long prefixes
            # through the largest bucket chunk by chunk); the sampled token is
            # meaningful only for the final chunk (last_idx = last real row)
            B, Sb = ids.shape
            positions = start + jnp.broadcast_to(jnp.arange(Sb)[None], (B, Sb))
            logits, pool = paged_forward(
                params, ids, pool, table, positions, config, block_size
            )
            last = jax.lax.dynamic_index_in_dim(logits, last_idx, axis=1, keepdims=False)
            tok = select_one(last[0], jax.random.fold_in(key, token_idx))
            return pool, tok.astype(jnp.int32)

        def _decode(params, pool, last_tok, tables, positions, keys, token_idx):
            logits, pool = paged_forward(
                params, last_tok[:, None], pool, tables, positions[:, None],
                config, block_size,
            )
            folded = jax.vmap(jax.random.fold_in)(keys, token_idx)
            tok = jax.vmap(select_one)(logits[:, -1], folded)
            return pool, tok.astype(jnp.int32)

        def _cow(pool, src, dst):
            # copy-on-write for the aligned prefix-cache edge case: duplicate
            # one physical block (all layers, K and V) into a private block
            # before the new sequence's first write can touch shared content
            return {
                "k": pool["k"].at[:, dst].set(pool["k"][:, src]),
                "v": pool["v"].at[:, dst].set(pool["v"][:, src]),
            }

        self.prefill_fn = jax.jit(_prefill, donate_argnums=(1,))
        self.decode_fn = jax.jit(_decode, donate_argnums=(1,))
        self.cow_fn = jax.jit(_cow, donate_argnums=(0,))

        if self.spec_tokens > 0:
            n_draft = int(draft_layers)
            d_cfg = draft_config(config, n_draft)
            # truncated-layer self-draft: layer i IS verifier layer i (shared
            # leaves, no copy), so the verifier's landed KV is valid draft KV
            # and the draft needs no pool/prefill/warmup of its own
            self.draft_params = draft_params(params, n_draft)

            def _draft(dparams, pool, last_tok, tables, positions, keys, token_idx):
                # one S=1 step of the truncated model over the SHARED pool's
                # first n layers. Its KV writes let draft step j+1 attend to
                # draft step j's candidate; the verify step recomputes the
                # same layer-i KV for accepted tokens (identical math), so
                # the overwrite is value-exact, and rejected positions are
                # re-written before any later read (scatter-then-attend).
                dpool = {"k": pool["k"][:n_draft], "v": pool["v"][:n_draft]}
                logits, dpool = paged_forward(
                    dparams, last_tok[:, None], dpool, tables, positions[:, None],
                    d_cfg, block_size,
                )
                pool = {
                    "k": pool["k"].at[:n_draft].set(dpool["k"]),
                    "v": pool["v"].at[:n_draft].set(dpool["v"]),
                }
                folded = jax.vmap(jax.random.fold_in)(keys, token_idx)
                tok = jax.vmap(select_one)(logits[:, -1], folded)
                return pool, tok.astype(jnp.int32)

            def _verify(params, pool, cand, tables, positions, keys, token_idx):
                # cand [B, k+1]: column 0 = the last confirmed token, columns
                # 1..k = draft proposals. ONE batched S=k+1 forward scatter-
                # writes KV for every candidate position and then selects —
                # per (row, column) — the token the NON-speculative stream
                # would emit at fold index token_idx + j. The host accepts
                # the longest prefix where the draft matched those selections
                # exactly, so the emitted stream is bitwise the single-stream
                # one in greedy AND sampled modes.
                B, S = cand.shape
                pos = positions[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
                logits, pool = paged_forward(
                    params, cand, pool, tables, pos, config, block_size
                )
                idx = token_idx[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
                folded = jax.vmap(jax.vmap(jax.random.fold_in, in_axes=(None, 0)))(
                    keys, idx
                )
                sel = jax.vmap(jax.vmap(select_one))(logits, folded)
                return pool, sel.astype(jnp.int32)

            self.draft_fn = jax.jit(_draft, donate_argnums=(1,))
            self.verify_fn = jax.jit(_verify, donate_argnums=(1,))
        # Persistent-compile-cache warm boot: when a cache dir is configured
        # (replacement replicas get it via ReplicaSpec.compile_cache_dir),
        # warmup AOT-compiles every lattice point through the cache — hits
        # load in milliseconds — and the step paths dispatch to these
        # executables; with no dir this stays empty and behavior is
        # byte-identical to the plain jit path.
        self.compile_cache_dir = compile_cache_dir
        self._aot: dict = {}  # ("prefill"|"decode", *bucket shape) -> executable
        self.cache_stats = {"hit": 0, "miss": 0, "corrupt": 0, "uncached": 0, "error": 0}

        # live observability (PR 15): arm tracing/metrics from the env once
        # per engine — both stay None-branch no-ops when unconfigured
        _tracing.maybe_arm_from_env()
        _metrics.maybe_enable_from_env()

        # stats for the telemetry records / bench payloads
        self.steps = 0
        self.decode_tokens = 0
        self.prefill_tokens = 0
        self.prefill_calls = 0
        #: prompt tokens whose KV came straight from the prefix cache — i.e.
        #: prefill work NOT done (the bench's ``prefill_tokens_saved``)
        self.prefix_cached_tokens = 0
        #: re-prefilled tokens: KV this engine computed a second time. The
        #: goodput ledger's token-waste attribution — preempt/resume
        #: re-prefills vs failover/handoff resumes seeded via ``generated``
        self.preempt_prefill_tokens = 0
        self.resume_prefill_tokens = 0
        self.max_running = 0
        self._occupancy_sum = 0.0
        self._occupancy_steps = 0
        #: speculative decoding: draft tokens proposed / accepted, and the
        #: accepted-per-step histogram (index = draft tokens accepted that
        #: slot-step, 0..k) the report's serving section renders
        self.draft_proposed_tokens = 0
        self.draft_accepted_tokens = 0
        self.spec_accept_hist = np.zeros(max(self.spec_tokens, 0) + 1, np.int64)

    # -- lifecycle -----------------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        eos_token_id: Optional[int] = None,
        rng_seed: int = 0,
        arrival_t: Optional[float] = None,
        generated: Optional["list[int]"] = None,
        trace: Optional[dict] = None,
    ) -> Request:
        """Enqueue one request; returns its :class:`Request` handle (live —
        ``generated``/``status`` update as the engine steps).

        ``generated`` seeds the request with tokens already produced by a
        PREVIOUS engine (the router's cross-replica failover resume): the
        prefill covers ``prompt + generated`` and sampling continues at fold
        index ``len(generated)`` — exactly the scheduler's preempt/resume
        state, so the continuation is bitwise-identical to an unfailed run.
        ``max_new_tokens`` stays the request's TOTAL new-token budget.

        ``trace`` is a propagated :class:`~accelerate_tpu.telemetry.tracing.
        TraceContext` dict (the router's dispatch span): engine spans parent
        under it and accumulate on ``Request.trace_spans`` for the owner to
        emit. With no ``trace`` and tracing armed, the engine roots its own
        trace and emits it at completion."""
        req = Request(
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id,
            rng_seed=rng_seed,
            arrival_t=time.monotonic() if arrival_t is None else arrival_t,
        )
        if generated:
            if len(generated) >= max_new_tokens:
                raise ValueError(
                    f"resume with {len(generated)} generated token(s) >= "
                    f"max_new_tokens={max_new_tokens}: nothing left to decode"
                )
            req.generated = [int(t) for t in generated]
        ctx = _tracing.TraceContext.from_wire(trace)
        if ctx is None and _tracing.is_armed():
            ctx = _tracing.new_trace()
            req._trace_owner = True
        if ctx is not None:
            req.trace = ctx
            req._span_root = _tracing.span_open(
                ctx, "engine_request", component="engine", rid=int(req.rid),
                prompt_tokens=int(req.prompt.size),
                resumed_tokens=len(req.generated),
            )
            req._span_queue = _tracing.span_open(
                ctx, "queue_wait", parent_id=req._span_root["span_id"],
                component="engine",
            )
            req.trace_spans += [req._span_root, req._span_queue]
        self.scheduler.submit(req)
        return req

    def warmup(self) -> dict:
        """Compile every lattice point up front (decode (slots, width) cross
        product + per-length prefill) so serving never pays a compile — and so
        the recompile detector's baseline is exact. Returns the per-function
        compile counts; the jit caches must never grow past them.

        With ``compile_cache_dir`` configured (and the cache enabled), every
        point goes through :func:`accelerate_tpu.compile_cache.aot_compile`
        instead: a cached point LOADS in milliseconds (a replacement replica
        boots warm), a missed point compiles once and is exported for the
        next boot. ``cache_stats`` records the per-point outcomes."""
        from .. import compile_cache as _ccache

        warmup_t0 = time.monotonic()
        cache = None
        if self.compile_cache_dir is not None:
            cache = _ccache.get_cache(self.compile_cache_dir)
        key = np.zeros((2,), np.uint32)
        for Sb, W in self.lattice.prefill_points():
            ids = np.zeros((1, Sb), np.int32)
            table = np.full((1, W), NULL_BLOCK, np.int32)
            args = (
                self.params, self.pool, ids, table, np.int32(0), np.int32(0),
                key, np.int32(0),
            )
            if cache is not None:
                executable, outcome = _ccache.aot_compile(
                    f"serving_prefill[{Sb}x{W}]", self.prefill_fn, args,
                    mesh=self.mesh, cache=cache,
                )
                self.cache_stats[outcome] = self.cache_stats.get(outcome, 0) + 1
                if executable is not None:
                    self._aot[("prefill", Sb, W)] = executable
                    continue
            self.pool, tok = self.prefill_fn(*args)
        for Bb, W in self.lattice.decode_points():
            last = np.zeros((Bb,), np.int32)
            tables = np.full((Bb, W), NULL_BLOCK, np.int32)
            positions = np.zeros((Bb,), np.int32)
            keys = np.zeros((Bb, 2), np.uint32)
            token_idx = np.zeros((Bb,), np.int32)
            args = (self.params, self.pool, last, tables, positions, keys, token_idx)
            if cache is not None:
                executable, outcome = _ccache.aot_compile(
                    f"serving_decode[{Bb}x{W}]", self.decode_fn, args,
                    mesh=self.mesh, cache=cache,
                )
                self.cache_stats[outcome] = self.cache_stats.get(outcome, 0) + 1
                if executable is not None:
                    self._aot[("decode", Bb, W)] = executable
                    continue
            self.pool, tok = self.decode_fn(*args)
        if self.spec_tokens > 0:
            # the draft + k-verify families: one point per decode point each
            # (verify's S=k+1 width is static, so it is one extra warmed
            # shape per (slots, width), not a new lattice axis)
            for Bb, W in self.lattice.decode_points():
                last = np.zeros((Bb,), np.int32)
                tables = np.full((Bb, W), NULL_BLOCK, np.int32)
                positions = np.zeros((Bb,), np.int32)
                keys = np.zeros((Bb, 2), np.uint32)
                token_idx = np.zeros((Bb,), np.int32)
                args = (
                    self.draft_params, self.pool, last, tables, positions,
                    keys, token_idx,
                )
                done = False
                if cache is not None:
                    executable, outcome = _ccache.aot_compile(
                        f"serving_draft[{Bb}x{W}]", self.draft_fn, args,
                        mesh=self.mesh, cache=cache,
                    )
                    self.cache_stats[outcome] = self.cache_stats.get(outcome, 0) + 1
                    if executable is not None:
                        self._aot[("draft", Bb, W)] = executable
                        done = True
                if not done:
                    self.pool, tok = self.draft_fn(*args)
                cand = np.zeros((Bb, self.spec_tokens + 1), np.int32)
                args = (
                    self.params, self.pool, cand, tables, positions, keys, token_idx
                )
                done = False
                if cache is not None:
                    executable, outcome = _ccache.aot_compile(
                        f"serving_verify[{Bb}x{W}]", self.verify_fn, args,
                        mesh=self.mesh, cache=cache,
                    )
                    self.cache_stats[outcome] = self.cache_stats.get(outcome, 0) + 1
                    if executable is not None:
                        self._aot[("verify", Bb, W)] = executable
                        done = True
                if not done:
                    self.pool, tok = self.verify_fn(*args)
        if self.prefix_cache:
            # the COW copy is one more lattice point (a single shape): warm it
            # here — copying the null block onto itself writes nothing live
            args = (self.pool, np.int32(NULL_BLOCK), np.int32(NULL_BLOCK))
            done = False
            if cache is not None:
                executable, outcome = _ccache.aot_compile(
                    "serving_cow", self.cow_fn, args, mesh=self.mesh, cache=cache,
                )
                self.cache_stats[outcome] = self.cache_stats.get(outcome, 0) + 1
                if executable is not None:
                    self._aot[("cow",)] = executable
                    done = True
            if not done:
                self.pool = self.cow_fn(*args)
        jax.block_until_ready(self.pool)
        counts = self.jit_cache_sizes()
        if tel.is_enabled():
            warmup_dur = time.monotonic() - warmup_t0
            tel.emit(
                "serving", phase="warmup", dur_s=round(warmup_dur, 6), **counts,
                **(
                    {"cache_" + k: v for k, v in self.cache_stats.items() if v}
                    if cache is not None else {}
                ),
            )
            _goodput.note("warmup", warmup_dur)
        return counts

    def jit_cache_sizes(self) -> dict:
        """Compiled-entry counts for the two step functions (live jit cache
        plus cache-loaded AOT executables) — after :meth:`warmup` these must
        equal the lattice sizes forever."""
        aot_prefill = sum(1 for k in self._aot if k[0] == "prefill")
        aot_decode = sum(1 for k in self._aot if k[0] == "decode")
        out = {
            "prefill_compiles": int(self.prefill_fn._cache_size()) + aot_prefill,
            "decode_compiles": int(self.decode_fn._cache_size()) + aot_decode,
        }
        if self.prefix_cache:
            out["cow_compiles"] = int(self.cow_fn._cache_size()) + (
                1 if ("cow",) in self._aot else 0
            )
        if self.spec_tokens > 0:
            out["draft_compiles"] = int(self.draft_fn._cache_size()) + sum(
                1 for k in self._aot if k[0] == "draft"
            )
            out["verify_compiles"] = int(self.verify_fn._cache_size()) + sum(
                1 for k in self._aot if k[0] == "verify"
            )
        return out

    # -- the step loop -------------------------------------------------------

    def step(self, now: Optional[float] = None) -> "list[Request]":
        """One engine iteration: admit+prefill, decode one token for every
        live slot, complete/free finished sequences. Returns the requests
        that left the engine this step — status FINISHED, or REJECTED (with
        ``Request.error`` set) for requests whose worst case can never fit
        this engine's pool/lattice."""
        now = time.monotonic() if now is None else now
        step_t0 = time.monotonic()
        # chaos fault point: a seeded replica kill/hang/slow lands HERE, mid
        # decode loop (resilience/chaos.py, point "serving_decode") — one
        # ``is None`` check when disarmed
        _chaos_inject("serving_decode", self.steps)
        finished: "list[Request]" = []

        prefills = 0
        prefill_tokens_before = self.prefill_tokens
        prefix_cached_before = self.prefix_cached_tokens
        preempt_before = self.preempt_prefill_tokens
        resume_before = self.resume_prefill_tokens
        decode_before = self.decode_tokens
        proposed_before = self.draft_proposed_tokens
        accepted_before = self.draft_accepted_tokens
        hist_before = self.spec_accept_hist.copy()
        admitted = self.scheduler.admissions()
        while self.scheduler.rejected:
            req = self.scheduler.rejected.pop()
            req.finish_t = now
            self._close_trace(req, "rejected")
            finished.append(req)  # returned to the caller, status REJECTED
            if _metrics.is_enabled():
                _metrics.inc("accelerate_engine_requests_total", outcome="rejected")
            if tel.is_enabled():
                tel.emit(
                    "serving_request", rid=req.rid, error=req.error,
                    new_tokens=0, prompt_tokens=int(req.prompt.size),
                )
        for req in admitted:
            self._prefill_request(req, now)
            prefills += 1
            if req.done:
                self.scheduler.complete(req, now)
                self._finish_request(req, now)
                finished.append(req)

        running = [r for r in self.scheduler.running()]
        if running:
            # reserve the next KV slot(s) for every live sequence FIRST: a
            # grow may preempt the youngest, and the decode batch must be
            # built from the survivors. Speculative decoding reserves up to
            # k+1 positions (the verify step's write span), clamped to the
            # request's remaining budget so admission's worst-case bound
            # still covers the peak; leftover reservations from a short
            # accept are reused, so the per-step delta is what LAST step
            # actually emitted.
            for req in list(running):
                if req.slot is not None:
                    if self.spec_tokens > 0:
                        remaining = req.max_new_tokens - len(req.generated)
                        target = (req.prefix_len - 1) + min(
                            self.spec_tokens + 1, remaining
                        )
                        self.scheduler.grow(
                            req, target - self.allocator.tokens(req.rid)
                        )
                    else:
                        self.scheduler.grow(req)
            running = self.scheduler.running()
        if running:
            if self.spec_tokens > 0:
                self._spec_decode_batch(running)
            else:
                self._decode_batch(running)
            for req in running:
                if req.done:
                    self.scheduler.complete(req, now)
                    self._finish_request(req, now)
                    finished.append(req)

        self.steps += 1
        if self.scheduler.idle():
            # an idle engine is not a stalled one: deregister so a quiet
            # traffic window can never trip the watchdog (the next step's
            # beat re-registers the source)
            _watchdog.unregister(self.heartbeat_name)
        else:
            _watchdog.beat(self.heartbeat_name, step=self.steps)
        occupancy = len(running) / self.max_slots
        self.max_running = max(self.max_running, len(running))
        self._occupancy_sum += occupancy
        self._occupancy_steps += 1
        if _metrics.is_enabled():
            alloc_occ = self.allocator.occupancy()
            # gauges are last-write-wins: label them per engine so N
            # LocalReplica engines in one process (one shared registry)
            # don't clobber each other's depth (histograms/counters below
            # aggregate across engines by design — fleet-level percentiles)
            _metrics.set_gauge("accelerate_engine_queue_depth",
                               self.scheduler.queue_depth, engine=self.heartbeat_name)
            _metrics.set_gauge("accelerate_engine_running", len(running),
                               engine=self.heartbeat_name)
            _metrics.observe("accelerate_engine_queue_depth_hist", self.scheduler.queue_depth,
                             buckets=_metrics.DEPTH_BUCKETS)
            _metrics.observe("accelerate_batch_occupancy", occupancy,
                             buckets=_metrics.OCCUPANCY_BUCKETS)
            _metrics.observe("accelerate_block_pool_occupancy", alloc_occ,
                             buckets=_metrics.OCCUPANCY_BUCKETS)
            _metrics.inc("accelerate_decode_tokens_total",
                         self.decode_tokens - decode_before)
            _metrics.inc("accelerate_prefill_tokens_total",
                         self.prefill_tokens - prefill_tokens_before)
            _metrics.inc("accelerate_prefix_hit_tokens_total",
                         self.prefix_cached_tokens - prefix_cached_before)
            if running:
                # per-token latency: without speculation every live request
                # earned exactly one token this step, so the step wall IS its
                # token interval; with speculation a request earned
                # (emitted / batch) tokens on average, so divide the wall by
                # that per-request yield
                decode_delta = self.decode_tokens - decode_before
                _metrics.observe(
                    "accelerate_per_token_latency_seconds",
                    (time.monotonic() - step_t0) * len(running)
                    / max(decode_delta, 1),
                )
            _metrics.maybe_snapshot()
        if tel.is_enabled():
            alloc = self.allocator.stats()
            step_dur = time.monotonic() - step_t0
            prefill_delta = self.prefill_tokens - prefill_tokens_before
            preempt_delta = self.preempt_prefill_tokens - preempt_before
            resume_delta = self.resume_prefill_tokens - resume_before
            decode_delta = self.decode_tokens - decode_before
            spec_fields = {}
            rejected_delta = 0
            if self.spec_tokens > 0:
                proposed_delta = self.draft_proposed_tokens - proposed_before
                accepted_delta = self.draft_accepted_tokens - accepted_before
                rejected_delta = proposed_delta - accepted_delta
                spec_fields = dict(
                    draft_proposed_tokens=proposed_delta,
                    draft_accepted_tokens=accepted_delta,
                    draft_rejected_tokens=rejected_delta,
                    # per-step accepted-count histogram delta (index = draft
                    # tokens accepted for one slot-step, 0..k) — the report's
                    # serving section sums these elementwise
                    spec_accept_hist=(self.spec_accept_hist - hist_before).tolist(),
                )
            tel.emit(
                "serving",
                phase="step",
                dur_s=round(step_dur, 6),
                queue_depth=self.scheduler.queue_depth,
                running=len(running),
                occupancy=round(occupancy, 6),
                prefills=prefills,
                prefill_tokens=prefill_delta,
                prefix_hit_tokens=self.prefix_cached_tokens - prefix_cached_before,
                preempt_reprefill_tokens=preempt_delta,
                resume_reprefill_tokens=resume_delta,
                decode_tokens=decode_delta,
                preemptions=self.scheduler.preemption_count,
                free_blocks=alloc["free_blocks"],
                live_tokens=alloc["live_tokens"],
                block_occupancy=alloc["occupancy"],
                fragmentation=alloc["fragmentation"],
                **spec_fields,
            )
            _goodput.note_serving_step(
                step_dur,
                # rejected verify rows were computed but never emitted: they
                # count as computed AND as waste (cause "draft_rejected")
                computed_tokens=prefill_delta + decode_delta + rejected_delta,
                wasted_tokens=preempt_delta + resume_delta + rejected_delta,
            )
            _goodput.maybe_emit()
        return finished

    def run(self, max_steps: int = 100_000) -> "list[Request]":
        """Step until idle (every submitted request finished); returns all
        completions in finish order."""
        done: "list[Request]" = []
        for _ in range(max_steps):
            if self.scheduler.idle():
                return done
            done.extend(self.step())
        raise RuntimeError(f"engine not idle after {max_steps} steps")

    # -- internals -----------------------------------------------------------

    def _request_key(self, req: Request) -> np.ndarray:
        # cached: the key is a pure function of rng_seed, and rebuilding it
        # would add a device dispatch per slot per decode step
        if req._key is None:
            req._key = np.asarray(jax.random.PRNGKey(req.rng_seed), np.uint32)
        return req._key

    def _prefill_request(self, req: Request, now: float) -> None:
        """Prefill the request's UNCACHED prefix tail in length-bucketed
        CHUNKS: each chunk runs at the smallest covering prefill bucket (the
        largest bucket for all but the tail), so arbitrarily long prefixes —
        e.g. a resumed request's prompt + generated — stay inside the
        compiled lattice. Only the final chunk's sampled token is kept.

        Prefix-cache admission already mapped the cached blocks into the
        table: ``req.cached_tokens`` leading positions hold valid KV and are
        skipped (the attention inside each chunk reads them through the block
        table, so the math is position-exact and bitwise-identical to an
        unshared run). A pending copy-on-write pair is applied to the pool
        FIRST — the one write this request aims below its uncached tail goes
        into its private copy, never a shared block."""
        prefix = req.output_ids()
        span_prefill = None
        if req.trace is not None:
            if req._span_queue is not None and "t1_ns" not in req._span_queue:
                _tracing.span_close(req._span_queue)
            span_prefill = _tracing.span_open(
                req.trace, "prefill", parent_id=req._span_root["span_id"],
                component="engine", prefix_tokens=int(prefix.size),
                cached_tokens=int(req.cached_tokens),
                cow=req.cow_block is not None,
                resume=req.preemptions > 0,
            )
            req.trace_spans.append(span_prefill)
        if req.cow_block is not None:
            src, dst = req.cow_block
            cow_t0 = _tracing.now_ns() if span_prefill is not None else 0
            fn = self._aot.get(("cow",), self.cow_fn)
            self.pool = fn(self.pool, np.int32(src), np.int32(dst))
            # the copy is issued (ordered before any later pool op): release
            # the allocator's pin so src can park in the reclaimable pool
            self.allocator.cow_done(src)
            req.cow_block = None
            if span_prefill is not None:
                req.trace_spans.append(_tracing.make_span(
                    req.trace, "cow_copy", cow_t0, _tracing.now_ns(),
                    parent_id=span_prefill["span_id"], component="engine",
                    src_block=int(src), dst_block=int(dst),
                ))
        W = self.lattice.prefill_points()[0][1]
        table = self.allocator.block_table(req.rid, pad_to=W)[None]
        chunk_cap = self.lattice.prefill_buckets[-1]
        key = self._request_key(req)
        token_idx = np.int32(len(req.generated))
        start = int(req.cached_tokens)
        self.prefix_cached_tokens += start
        self.prefill_tokens += int(prefix.size) - start
        # token-goodput waste attribution: a prefill covering already-produced
        # work is recomputation. Preempt/resume re-runs carry preemptions>0;
        # a failover/handoff resume arrives with ``generated`` pre-seeded.
        if req.preemptions > 0:
            self.preempt_prefill_tokens += int(prefix.size) - start
        elif req.generated:
            self.resume_prefill_tokens += int(prefix.size) - start
        while start < prefix.size:
            chunk = prefix[start : start + chunk_cap]
            Sb = self.lattice.prefill_bucket(chunk.size)
            ids = np.zeros((1, Sb), np.int32)
            ids[0, : chunk.size] = chunk
            chunk_t0 = _tracing.now_ns() if span_prefill is not None else 0
            fn = self._aot.get(("prefill", Sb, W), self.prefill_fn)
            self.pool, tok = fn(
                self.params, self.pool, ids, table, np.int32(start),
                np.int32(chunk.size - 1), key, token_idx,
            )
            if span_prefill is not None:
                req.trace_spans.append(_tracing.make_span(
                    req.trace, "prefill_chunk", chunk_t0, _tracing.now_ns(),
                    parent_id=span_prefill["span_id"], component="engine",
                    start=int(start), tokens=int(chunk.size), bucket=int(Sb),
                ))
            start += chunk.size
        if span_prefill is not None:
            _tracing.span_close(span_prefill)
        req.generated.append(int(tok))
        if req.first_token_t is None:
            req.first_token_t = now
        self.prefill_calls += 1

    def _decode_batch(self, running: "list[Request]") -> None:
        Bb = self.lattice.slot_bucket(len(running))
        W = self.lattice.block_bucket(
            max(self.allocator.num_seq_blocks(r.rid) for r in running)
        )
        last = np.zeros((Bb,), np.int32)
        tables = np.full((Bb, W), NULL_BLOCK, np.int32)
        positions = np.zeros((Bb,), np.int32)
        keys = np.zeros((Bb, 2), np.uint32)
        token_idx = np.zeros((Bb,), np.int32)
        for i, req in enumerate(running):
            last[i] = req.generated[-1]
            tables[i] = self.allocator.block_table(req.rid, pad_to=W)
            positions[i] = req.prefix_len - 1
            keys[i] = self._request_key(req)
            token_idx[i] = len(req.generated)
        # gate on the requests' own contexts, not the local arming state (a
        # ProcessReplica child traces whenever the router propagated a ctx) —
        # and only for SAMPLED traces: per-token decode spans are the bulk of
        # a trace's cost, and an unsampled trace keeps only its cheap
        # structural spans (the router flips sampled on for failover
        # redispatches, whose forced emission needs the detail)
        decode_t0 = (
            _tracing.now_ns()
            if any(r.trace is not None and r.trace.get("sampled") for r in running)
            else 0
        )
        fn = self._aot.get(("decode", Bb, W), self.decode_fn)
        self.pool, toks = fn(
            self.params, self.pool, last, tables, positions, keys, token_idx
        )
        toks = np.asarray(jax.device_get(toks))
        if decode_t0:
            decode_t1 = _tracing.now_ns()
            for req in running:
                if req.trace is not None and req.trace.get("sampled"):
                    req.trace_spans.append(_tracing.make_span(
                        req.trace, "decode_step", decode_t0, decode_t1,
                        parent_id=req._span_root["span_id"], component="engine",
                        step=int(self.steps), batch=len(running),
                        token_idx=len(req.generated),
                    ))
        for i, req in enumerate(running):
            req.generated.append(int(toks[i]))
            if self.prefix_cache:
                # this decode wrote position prefix_len-2's token (the last
                # PREVIOUS token) — when the written count crosses a block
                # boundary, the just-filled block becomes immutable and
                # content-indexable for future prefix matches
                written = req.prefix_len - 1
                if written > 0 and written % self.block_size == 0:
                    self.allocator.register_full_blocks(
                        req.rid, req.output_ids()[:-1]
                    )
        self.decode_tokens += len(running)

    def _spec_decode_batch(self, running: "list[Request]") -> None:
        """One speculative decode round for every live slot: k sequential S=1
        steps of the truncated self-draft propose candidates, ONE batched
        S=k+1 verify forward (which dispatches to the chunked-prefill paged
        kernel) scatter-writes their KV and computes — per candidate row —
        the token the non-speculative fold stream would emit there, and the
        host accepts the longest candidate prefix matching those emissions
        EXACTLY (bitwise accept: greedy argmax or sampled rejection off the
        per-slot fold streams, both byte-equal to single-stream decode).

        Every request emits at least the verifier's own token (row 0), so a
        0%-accept workload degrades to one-token-per-step decode, never
        stalls. KV safety: rejected rows' pool writes sit past the emitted
        prefix and are position-masked out of every read until the next
        step's scatter overwrites them."""
        k = self.spec_tokens
        Bb = self.lattice.slot_bucket(len(running))
        W = self.lattice.block_bucket(
            max(self.allocator.num_seq_blocks(r.rid) for r in running)
        )
        last = np.zeros((Bb,), np.int32)
        tables = np.full((Bb, W), NULL_BLOCK, np.int32)
        positions = np.zeros((Bb,), np.int32)
        keys = np.zeros((Bb, 2), np.uint32)
        token_idx = np.zeros((Bb,), np.int32)
        rows = np.ones((Bb,), np.int32)
        for i, req in enumerate(running):
            last[i] = req.generated[-1]
            tables[i] = self.allocator.block_table(req.rid, pad_to=W)
            positions[i] = req.prefix_len - 1
            keys[i] = self._request_key(req)
            token_idx[i] = len(req.generated)
            # emit at most as many rows as the grow phase reserved KV room
            # for (clamped by the request's remaining new-token budget)
            rows[i] = self.allocator.tokens(req.rid) - (req.prefix_len - 1)
        decode_t0 = (
            _tracing.now_ns()
            if any(r.trace is not None and r.trace.get("sampled") for r in running)
            else 0
        )
        cand = np.zeros((Bb, k + 1), np.int32)
        cand[:, 0] = last
        dfn = self._aot.get(("draft", Bb, W), self.draft_fn)
        d_last, d_pos, d_idx = last, positions, token_idx
        for j in range(k):
            self.pool, d_tok = dfn(
                self.draft_params, self.pool, d_last, tables, d_pos, keys, d_idx
            )
            d_tok = np.asarray(jax.device_get(d_tok)).astype(np.int32)
            cand[:, j + 1] = d_tok
            d_last, d_pos, d_idx = d_tok, d_pos + 1, d_idx + 1
        vfn = self._aot.get(("verify", Bb, W), self.verify_fn)
        self.pool, sel = vfn(
            self.params, self.pool, cand, tables, positions, keys, token_idx
        )
        sel = np.asarray(jax.device_get(sel))
        emitted = 0
        accepted_by_req: "list[int]" = []
        for i, req in enumerate(running):
            r_i = int(min(rows[i], k + 1))
            before = req.prefix_len - 1
            n_acc = 0
            for j in range(r_i):
                tok = int(sel[i, j])
                req.generated.append(tok)
                emitted += 1
                if req.done:
                    break
                if j + 1 < r_i and int(cand[i, j + 1]) == tok:
                    n_acc += 1
                    continue
                break
            accepted_by_req.append(n_acc)
            self.draft_proposed_tokens += max(r_i - 1, 0)
            self.draft_accepted_tokens += n_acc
            self.spec_accept_hist[n_acc] += 1
            if _metrics.is_enabled():
                _metrics.observe(
                    "accelerate_spec_accepted_tokens", float(n_acc),
                    buckets=tuple(float(b) for b in range(k + 1)),
                )
            if self.prefix_cache:
                written = req.prefix_len - 1
                if written // self.block_size > before // self.block_size:
                    # a multi-token accept can cross MORE than one block
                    # boundary in one step; registration is incremental, so
                    # one call covers them all
                    self.allocator.register_full_blocks(
                        req.rid, req.output_ids()[:-1]
                    )
        if decode_t0:
            decode_t1 = _tracing.now_ns()
            for i, req in enumerate(running):
                if req.trace is not None and req.trace.get("sampled"):
                    req.trace_spans.append(_tracing.make_span(
                        req.trace, "decode_step", decode_t0, decode_t1,
                        parent_id=req._span_root["span_id"], component="engine",
                        step=int(self.steps), batch=len(running),
                        token_idx=int(token_idx[i]),
                        k_accepted=int(accepted_by_req[i]),
                    ))
        self.decode_tokens += emitted

    def _close_trace(self, req: Request, outcome: str) -> None:
        """Close the request's open spans with the terminal ``outcome``; the
        trace's OWNER emits — this engine when it rooted the trace, the
        router (via the replica event stream) when the context was
        propagated in."""
        if req.trace is None:
            return
        if req._span_queue is not None and "t1_ns" not in req._span_queue:
            _tracing.span_close(req._span_queue)
        if req._span_root is not None and "t1_ns" not in req._span_root:
            _tracing.span_close(
                req._span_root, outcome=outcome, tokens=len(req.generated),
                preemptions=int(req.preemptions),
            )
        if req._trace_owner:
            _tracing.finish_trace(
                req.trace, req.trace_spans, forced=outcome != "finished"
            )

    def _finish_request(self, req: Request, now: float) -> None:
        self._close_trace(req, "finished")
        if _metrics.is_enabled():
            _metrics.inc("accelerate_engine_requests_total", outcome="finished")
            if req.first_token_t is not None:
                _metrics.observe("accelerate_engine_ttft_seconds",
                                 req.first_token_t - req.arrival_t)
            _metrics.observe("accelerate_engine_request_latency_seconds",
                             (req.finish_t or now) - req.arrival_t)
        self._emit_completion(req)

    def _emit_completion(self, req: Request) -> None:
        if not tel.is_enabled():
            return
        tel.emit(
            "serving_request",
            rid=req.rid,
            prompt_tokens=int(req.prompt.size),
            new_tokens=len(req.generated),
            latency_s=round((req.finish_t or 0.0) - req.arrival_t, 6),
            ttft_s=round((req.first_token_t or 0.0) - req.arrival_t, 6)
            if req.first_token_t is not None
            else None,
            preemptions=req.preemptions,
        )

    def stats(self) -> dict:
        out = {
            "steps": self.steps,
            "decode_tokens": self.decode_tokens,
            "prefill_tokens": self.prefill_tokens,
            "prefill_calls": self.prefill_calls,
            "preempt_prefill_tokens": self.preempt_prefill_tokens,
            "resume_prefill_tokens": self.resume_prefill_tokens,
            "preemptions": self.scheduler.preemption_count,
            "max_running": self.max_running,
            "mean_occupancy": round(
                self._occupancy_sum / max(self._occupancy_steps, 1), 6
            ),
            **self.jit_cache_sizes(),
            **self.allocator.stats(),
        }
        if self.spec_tokens > 0:
            out.update(
                spec_tokens=self.spec_tokens,
                draft_layers=self.draft_layers,
                draft_proposed_tokens=self.draft_proposed_tokens,
                draft_accepted_tokens=self.draft_accepted_tokens,
                draft_rejected_tokens=(
                    self.draft_proposed_tokens - self.draft_accepted_tokens
                ),
                spec_accept_rate=round(
                    self.draft_accepted_tokens / self.draft_proposed_tokens, 6
                )
                if self.draft_proposed_tokens
                else 0.0,
                spec_accept_hist=self.spec_accept_hist.tolist(),
            )
        if self.prefix_cache:
            # hit rate over PROMPT tokens: cached / (cached + actually
            # prefilled) — the fraction of prefill work the cache deleted
            total = self.prefix_cached_tokens + self.prefill_tokens
            # cow_copies rides in from allocator.stats() above — the
            # allocator's count is the single source (every allocated COW
            # pair is applied in the same step's prefill phase)
            out.update(
                prefill_tokens_saved=self.prefix_cached_tokens,
                prefix_hit_rate=round(self.prefix_cached_tokens / total, 6)
                if total else 0.0,
            )
        return out
