"""The serving router: N replicated engines, one front door, no lost work.

PR 11's :class:`~accelerate_tpu.serving.engine.ServingEngine` decodes fast
but dies alone: a wedged or SIGKILLed engine loses every in-flight request,
and its only overload answer is hard rejection. The router closes both gaps
by treating replicas as preemptible compute (Podracer, PAPERS.md
2104.06272) behind a clean dispatch boundary (the MPMD-disaggregation
router/replica split, PAPERS.md 2412.14374):

- **dispatch** — queued requests go to the HEALTHY replica with the fewest
  outstanding tokens (prompt + remaining budget of everything in flight
  there), bounded per replica so one engine never hoards the queue;
- **health** — every replica event refreshes a heartbeat; a replica whose
  process/thread died, whose worker reported ``fatal``, or whose heartbeat
  went stale while it held work is marked DEAD (and killed, so a wedged
  child doesn't linger). ``drain()`` marks a replica DRAINING: in-flight
  work finishes, nothing new is dispatched — the rolling-restart state.
  Each replica is also a watchdog heartbeat source
  (``serving_replica:<name>``), so a stall produces a flight-recorder dump
  naming the replica;
- **failover** — a DEAD replica's in-flight requests re-queue at the FRONT
  with their ``generated``-so-far (streamed per step by the worker) and
  resume on a survivor via ``ServingEngine.submit(generated=...)``. Because
  sampling is a pure function of (prompt, rng_seed, fold index), the
  retried output is BITWISE-identical to an unfailed run, and terminal
  dedup (a request finalizes exactly once, stale-replica events are
  ignored) makes retry exactly-once;
- **overload** — admission runs through
  :class:`~accelerate_tpu.serving.admission.AdmissionController`:
  token-bucket rate limiting, bounded priority queues, shedding with a
  distinct :attr:`RouterRequestStatus.SHED` outcome, and per-request
  deadlines (expired queued work returns ``EXPIRED`` instead of occupying a
  slot).

``tests/test_router.py`` holds the invariants (chaos SIGKILL + wedge-forever
hang under Poisson load → every admitted request completes exactly once,
bitwise-equal to the single-stream reference; shed paths by priority), and
``make doctor`` check 13 re-proves them end to end.
"""

from __future__ import annotations

import enum
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from ..telemetry import events as tel
from ..telemetry import goodput as _goodput
from ..telemetry import metrics as _metrics
from ..telemetry import tracing as _tracing
from ..telemetry import watchdog as _watchdog
from .admission import PRIORITY_BATCH, AdmissionController
from .replica import ReplicaState

__all__ = ["RouterRequestStatus", "RouterRequest", "ServingRouter"]


class RouterRequestStatus(enum.Enum):
    QUEUED = "queued"        # admitted, waiting for a replica
    DISPATCHED = "dispatched"  # in flight on a replica
    FINISHED = "finished"    # completed exactly once; ``generated`` is final
    SHED = "shed"            # refused by overload control (rate/queue/displaced)
    EXPIRED = "expired"      # deadline passed before dispatch
    FAILED = "failed"        # retries exhausted / engine rejection / no replicas

    @property
    def terminal(self) -> bool:
        return self not in (RouterRequestStatus.QUEUED, RouterRequestStatus.DISPATCHED)


_rid_counter = itertools.count()


@dataclass(eq=False)  # identity equality: requests are stateful handles
class RouterRequest:
    """One routed request plus its durable progress. ``generated`` is kept
    current from the worker's per-step progress events, which is exactly the
    state failover resume needs."""

    prompt: np.ndarray
    max_new_tokens: int
    rid: str = field(default_factory=lambda: f"q{next(_rid_counter)}")
    eos_token_id: Optional[int] = None
    rng_seed: int = 0
    priority: int = PRIORITY_BATCH
    deadline_t: Optional[float] = None  # absolute, in router-clock time
    arrival_t: float = 0.0

    status: RouterRequestStatus = RouterRequestStatus.QUEUED
    generated: "list[int]" = field(default_factory=list)
    replica: Optional[str] = None
    retries: int = 0  # failover re-dispatches survived
    # len(generated) at the moment of the CURRENT dispatch: until progress
    # moves past it, the new replica still owes the (re-)prefill of
    # prompt + generated — the load metric must count that work
    _resume_from: int = field(default=0, repr=False)
    preemptions: int = 0
    error: Optional[str] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    # distributed tracing (telemetry/tracing.py): the root context and this
    # request's assembled spans — router-side admission/dispatch spans plus
    # the engine spans shipped back in the replica's ``done`` event. None /
    # empty while tracing is disarmed.
    trace: Optional[dict] = field(default=None, repr=False)
    trace_spans: "list[dict]" = field(default_factory=list, repr=False)
    _span_root: Optional[dict] = field(default=None, repr=False)
    _span_dispatch: Optional[dict] = field(default=None, repr=False)
    # disaggregated serving (serving/disagg.py): which prefill replica ran
    # the prefill hop, how long that hop took, when its KV handoff landed at
    # the router, and the verified wire-form handoff awaiting (re-)dispatch
    # to the decode tier (kept until FINISHED so a decode-replica death can
    # re-deliver it — adopt_block dedup makes re-delivery idempotent)
    prefill_replica: Optional[str] = None
    prefill_s: Optional[float] = None
    handoff_t: Optional[float] = None
    _handoff: Optional[dict] = field(default=None, repr=False)
    _dispatch_t: float = field(default=0.0, repr=False)
    # correctness canary (serving/canary.py): probe requests bypass
    # admission, SLO observation, failover, and every user-facing counter —
    # their only job is the bitwise verdict on ONE replica
    canary: bool = False
    _golden: Optional[Any] = field(default=None, repr=False)

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)

    @property
    def cost_tokens(self) -> int:
        """Worst-case token cost, what admission charges."""
        return int(self.prompt.size) + self.max_new_tokens

    @property
    def remaining_tokens(self) -> int:
        return max(0, self.max_new_tokens - len(self.generated))

    @property
    def done_decoding(self) -> bool:
        """All tokens already streamed back — nothing left to resume."""
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (
            self.eos_token_id is not None
            and bool(self.generated)
            and self.generated[-1] == self.eos_token_id
        )

    def output_ids(self) -> np.ndarray:
        """prompt + generated, the ``greedy_generate`` layout."""
        return np.concatenate([self.prompt, np.asarray(self.generated, np.int32)])


class ServingRouter:
    """Health-checked dispatch over replicated serving engines."""

    def __init__(
        self,
        replicas: "list",
        *,
        admission: Optional[AdmissionController] = None,
        health_timeout_s: float = 5.0,
        max_retries: int = 3,
        max_outstanding_per_replica: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        self_heal: bool = False,
        max_respawns_per_replica: int = 2,
        respawn_backoff_base_s: float = 0.1,
        respawn_backoff_max_s: float = 30.0,
        slo_monitor: Optional[Any] = None,
        slo_eval_interval_s: float = 1.0,
        autoscaler: Optional[Any] = None,
        canary: Optional[Any] = None,
    ):
        if not replicas:
            raise ValueError("need at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        self.replicas: "dict[str, Any]" = {r.name: r for r in replicas}
        self.clock = clock
        self.admission = admission or AdmissionController(clock=clock)
        self.health_timeout_s = float(health_timeout_s)
        self.max_retries = int(max_retries)
        self.max_outstanding_per_replica = max_outstanding_per_replica
        now = clock()
        self._last_event: "dict[str, float]" = {n: now for n in self.replicas}
        self._inflight: "dict[str, RouterRequest]" = {}
        # cumulative counters (the telemetry records carry these, so the
        # report section can take a max instead of re-summing)
        self.dispatched = 0
        self.completed = 0
        self.failed = 0
        self.expired = 0
        self.shed = 0
        self.failovers = 0
        self.shed_by_reason: "dict[str, int]" = {}
        self._per_replica: "dict[str, dict]" = {
            n: {"dispatched": 0, "completed": 0, "failovers": 0, "respawns": 0}
            for n in self.replicas
        }
        # Self-healing (supervisor semantics at router scope): a DEAD replica
        # with a stored spec is respawned under a bounded per-replica budget
        # with exponential backoff, so a chaos-killed fleet heals back to N
        # instead of shrinking. Respawned engines warm-boot from the
        # persistent compile cache when ReplicaSpec.compile_cache_dir is set.
        self.self_heal = bool(self_heal)
        self.max_respawns_per_replica = int(max_respawns_per_replica)
        self.respawn_backoff_base_s = float(respawn_backoff_base_s)
        self.respawn_backoff_max_s = float(respawn_backoff_max_s)
        self.respawns = 0
        self._respawn_not_before: "dict[str, float]" = {}
        # replicas the operator put in DRAINING before they died: a requested
        # scale-down must never be undone by a self-heal respawn
        self._decommissioned: "set[str]" = set()
        # live observability (PR 15): tracing/metrics arm from the env (both
        # None-branch no-ops when unconfigured); an optional
        # telemetry.slo.SLOMonitor turns per-request outcomes into burn-rate
        # evaluation, and a replica BURNING its fast ttft window counts
        # toward DRAINING pressure in _dispatch.
        _tracing.maybe_arm_from_env()
        _metrics.maybe_enable_from_env()
        self.slo_monitor = slo_monitor
        self.slo_eval_interval_s = float(slo_eval_interval_s)
        self._last_slo_eval = float("-inf")
        self._burning_replicas: "set[str]" = set()
        #: the most recent burn-rate evaluation (list of per-objective
        #: records) — the autoscaler's trigger input
        self.last_slo_results: "list[dict]" = []
        # optional serving/autoscaler.py policy, consulted once per poll
        # right after the burn-rate evaluation it keys off
        self.autoscaler = autoscaler
        # optional serving/canary.py probe: periodic golden requests whose
        # bitwise verdict feeds the same DRAINING pressure as SLO burn
        self.canary = canary
        self._canary_failed: "set[str]" = set()
        self._canary_cursor = 0
        self.canary_inconclusive = 0
        for n in self.replicas:
            _watchdog.register(f"serving_replica:{n}")

    # -- submission ----------------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        eos_token_id: Optional[int] = None,
        rng_seed: int = 0,
        priority: int = PRIORITY_BATCH,
        deadline_s: Optional[float] = None,
        arrival_t: Optional[float] = None,
    ) -> RouterRequest:
        """Admit-or-shed one request. Always returns the handle — check
        ``status``: SHED means overload control refused it NOW (distinct
        from any failure), QUEUED means the router owns it until a terminal
        state."""
        now = self.clock() if arrival_t is None else arrival_t
        req = RouterRequest(
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id,
            rng_seed=rng_seed,
            priority=priority,
            deadline_t=(now + deadline_s) if deadline_s is not None else None,
            arrival_t=now,
        )
        admission_t0 = 0
        if _tracing.is_armed():
            req.trace = _tracing.new_trace()
            req._span_root = _tracing.span_open(
                req.trace, "request", component="router", rid=req.rid,
                prompt_tokens=int(req.prompt.size),
                max_new_tokens=int(req.max_new_tokens),
                priority=int(req.priority),
            )
            req.trace_spans.append(req._span_root)
            admission_t0 = _tracing.now_ns()
        verdict = self.admission.try_admit(req, cost=req.cost_tokens, now=now)
        if admission_t0:
            req.trace_spans.append(_tracing.make_span(
                req.trace, "admission", admission_t0, _tracing.now_ns(),
                parent_id=req._span_root["span_id"], component="router",
                admitted=bool(verdict.admitted), reason=verdict.reason,
            ))
        for victim in verdict.evicted:
            self._finalize(
                victim, RouterRequestStatus.SHED, now,
                error="shed: displaced by higher-priority admission",
            )
        if not verdict.admitted:
            self._finalize(req, RouterRequestStatus.SHED, now, error=f"shed: {verdict.reason}")
        return req

    # -- the poll loop -------------------------------------------------------

    def poll(self, now: Optional[float] = None) -> "list[RouterRequest]":
        """One router iteration: drain replica events, health-check, expire
        deadlines, dispatch. Returns the requests that reached a terminal
        state during this poll."""
        now = self.clock() if now is None else now
        self._terminal_this_poll: "list[RouterRequest]" = []
        activity = self._drain_events(now)
        activity |= self._check_health(now)
        if self.self_heal:
            activity |= self._heal(now)
        for req in self.admission.expire(now):
            self._finalize(
                req, RouterRequestStatus.EXPIRED, now,
                error="expired: deadline passed before dispatch",
            )
            activity = True
        if (
            self.slo_monitor is not None
            and now - self._last_slo_eval >= self.slo_eval_interval_s
        ):
            # burn-rate evaluation (throttled): emits slo_violation records
            # on episode entry, and refreshes the burning-replica set the
            # dispatch loop treats as DRAINING pressure
            self._last_slo_eval = now
            self.last_slo_results = self.slo_monitor.evaluate(now=now)
            if "ttft" in getattr(self.slo_monitor, "objectives", {}):
                self._burning_replicas = set(
                    self.slo_monitor.burning_sources("ttft", now=now)
                )
        if self.autoscaler is not None:
            activity |= bool(self.autoscaler.maybe_act(self, now))
        if self.canary is not None:
            activity |= self._canary_tick(now)
        activity |= self._dispatch(now)
        if activity and _metrics.is_enabled():
            _metrics.set_gauge("accelerate_router_queue_depth", self.admission.depth)
            _metrics.set_gauge("accelerate_router_inflight", len(self._inflight))
            _metrics.observe("accelerate_router_queue_depth_hist", self.admission.depth,
                             buckets=_metrics.DEPTH_BUCKETS)
            _metrics.maybe_snapshot()
        if activity and tel.is_enabled():
            self._emit_poll(now)
        return self._terminal_this_poll

    def run(
        self, *, timeout_s: float = 300.0, poll_interval_s: float = 0.002
    ) -> "list[RouterRequest]":
        """Poll until every admitted request is terminal; returns them in
        finish order. Raises RuntimeError on wall-clock timeout (the router
        must never wedge silently — that is the failure mode this PR
        exists to kill)."""
        done: "list[RouterRequest]" = []
        deadline = time.monotonic() + timeout_s
        while True:
            done.extend(self.poll())
            if not self._inflight and self.admission.depth == 0:
                return done
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"router not idle after {timeout_s}s: "
                    f"{len(self._inflight)} in flight, {self.admission.depth} queued"
                )
            time.sleep(poll_interval_s)

    def wait_ready(self, timeout_s: float = 300.0, poll_interval_s: float = 0.01) -> None:
        """Block until no replica is STARTING (each is HEALTHY — warmed and
        compiled — or already DEAD). Benchmarks and tests call this so the
        measured window never includes warmup, and so load balancing sees
        the whole fleet instead of whichever replica compiled first."""
        deadline = time.monotonic() + timeout_s
        while any(r.state is ReplicaState.STARTING for r in self.replicas.values()):
            self.poll()
            if time.monotonic() > deadline:
                starting = [
                    n for n, r in self.replicas.items()
                    if r.state is ReplicaState.STARTING
                ]
                raise RuntimeError(f"replicas never became ready: {starting}")
            time.sleep(poll_interval_s)

    # -- lifecycle -----------------------------------------------------------

    def drain(self, name: str) -> None:
        """Stop dispatching to ``name``; its in-flight work finishes."""
        rep = self.replicas[name]
        if rep.state in (ReplicaState.STARTING, ReplicaState.HEALTHY):
            rep.state = ReplicaState.DRAINING
            self._emit_replica(rep, self.clock())

    def add_replica(self, replica) -> None:
        """Register a freshly spawned replica mid-flight (the autoscaler's
        scale-up path): it joins STARTING, becomes dispatchable at its ready
        event, and participates in health/heal/telemetry like a founding
        member. Re-adding a name revives a decommissioned slot."""
        name = replica.name
        if name in self.replicas and self.replicas[name].state is not ReplicaState.DEAD:
            raise ValueError(f"replica {name!r} is already registered and live")
        self.replicas[name] = replica
        self._last_event[name] = self.clock()  # warmup counts as liveness
        self._per_replica.setdefault(
            name, {"dispatched": 0, "completed": 0, "failovers": 0, "respawns": 0}
        )
        self._decommissioned.discard(name)
        _watchdog.register(f"serving_replica:{name}")
        self._emit_replica(replica, self.clock())

    def close(self) -> None:
        _metrics.snapshot_now()  # persist the final counters for the report
        for n, rep in self.replicas.items():
            _watchdog.unregister(f"serving_replica:{n}")
            try:
                rep.close()
            except Exception:
                rep.kill()

    # -- internals -----------------------------------------------------------

    def _outstanding(self, name: str) -> "list[RouterRequest]":
        return [r for r in self._inflight.values() if r.replica == name]

    def outstanding_tokens(self, name: str) -> int:
        """The dispatch-balancing load metric: remaining new-token budget of
        everything in flight on ``name``, plus the (re-)prefill still owed —
        ``prompt + generated-at-dispatch`` for any request that has not yet
        produced a token on THIS replica (a failover resume re-prefills its
        whole prefix, which is exactly why a freshly burdened survivor must
        not look light)."""
        total = 0
        for r in self._outstanding(name):
            total += r.remaining_tokens
            if len(r.generated) == r._resume_from:
                total += int(r.prompt.size) + r._resume_from
        return total

    def _drain_events(self, now: float) -> bool:
        activity = False
        for name, rep in self.replicas.items():
            events = rep.drain_events()
            if rep.state is ReplicaState.DEAD:
                continue  # drained to drop: a zombie's late results must not
                # double-complete work a survivor now owns
            for ev in events:
                self._last_event[name] = now
                _watchdog.beat(f"serving_replica:{name}")
                kind = ev.get("event")
                if kind == "ready" and rep.state is ReplicaState.STARTING:
                    rep.state = ReplicaState.HEALTHY
                    # warmup compile/cache counts: the autoscaler's warm-join
                    # assertion (join_compiles == 0) reads these
                    rep.ready_info = {k: v for k, v in ev.items() if k != "event"}
                    self._emit_replica(rep, now)
                    activity = True
                elif kind == "step":
                    for rid, toks in (ev.get("progress") or {}).items():
                        req = self._inflight.get(rid)
                        if req is None or req.replica != name:
                            continue
                        if req.first_token_t is None:
                            req.first_token_t = now
                        req.generated.extend(int(t) for t in toks)
                elif kind == "done":
                    req = self._inflight.get(ev.get("rid"))
                    if req is None or req.replica != name:
                        continue  # stale: this request was failed over already
                    del self._inflight[req.rid]
                    if req.canary:
                        # probe verdict path: no counters, no SLO, no trace —
                        # only the bitwise comparison against the golden
                        self._canary_result(req, ev, now)
                        activity = True
                        continue
                    if req.trace is not None:
                        # the engine's spans ride home in the done event; the
                        # router is the trace's single writer
                        req.trace_spans.extend(ev.get("spans") or [])
                        if req._span_dispatch is not None:
                            _tracing.span_close(
                                req._span_dispatch, outcome=str(ev.get("status"))
                            )
                            req._span_dispatch = None
                    if ev.get("status") == "finished":
                        req.generated = [int(t) for t in ev.get("tokens", [])]
                        req.preemptions = int(ev.get("preemptions", 0))
                        self.completed += 1
                        self._per_replica[name]["completed"] += 1
                        self._finalize(req, RouterRequestStatus.FINISHED, now, count=False)
                    else:  # the engine itself rejected it (pool/lattice cap):
                        # no replica can run it — a retry would reject again
                        self._finalize(
                            req, RouterRequestStatus.FAILED, now,
                            error=ev.get("error") or "rejected by engine",
                        )
                    activity = True
                elif kind == "handoff":
                    activity |= self._on_handoff(name, rep, ev, now)
                elif kind == "fatal":
                    self._fail_replica(rep, f"worker died: {ev.get('error')}", now)
                    activity = True
                    break  # remaining events are from a dead worker
        return activity

    def _on_handoff(self, name: str, rep, ev: dict, now: float) -> bool:
        """A prefill-tier worker finished a request's prefill hop and shipped
        its KV. The base router runs no prefill tier — DisaggRouter
        (serving/disagg.py) overrides this with verify + requeue-to-decode;
        here a stray handoff event is dropped like any stale event."""
        return False

    def _check_health(self, now: float) -> bool:
        activity = False
        for name, rep in self.replicas.items():
            if rep.state is ReplicaState.DEAD:
                continue
            if not rep.alive():
                self._fail_replica(rep, "replica process/thread died", now)
                activity = True
                continue
            age = now - self._last_event[name]
            if self._outstanding(name) and age > self.health_timeout_s:
                self._fail_replica(
                    rep, f"heartbeat stale for {age:.1f}s with work in flight", now
                )
                activity = True
        return activity

    def _heal_pending(self) -> bool:
        """True while some DEAD replica can still be respawned — queued work
        must WAIT for the heal instead of being failed loudly."""
        if not self.self_heal:
            return False
        return any(
            rep.state is ReplicaState.DEAD
            and hasattr(rep, "respawn")
            and name not in self._decommissioned
            and self._per_replica[name]["respawns"] < self.max_respawns_per_replica
            for name, rep in self.replicas.items()
        )

    def _heal(self, now: float) -> bool:
        """Respawn DEAD replicas from their stored specs, bounded by
        ``max_respawns_per_replica`` with exponential backoff (the
        supervisor's restart semantics at router scope)."""
        activity = False
        for name, rep in list(self.replicas.items()):
            if rep.state is not ReplicaState.DEAD or not hasattr(rep, "respawn"):
                continue
            if name in self._decommissioned:
                continue  # the operator drained it: its death is a shutdown
            used = self._per_replica[name]["respawns"]
            if used >= self.max_respawns_per_replica:
                continue
            if now < self._respawn_not_before.get(name, 0.0):
                continue
            try:
                fresh = rep.respawn()
            except Exception as exc:
                # an unspawnable replica burns budget too — otherwise a sick
                # host would be retried forever with zero backpressure
                self._per_replica[name]["respawns"] = used + 1
                self._respawn_not_before[name] = now + self._respawn_backoff(used + 1)
                if tel.is_enabled():
                    tel.emit(
                        "serving_replica", replica=name, state="respawn_failed",
                        reason=f"{type(exc).__name__}: {exc}", respawns=used + 1,
                    )
                continue
            self.replicas[name] = fresh
            self._per_replica[name]["respawns"] = used + 1
            self.respawns += 1
            self._respawn_not_before[name] = now + self._respawn_backoff(used + 1)
            self._last_event[name] = now  # STARTING: warmup counts as liveness
            _watchdog.register(f"serving_replica:{name}")
            if tel.is_enabled():
                tel.emit(
                    "serving_replica", replica=name, state="respawned",
                    respawns=used + 1, budget=self.max_respawns_per_replica,
                    prev_reason=getattr(rep, "reason", None),
                )
            activity = True
        return activity

    def _respawn_backoff(self, attempt: int) -> float:
        return min(
            self.respawn_backoff_max_s,
            self.respawn_backoff_base_s * (2.0 ** max(0, attempt - 1)),
        )

    def _fail_replica(self, rep, reason: str, now: float) -> None:
        """DEAD transition + failover of everything in flight there."""
        if rep.state is ReplicaState.DRAINING:
            # dying while drained is the tail end of a requested scale-down —
            # remember that so self-heal never resurrects it
            self._decommissioned.add(rep.name)
        rep.state = ReplicaState.DEAD
        rep.reason = reason
        # a declared-dead replica is diagnosed, not stalling: stop watching
        # it so the watchdog doesn't re-dump a known death every interval
        _watchdog.unregister(f"serving_replica:{rep.name}")
        try:
            rep.kill()  # reap a wedged child; harmless if already gone
        except Exception:
            pass
        if tel.is_enabled():
            tel.emit("serving_replica", replica=rep.name, state="dead", reason=reason)
        self._emit_replica(rep, now)
        _metrics.inc("accelerate_replica_deaths_total", replica=rep.name)
        for req in self._outstanding(rep.name):
            del self._inflight[req.rid]
            if req.canary:
                # a probe's job was to test THIS replica — never failed over
                # (retrying elsewhere would launder the evidence), and a
                # death before the verdict is inconclusive, not a mismatch
                req.status = RouterRequestStatus.FAILED
                req.error = f"canary dropped: {reason}"
                req.finish_t = now
                self.canary_inconclusive += 1
                continue
            req.replica = None
            req.retries += 1
            self.failovers += 1
            self._per_replica[rep.name]["failovers"] += 1
            _metrics.inc("accelerate_failovers_total")
            if req._span_dispatch is not None:
                # the hop that died: closed with the failover verdict so the
                # retry lineage (this span + the next dispatch's) is explicit
                _tracing.span_close(
                    req._span_dispatch, outcome="failover", reason=reason,
                    streamed_tokens=len(req.generated),
                )
                req._span_dispatch = None
            if req.done_decoding:
                # every token was already streamed back before the death —
                # the work is done, only the done event was lost
                self.completed += 1
                self._finalize(req, RouterRequestStatus.FINISHED, now, count=False)
            elif req.retries > self.max_retries:
                self._finalize(
                    req, RouterRequestStatus.FAILED, now,
                    error=f"failed: {req.retries} replica deaths (last: {reason})",
                )
            else:
                req.status = RouterRequestStatus.QUEUED
                self.admission.requeue_front(req)

    # -- correctness canaries (serving/canary.py) ----------------------------

    def _canary_tick(self, now: float) -> bool:
        """Inject the next due golden probe into one healthy replica.

        Probes round-robin across the dispatchable fleet so every replica
        gets its turn under the bitwise lens. They bypass admission (a
        saturated queue must not starve correctness checking) but respect
        replica capacity — a probe that has to wait simply retries next
        poll, with the schedule advancing only on injection."""
        probe = self.canary
        if not probe.due(now):
            return False
        # probes only target unified "serving"-role replicas: a disaggregated
        # tier member runs half a request by construction (prefill-only or
        # handoff-fed decode), so a direct golden submit is not well-formed
        # there — on a pure disagg fleet the canary plane is a no-op
        # (see DisaggRouter's docstring)
        targets = sorted(
            r.name for r in self.replicas.values()
            if r.state is ReplicaState.HEALTHY
            and getattr(r, "role", "serving") == "serving"
            and len(self._outstanding(r.name)) < self._replica_capacity(r)
        )
        if not targets:
            return False
        name = targets[self._canary_cursor % len(targets)]
        self._canary_cursor += 1
        probe.schedule(now)
        golden = probe.next_golden()
        req = RouterRequest(
            prompt=np.asarray(golden.prompt, np.int32),
            max_new_tokens=golden.max_new_tokens,
            rid=f"canary-{self._canary_cursor}",
            rng_seed=golden.rng_seed,
            arrival_t=now,
        )
        req.canary = True
        req._golden = golden
        req.replica = name
        req._dispatch_t = now
        req.status = RouterRequestStatus.DISPATCHED
        self._inflight[req.rid] = req
        self.replicas[name].submit({
            "rid": req.rid,
            "prompt": [int(t) for t in req.prompt],
            "max_new": req.max_new_tokens,
            "eos": req.eos_token_id,
            "rng_seed": req.rng_seed,
            "generated": [],
        })
        return True

    def _canary_result(self, req: RouterRequest, ev: dict, now: float) -> None:
        """Bitwise verdict on a returned probe. Every probe emits a
        ``canary`` record; a mismatch additionally emits ``canary_failure``
        naming the first differing token, joins the DRAINING-pressure set,
        and (by default) drains the replica outright — wrong tokens are a
        harder failure than a burning SLO."""
        probe = self.canary
        golden = req._golden
        name = req.replica
        req.finish_t = now
        if ev.get("status") != "finished":
            # the engine rejected the probe (pool/lattice cap): that says
            # nothing about token correctness — inconclusive, no verdict
            self.canary_inconclusive += 1
            req.status = RouterRequestStatus.FAILED
            req.error = str(ev.get("error") or "rejected by engine")
            if tel.is_enabled():
                tel.emit(
                    "canary", replica=name, rid=req.rid, golden=golden.name,
                    result="inconclusive", error=req.error,
                )
            return
        tokens = [int(t) for t in ev.get("tokens", [])]
        req.generated = tokens
        req.status = RouterRequestStatus.FINISHED
        mismatch = probe.check(golden, tokens)
        ok = mismatch is None
        probe.record_result(name, ok)
        result = "match" if ok else "mismatch"
        if tel.is_enabled():
            tel.emit("canary", replica=name, rid=req.rid, golden=golden.name,
                     result=result)
        if _metrics.is_enabled():
            _metrics.inc("accelerate_canary_probes_total",
                         replica=name, result=result)
        if ok:
            return
        self._canary_failed.add(name)
        drained = False
        rep = self.replicas.get(name)
        if probe.drain_on_failure and rep is not None:
            if rep.state in (ReplicaState.STARTING, ReplicaState.HEALTHY):
                self.drain(name)
                drained = True
        if tel.is_enabled():
            tel.emit("canary_failure", replica=name, rid=req.rid,
                     drained=drained, **mismatch)
        if _metrics.is_enabled():
            _metrics.inc("accelerate_canary_failures_total", replica=name)

    def _replica_capacity(self, rep) -> int:
        if self.max_outstanding_per_replica is not None:
            return self.max_outstanding_per_replica
        max_slots = getattr(getattr(rep, "spec", None), "max_slots", 4)
        return 2 * max_slots  # slots busy + one queued wave behind them

    def _dispatch(self, now: float) -> bool:
        # prefill-role replicas never take plain dispatches: they belong to
        # DisaggRouter's two-tier _dispatch override — the filter keeps a
        # mixed fleet safe even if someone hands one to the base router
        live = [
            r for r in self.replicas.values()
            if r.state in (ReplicaState.STARTING, ReplicaState.HEALTHY)
            and getattr(r, "role", "serving") != "prefill"
        ]
        if not live:
            if self._heal_pending():
                # a respawn is coming (budget remains): queued work waits for
                # the healed replica instead of failing
                return False
            # every replica is DEAD or DRAINING — and DRAINING never returns
            # to HEALTHY, so queued work can never run. Fail it loudly (the
            # in-flight work on DRAINING replicas still finishes normally);
            # the alternative is wedging until run()'s timeout.
            draining = any(
                r.state is ReplicaState.DRAINING for r in self.replicas.values()
            )
            reason = (
                "failed: no dispatchable replicas (all draining or dead)"
                if draining else "failed: no live replicas"
            )
            failed_any = False
            while True:
                req = self.admission.pop_next()
                if req is None:
                    return failed_any
                self._finalize(req, RouterRequestStatus.FAILED, now, error=reason)
                failed_any = True
        activity = False
        while True:
            ready = [
                r for r in live
                if r.state is ReplicaState.HEALTHY
                and len(self._outstanding(r.name)) < self._replica_capacity(r)
            ]
            if not ready:
                return activity
            req = self.admission.pop_next()
            if req is None:
                return activity
            if req.deadline_t is not None and req.deadline_t < now:
                self._finalize(
                    req, RouterRequestStatus.EXPIRED, now,
                    error="expired: deadline passed before dispatch",
                )
                activity = True
                continue
            # a replica burning its fast SLO window (self._burning_replicas)
            # or carrying a canary mismatch (self._canary_failed) counts
            # toward DRAINING pressure: it loses ties and is only chosen
            # when every ready replica is suspect — never a deadlock,
            # always a lean away from the replica under a cloud
            target = min(
                ready,
                key=lambda r: (
                    r.name in self._burning_replicas
                    or r.name in self._canary_failed,
                    self.outstanding_tokens(r.name),
                ),
            )
            req.replica = target.name
            req._resume_from = len(req.generated)
            req._dispatch_t = now
            req.status = RouterRequestStatus.DISPATCHED
            self._inflight[req.rid] = req
            self.dispatched += 1
            self._per_replica[target.name]["dispatched"] += 1
            payload = {
                "rid": req.rid,
                "prompt": [int(t) for t in req.prompt],
                "max_new": req.max_new_tokens,
                "eos": req.eos_token_id,
                "rng_seed": req.rng_seed,
                "generated": list(req.generated),
            }
            if req.trace is not None:
                # one dispatch span per attempt: a failed-over request shows
                # its full retry lineage (attempt numbers, replicas) as
                # sibling dispatch spans under one trace_id
                req._span_dispatch = _tracing.span_open(
                    req.trace, "dispatch", parent_id=req._span_root["span_id"],
                    component="router", replica=target.name,
                    attempt=int(req.retries),
                    resume_tokens=len(req.generated),
                )
                req.trace_spans.append(req._span_dispatch)
                wire_ctx = _tracing.TraceContext(req.trace).child(
                    req._span_dispatch["span_id"]
                )
                if req.retries > 0:
                    # a failover survivor's trace is FORCE-emitted at finalize
                    # — flip sampled on for this hop so the engine records
                    # full decode detail instead of the unsampled skeleton
                    wire_ctx = _tracing.TraceContext(wire_ctx, sampled=True)
                payload["trace"] = dict(wire_ctx)  # plain dict on the wire:
                # both transports JSON it verbatim
            target.submit(payload)
            activity = True

    def _finalize(
        self,
        req: RouterRequest,
        status: RouterRequestStatus,
        now: float,
        error: Optional[str] = None,
        count: bool = True,
    ) -> None:
        req.status = status
        req.finish_t = now
        if error is not None:
            req.error = error
        if count:
            if status is RouterRequestStatus.SHED:
                self.shed += 1
                reason = (error or "shed: ?").split("shed: ", 1)[-1]
                self.shed_by_reason[reason] = self.shed_by_reason.get(reason, 0) + 1
            elif status is RouterRequestStatus.EXPIRED:
                self.expired += 1
            elif status is RouterRequestStatus.FAILED:
                self.failed += 1
        if req.trace is not None:
            # close any dangling dispatch span (e.g. FAILED with the replica
            # gone) and the root, then emit: sampled traces always, and
            # FORCED for the traces an operator will ask about — shed,
            # expired, failed, or failover survivors
            if req._span_dispatch is not None:
                _tracing.span_close(req._span_dispatch, outcome=status.value)
                req._span_dispatch = None
            _tracing.span_close(
                req._span_root, outcome=status.value, retries=int(req.retries),
                tokens=len(req.generated), error=req.error,
            )
            _tracing.finish_trace(
                req.trace, req.trace_spans,
                forced=status is not RouterRequestStatus.FINISHED or req.retries > 0,
            )
        if _metrics.is_enabled():
            _metrics.inc("accelerate_router_requests_total", outcome=status.value)
            if status is RouterRequestStatus.FINISHED:
                _metrics.observe("accelerate_router_request_latency_seconds",
                                 now - req.arrival_t)
                if req.first_token_t is not None:
                    _metrics.observe("accelerate_router_ttft_seconds",
                                     req.first_token_t - req.arrival_t)
        if self.slo_monitor is not None:
            self._observe_slo(req, status, now)
        terminal = getattr(self, "_terminal_this_poll", None)
        if terminal is not None and status is not RouterRequestStatus.SHED:
            terminal.append(req)
        if tel.is_enabled():
            record = dict(
                phase="request",
                rid=req.rid,
                outcome=status.value,
                priority=req.priority,
                replica=req.replica,
                retries=req.retries,
                prompt_tokens=int(req.prompt.size),
                new_tokens=len(req.generated),
                latency_s=round(now - req.arrival_t, 6),
                ttft_s=round(req.first_token_t - req.arrival_t, 6)
                if req.first_token_t is not None
                else None,
                error=req.error,
            )
            if req.prefill_replica is not None:
                # disaggregated request: which prefill replica ran the prefill
                # hop and how long it took — the report's per-tier breakdown
                record["prefill_replica"] = req.prefill_replica
                record["prefill_s"] = (
                    round(req.prefill_s, 6) if req.prefill_s is not None else None
                )
            tel.emit("router", **record)
            if status in (RouterRequestStatus.FAILED, RouterRequestStatus.EXPIRED) \
                    and (req.replica is not None or req.generated):
                # abandoned after compute was spent on it: everything prefilled
                # or decoded for this request is badput in the token ledger
                _goodput.note_serving_step(
                    0.0,
                    wasted_tokens=int(req.prompt.size) + len(req.generated),
                )

    def _observe_slo(self, req: RouterRequest, status: RouterRequestStatus,
                     now: float) -> None:
        """Feed one terminal outcome into the SLO monitor (only objectives
        the monitor actually declares): ``shed_rate`` sees every submission,
        ``availability`` and ``ttft`` see admitted work (a request that died
        without a first token is an over-threshold ttft by definition)."""
        objectives = getattr(self.slo_monitor, "objectives", {})
        shed = status is RouterRequestStatus.SHED
        if "shed_rate" in objectives:
            self.slo_monitor.observe("shed_rate", good=not shed, now=now)
        if shed:
            return
        # per-replica attribution only for requests that lived on ONE
        # replica: a failover survivor's ttft/latency was inflated by the
        # DEAD replica (death detection + re-prefill), and blaming the
        # healthy survivor would drain exactly the replica that absorbed
        # the work — retried requests count toward the GLOBAL burn only
        source = req.replica if req.retries == 0 else None
        if "availability" in objectives:
            self.slo_monitor.observe(
                "availability",
                good=status is RouterRequestStatus.FINISHED,
                source=source,
                now=now,
            )
        if "ttft" in objectives:
            ttft = (
                req.first_token_t - req.arrival_t
                if req.first_token_t is not None
                else float("inf")
            )
            self.slo_monitor.observe("ttft", value=ttft, source=source, now=now)

    # -- telemetry -----------------------------------------------------------

    def _emit_replica(self, rep, now: float) -> None:
        if not tel.is_enabled():
            return
        per = self._per_replica[rep.name]
        tel.emit(
            "serving_replica",
            replica=rep.name,
            state=rep.state.value,
            role=getattr(rep, "role", "serving"),
            transport=getattr(rep, "transport", "?"),
            outstanding_requests=len(self._outstanding(rep.name)),
            outstanding_tokens=self.outstanding_tokens(rep.name),
            heartbeat_age_s=round(now - self._last_event[rep.name], 3),
            dispatched=per["dispatched"],
            completed=per["completed"],
            failovers=per["failovers"],
            respawns=per["respawns"],
        )

    def _emit_poll(self, now: float) -> None:
        tel.emit(
            "router",
            phase="poll",
            queued=self.admission.depth,
            queued_by_priority={str(k): v for k, v in self.admission.depth_by_priority().items()},
            inflight=len(self._inflight),
            dispatched=self.dispatched,
            completed=self.completed,
            shed=self.shed,
            expired=self.expired,
            failed=self.failed,
            failovers=self.failovers,
            replicas={n: r.state.value for n, r in self.replicas.items()},
        )
        for rep in self.replicas.values():
            self._emit_replica(rep, now)

    def stats(self) -> dict:
        return {
            "replicas": {n: r.state.value for n, r in self.replicas.items()},
            "queued": self.admission.depth,
            "inflight": len(self._inflight),
            "dispatched": self.dispatched,
            "completed": self.completed,
            "failed": self.failed,
            "expired": self.expired,
            "shed": self.shed,
            "shed_by_reason": dict(self.shed_by_reason),
            "failovers": self.failovers,
            "respawns": self.respawns,
            "per_replica": {n: dict(v) for n, v in self._per_replica.items()},
            "canary": (
                dict(self.canary.stats(),
                     inconclusive=self.canary_inconclusive,
                     failed_replicas=sorted(self._canary_failed))
                if self.canary is not None
                else None
            ),
        }
