"""Production serving: continuous batching over a paged KV cache.

The serve-many-concurrent-requests counterpart of ``generation.py``'s
single-stream decode (ROADMAP item 1). Three pillars:

- :mod:`~accelerate_tpu.serving.kv_pager` — fixed-size KV blocks in one
  preallocated device pool, host-side block allocator, paged attention;
- :mod:`~accelerate_tpu.serving.scheduler` — step-granular admission,
  immediate completion/backfill, LIFO preemption with persisted resume;
- :mod:`~accelerate_tpu.serving.engine` — the
  :class:`~accelerate_tpu.serving.engine.ServingEngine` step loop, compiled
  only over the :mod:`~accelerate_tpu.serving.buckets` shape lattice so
  admission churn never recompiles.

See ``docs/serving.md`` for the guide and ``benchmarks/serving/`` for the
continuous-vs-static Poisson-load benchmark (``make bench-serve``).
"""

from .buckets import BucketLattice
from .engine import ServingEngine, paged_forward
from .kv_pager import (
    NULL_BLOCK,
    BlockAllocator,
    BlockAllocatorError,
    BlockPoolExhausted,
    init_block_pool,
    paged_attention,
)
from .scheduler import Request, RequestStatus, Scheduler, SchedulingError

__all__ = [
    "BucketLattice",
    "ServingEngine",
    "paged_forward",
    "NULL_BLOCK",
    "BlockAllocator",
    "BlockAllocatorError",
    "BlockPoolExhausted",
    "init_block_pool",
    "paged_attention",
    "Request",
    "RequestStatus",
    "Scheduler",
    "SchedulingError",
]
