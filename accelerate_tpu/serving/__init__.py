"""Production serving: continuous batching over a paged KV cache, replicated
behind a fault-tolerant router.

The serve-many-concurrent-requests counterpart of ``generation.py``'s
single-stream decode (ROADMAP item 1). Five pillars:

- :mod:`~accelerate_tpu.serving.kv_pager` — fixed-size KV blocks in one
  preallocated device pool, host-side block allocator, paged attention;
- :mod:`~accelerate_tpu.serving.scheduler` — step-granular admission,
  immediate completion/backfill, LIFO preemption with persisted resume;
- :mod:`~accelerate_tpu.serving.engine` — the
  :class:`~accelerate_tpu.serving.engine.ServingEngine` step loop, compiled
  only over the :mod:`~accelerate_tpu.serving.buckets` shape lattice so
  admission churn never recompiles;
- :mod:`~accelerate_tpu.serving.replica` — one warmed engine per unit of
  failure (thread- or subprocess-backed), streaming per-step token progress;
- :mod:`~accelerate_tpu.serving.router` +
  :mod:`~accelerate_tpu.serving.admission` — health-checked
  least-outstanding-tokens dispatch over N replicas with deadlines,
  exactly-once token-exact failover, token-bucket admission, priority
  shedding (distinct ``SHED`` status) and bounded-queue backpressure;
- :mod:`~accelerate_tpu.serving.disagg` +
  :mod:`~accelerate_tpu.serving.autoscaler` — disaggregated prefill/decode:
  role-split engines joined by a content-addressed KV handoff
  (:class:`~accelerate_tpu.serving.disagg.KVHandoff` behind a
  :class:`~accelerate_tpu.serving.disagg.KVTransport`), two-tier dispatch
  (:class:`~accelerate_tpu.serving.disagg.DisaggRouter`), and an
  SLO-burn-driven :class:`~accelerate_tpu.serving.autoscaler.
  AutoscalerPolicy` whose scale-ups join warm via compile-cache
  pre-shipping;
- :mod:`~accelerate_tpu.serving.canary` — bitwise correctness canaries:
  golden requests precomputed from the single-stream reference at startup
  and periodically injected by the router
  (:class:`~accelerate_tpu.serving.canary.CanaryProbe`); a mismatching
  replica emits ``canary_failure`` and counts toward DRAINING pressure
  exactly like an SLO-burning one.

See ``docs/serving.md`` for the guide and ``benchmarks/serving/`` for the
continuous-vs-static and replicated Poisson-load benchmarks
(``make bench-serve``).
"""

from .admission import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    AdmissionController,
    AdmissionVerdict,
    TokenBucket,
)
from .buckets import BucketLattice
from .engine import ServingEngine, paged_forward
from .kv_pager import (
    NULL_BLOCK,
    BlockAllocator,
    BlockAllocatorError,
    BlockPoolExhausted,
    PrefixAllocation,
    PrefixPlan,
    init_block_pool,
    paged_attention,
)
from .autoscaler import AutoscalerPolicy, lattice_fns
from .canary import CanaryGolden, CanaryProbe, precompute_goldens
from .disagg import (
    DecodeEngine,
    DisaggRouter,
    KVHandoff,
    KVTransport,
    LocalBlockCopyTransport,
    PrefillEngine,
)
from .replica import LocalReplica, ProcessReplica, ReplicaSpec, ReplicaState
from .router import RouterRequest, RouterRequestStatus, ServingRouter
from .scheduler import Request, RequestStatus, Scheduler, SchedulingError

__all__ = [
    "BucketLattice",
    "ServingEngine",
    "paged_forward",
    "NULL_BLOCK",
    "BlockAllocator",
    "BlockAllocatorError",
    "BlockPoolExhausted",
    "init_block_pool",
    "paged_attention",
    "PrefixPlan",
    "PrefixAllocation",
    "Request",
    "RequestStatus",
    "Scheduler",
    "SchedulingError",
    "PRIORITY_INTERACTIVE",
    "PRIORITY_BATCH",
    "TokenBucket",
    "AdmissionVerdict",
    "AdmissionController",
    "ReplicaState",
    "ReplicaSpec",
    "LocalReplica",
    "ProcessReplica",
    "RouterRequest",
    "RouterRequestStatus",
    "ServingRouter",
    "KVHandoff",
    "KVTransport",
    "LocalBlockCopyTransport",
    "PrefillEngine",
    "DecodeEngine",
    "DisaggRouter",
    "AutoscalerPolicy",
    "lattice_fns",
    "CanaryGolden",
    "CanaryProbe",
    "precompute_goldens",
]
