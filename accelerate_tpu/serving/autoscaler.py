"""SLO-driven autoscaling for the decode tier, warm by construction.

The PR 15 burn-rate monitor turned per-request outcomes into an error-budget
signal; this module closes the loop: an :class:`AutoscalerPolicy` plugged
into the router (``ServingRouter(autoscaler=...)``, consulted once per poll
right after the SLO evaluation it keys off) GROWS the decode tier when the
``ttft`` objective is burning and SHRINKS it after sustained idleness —
through the exact replica machinery the PR 13 self-heal path uses
(spawn-from-spec, ``router.add_replica``, drain-to-decommission).

Scale-up is **warm by construction**: before the joiner boots, the policy
pre-ships the relevant compile-cache entries
(:func:`~accelerate_tpu.compile_cache.preship` — exactly the joiner's
warmup lattice, :func:`lattice_fns`) into the joiner's cache directory, so
its warmup is all cache hits and ``join_compiles == 0``. The joiner's ready
event carries its cache outcomes (``router.replicas[name].ready_info``),
which is how :meth:`AutoscalerPolicy.maybe_act` asserts the warm join and
how the bench payload reports it.

Hysteresis, all on an injectable clock (tested on a synthetic one):

- grow only while the ``ttft`` objective is VIOLATING (both burn windows
  over threshold — the monitor's own episode hysteresis), at most one
  pending join at a time, never past ``max_decode``;
- shrink only after ``idle_shrink_after_s`` of continuous empty
  queue + zero in-flight, never below ``min_decode``;
- every action arms ``cooldown_s`` before the next one, so a burn episode
  that outlives one scale-up cannot flap the fleet.

Every decision is one ``autoscale`` telemetry record (schema in
``docs/telemetry.md``); the report CLI renders them as the ``autoscaler``
section.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

from ..telemetry import events as tel
from ..telemetry import goodput as _goodput
from ..telemetry import metrics as _metrics
from .replica import ReplicaState, ReplicaSpec

__all__ = ["AutoscalerPolicy", "lattice_fns"]


def lattice_fns(spec: ReplicaSpec) -> "set[str]":
    """The compile-cache ``fn`` names a replica built from ``spec`` warms —
    the exact pre-ship set (shipping anything else wastes joiner disk;
    shipping less makes the join cold). Mirrors the engine's lattice
    derivation, including the default power-of-two lattice when the spec
    pins no buckets."""
    lat = spec.lattice()
    if lat is None:
        from .buckets import BucketLattice

        config = spec.config()
        mbps = spec.max_blocks_per_seq
        if mbps is None:
            mbps = spec.num_blocks - 1  # allocator.usable_blocks
        max_prefill = min(config.max_seq_len, mbps * spec.block_size)
        lat = BucketLattice.from_limits(spec.max_slots, mbps, max_prefill)
    fns = {f"serving_prefill[{S}x{W}]" for S, W in lat.prefill_points()}
    fns |= {f"serving_decode[{B}x{W}]" for B, W in lat.decode_points()}
    fns |= {"serving_cow", "serving_land"}
    return fns


class AutoscalerPolicy:
    """Grow/shrink the decode tier off the router's burn-rate signal.

    ``template_spec`` is the recipe for joiners (its ``role`` is forced to
    ``"decode"`` and its ``compile_cache_dir`` pointed at the joiner's own
    pre-shipped directory); ``spawn(name, spec)`` builds the replica
    (defaults to :class:`~accelerate_tpu.serving.replica.LocalReplica`).
    ``source_cache_dir`` names the warm cache to pre-ship from — typically
    the founding decode replicas' directory; ``joiner_cache_dir(name)``
    maps a joiner to its cache directory (default: share the source
    directory, which is already warm by definition)."""

    def __init__(
        self,
        template_spec: ReplicaSpec,
        *,
        spawn: Optional[Callable[[str, ReplicaSpec], Any]] = None,
        min_decode: int = 1,
        max_decode: int = 4,
        cooldown_s: float = 30.0,
        idle_shrink_after_s: float = 60.0,
        source_cache_dir: Optional[str] = None,
        joiner_cache_dir: Optional[Callable[[str], str]] = None,
        clock: Callable[[], float] = time.monotonic,
        name_prefix: str = "scale",
    ):
        if min_decode < 1:
            raise ValueError(f"min_decode must be >= 1, got {min_decode}")
        if max_decode < min_decode:
            raise ValueError(f"max_decode={max_decode} < min_decode={min_decode}")
        self.template_spec = template_spec
        self.spawn = spawn or self._default_spawn
        self.min_decode = int(min_decode)
        self.max_decode = int(max_decode)
        self.cooldown_s = float(cooldown_s)
        self.idle_shrink_after_s = float(idle_shrink_after_s)
        self.source_cache_dir = source_cache_dir
        self.joiner_cache_dir = joiner_cache_dir
        self.clock = clock
        self.name_prefix = name_prefix
        #: every decision, in order — the bench payload and tests read this
        self.events: "list[dict]" = []
        self.scale_ups = 0
        self.scale_downs = 0
        self._counter = 0
        self._cooldown_until = float("-inf")
        self._idle_since: Optional[float] = None
        #: joiner name -> spawn time, while its warmup is still running
        self._pending: "dict[str, float]" = {}

    @staticmethod
    def _default_spawn(name: str, spec: ReplicaSpec):
        from .replica import LocalReplica

        return LocalReplica(name, spec)

    # -- the per-poll hook ---------------------------------------------------

    def maybe_act(self, router, now: Optional[float] = None) -> bool:
        """One autoscaling decision against ``router``'s current state.
        Called by the router's poll loop; safe to call every poll — all the
        hysteresis lives here. Returns True when anything happened."""
        now = self.clock() if now is None else now
        acted = self._note_joins(router, now)
        decode_live = [
            r for r in router.replicas.values()
            if getattr(r, "role", "serving") != "prefill"
            and r.state in (ReplicaState.STARTING, ReplicaState.HEALTHY)
        ]
        # -- grow: the ttft objective is in a burn episode -------------------
        burn = next(
            (
                rec for rec in getattr(router, "last_slo_results", [])
                if rec.get("slo") == "ttft" and rec.get("violating")
            ),
            None,
        )
        if (
            burn is not None
            and now >= self._cooldown_until
            and not self._pending
            and len(decode_live) < self.max_decode
        ):
            self._scale_up(router, now, burn)
            return True
        # -- shrink: sustained idleness --------------------------------------
        idle = router.admission.depth == 0 and not router._inflight
        if not idle:
            self._idle_since = None
            return acted
        if self._idle_since is None:
            self._idle_since = now
            return acted
        if (
            now - self._idle_since >= self.idle_shrink_after_s
            and now >= self._cooldown_until
            and not self._pending
            and len(decode_live) > self.min_decode
        ):
            self._scale_down(router, now, decode_live)
            return True
        return acted

    # -- internals -----------------------------------------------------------

    def _record(self, router, now: float, **fields) -> dict:
        rec = {"t": now, **fields}
        self.events.append(rec)
        _metrics.inc("accelerate_autoscale_actions_total",
                     action=fields.get("action", "?"))
        if tel.is_enabled():
            tel.emit("autoscale", **{k: v for k, v in rec.items() if k != "t"},
                     decode_replicas=len([
                         r for r in router.replicas.values()
                         if getattr(r, "role", "serving") != "prefill"
                         and r.state in (ReplicaState.STARTING, ReplicaState.HEALTHY)
                     ]))
        return rec

    def _note_joins(self, router, now: float) -> bool:
        """Resolve pending joins: a joiner that reached HEALTHY reports its
        time-to-ready and whether the join was warm (zero compiles — every
        warmup point was a cache hit); one that died reports the failure
        and releases the pending slot so the next burn can retry."""
        acted = False
        for name in list(self._pending):
            rep = router.replicas.get(name)
            if rep is None or rep.state is ReplicaState.DEAD:
                self._pending.pop(name)
                self._record(router, now, action="join_failed", replica=name,
                             reason=getattr(rep, "reason", "replica missing"))
                acted = True
                continue
            if rep.state is not ReplicaState.HEALTHY:
                continue  # still warming
            spawned = self._pending.pop(name)
            info = getattr(rep, "ready_info", None) or {}
            join_compiles = sum(
                int(info.get(k, 0))
                for k in ("cache_miss", "cache_uncached", "cache_error")
            )
            self._record(
                router, now,
                action="join_ready",
                replica=name,
                time_to_ready_s=round(now - spawned, 6),
                join_compiles=join_compiles,
                warm=join_compiles == 0,
            )
            # the joiner's warm-up window is capacity the fleet paid for but
            # could not serve with — scaleup_wait in the goodput taxonomy
            _goodput.note("scaleup_wait", now - spawned)
            acted = True
        return acted

    def _scale_up(self, router, now: float, burn: dict) -> None:
        from .. import compile_cache as _ccache

        self._counter += 1
        name = f"{self.name_prefix}{self._counter}"
        spec = dataclasses.replace(self.template_spec, role="decode")
        preshipped = None
        if self.source_cache_dir is not None:
            dst = (
                self.joiner_cache_dir(name)
                if self.joiner_cache_dir is not None
                else self.source_cache_dir
            )
            spec = dataclasses.replace(spec, compile_cache_dir=dst)
            if dst != self.source_cache_dir:
                # push exactly the joiner's warmup lattice into its cache dir
                # BEFORE boot — the warmup then hits on every point
                preshipped = _ccache.preship(
                    self.source_cache_dir, dst, fns=lattice_fns(spec)
                )
        replica = self.spawn(name, spec)
        router.add_replica(replica)
        self._pending[name] = now
        self._cooldown_until = now + self.cooldown_s
        self.scale_ups += 1
        self._record(
            router, now,
            action="scale_up",
            replica=name,
            trigger="ttft_burn",
            fast_burn=burn.get("fast_burn"),
            burn_threshold=burn.get("burn_threshold"),
            preshipped=preshipped,
        )

    def _scale_down(self, router, now: float, decode_live: "list") -> None:
        """Retire one decode replica: newest joiner first (founding members
        are the steady-state fleet), least-loaded as the tiebreak. Drain +
        stop — the worker exits once told, the router's health check books
        the death as a decommission (DRAINING death never self-heals)."""
        victim = max(
            decode_live,
            key=lambda r: (
                r.name.startswith(self.name_prefix),
                -len(router._outstanding(r.name)),
                r.name,
            ),
        )
        idle_s = now - (self._idle_since if self._idle_since is not None else now)
        router.drain(victim.name)
        victim.stop()
        self._idle_since = None
        self._cooldown_until = now + self.cooldown_s
        self.scale_downs += 1
        self._record(
            router, now,
            action="scale_down",
            replica=victim.name,
            trigger="sustained_idle",
            idle_s=round(idle_s, 6),
        )

    def stats(self) -> dict:
        return {
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "pending_joins": sorted(self._pending),
            "events": list(self.events),
        }
