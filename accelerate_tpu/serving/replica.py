"""Engine replicas: one warmed :class:`~accelerate_tpu.serving.engine.
ServingEngine` per unit of failure, behind a transport the router can watch.

The Podracer lesson (PAPERS.md, 2104.06272) applied to serving: treat each
engine as PREEMPTIBLE — it can crash, hang, or slow down at any step — and
make the unit above it (the :class:`~accelerate_tpu.serving.router.
ServingRouter`) route work around the failure instead of sharing its fate.
Two transports implement the same replica surface:

- :class:`LocalReplica` — the engine loop in a daemon thread of this
  process. Zero spawn cost, shares the imported jax runtime; the transport
  for benchmarks, doctor check 13, and fast tier-1 tests. A thread cannot
  be SIGKILLed, so abrupt death is modeled by :meth:`~LocalReplica.kill`
  (the loop exits without flushing in-flight work) or a chaos ``crash``
  fault.
- :class:`ProcessReplica` — the engine loop in a child process
  (``python -m accelerate_tpu.serving.replica``), speaking JSON lines over
  stdin/stdout. Real OS-level failure semantics: a chaos ``sigkill`` is an
  actual SIGKILL (no handlers run, in-flight state gone), a ``hang`` wedges
  the child until the router's heartbeat watch declares it dead.

Both run the same :class:`_EngineWorker` loop: drain submit commands, step
the engine, and stream one ``step`` event per engine step carrying each
request's newly generated tokens. Those per-step progress deltas are what
make failover token-exact — the router always holds every in-flight
request's ``generated``-so-far, so a survivor resumes via
``ServingEngine.submit(generated=...)`` (the scheduler's preempt/resume
state) and the retried output is bitwise-identical to an unfailed run.

The worker registers the engine as watchdog heartbeat source
``serving_decode:<name>`` (beats per step), so a hang inside batched decode
produces a stall dump naming the replica — the same forensics train steps
get.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import queue
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..telemetry import events as tel
from ..telemetry import goodput as _goodput

__all__ = [
    "REPLICA_SPEC_ENV_VAR",
    "ReplicaState",
    "ReplicaSpec",
    "LocalReplica",
    "ProcessReplica",
]

REPLICA_SPEC_ENV_VAR = "ACCELERATE_REPLICA_SPEC"


class ReplicaState(enum.Enum):
    STARTING = "starting"  # spawned, engine still building/warming
    HEALTHY = "healthy"    # ready event seen, heartbeats fresh
    DRAINING = "draining"  # no new dispatch; in-flight work finishes
    DEAD = "dead"          # crashed or stalled; in-flight work failed over


@dataclass(frozen=True)
class ReplicaSpec:
    """A serializable engine recipe, so every replica — thread or child
    process — builds the SAME engine over the SAME params (``init_llama``
    with ``param_seed`` is deterministic per backend), which is what makes
    cross-replica retry bitwise-safe. ``model`` holds ``LlamaConfig`` field
    overrides; bucket tuples of ``None`` fall back to the engine's
    power-of-two lattice."""

    model: "dict[str, Any]"
    param_seed: int = 0
    num_blocks: int = 49
    block_size: int = 8
    max_slots: int = 4
    max_blocks_per_seq: Optional[int] = None
    slot_buckets: Optional["tuple[int, ...]"] = None
    block_buckets: Optional["tuple[int, ...]"] = None
    prefill_buckets: Optional["tuple[int, ...]"] = None
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    param_dtype: str = "bfloat16"
    # persistent compile cache (compile_cache/): a replacement/respawned
    # replica warm-boots its whole lattice from here instead of recompiling
    compile_cache_dir: Optional[str] = None
    # disaggregated serving (serving/disagg.py): "serving" builds the
    # monolithic prefill+decode engine (the default — every pre-disagg spec
    # round-trips unchanged); "prefill" builds a PrefillEngine (chunked
    # prefill only, emits KV handoffs); "decode" builds a DecodeEngine
    # (lands handoffs, gates admission on them). The router dispatches by
    # this role: "prefill" replicas never see decode work and vice versa.
    role: str = "serving"
    # speculative decoding (monolithic "serving" role only): k draft tokens
    # per step from a truncated-layer self-draft of depth draft_layers —
    # output streams stay bitwise-identical to non-speculative decode
    spec_tokens: int = 0
    draft_layers: Optional[int] = None

    def config(self):
        from ..models.transformer import LlamaConfig

        return LlamaConfig(**self.model)

    def build_params(self):
        import jax
        import jax.numpy as jnp

        from ..models import init_llama

        dtype = jnp.dtype(self.param_dtype)
        return jax.tree_util.tree_map(
            lambda x: x.astype(dtype),
            init_llama(self.config(), jax.random.PRNGKey(self.param_seed)),
        )

    def lattice(self):
        from .buckets import BucketLattice

        if self.slot_buckets is None:
            return None
        return BucketLattice(
            slot_buckets=tuple(self.slot_buckets),
            block_buckets=tuple(self.block_buckets),
            prefill_buckets=tuple(self.prefill_buckets),
        )

    def build_engine(self, heartbeat_name: str = "serving_decode"):
        if self.role == "prefill":
            from .disagg import PrefillEngine as engine_cls
        elif self.role == "decode":
            from .disagg import DecodeEngine as engine_cls
        else:
            from .engine import ServingEngine as engine_cls

        extra = {}
        if self.role not in ("prefill", "decode") and self.spec_tokens:
            # the disagg engines don't take the speculative knobs (decode
            # tiers verify against handed-off KV they don't re-prefill)
            extra = dict(spec_tokens=self.spec_tokens, draft_layers=self.draft_layers)
        return engine_cls(
            self.build_params(),
            self.config(),
            num_blocks=self.num_blocks,
            block_size=self.block_size,
            max_slots=self.max_slots,
            max_blocks_per_seq=self.max_blocks_per_seq,
            lattice=self.lattice(),
            temperature=self.temperature,
            top_k=self.top_k,
            top_p=self.top_p,
            heartbeat_name=heartbeat_name,
            compile_cache_dir=self.compile_cache_dir,
            **extra,
        )

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, payload: str) -> "ReplicaSpec":
        return cls(**json.loads(payload))


# ---------------------------------------------------------------------------
# the worker loop (shared by both transports)


class _EngineWorker:
    """Drive one engine from a command stream, emitting an event stream.

    Commands: ``{"cmd": "submit", "rid", "prompt", "max_new", "eos",
    "rng_seed", "generated"}`` and ``{"cmd": "stop"}``. Events: ``ready``
    (warmup compile counts), ``step`` (per engine step: progress deltas per
    request), ``done`` (terminal status + authoritative full token list),
    ``beat`` (throttled idle liveness), ``fatal`` (the loop died on an
    exception — chaos ``crash`` faults land here)."""

    def __init__(
        self,
        engine,
        recv: Callable[[float], Optional[dict]],
        send: Callable[[dict], None],
        killed: Optional[threading.Event] = None,
        idle_beat_s: float = 0.1,
    ):
        self.engine = engine
        self.recv = recv
        self.send = send
        self.killed = killed or threading.Event()
        self.idle_beat_s = idle_beat_s

    def run(self) -> None:
        import numpy as np

        from .scheduler import RequestStatus

        try:
            ready = {"event": "ready", **self.engine.warmup()}
            # AOT cache outcomes ride the ready event so the router (and the
            # autoscaler's warm-join assertion) can tell a zero-compile warm
            # boot from a cold one without reaching into the worker
            for k, v in getattr(self.engine, "cache_stats", {}).items():
                if v:
                    ready[f"cache_{k}"] = v
            self.send(ready)
            handles: "dict[str, Any]" = {}  # router rid -> engine Request
            sent: "dict[str, int]" = {}  # router rid -> tokens already reported
            last_beat = 0.0
            idle_since = None  # start of the current no-work spell, if any
            while not self.killed.is_set():
                cmd = self.recv(self.idle_beat_s if self.engine.scheduler.idle() else 0.0)
                while cmd is not None:
                    if cmd.get("cmd") == "stop":
                        return
                    if cmd.get("cmd") == "submit":
                        extra = {}
                        if cmd.get("handoff") is not None:
                            # disaggregated decode dispatch: the wire-form KV
                            # handoff rides the submit, and DecodeEngine.submit
                            # gates the request's admission on landing it
                            extra["handoff"] = cmd["handoff"]
                        req = self.engine.submit(
                            np.asarray(cmd["prompt"], np.int32),
                            int(cmd["max_new"]),
                            eos_token_id=cmd.get("eos"),
                            rng_seed=int(cmd.get("rng_seed", 0)),
                            generated=cmd.get("generated") or None,
                            # propagated trace context: engine spans parent
                            # under the router's dispatch span and ship back
                            # inside the done event (the router owns emission)
                            trace=cmd.get("trace"),
                            **extra,
                        )
                        handles[cmd["rid"]] = req
                        sent[cmd["rid"]] = len(req.generated)
                    cmd = self.recv(0.0)
                if self.engine.scheduler.idle():
                    now = time.monotonic()
                    if idle_since is None:
                        idle_since = now
                    if now - last_beat >= self.idle_beat_s:
                        last_beat = now
                        self.send({"event": "beat"})
                    continue
                if idle_since is not None:
                    # evidenced idle capacity: the goodput ledger attributes
                    # this gap to `idle` instead of leaving it unattributed
                    idle_dur = time.monotonic() - idle_since
                    idle_since = None
                    if idle_dur > 1e-3 and tel.is_enabled():
                        tel.emit("serving", phase="idle", dur_s=round(idle_dur, 6))
                        _goodput.note("idle", idle_dur)
                finished = self.engine.step()
                progress = {}
                for rid, req in handles.items():
                    n = len(req.generated)
                    if n > sent[rid]:
                        progress[rid] = [int(t) for t in req.generated[sent[rid] :]]
                        sent[rid] = n
                self.send(
                    {
                        "event": "step",
                        "step": self.engine.steps,
                        "running": len(self.engine.scheduler.running()),
                        "queued": self.engine.scheduler.queue_depth,
                        "progress": progress,
                    }
                )
                for req in finished:
                    rid = next(k for k, v in handles.items() if v is req)
                    done_event = {
                        "event": "done",
                        "rid": rid,
                        "status": "finished"
                        if req.status is RequestStatus.FINISHED
                        else "rejected",
                        "tokens": [int(t) for t in req.generated],
                        "error": req.error,
                        "preemptions": req.preemptions,
                    }
                    if (
                        req.trace_spans
                        and not req._trace_owner
                        and (
                            req.trace.get("sampled")
                            or req.status is not RequestStatus.FINISHED
                        )
                    ):
                        # span dicts are JSON-able by construction; they ride
                        # the event stream so the ROUTER (one writer per
                        # trace) assembles and emits the whole trace. An
                        # UNSAMPLED finished request ships nothing — the only
                        # way the router would emit it is a failover, and
                        # failover redispatches arrive with sampled flipped
                        # on (the previous hop's spans died with the replica)
                        done_event["spans"] = req.trace_spans
                    self.send(done_event)
                    handles.pop(rid)
                    sent.pop(rid)
                pop = getattr(self.engine, "pop_handoffs", None)
                if pop is not None:
                    # PrefillEngine: each prefilled request leaves as a KV
                    # handoff event (wire dict), not a done event — the router
                    # requeues it toward the decode tier. The step event above
                    # already reported tok0 as progress, and FIFO transports
                    # deliver it first, so the router's generated-so-far view
                    # is consistent by the time the handoff lands.
                    for req, wire in pop():
                        rid = next(k for k, v in handles.items() if v is req)
                        ho_event = {"event": "handoff", "rid": rid, "handoff": wire}
                        if (
                            req.trace_spans
                            and not req._trace_owner
                            and req.trace.get("sampled")
                        ):
                            ho_event["spans"] = req.trace_spans
                        self.send(ho_event)
                        handles.pop(rid, None)
                        sent.pop(rid, None)
        except BaseException as exc:  # the router must hear about ANY death
            try:
                self.send({"event": "fatal", "error": f"{type(exc).__name__}: {exc}"})
            except Exception:
                pass  # transport already gone — the heartbeat watch catches it


# ---------------------------------------------------------------------------
# transports


class LocalReplica:
    """The worker loop in a daemon thread of this process."""

    transport = "thread"

    def __init__(self, name: str, spec: ReplicaSpec, *, idle_beat_s: float = 0.05):
        self.name = name
        self.spec = spec
        self._idle_beat_s = idle_beat_s
        self.state = ReplicaState.STARTING
        self._inbox: "queue.Queue[dict]" = queue.Queue()
        self._outbox: "queue.Queue[dict]" = queue.Queue()
        self._killed = threading.Event()
        self._worker: Optional[_EngineWorker] = None

        def _run():
            engine = spec.build_engine(heartbeat_name=f"serving_decode:{name}")
            self._worker = _EngineWorker(
                engine,
                recv=self._recv,
                send=self._outbox.put,
                killed=self._killed,
                idle_beat_s=idle_beat_s,
            )
            self._worker.run()

        self._thread = threading.Thread(
            target=_run, name=f"serving-replica-{name}", daemon=True
        )
        self._thread.start()

    def _recv(self, timeout: float) -> Optional[dict]:
        try:
            return self._inbox.get(timeout=timeout) if timeout > 0 else self._inbox.get_nowait()
        except queue.Empty:
            return None

    @property
    def role(self) -> str:
        return getattr(self.spec, "role", "serving")

    # -- router surface ------------------------------------------------------

    def submit(self, payload: dict) -> None:
        self._inbox.put(dict(payload, cmd="submit"))

    def drain_events(self) -> "list[dict]":
        events = []
        while True:
            try:
                events.append(self._outbox.get_nowait())
            except queue.Empty:
                return events

    def alive(self) -> bool:
        return self._thread.is_alive()

    def kill(self) -> None:
        """Abrupt death: the loop exits at its next check WITHOUT reporting
        in-flight work (a hung loop never reaches the check — the heartbeat
        watch handles that, same as a real process)."""
        self._killed.set()

    def stop(self) -> None:
        self._inbox.put({"cmd": "stop"})

    def close(self, timeout: float = 5.0) -> None:
        self.stop()
        self._killed.set()
        self._thread.join(timeout=timeout)

    def respawn(self) -> "LocalReplica":
        """A fresh incarnation from the stored spec (the router's self-heal
        path) — warm-booted via ``spec.compile_cache_dir`` when set."""
        return LocalReplica(self.name, self.spec, idle_beat_s=self._idle_beat_s)


class ProcessReplica:
    """The worker loop in a child process, JSON lines over stdin/stdout.

    ``chaos_schedule`` (a JSON string / ``@file`` ref, see
    ``resilience/chaos.py``) arms fault injection in the CHILD only — the
    way chaos tests kill one replica mid-decode without touching the
    router's process."""

    transport = "process"

    def __init__(
        self,
        name: str,
        spec: ReplicaSpec,
        *,
        chaos_schedule: Optional[str] = None,
        env: Optional[dict] = None,
        idle_beat_s: float = 0.05,
    ):
        from ..resilience.chaos import CHAOS_ENV_VAR

        self.name = name
        self.spec = spec
        self._idle_beat_s = idle_beat_s
        self._base_env = None if env is None else dict(env)
        self.state = ReplicaState.STARTING
        self._outbox: "queue.Queue[dict]" = queue.Queue()
        # the child inherits the parent's environment verbatim (no platform
        # pinning: silently forcing JAX_PLATFORMS=cpu would downgrade every
        # process replica on a TPU host with no error, only bad throughput —
        # CPU-only tests pass JAX_PLATFORMS=cpu themselves)
        child_env = dict(os.environ if env is None else env)
        child_env[REPLICA_SPEC_ENV_VAR] = spec.to_json()
        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        child_env["PYTHONPATH"] = os.pathsep.join(
            p for p in (repo, child_env.get("PYTHONPATH")) if p
        )
        if chaos_schedule is not None:
            child_env[CHAOS_ENV_VAR] = chaos_schedule
        else:
            child_env.pop(CHAOS_ENV_VAR, None)  # a parent-armed schedule must
            # not leak into every replica — chaos targets are explicit
        # the ROUTER host owns the /metrics endpoint: a child inheriting the
        # parent's fixed port would fail the bind (degrading to a warning,
        # but N warning-spewing children serve nobody — the child's registry
        # still arms via telemetry and its spans ship over the event stream)
        from ..telemetry.metrics import METRICS_PORT_ENV_VAR

        child_env.pop(METRICS_PORT_ENV_VAR, None)
        # -c instead of -m: runpy would re-execute a module the package
        # __init__ already imported and warn about it
        worker = (
            "import sys; from accelerate_tpu.serving.replica import _worker_main; "
            "sys.exit(_worker_main(sys.argv[1:]))"
        )
        self.proc = subprocess.Popen(
            [
                sys.executable,
                "-c",
                worker,
                "--name",
                name,
                "--idle-beat-s",
                str(idle_beat_s),
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=None,  # pass through: replica tracebacks stay debuggable
            text=True,
            bufsize=1,
            env=child_env,
        )
        self._reader = threading.Thread(
            target=self._pump, name=f"serving-replica-{name}-reader", daemon=True
        )
        self._reader.start()

    def _pump(self) -> None:
        for line in self.proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                self._outbox.put(json.loads(line))
            except ValueError:
                pass  # stray non-protocol output (jax logs) — never fatal

    @property
    def role(self) -> str:
        return getattr(self.spec, "role", "serving")

    # -- router surface ------------------------------------------------------

    def submit(self, payload: dict) -> None:
        try:
            self.proc.stdin.write(json.dumps(dict(payload, cmd="submit")) + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError):
            pass  # child died — the router's liveness check fails it over

    def drain_events(self) -> "list[dict]":
        events = []
        while True:
            try:
                events.append(self._outbox.get_nowait())
            except queue.Empty:
                return events

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()

    def stop(self) -> None:
        try:
            self.proc.stdin.write(json.dumps({"cmd": "stop"}) + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError):
            pass

    def close(self, timeout: float = 10.0) -> None:
        self.stop()
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5.0)

    def respawn(self) -> "ProcessReplica":
        """A fresh child from the stored spec (the router's self-heal path),
        warm-booted via ``spec.compile_cache_dir`` when set. The chaos
        schedule is deliberately NOT re-armed: it is test instrumentation
        aimed at the incarnation it already killed — a healed replica must
        serve, not re-die deterministically."""
        return ProcessReplica(
            self.name, self.spec, env=self._base_env, idle_beat_s=self._idle_beat_s
        )


# ---------------------------------------------------------------------------
# child entry point: `python -m accelerate_tpu.serving.replica`


def _worker_main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="python -m accelerate_tpu.serving.replica")
    parser.add_argument("--name", default="replica")
    parser.add_argument("--idle-beat-s", type=float, default=0.05)
    args = parser.parse_args(argv)

    payload = os.environ.get(REPLICA_SPEC_ENV_VAR, "").strip()
    if not payload:
        print(f"{REPLICA_SPEC_ENV_VAR} not set", file=sys.stderr)
        return 2
    spec = ReplicaSpec.from_json(payload)

    from ..resilience import chaos
    from ..telemetry import watchdog

    chaos.maybe_arm_from_env()
    watchdog.maybe_start_from_env()

    engine = spec.build_engine(heartbeat_name=f"serving_decode:{args.name}")

    inbox: "queue.Queue[dict]" = queue.Queue()

    def _pump_stdin():
        for line in sys.stdin:
            line = line.strip()
            if line:
                try:
                    inbox.put(json.loads(line))
                except ValueError:
                    pass
        inbox.put({"cmd": "stop"})  # router closed the pipe: shut down

    threading.Thread(target=_pump_stdin, daemon=True).start()

    def _recv(timeout: float) -> Optional[dict]:
        try:
            return inbox.get(timeout=timeout) if timeout > 0 else inbox.get_nowait()
        except queue.Empty:
            return None

    def _send(event: dict) -> None:
        sys.stdout.write(json.dumps(event) + "\n")
        sys.stdout.flush()

    _EngineWorker(engine, recv=_recv, send=_send, idle_beat_s=args.idle_beat_s).run()
    return 0


if __name__ == "__main__":
    sys.exit(_worker_main())
