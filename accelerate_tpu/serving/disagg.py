"""Disaggregated prefill/decode serving: role-split engines, KV handoff.

The monolithic :class:`~accelerate_tpu.serving.engine.ServingEngine` runs
chunked prefill and batched decode on one device program, so a long prompt
stalls every decode slot behind it. The MPMD split (PAPERS.md 2412.14374:
one program per role, point-to-point transfer between them) breaks that
interference:

- :class:`PrefillEngine` — chunked prefill ONLY. Each admitted request is
  prefilled (sampling its first token at fold index 0, exactly like the
  monolith), then leaves the engine as a **content-addressed KV handoff**:
  the prompt's full blocks, identified by the prefix-cache chain hashes
  (``h_i = H(h_{i-1}, tokens_i)``) and carried with their pool content. The
  freed blocks stay registered in the prefill engine's own LRU pool, so a
  shared prompt prefix is prefilled once per prefill replica, ever.
- :class:`DecodeEngine` — batched decode ONLY. A handoff **lands** by
  adopting each block into the decode pool's content index
  (:meth:`~accelerate_tpu.serving.kv_pager.BlockAllocator.adopt_block`) and
  writing its content with one compiled block write (``serving_land``, part
  of the warmup lattice). Admission of the request is GATED until its
  handoff has landed; the normal prefix-cache admission then maps the landed
  blocks and the engine re-prefills only the sub-block tail — resuming via
  the same ``submit(generated=...)`` machinery failover uses, so the decoded
  stream is bitwise-identical to the monolith's.
- :class:`KVTransport` — how handoff bytes move. The shipped
  :class:`LocalBlockCopyTransport` gathers/writes through host memory
  (shared-host tests, LocalReplica fleets); a DCN/ICI implementation slots
  in behind the same two-method surface.
- :class:`DisaggRouter` — two-tier dispatch over one replica fleet: requests
  with no progress go to the prefill tier (fewest outstanding requests, then
  fewest pending prompt tokens), requests carrying progress or a verified
  handoff go to the decode tier (least-outstanding-tokens, the base
  policy). The handoff hop is checksum- and chain-hash-verified at the
  router; a corrupt or dropped handoff re-runs prefill from scratch
  (``generated`` cleared, so the re-run samples fold 0 again) — exactly-once
  and bitwise parity hold across the extra hop, chaos point ``kv_handoff``
  proves it (``make doctor`` check 17).

Wire format: a handoff travels as a JSON-able dict (tokens, hex chain
hashes, base64 float32 block content, CRC32) on BOTH transports, so thread
and process replicas exercise one code path. bf16→f32 widening is exact and
f32→bf16 truncation restores the original bits, so shipping KV as float32
preserves bitwise parity end to end.
"""

from __future__ import annotations

import base64
import time
import zlib
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..telemetry import events as tel
from ..telemetry import goodput as _goodput
from ..telemetry import metrics as _metrics
from ..telemetry import tracing as _tracing
from ..telemetry import watchdog as _watchdog
from .engine import ServingEngine
from .kv_pager import NULL_BLOCK, BlockPoolExhausted, _chain_hash
from .replica import ReplicaState
from .router import RouterRequestStatus, ServingRouter
from .scheduler import Request

__all__ = [
    "KVHandoff",
    "KVTransport",
    "LocalBlockCopyTransport",
    "PrefillEngine",
    "DecodeEngine",
    "DisaggRouter",
]


def _inject_handoff_fault(step: int) -> bool:
    """Chaos point ``kv_handoff`` (resilience/chaos.py). Returns True when a
    ``corrupt`` fault fired — the caller delivers a deliberately damaged
    payload for the router's verify to catch; ``crash``/``hang``/``slow``
    behave exactly as at any other point (die / wedge / delay)."""
    # lazy import, same reason as engine._chaos_inject: serving must not pay
    # for (or cyclically import) the resilience stack at module load
    from ..resilience import chaos as _chaos

    try:
        _chaos.maybe_inject("kv_handoff", step=step)
    except _chaos.ChaosCorruptionError:
        return True
    return False


# ---------------------------------------------------------------------------
# the transfer unit


@dataclass(eq=False)
class KVHandoff:
    """One request's prefilled KV, content-addressed and self-verifying.

    Covers the PROMPT's full blocks only (``P // block_size`` of them — the
    prefill engine writes KV for prompt positions, and partial tail blocks
    are cheaper to re-prefill than to ship sub-block state). ``hashes`` are
    the prefix-cache chain hashes, recomputable from ``prompt`` alone, so
    the receiver can prove the payload describes this exact prompt; ``crc``
    covers the block content bytes. ``first_token`` is the token the prefill
    engine sampled at fold index 0 — the decode side resumes with
    ``generated=[first_token]`` and samples fold 1 next, exactly the
    monolith's schedule."""

    prompt: np.ndarray            # int32 [P]
    first_token: int
    block_size: int
    hashes: "tuple[bytes, ...]"   # chain hashes over prompt full blocks
    k: np.ndarray                 # float32 [n_blocks, L, block_size, Hkv, D]
    v: np.ndarray
    crc: int
    src_replica: Optional[str] = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)

    @property
    def n_blocks(self) -> int:
        return len(self.hashes)

    @property
    def nbytes(self) -> int:
        return int(self.k.nbytes) + int(self.v.nbytes)

    @classmethod
    def capture(cls, engine: ServingEngine, req: Request,
                src_replica: Optional[str] = None) -> "KVHandoff":
        """Gather the request's prompt full blocks out of ``engine``'s pool.
        Must run BEFORE ``scheduler.complete`` releases the sequence (the
        block table lookup raises after the free)."""
        alloc = engine.allocator
        n_full = int(req.prompt.size) // engine.block_size
        hashes = tuple(alloc.chain_hashes(req.rid)[:n_full])
        shape = engine.pool["k"].shape  # [L, num_blocks, B, Hkv, D]
        if hashes:
            idx = np.asarray(
                alloc.block_table(req.rid)[: len(hashes)], np.int32
            )
            # [L, n, B, Hkv, D] -> [n, L, B, Hkv, D]; bf16 -> f32 is exact
            k = np.asarray(jax.device_get(
                engine.pool["k"][:, idx].astype(jnp.float32).transpose(1, 0, 2, 3, 4)
            ))
            v = np.asarray(jax.device_get(
                engine.pool["v"][:, idx].astype(jnp.float32).transpose(1, 0, 2, 3, 4)
            ))
        else:  # prompt shorter than one block: the handoff carries only tok0
            k = np.zeros((0, shape[0], shape[2], shape[3], shape[4]), np.float32)
            v = np.zeros_like(k)
        crc = zlib.crc32(v.tobytes(), zlib.crc32(k.tobytes()))
        return cls(
            prompt=req.prompt,
            first_token=int(req.generated[0]),
            block_size=engine.block_size,
            hashes=hashes,
            k=k,
            v=v,
            crc=crc,
            src_replica=src_replica,
        )

    def verify(self) -> "list[str]":
        """Every way this payload can be wrong, as human-readable problems
        (empty list == intact): CRC over the content bytes, shape/hash-count
        consistency, and the chain hashes recomputed from the prompt — a
        payload claiming blocks the prompt doesn't have cannot pass."""
        problems: "list[str]" = []
        crc = zlib.crc32(self.v.tobytes(), zlib.crc32(self.k.tobytes()))
        if crc != self.crc:
            problems.append(
                f"payload checksum mismatch (got {crc:#010x}, "
                f"declared {self.crc:#010x})"
            )
        if self.k.shape != self.v.shape or self.k.shape[0] != len(self.hashes):
            problems.append(
                f"shape mismatch: k{self.k.shape} v{self.v.shape} "
                f"vs {len(self.hashes)} hash(es)"
            )
        if len(self.hashes) > int(self.prompt.size) // self.block_size:
            problems.append(
                f"{len(self.hashes)} block(s) exceed the prompt's "
                f"{int(self.prompt.size) // self.block_size} full block(s)"
            )
            return problems
        prev = b""
        for i, h in enumerate(self.hashes):
            expect = _chain_hash(
                prev, self.prompt[i * self.block_size : (i + 1) * self.block_size]
            )
            if h != expect:
                problems.append(f"chain hash {i} does not match the prompt")
                break
            prev = h
        return problems

    def to_wire(self) -> dict:
        """JSON-able dict — the form a handoff ALWAYS travels in, so thread
        and process transports exercise one serialization path."""
        return {
            "prompt": [int(t) for t in self.prompt],
            "first_token": int(self.first_token),
            "block_size": int(self.block_size),
            "hashes": [h.hex() for h in self.hashes],
            "shape": [int(s) for s in self.k.shape],
            "k": base64.b64encode(self.k.tobytes()).decode("ascii"),
            "v": base64.b64encode(self.v.tobytes()).decode("ascii"),
            "crc": int(self.crc),
            "src": self.src_replica,
        }

    @classmethod
    def from_wire(cls, wire: dict) -> "KVHandoff":
        shape = tuple(int(s) for s in wire["shape"])
        k = np.frombuffer(base64.b64decode(wire["k"]), np.float32).reshape(shape)
        v = np.frombuffer(base64.b64decode(wire["v"]), np.float32).reshape(shape)
        return cls(
            prompt=np.asarray(wire["prompt"], np.int32),
            first_token=int(wire["first_token"]),
            block_size=int(wire["block_size"]),
            hashes=tuple(bytes.fromhex(h) for h in wire["hashes"]),
            k=k,
            v=v,
            crc=int(wire["crc"]),
            src_replica=wire.get("src"),
        )

    @classmethod
    def verify_wire(
        cls, wire: dict, prompt=None
    ) -> "tuple[Optional[KVHandoff], list[str]]":
        """Decode + verify in one step, never raising: an undecodable wire
        dict is just another corruption verdict (the router re-runs
        prefill either way)."""
        try:
            h = cls.from_wire(wire)
        except Exception as exc:
            return None, [f"undecodable handoff: {type(exc).__name__}: {exc}"]
        problems = h.verify()
        if prompt is not None and not np.array_equal(
            h.prompt, np.asarray(prompt, np.int32).reshape(-1)
        ):
            problems.append("handoff prompt differs from the request's prompt")
        return h, problems


def corrupt_wire(wire: dict) -> dict:
    """Damage a wire-form handoff IN TRANSIT (after its CRC was computed) —
    the ``corrupt`` chaos fault's payload model. Flips one content byte, or
    the CRC itself when the payload is empty, so verification always
    catches it."""
    if wire.get("k"):
        raw = bytearray(base64.b64decode(wire["k"]))
        raw[0] ^= 0xFF
        wire["k"] = base64.b64encode(bytes(raw)).decode("ascii")
    else:
        wire["crc"] = int(wire["crc"]) ^ 1
    return wire


# ---------------------------------------------------------------------------
# transports


class KVTransport:
    """How handoff bytes move from a prefill pool to a decode pool. Two
    methods; implementations may batch, compress, or DMA as they like, as
    long as ``pack`` snapshots before the source sequence is freed and
    ``deliver`` is idempotent per chain hash (re-delivery after a decode
    failover must not duplicate blocks)."""

    def pack(self, engine: ServingEngine, req: Request) -> dict:
        """Snapshot ``req``'s prompt KV out of ``engine`` as a wire dict."""
        raise NotImplementedError

    def deliver(self, handoff: KVHandoff, engine: ServingEngine) -> dict:
        """Land ``handoff`` into ``engine``'s pool; returns stats
        (``landed``/``dedup`` block counts). Raises
        :class:`~accelerate_tpu.serving.kv_pager.BlockPoolExhausted` when the
        pool can't take a block right now (the caller retries later —
        partial progress is safe, adopted blocks dedup on retry)."""
        raise NotImplementedError


class LocalBlockCopyTransport(KVTransport):
    """Host-memory block copy: gather on the prefill side, one compiled
    block write per landed block on the decode side. The shared-host
    reference transport (LocalReplica fleets, ProcessReplica on one
    machine); a DCN/ICI transport replaces the host round-trip, nothing
    else."""

    def pack(self, engine: ServingEngine, req: Request) -> dict:
        name = getattr(engine, "heartbeat_name", None)
        return KVHandoff.capture(engine, req, src_replica=name).to_wire()

    def deliver(self, handoff: KVHandoff, engine: "DecodeEngine") -> dict:
        landed = dedup = 0
        land = engine._aot.get(("land",), engine.land_fn)
        for i, h in enumerate(handoff.hashes):
            blk = engine.allocator.adopt_block(h)
            if blk is None:
                dedup += 1  # content-addressed: this block is already here
                continue
            engine.pool = land(
                engine.pool, np.int32(blk), handoff.k[i], handoff.v[i]
            )
            landed += 1
        return {"landed": landed, "dedup": dedup}


# ---------------------------------------------------------------------------
# role-split engines


class PrefillEngine(ServingEngine):
    """Chunked prefill only: every admitted request is prefilled (first
    token sampled at fold 0, the monolith's schedule), packed into a KV
    handoff, and released — the engine never decodes. Completed sequences'
    registered blocks park in this engine's LRU pool, so the prefill tier
    accumulates a warm prompt-prefix cache of its own."""

    def __init__(self, *args, transport: Optional[KVTransport] = None, **kwargs):
        kwargs.setdefault("prefix_cache", True)
        super().__init__(*args, **kwargs)
        if not self.prefix_cache:
            raise ValueError("PrefillEngine requires prefix_cache=True "
                             "(chain hashes ARE the handoff addresses)")
        self.transport = transport or LocalBlockCopyTransport()
        self._handoffs: "list[tuple[Request, dict]]" = []
        self.handoffs_packed = 0
        self.handoffs_corrupted = 0

    def pop_handoffs(self) -> "list[tuple[Request, dict]]":
        """Drain the handoffs packed since the last call (the replica worker
        turns each into a ``handoff`` event)."""
        out, self._handoffs = self._handoffs, []
        return out

    def step(self, now: Optional[float] = None) -> "list[Request]":
        now = time.monotonic() if now is None else now
        step_t0 = time.monotonic()
        finished: "list[Request]" = []
        prefills = 0
        prefill_tokens_before = self.prefill_tokens
        prefix_cached_before = self.prefix_cached_tokens
        admitted = self.scheduler.admissions()
        while self.scheduler.rejected:
            req = self.scheduler.rejected.pop()
            req.finish_t = now
            self._close_trace(req, "rejected")
            finished.append(req)
            if _metrics.is_enabled():
                _metrics.inc("accelerate_engine_requests_total", outcome="rejected")
            if tel.is_enabled():
                tel.emit(
                    "serving_request", rid=req.rid, error=req.error,
                    new_tokens=0, prompt_tokens=int(req.prompt.size),
                )
        for req in admitted:
            self._prefill_request(req, now)
            prefills += 1
            # chaos point "kv_handoff": the prefill work is DONE but the
            # handoff has not left yet — a crash here is the dropped-handoff
            # case the router must absorb by re-running prefill elsewhere;
            # a corrupt fault damages the payload we are about to ship
            corrupt = _inject_handoff_fault(self.steps)
            pack_t0 = _tracing.now_ns() if req.trace is not None else 0
            wire = self.transport.pack(self, req)
            if corrupt:
                corrupt_wire(wire)
                self.handoffs_corrupted += 1
            if pack_t0:
                req.trace_spans.append(_tracing.make_span(
                    req.trace, "kv_pack", pack_t0, _tracing.now_ns(),
                    parent_id=req._span_root["span_id"], component="engine",
                    blocks=len(wire.get("hashes", [])),
                ))
            # complete BEFORE shipping: frees the sequence, parking its
            # registered blocks in this engine's LRU (the tier-local prompt
            # cache); the wire dict snapshotted the content already
            self.scheduler.complete(req, now)
            self._close_trace(req, "handoff")
            self.handoffs_packed += 1
            self._handoffs.append((req, wire))
            if _metrics.is_enabled():
                _metrics.inc("accelerate_engine_requests_total", outcome="handoff")
        self.steps += 1
        if self.scheduler.idle():
            _watchdog.unregister(self.heartbeat_name)
        else:
            _watchdog.beat(self.heartbeat_name, step=self.steps)
        if _metrics.is_enabled():
            _metrics.set_gauge("accelerate_engine_queue_depth",
                               self.scheduler.queue_depth, engine=self.heartbeat_name)
            _metrics.inc("accelerate_prefill_tokens_total",
                         self.prefill_tokens - prefill_tokens_before)
            _metrics.inc("accelerate_prefix_hit_tokens_total",
                         self.prefix_cached_tokens - prefix_cached_before)
            _metrics.maybe_snapshot()
        if tel.is_enabled() and (prefills or finished):
            alloc = self.allocator.stats()
            step_dur = time.monotonic() - step_t0
            tel.emit(
                "serving",
                phase="step",
                dur_s=round(step_dur, 6),
                queue_depth=self.scheduler.queue_depth,
                running=0,
                occupancy=0.0,
                prefills=prefills,
                prefill_tokens=self.prefill_tokens - prefill_tokens_before,
                prefix_hit_tokens=self.prefix_cached_tokens - prefix_cached_before,
                decode_tokens=0,
                preemptions=self.scheduler.preemption_count,
                free_blocks=alloc["free_blocks"],
                live_tokens=alloc["live_tokens"],
                block_occupancy=alloc["occupancy"],
                fragmentation=alloc["fragmentation"],
            )
            _goodput.note_serving_step(
                step_dur,
                computed_tokens=self.prefill_tokens - prefill_tokens_before,
                wasted_tokens=0,
            )
            _goodput.maybe_emit()
        return finished

    def stats(self) -> dict:
        out = super().stats()
        out.update(
            handoffs_packed=self.handoffs_packed,
            handoffs_corrupted=self.handoffs_corrupted,
        )
        return out


class DecodeEngine(ServingEngine):
    """Batched decode only, fed by landed KV handoffs. A handed-off request
    is admission-GATED until its blocks are in the pool's content index;
    the normal prefix-cache admission then maps them (``cached_tokens``
    covers every landed block) and the engine re-prefills only the
    sub-block prompt tail — through the same resume path failover uses, so
    the output stream is bitwise-identical to the monolith's."""

    def __init__(self, *args, transport: Optional[KVTransport] = None, **kwargs):
        kwargs.setdefault("prefix_cache", True)
        super().__init__(*args, **kwargs)
        if not self.prefix_cache:
            raise ValueError("DecodeEngine requires prefix_cache=True "
                             "(handoffs land through the content index)")
        self.transport = transport or LocalBlockCopyTransport()
        #: engine rid -> handoff not yet landed; membership IS the admission
        #: gate (scheduler.admission_gate below)
        self._awaiting: "dict[Any, KVHandoff]" = {}
        self.handoffs_landed = 0
        self.handoff_blocks = 0
        self.handoff_dedup_blocks = 0
        L, _, B, Hkv, D = self.pool["k"].shape
        self._land_shape = (L, B, Hkv, D)

        def _land(pool, blk, k_content, v_content):
            # one block's content (all layers, K and V) into the pool at a
            # dynamic physical index — the decode half of a KV handoff; f32
            # content casts back to the pool dtype bit-exactly (the prefill
            # side widened from that dtype)
            return {
                "k": pool["k"].at[:, blk].set(k_content.astype(pool["k"].dtype)),
                "v": pool["v"].at[:, blk].set(v_content.astype(pool["v"].dtype)),
            }

        self.land_fn = jax.jit(_land, donate_argnums=(0,))
        self.scheduler.admission_gate = lambda r: r.rid not in self._awaiting

    def submit(self, *args, handoff: Optional[dict] = None, **kwargs) -> Request:
        req = super().submit(*args, **kwargs)
        if handoff is not None:
            self._awaiting[req.rid] = (
                handoff if isinstance(handoff, KVHandoff)
                else KVHandoff.from_wire(handoff)
            )
        return req

    def warmup(self) -> dict:
        from .. import compile_cache as _ccache

        # warm the landing write FIRST so the base warmup's telemetry record
        # (and its returned counts, via the jit_cache_sizes override) already
        # include the ``serving_land`` lattice point
        cache = None
        if self.compile_cache_dir is not None:
            cache = _ccache.get_cache(self.compile_cache_dir)
        content = np.zeros(self._land_shape, np.float32)
        args = (self.pool, np.int32(NULL_BLOCK), content, content)
        done = False
        if cache is not None:
            executable, outcome = _ccache.aot_compile(
                "serving_land", self.land_fn, args, mesh=self.mesh, cache=cache,
            )
            self.cache_stats[outcome] = self.cache_stats.get(outcome, 0) + 1
            if executable is not None:
                self._aot[("land",)] = executable
                done = True
        if not done:
            self.pool = self.land_fn(*args)
        return super().warmup()

    def jit_cache_sizes(self) -> dict:
        out = super().jit_cache_sizes()
        out["land_compiles"] = int(self.land_fn._cache_size()) + (
            1 if ("land",) in self._aot else 0
        )
        return out

    def step(self, now: Optional[float] = None) -> "list[Request]":
        self._land_pending()
        return super().step(now)

    def _land_pending(self) -> None:
        """Land every awaiting handoff that fits, in arrival order. A full
        pool defers the rest to the next step (running sequences drain and
        free blocks); if NOTHING is running the wait could never end, so the
        gate opens instead — normal admission re-prefills the whole prompt
        (or rejects it), which is slower but still bitwise-correct."""
        for rid in list(self._awaiting):
            h = self._awaiting[rid]
            try:
                st = self.transport.deliver(h, self)
            except BlockPoolExhausted:
                if not self.scheduler.running():
                    del self._awaiting[rid]
                break
            self.handoffs_landed += 1
            self.handoff_blocks += int(st.get("landed", 0))
            self.handoff_dedup_blocks += int(st.get("dedup", 0))
            del self._awaiting[rid]

    def stats(self) -> dict:
        out = super().stats()
        out.update(
            handoffs_landed=self.handoffs_landed,
            handoff_blocks=self.handoff_blocks,
            handoff_dedup_blocks=self.handoff_dedup_blocks,
            handoffs_awaiting=len(self._awaiting),
        )
        return out


# ---------------------------------------------------------------------------
# the two-tier router


class DisaggRouter(ServingRouter):
    """Role-aware dispatch over a prefill tier + a decode tier.

    A fresh request's first hop goes to the prefill tier; its ``handoff``
    event comes back through :meth:`_on_handoff`, is verified (CRC + chain
    hashes recomputed from the prompt), and the request re-queues toward
    the decode tier carrying the wire-form handoff. Every base-router
    invariant survives the extra hop:

    - **exactly-once**: the handoff event is consumed with the same
      stale-replica dedup as ``done`` events; terminal finalize still
      happens exactly once.
    - **failover**: a prefill replica dying mid-hop clears the request's
      progress (its first token must be re-sampled at fold 0 by the re-run)
      and requeues it to the surviving prefill tier; a decode replica dying
      requeues with progress + handoff intact (re-delivery dedups by chain
      hash). A handoff failing verification counts as a retry and re-runs
      prefill from scratch.
    - **tracing**: one trace_id spans prefill-hop → handoff → decode-hop;
      each hop is a ``dispatch`` span tagged ``hop=prefill|decode``.

    Correctness canaries (serving/canary.py) target only unified
    ``serving``-role replicas: a tier member runs half a request by
    construction, so there is no single replica a golden probe could hold
    to the single-stream reference — on a pure disagg fleet the canary
    plane is a no-op (the end-to-end bitwise invariant is covered by
    tests/test_disagg.py instead).
    """

    def __init__(self, prefill_replicas: "list", decode_replicas: "list",
                 **kwargs):
        if not prefill_replicas or not decode_replicas:
            raise ValueError("need at least one replica per tier")
        super().__init__(list(prefill_replicas) + list(decode_replicas), **kwargs)
        self.handoffs = 0
        self.handoff_corrupt = 0

    # -- tier views ----------------------------------------------------------

    def tier(self, role: str) -> "list":
        want_prefill = role == "prefill"
        return [
            r for r in self.replicas.values()
            if (getattr(r, "role", "serving") == "prefill") == want_prefill
        ]

    def _pending_prompt_tokens(self, name: str) -> int:
        return sum(int(r.prompt.size) for r in self._outstanding(name))

    # -- the handoff hop -----------------------------------------------------

    def _on_handoff(self, name: str, rep, ev: dict, now: float) -> bool:
        req = self._inflight.get(ev.get("rid"))
        if req is None or req.replica != name:
            return False  # stale: this request was failed over already
        del self._inflight[req.rid]
        if req.trace is not None:
            req.trace_spans.extend(ev.get("spans") or [])
            if req._span_dispatch is not None:
                _tracing.span_close(req._span_dispatch, outcome="handoff")
                req._span_dispatch = None
        wire = ev.get("handoff") or {}
        handoff, problems = KVHandoff.verify_wire(wire, prompt=req.prompt)
        if problems:
            # delivered but damaged (the chaos ``corrupt`` model, or any real
            # in-transit corruption): burn a retry and re-run prefill from
            # scratch — progress cleared so the re-run samples fold 0 again
            self.handoff_corrupt += 1
            req.replica = None
            req.retries += 1
            req.generated = []
            req.first_token_t = None
            req._handoff = None
            req.prefill_replica = None
            self._emit_handoff(req, name, wire, now, outcome="corrupt",
                               problems=problems)
            if req.retries > self.max_retries:
                self._finalize(
                    req, RouterRequestStatus.FAILED, now,
                    error=f"failed: handoff corrupt x{req.retries} "
                          f"({problems[0]})",
                )
            else:
                req.status = RouterRequestStatus.QUEUED
                self.admission.requeue_front(req)
            return True
        self.handoffs += 1
        per = self._per_replica[name]
        per["handoffs"] = per.get("handoffs", 0) + 1
        req.prefill_replica = name
        req.prefill_s = now - req._dispatch_t
        req.handoff_t = now
        if not req.generated:
            # the step event normally delivered tok0 already; the handoff's
            # copy is authoritative when it didn't (e.g. event coalescing)
            req.generated = [int(handoff.first_token)]
        if req.first_token_t is None:
            req.first_token_t = now
        self._emit_handoff(req, name, wire, now, outcome="ok")
        if req.done_decoding:
            # max_new_tokens == 1: the prefill hop produced everything
            self.completed += 1
            per["completed"] += 1
            self._finalize(req, RouterRequestStatus.FINISHED, now, count=False)
        else:
            req._handoff = wire
            req.status = RouterRequestStatus.QUEUED
            self.admission.requeue_front(req)
        return True

    def _emit_handoff(self, req, name: str, wire: dict, now: float, *,
                      outcome: str, problems: "Optional[list]" = None) -> None:
        _metrics.inc("accelerate_kv_handoffs_total", outcome=outcome)
        if not tel.is_enabled():
            return
        tel.emit(
            "kv_handoff",
            rid=req.rid,
            prefill_replica=name,
            outcome=outcome,
            blocks=len(wire.get("hashes") or []),
            bytes=len(wire.get("k") or "") + len(wire.get("v") or ""),
            prefill_s=round(now - req._dispatch_t, 6),
            retries=req.retries,
            error="; ".join(problems) if problems else None,
        )

    # -- failover ------------------------------------------------------------

    def _fail_replica(self, rep, reason: str, now: float) -> None:
        if getattr(rep, "role", "serving") == "prefill":
            for req in self._outstanding(rep.name):
                if not req.done_decoding:
                    # tok0 may have streamed back as progress, but the handoff
                    # died with the replica: the re-run must sample at fold 0
                    # again, so the resume state is wiped (keeping generated
                    # would make the prefill re-run resume at fold 1 with no
                    # KV — wrong tokens, silently)
                    req.generated = []
                    req.first_token_t = None
                    req._handoff = None
                    req.prefill_replica = None
        super()._fail_replica(rep, reason, now)

    # -- two-tier dispatch ---------------------------------------------------

    def _dispatch(self, now: float) -> bool:
        live_p = [
            r for r in self.tier("prefill")
            if r.state in (ReplicaState.STARTING, ReplicaState.HEALTHY)
        ]
        live_d = [
            r for r in self.tier("decode")
            if r.state in (ReplicaState.STARTING, ReplicaState.HEALTHY)
        ]
        activity = False
        stash: "list" = []  # popped but undispatchable NOW (tier busy)
        while True:
            req = self.admission.pop_next()
            if req is None:
                break
            if req.deadline_t is not None and req.deadline_t < now:
                self._finalize(
                    req, RouterRequestStatus.EXPIRED, now,
                    error="expired: deadline passed before dispatch",
                )
                activity = True
                continue
            # progress or a verified handoff binds the request to the decode
            # tier (resume must not re-run prefill); a clean request starts
            # at the prefill tier
            decode_bound = bool(req.generated) or req._handoff is not None
            live = live_d if decode_bound else live_p
            hop = "decode" if decode_bound else "prefill"
            if not live:
                if self._heal_pending():
                    stash.append(req)  # a respawn is coming: wait for it
                    continue
                self._finalize(
                    req, RouterRequestStatus.FAILED, now,
                    error=f"failed: no live {hop} replicas",
                )
                activity = True
                continue
            ready = [
                r for r in live
                if r.state is ReplicaState.HEALTHY
                and len(self._outstanding(r.name)) < self._replica_capacity(r)
            ]
            if not ready:
                # this tier is saturated/warming — park the request and keep
                # draining the queue so the OTHER tier is never head-of-line
                # blocked behind it
                stash.append(req)
                continue
            if decode_bound:
                # the base policy: least outstanding tokens, burning replicas
                # lose ties (SLO pressure leans dispatch away from them)
                target = min(
                    ready,
                    key=lambda r: (
                        r.name in self._burning_replicas,
                        self.outstanding_tokens(r.name),
                    ),
                )
            else:
                # prefill cost is prompt-length-proportional: fewest queued
                # requests first, pending prompt tokens as the tiebreak
                target = min(
                    ready,
                    key=lambda r: (
                        len(self._outstanding(r.name)),
                        self._pending_prompt_tokens(r.name),
                    ),
                )
            self._send(req, target, now, hop)
            activity = True
        for req in reversed(stash):  # restore original queue order
            self.admission.requeue_front(req)
        return activity

    def _send(self, req, target, now: float, hop: str) -> None:
        req.replica = target.name
        req._resume_from = len(req.generated)
        req._dispatch_t = now
        req.status = RouterRequestStatus.DISPATCHED
        self._inflight[req.rid] = req
        self.dispatched += 1
        self._per_replica[target.name]["dispatched"] += 1
        payload = {
            "rid": req.rid,
            "prompt": [int(t) for t in req.prompt],
            "max_new": req.max_new_tokens,
            "eos": req.eos_token_id,
            "rng_seed": req.rng_seed,
            "generated": list(req.generated),
        }
        if hop == "decode" and req._handoff is not None:
            payload["handoff"] = req._handoff
        if req.trace is not None:
            req._span_dispatch = _tracing.span_open(
                req.trace, "dispatch", parent_id=req._span_root["span_id"],
                component="router", replica=target.name, hop=hop,
                attempt=int(req.retries),
                resume_tokens=len(req.generated),
            )
            req.trace_spans.append(req._span_dispatch)
            wire_ctx = _tracing.TraceContext(req.trace).child(
                req._span_dispatch["span_id"]
            )
            if req.retries > 0:
                wire_ctx = _tracing.TraceContext(wire_ctx, sampled=True)
            payload["trace"] = dict(wire_ctx)
        target.submit(payload)

    # -- views ---------------------------------------------------------------

    def stats(self) -> dict:
        out = super().stats()
        out.update(
            handoffs=self.handoffs,
            handoff_corrupt=self.handoff_corrupt,
            tiers={
                "prefill": sorted(r.name for r in self.tier("prefill")),
                "decode": sorted(r.name for r in self.tier("decode")),
            },
        )
        return out
