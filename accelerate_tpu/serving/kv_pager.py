"""Paged KV cache: fixed-size blocks in one preallocated device pool.

The single-stream decode path (``generation.init_kv_cache``) reserves
``max_len`` cache slots per sequence up front — fine for one request, fatal
for serving: a 16-token reply and a 2k-token reply would each pin
``max_len`` slots, so heterogeneous traffic wastes most of HBM on slots that
are never written. The paged design (vLLM's PagedAttention, arXiv:2309.06180)
carves ONE preallocated pool into fixed-size blocks:

- device side: ``{"k","v"}: [L, num_blocks, block_size, Hkv, D]`` — allocated
  once at engine start, never resized (no allocation churn, no recompiles);
- host side: :class:`BlockAllocator` — a free list plus per-sequence block
  tables mapping logical block index -> physical block. Sequences grow one
  block at a time (``append``), release everything on completion/eviction
  (``free``), and the freed blocks are immediately reusable by any sequence,
  so memory tracks the LIVE token count instead of the worst case.

Physical block 0 is reserved as the **null block**: inactive batch slots and
padded table entries point at it, so their (masked, never-read) scatter
writes can never corrupt a live sequence's cache.

:func:`paged_attention` is the paged variant of the contiguous
``generation._cached_attention``: gather the sequence's blocks via its block
table, then run the SAME shared masked-attention core
(``generation._masked_attention``) — masked slots contribute exactly 0 to the
softmax, so paged decode is bitwise-identical to contiguous decode (the
parity tests in ``tests/test_serving.py`` hold this line).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from ..generation import _masked_attention
from ..models.transformer import LlamaConfig

__all__ = [
    "NULL_BLOCK",
    "BlockPoolExhausted",
    "BlockAllocatorError",
    "BlockAllocator",
    "init_block_pool",
    "paged_attention",
]

#: physical block index reserved for inactive/padded writes (never allocated)
NULL_BLOCK = 0


class BlockAllocatorError(RuntimeError):
    """Misuse of the allocator: double-free, append/lookup after free."""


class BlockPoolExhausted(RuntimeError):
    """No free block available — the scheduler should preempt or defer."""


def init_block_pool(
    config: LlamaConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16
) -> dict:
    """Device pool ``{"k","v"}: [L, num_blocks, block_size, Hkv, D]``
    (``num_blocks`` INCLUDES the reserved null block 0)."""
    shape = (config.n_layers, num_blocks, block_size, config.n_kv_heads, config.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


class BlockAllocator:
    """Host-side block bookkeeping for one device pool.

    Free blocks live on a LIFO free list (hot reuse: a just-freed block is
    handed out next, so the working set stays compact). Per-sequence state is
    a block table (physical block ids, logical order) plus the sequence's
    token count; ``append`` grows the table only when the token count crosses
    a block boundary. Fragmentation here is purely INTERNAL (the unwritten
    tail of each sequence's last block) — fixed-size blocks cannot fragment
    externally, which is the point of paging.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the reserved null block)")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # LIFO: lowest ids are handed out first at start, re-frees come back
        # on top. Block 0 is never on the list (reserved null block).
        self._free: "list[int]" = list(range(num_blocks - 1, 0, -1))
        self._tables: "dict[object, list[int]]" = {}
        self._tokens: "dict[object, int]" = {}

    # -- capacity ------------------------------------------------------------

    @property
    def usable_blocks(self) -> int:
        """Allocatable blocks (pool minus the null block)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.usable_blocks - self.free_blocks

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens``."""
        return max(1, -(-n_tokens // self.block_size))

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_for(n_tokens) <= self.free_blocks

    # -- lifecycle -----------------------------------------------------------

    def allocate(self, seq_id, n_tokens: int) -> "list[int]":
        """Create a sequence holding ``n_tokens`` (its prompt); returns the
        block table. :class:`BlockPoolExhausted` when the pool can't cover it
        (nothing is allocated on failure — all-or-nothing)."""
        if seq_id in self._tables:
            raise BlockAllocatorError(f"sequence {seq_id!r} already allocated")
        need = self.blocks_for(n_tokens)
        if need > self.free_blocks:
            raise BlockPoolExhausted(
                f"need {need} block(s) for {n_tokens} token(s), "
                f"only {self.free_blocks} free"
            )
        table = [self._free.pop() for _ in range(need)]
        self._tables[seq_id] = table
        self._tokens[seq_id] = n_tokens
        return list(table)

    def append(self, seq_id, n_tokens: int = 1) -> "list[int]":
        """Grow a sequence by ``n_tokens``; allocates new block(s) only when
        the count crosses a block boundary. Returns the block ids newly
        allocated (often empty). On exhaustion the sequence is left unchanged
        and :class:`BlockPoolExhausted` propagates — the scheduler preempts."""
        if seq_id not in self._tables:
            raise BlockAllocatorError(
                f"append on unknown/freed sequence {seq_id!r} (use-after-free?)"
            )
        have = len(self._tables[seq_id])
        need = self.blocks_for(self._tokens[seq_id] + n_tokens) - have
        if need > self.free_blocks:
            raise BlockPoolExhausted(
                f"sequence {seq_id!r} needs {need} more block(s), "
                f"only {self.free_blocks} free"
            )
        new = [self._free.pop() for _ in range(max(0, need))]
        self._tables[seq_id].extend(new)
        self._tokens[seq_id] += n_tokens
        return new

    def free(self, seq_id) -> int:
        """Release all of a sequence's blocks back to the free list; returns
        how many. Double-free raises :class:`BlockAllocatorError`."""
        if seq_id not in self._tables:
            raise BlockAllocatorError(f"double free of sequence {seq_id!r}")
        table = self._tables.pop(seq_id)
        del self._tokens[seq_id]
        self._free.extend(reversed(table))  # LIFO: first-allocated reused last
        return len(table)

    # -- views ---------------------------------------------------------------

    def block_table(self, seq_id, pad_to: Optional[int] = None) -> np.ndarray:
        """The sequence's physical block ids (logical order) as int32,
        padded with the null block to ``pad_to`` (the bucketed table width)."""
        if seq_id not in self._tables:
            raise BlockAllocatorError(
                f"block_table of unknown/freed sequence {seq_id!r} (use-after-free?)"
            )
        table = self._tables[seq_id]
        width = len(table) if pad_to is None else pad_to
        if len(table) > width:
            raise ValueError(f"table of {len(table)} block(s) does not fit pad_to={pad_to}")
        out = np.full((width,), NULL_BLOCK, np.int32)
        out[: len(table)] = table
        return out

    def tokens(self, seq_id) -> int:
        if seq_id not in self._tokens:
            raise BlockAllocatorError(f"tokens of unknown/freed sequence {seq_id!r}")
        return self._tokens[seq_id]

    def num_seq_blocks(self, seq_id) -> int:
        if seq_id not in self._tables:
            raise BlockAllocatorError(f"blocks of unknown/freed sequence {seq_id!r}")
        return len(self._tables[seq_id])

    def live_sequences(self) -> "list":
        return list(self._tables)

    def occupancy(self) -> float:
        """Fraction of usable blocks currently allocated."""
        return self.used_blocks / self.usable_blocks

    def fragmentation(self) -> float:
        """Internal fragmentation: fraction of ALLOCATED slots not holding a
        token (the unwritten tails of last blocks). 0.0 when nothing is
        allocated."""
        allocated_slots = self.used_blocks * self.block_size
        if not allocated_slots:
            return 0.0
        live_tokens = sum(self._tokens.values())
        return (allocated_slots - live_tokens) / allocated_slots

    def stats(self) -> dict:
        return {
            "block_size": self.block_size,
            "usable_blocks": self.usable_blocks,
            "free_blocks": self.free_blocks,
            "used_blocks": self.used_blocks,
            "sequences": len(self._tables),
            "live_tokens": sum(self._tokens.values()),
            "occupancy": round(self.occupancy(), 6),
            "fragmentation": round(self.fragmentation(), 6),
        }


def paged_attention(q, k_pool, v_pool, block_tables, q_positions, scale=None):
    """Paged variant of ``generation._cached_attention``.

    q ``[B, S, H, D]``; per-layer pools ``[num_blocks, block_size, Hkv, D]``;
    ``block_tables [B, W]`` (physical block ids, null-padded);
    ``q_positions [B, S]`` per-row absolute positions. Gathers each row's
    blocks into a contiguous ``[B, W*block_size, Hkv, D]`` view and runs the
    shared masked-attention core: a slot at gathered position ``t`` holds
    logical token ``t`` of that sequence, and only slots with ``t <=
    q_position`` are attended, so null/stale slots are masked to an exact
    0 contribution (bitwise parity with the contiguous path)."""
    B = q.shape[0]
    k_cache = k_pool[block_tables].reshape(B, -1, k_pool.shape[2], k_pool.shape[3])
    v_cache = v_pool[block_tables].reshape(B, -1, v_pool.shape[2], v_pool.shape[3])
    kv_pos = jnp.arange(k_cache.shape[1])
    allow = kv_pos[None, None, :] <= q_positions[:, :, None]  # [B, S, T]
    return _masked_attention(q, k_cache, v_cache, allow[:, None], scale)
