"""Paged KV cache: fixed-size blocks in one preallocated device pool.

The single-stream decode path (``generation.init_kv_cache``) reserves
``max_len`` cache slots per sequence up front — fine for one request, fatal
for serving: a 16-token reply and a 2k-token reply would each pin
``max_len`` slots, so heterogeneous traffic wastes most of HBM on slots that
are never written. The paged design (vLLM's PagedAttention, arXiv:2309.06180)
carves ONE preallocated pool into fixed-size blocks:

- device side: ``{"k","v"}: [L, num_blocks, block_size, Hkv, D]`` — allocated
  once at engine start, never resized (no allocation churn, no recompiles);
- host side: :class:`BlockAllocator` — a free list plus per-sequence block
  tables mapping logical block index -> physical block. Sequences grow one
  block at a time (``append``), release everything on completion/eviction
  (``free``), and the freed blocks are immediately reusable by any sequence,
  so memory tracks the LIVE token count instead of the worst case.

Physical block 0 is reserved as the **null block**: inactive batch slots and
padded table entries point at it, so their (masked, never-read) scatter
writes can never corrupt a live sequence's cache.

**Automatic prefix caching** (``prefix_caching=True``, vLLM's automatic
prefix caching applied to this pool): every FULL block is content-addressed
by a hash chained over its token ids (``h_i = H(h_{i-1}, tokens_i)``), so a
block match is a whole-prefix match by construction. Admission looks up the
longest cached block-aligned prefix of the new request's tokens
(:meth:`BlockAllocator.plan_prefix`) and maps those physical blocks straight
into the new block table with a reference count bump — only the uncached
tail is ever prefilled again. Shared blocks are immutable (full, and every
write the engine issues lands at positions at or past the uncached tail);
the one aligned edge case — the whole prefix matches, but the engine still
needs the last position's logits to sample — is handled by **copy-on-write**:
the final matched block is copied into a private block before the sequence
touches it, so a shared block is never written, period. ``free`` decrements
refcounts; a cached block whose count reaches zero parks in an LRU pool
(content intact, still matchable) and is only truly reclaimed when the free
list runs dry — reclaim-before-reject, so caching can never cause an
admission rejection that an uncached pool would have accepted.

:func:`paged_attention` is the paged variant of the contiguous
``generation._cached_attention``: gather the sequence's blocks via its block
table, then run the SAME shared masked-attention core
(``generation._masked_attention``) — masked slots contribute exactly 0 to the
softmax, so paged decode is bitwise-identical to contiguous decode (the
parity tests in ``tests/test_serving.py`` hold this line). The TPU Pallas
kernel behind ``ops.flash_attention.paged_attention`` replaces the gather
with VMEM block streaming; this function stays the reference semantics.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax.numpy as jnp

from ..generation import _masked_attention
from ..models.transformer import LlamaConfig
from ..telemetry import metrics as _metrics

__all__ = [
    "NULL_BLOCK",
    "BlockPoolExhausted",
    "BlockAllocatorError",
    "BlockAllocator",
    "PrefixPlan",
    "PrefixAllocation",
    "init_block_pool",
    "paged_attention",
]

#: physical block index reserved for inactive/padded writes (never allocated)
NULL_BLOCK = 0


class BlockAllocatorError(RuntimeError):
    """Misuse of the allocator: double-free, append/lookup after free."""


class BlockPoolExhausted(RuntimeError):
    """No free block available — the scheduler should preempt or defer."""


def init_block_pool(
    config: LlamaConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16
) -> dict:
    """Device pool ``{"k","v"}: [L, num_blocks, block_size, Hkv, D]``
    (``num_blocks`` INCLUDES the reserved null block 0)."""
    shape = (config.n_layers, num_blocks, block_size, config.n_kv_heads, config.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _chain_hash(prev: bytes, block_tokens: np.ndarray) -> bytes:
    """Hash of one full block chained over everything before it: a block's
    identity is (all preceding tokens, its own tokens) — so a single-block
    match IS a whole-prefix match. blake2b-128: collisions are what would
    silently splice one request's KV into another, so a real hash, not CRC."""
    return hashlib.blake2b(
        prev + np.asarray(block_tokens, np.int32).tobytes(), digest_size=16
    ).digest()


@dataclass(frozen=True)
class PrefixPlan:
    """Read-only admission plan for one token prefix (``plan_prefix``).

    ``matched`` are the cached physical blocks covering the longest cached
    block-aligned prefix; ``cached_tokens`` is how many leading tokens need
    NO prefill; ``cow`` flags the aligned edge case (the whole prefix is
    cached — the last matched block will be copied-on-write so the engine
    can recompute the final position's logits in a private block);
    ``fresh_blocks`` is what allocation will actually take from the pool —
    the only number admission accounting should charge. ``lru_pinned``
    counts matched blocks currently sitting in the reclaimable LRU pool:
    they are part of ``available_blocks`` today but this mapping will pin
    them, so admission must charge ``fresh_blocks + lru_pinned`` against
    the availability watermark (or the allocation it green-lit would
    throw)."""

    matched: "tuple[int, ...]"
    hashes: "tuple[bytes, ...]"
    cached_tokens: int
    cow: bool
    fresh_blocks: int
    lru_pinned: int = 0


@dataclass(frozen=True)
class PrefixAllocation:
    """Result of :meth:`BlockAllocator.allocate_with_prefix`: the block
    table, how many leading tokens are already cached (the engine prefills
    only from there), and the copy-on-write pair ``(src, dst)`` the engine
    must apply to the device pool BEFORE any write (``None`` when no COW)."""

    table: "list[int]"
    cached_tokens: int
    cow: "Optional[tuple[int, int]]"


class BlockAllocator:
    """Host-side block bookkeeping for one device pool.

    Free blocks live on a LIFO free list (hot reuse: a just-freed block is
    handed out next, so the working set stays compact). Per-sequence state is
    a block table (physical block ids, logical order) plus the sequence's
    token count; ``append`` grows the table only when the token count crosses
    a block boundary. Fragmentation here is purely INTERNAL (the unwritten
    tail of each sequence's last block) — fixed-size blocks cannot fragment
    externally, which is the point of paging.

    With ``prefix_caching=True`` every block carries a reference count and
    full blocks are content-addressed (module docstring has the full story):
    ``allocate_with_prefix`` maps cached blocks into new tables, ``free``
    only releases a block when its refcount hits zero, and zero-reference
    cached blocks park in an LRU pool reclaimed on demand before any
    exhaustion error. ``prefix_caching=False`` keeps every legacy code path
    byte-identical (refcounts exist but are always exactly one).
    """

    def __init__(self, num_blocks: int, block_size: int, *, prefix_caching: bool = False):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the reserved null block)")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.prefix_caching = prefix_caching
        # LIFO: lowest ids are handed out first at start, re-frees come back
        # on top. Block 0 is never on the list (reserved null block).
        self._free: "list[int]" = list(range(num_blocks - 1, 0, -1))
        self._tables: "dict[object, list[int]]" = {}
        self._tokens: "dict[object, int]" = {}
        # prefix-cache state (inert when prefix_caching is False):
        self._ref: "dict[int, int]" = {}  # physical block -> reference count
        self._cached: "dict[bytes, int]" = {}  # chain hash -> physical block
        self._block_hash: "dict[int, bytes]" = {}  # physical block -> chain hash
        #: cached blocks with zero references, oldest-unreferenced first —
        #: matchable until reclaimed by :meth:`_take_block`
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        #: per-sequence chain hashes of its full blocks registered so far
        self._chain: "dict[object, list[bytes]]" = {}
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.cow_copies = 0
        self.reclaimed_blocks = 0

    # -- capacity ------------------------------------------------------------

    @property
    def usable_blocks(self) -> int:
        """Allocatable blocks (pool minus the null block)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def reclaimable_blocks(self) -> int:
        """Cached-but-unreferenced blocks (the LRU pool): matchable today,
        reclaimed on demand when the free list runs dry."""
        return len(self._lru)

    @property
    def available_blocks(self) -> int:
        """What an allocation can actually draw on: truly free blocks plus
        the reclaimable LRU pool. This is the admission-accounting number —
        caching must never reject a request an uncached pool would admit."""
        return len(self._free) + len(self._lru)

    @property
    def used_blocks(self) -> int:
        """Blocks referenced by live sequences (shared blocks count once)."""
        return self.usable_blocks - self.available_blocks

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens``."""
        return max(1, -(-n_tokens // self.block_size))

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_for(n_tokens) <= self.available_blocks

    # -- prefix cache internals ----------------------------------------------

    def _take_block(self) -> int:
        """Pop a truly free block, reclaiming the least-recently-unreferenced
        cached block when the free list is dry (its index entry dies with it
        — the content is about to be overwritten). Caller checked capacity."""
        if self._free:
            return self._free.pop()
        blk, _ = self._lru.popitem(last=False)  # oldest unreferenced first
        h = self._block_hash.pop(blk)
        del self._cached[h]
        self.reclaimed_blocks += 1
        _metrics.inc("accelerate_blocks_reclaimed_total")
        return blk

    def _unref(self, blk: int) -> None:
        self._ref[blk] = self._ref.get(blk, 1) - 1
        if self._ref[blk] > 0:
            return
        del self._ref[blk]
        if blk in self._block_hash:
            # content-addressed and intact: park in the LRU pool, matchable
            # until the free list runs dry and _take_block reclaims it
            self._lru[blk] = None
        else:
            self._free.append(blk)

    def _match_chain(self, token_ids: np.ndarray) -> "tuple[list[int], list[bytes]]":
        """Walk the chain hash over full blocks of ``token_ids``; stop at the
        first block missing from the content index."""
        blocks: "list[int]" = []
        hashes: "list[bytes]" = []
        prev = b""
        for i in range(len(token_ids) // self.block_size):
            h = _chain_hash(prev, token_ids[i * self.block_size : (i + 1) * self.block_size])
            blk = self._cached.get(h)
            if blk is None:
                break
            blocks.append(blk)
            hashes.append(h)
            prev = h
        return blocks, hashes

    def plan_prefix(self, token_ids) -> PrefixPlan:
        """Read-only: what would ``allocate_with_prefix`` reuse and take for
        this prefix? ``fresh_blocks`` is the pool charge (shared blocks are
        free); admission's watermark check compares it to
        :attr:`available_blocks`. Mutates nothing."""
        token_ids = np.asarray(token_ids, np.int32).reshape(-1)
        n = int(token_ids.size)
        total = self.blocks_for(n)
        if not self.prefix_caching:
            return PrefixPlan((), (), 0, False, total)
        matched, hashes = self._match_chain(token_ids)
        pinned = sum(1 for b in matched if b in self._lru)
        if matched and len(matched) * self.block_size == n:
            # whole prefix cached — COW the last matched block so the engine
            # can recompute the final position's logits in a private block
            return PrefixPlan(
                tuple(matched), tuple(hashes), n - 1, True,
                total - len(matched) + 1, pinned,
            )
        return PrefixPlan(
            tuple(matched), tuple(hashes),
            len(matched) * self.block_size, False, total - len(matched), pinned,
        )

    # -- lifecycle -----------------------------------------------------------

    def allocate(self, seq_id, n_tokens: int) -> "list[int]":
        """Create a sequence holding ``n_tokens`` (its prompt) from fresh
        blocks only; returns the block table. :class:`BlockPoolExhausted`
        when the pool can't cover it (nothing is allocated on failure —
        all-or-nothing). Prefix-aware admission goes through
        :meth:`allocate_with_prefix` instead."""
        if seq_id in self._tables:
            raise BlockAllocatorError(f"sequence {seq_id!r} already allocated")
        need = self.blocks_for(n_tokens)
        if need > self.available_blocks:
            raise BlockPoolExhausted(
                f"need {need} block(s) for {n_tokens} token(s), "
                f"only {self.available_blocks} free"
            )
        table = [self._take_block() for _ in range(need)]
        for blk in table:
            self._ref[blk] = 1
        self._tables[seq_id] = table
        self._tokens[seq_id] = n_tokens
        self._chain[seq_id] = []
        return list(table)

    def allocate_with_prefix(
        self, seq_id, token_ids, plan: "Optional[PrefixPlan]" = None
    ) -> PrefixAllocation:
        """Create a sequence for ``token_ids``, mapping the longest cached
        block-aligned prefix into its table (refcount++) and taking fresh
        blocks only for the uncached tail. All-or-nothing on exhaustion.
        With caching off this is exactly :meth:`allocate`. ``plan`` skips
        re-hashing when the caller just ran :meth:`plan_prefix` for the SAME
        tokens with no allocator mutation in between (the scheduler's
        admission loop) — a stale plan here would map the wrong blocks."""
        token_ids = np.asarray(token_ids, np.int32).reshape(-1)
        n = int(token_ids.size)
        if not self.prefix_caching:
            return PrefixAllocation(self.allocate(seq_id, n), 0, None)
        if seq_id in self._tables:
            raise BlockAllocatorError(f"sequence {seq_id!r} already allocated")
        if plan is None:
            plan = self.plan_prefix(token_ids)
        # matched blocks sitting in the LRU pool are counted available but are
        # about to be pinned by this very mapping — they can't also serve as
        # fresh blocks, so subtract them from what the tail can draw on
        if plan.fresh_blocks > self.available_blocks - plan.lru_pinned:
            raise BlockPoolExhausted(
                f"need {plan.fresh_blocks} fresh block(s) for {n} token(s) "
                f"({len(plan.matched)} cached), only "
                f"{self.available_blocks - plan.lru_pinned} available"
            )
        for blk in plan.matched:
            self._ref[blk] = self._ref.get(blk, 0) + 1
            self._lru.pop(blk, None)
        table = list(plan.matched)
        cow: "Optional[tuple[int, int]]" = None
        if plan.cow:
            dst = self._take_block()
            self._ref[dst] = 1
            src = table[-1]
            table[-1] = dst
            # src keeps the reference we took above until the engine has
            # actually copied its content on device (:meth:`cow_done`) —
            # releasing it now would park it in the LRU pool where another
            # admission in the SAME step could reclaim and overwrite it
            # before the copy reads it (use-after-free)
            cow = (src, dst)
        for _ in range(self.blocks_for(n) - len(table)):
            blk = self._take_block()
            self._ref[blk] = 1
            table.append(blk)
        self._tables[seq_id] = table
        self._tokens[seq_id] = n
        self._chain[seq_id] = list(plan.hashes)
        # content-index the full blocks of the UNCACHED tail right now, not
        # after prefill: a request admitted later in the SAME engine step can
        # then map them, and admission order == prefill order guarantees the
        # writer's prefill lands before any reader's (the engine prefills
        # admitted requests in order, and preemption only runs after the
        # step's prefill phase)
        self.register_full_blocks(seq_id, token_ids)
        self.prefix_lookups += 1
        if plan.cached_tokens:
            self.prefix_hits += 1
            self.prefix_hit_tokens += plan.cached_tokens
        if cow is not None:
            self.cow_copies += 1
            _metrics.inc("accelerate_cow_copies_total")
        return PrefixAllocation(list(table), plan.cached_tokens, cow)

    def cow_done(self, blk: int) -> None:
        """Release the copy-on-write pin on ``blk`` (the ``src`` half of a
        :class:`PrefixAllocation`'s ``cow`` pair). The engine calls this
        exactly once, AFTER the device-side block copy has been issued — the
        pin is what keeps a zero-reference cached source block out of the
        reclaimable pool while a copy still needs its content."""
        self._unref(blk)

    def register_full_blocks(self, seq_id, written_token_ids) -> int:
        """Content-index every full block of ``seq_id`` not yet registered.
        ``written_token_ids`` are the tokens whose KV the engine has actually
        written (prompt + generated-so-far); the engine calls this after
        prefill and whenever decode fills a block. Idempotent and incremental
        (the per-sequence chain state remembers where it left off); a no-op
        with caching off. Returns how many blocks were newly indexed."""
        if not self.prefix_caching:
            return 0
        if seq_id not in self._tables:
            raise BlockAllocatorError(
                f"register on unknown/freed sequence {seq_id!r} (use-after-free?)"
            )
        written = np.asarray(written_token_ids, np.int32).reshape(-1)
        table = self._tables[seq_id]
        chain = self._chain[seq_id]
        n_full = min(int(written.size) // self.block_size, len(table))
        new = 0
        while len(chain) < n_full:
            i = len(chain)
            h = _chain_hash(
                chain[-1] if chain else b"",
                written[i * self.block_size : (i + 1) * self.block_size],
            )
            chain.append(h)
            blk = table[i]
            # first writer wins: identical content registered by another
            # sequence keeps its block; ours stays unregistered (it frees to
            # the free list instead of the LRU pool — no duplicate entries)
            if h not in self._cached and blk not in self._block_hash and blk != NULL_BLOCK:
                self._cached[h] = blk
                self._block_hash[blk] = h
                new += 1
        return new

    def chain_hashes(self, seq_id) -> "list[bytes]":
        """The sequence's registered full-block chain hashes, oldest first —
        the content addresses a KV handoff ships (``serving/disagg.py``).
        Raises on an unknown/freed sequence like every other lookup."""
        if seq_id not in self._tables:
            raise BlockAllocatorError(
                f"chain_hashes of unknown/freed sequence {seq_id!r} (use-after-free?)"
            )
        return list(self._chain.get(seq_id, []))

    def adopt_block(self, chain_hash: bytes) -> Optional[int]:
        """Content-index one externally produced full block (the decode side
        of a prefill→decode KV handoff): take a block, register it under
        ``chain_hash``, and park it UNREFERENCED in the LRU pool — matchable
        by the next admission's :meth:`plan_prefix`, reclaimable under
        pressure like any cached block, so a landing can never strand pool
        capacity. The caller writes the block's device content at the
        returned physical index. Returns ``None`` when the hash is already
        cached (content-addressed dedup: nothing to copy)."""
        if not self.prefix_caching:
            raise BlockAllocatorError("adopt_block requires prefix_caching=True")
        if chain_hash in self._cached:
            return None
        if self.available_blocks < 1:
            raise BlockPoolExhausted(
                "no block available to adopt a handed-off KV block"
            )
        blk = self._take_block()
        self._cached[chain_hash] = blk
        self._block_hash[blk] = chain_hash
        self._lru[blk] = None
        return blk

    def append(self, seq_id, n_tokens: int = 1) -> "list[int]":
        """Grow a sequence by ``n_tokens``; allocates new block(s) only when
        the count crosses a block boundary. Returns the block ids newly
        allocated (often empty). On exhaustion the sequence is left unchanged
        and :class:`BlockPoolExhausted` propagates — the scheduler preempts."""
        if seq_id not in self._tables:
            raise BlockAllocatorError(
                f"append on unknown/freed sequence {seq_id!r} (use-after-free?)"
            )
        have = len(self._tables[seq_id])
        need = self.blocks_for(self._tokens[seq_id] + n_tokens) - have
        if need > self.available_blocks:
            raise BlockPoolExhausted(
                f"sequence {seq_id!r} needs {need} more block(s), "
                f"only {self.available_blocks} free"
            )
        new = [self._take_block() for _ in range(max(0, need))]
        for blk in new:
            self._ref[blk] = 1
        self._tables[seq_id].extend(new)
        self._tokens[seq_id] += n_tokens
        return new

    def free(self, seq_id) -> int:
        """Drop all of a sequence's references; returns how many blocks it
        held. A block is only actually released when its reference count
        hits zero — cached blocks park in the LRU pool (still matchable),
        unregistered ones return to the free list. Double-free raises
        :class:`BlockAllocatorError`."""
        if seq_id not in self._tables:
            raise BlockAllocatorError(f"double free of sequence {seq_id!r}")
        table = self._tables.pop(seq_id)
        del self._tokens[seq_id]
        self._chain.pop(seq_id, None)
        for blk in reversed(table):  # LIFO: first-allocated reused last
            self._unref(blk)
        return len(table)

    # -- views ---------------------------------------------------------------

    def block_table(self, seq_id, pad_to: Optional[int] = None) -> np.ndarray:
        """The sequence's physical block ids (logical order) as int32,
        padded with the null block to ``pad_to`` (the bucketed table width)."""
        if seq_id not in self._tables:
            raise BlockAllocatorError(
                f"block_table of unknown/freed sequence {seq_id!r} (use-after-free?)"
            )
        table = self._tables[seq_id]
        width = len(table) if pad_to is None else pad_to
        if len(table) > width:
            raise ValueError(f"table of {len(table)} block(s) does not fit pad_to={pad_to}")
        out = np.full((width,), NULL_BLOCK, np.int32)
        out[: len(table)] = table
        return out

    def tokens(self, seq_id) -> int:
        if seq_id not in self._tokens:
            raise BlockAllocatorError(f"tokens of unknown/freed sequence {seq_id!r}")
        return self._tokens[seq_id]

    def num_seq_blocks(self, seq_id) -> int:
        if seq_id not in self._tables:
            raise BlockAllocatorError(f"blocks of unknown/freed sequence {seq_id!r}")
        return len(self._tables[seq_id])

    def live_sequences(self) -> "list":
        return list(self._tables)

    def occupancy(self) -> float:
        """Fraction of usable blocks currently allocated."""
        return self.used_blocks / self.usable_blocks

    def fragmentation(self) -> float:
        """Internal fragmentation: fraction of ALLOCATED slots not holding a
        token (the unwritten tails of last blocks). 0.0 when nothing is
        allocated. Shared blocks hold one physical copy serving several
        sequences' logical tokens, so sharing can push the logical count past
        the physical slots — clamp at 0 (sharing is the opposite of waste)."""
        allocated_slots = self.used_blocks * self.block_size
        if not allocated_slots:
            return 0.0
        live_tokens = sum(self._tokens.values())
        return max(0.0, (allocated_slots - live_tokens) / allocated_slots)

    def shared_blocks(self) -> int:
        """Physical blocks currently referenced by more than one sequence."""
        return sum(1 for c in self._ref.values() if c > 1)

    def stats(self) -> dict:
        out = {
            "block_size": self.block_size,
            "usable_blocks": self.usable_blocks,
            "free_blocks": self.free_blocks,
            "used_blocks": self.used_blocks,
            "sequences": len(self._tables),
            "live_tokens": sum(self._tokens.values()),
            "occupancy": round(self.occupancy(), 6),
            "fragmentation": round(self.fragmentation(), 6),
        }
        if self.prefix_caching:
            out.update(
                cached_blocks=len(self._block_hash),
                reclaimable_blocks=self.reclaimable_blocks,
                shared_blocks=self.shared_blocks(),
                prefix_lookups=self.prefix_lookups,
                prefix_hits=self.prefix_hits,
                prefix_hit_tokens=self.prefix_hit_tokens,
                cow_copies=self.cow_copies,
                reclaimed_blocks=self.reclaimed_blocks,
            )
        return out


def paged_attention(q, k_pool, v_pool, block_tables, q_positions, scale=None):
    """Paged variant of ``generation._cached_attention``.

    q ``[B, S, H, D]``; per-layer pools ``[num_blocks, block_size, Hkv, D]``;
    ``block_tables [B, W]`` (physical block ids, null-padded);
    ``q_positions [B, S]`` per-row absolute positions. Gathers each row's
    blocks into a contiguous ``[B, W*block_size, Hkv, D]`` view and runs the
    shared masked-attention core: a slot at gathered position ``t`` holds
    logical token ``t`` of that sequence, and only slots with ``t <=
    q_position`` are attended, so null/stale slots are masked to an exact
    0 contribution (bitwise parity with the contiguous path)."""
    B = q.shape[0]
    k_cache = k_pool[block_tables].reshape(B, -1, k_pool.shape[2], k_pool.shape[3])
    v_cache = v_pool[block_tables].reshape(B, -1, v_pool.shape[2], v_pool.shape[3])
    kv_pos = jnp.arange(k_cache.shape[1])
    allow = kv_pos[None, None, :] <= q_positions[:, :, None]  # [B, S, T]
    return _masked_attention(q, k_cache, v_cache, allow[:, None], scale)
