"""Bitwise correctness canaries: the parity invariant as a live probe.

The repo's signature serving invariant is that batched, paged, preempted,
failed-over, speculative — every production path — produces tokens
bitwise-equal to the single-stream reference (``greedy_generate`` /
``sample_generate`` on the same prompt and rng seed). The test suite
proves that at commit time; this module turns it into a *continuous*
production probe: at startup the router precomputes golden token streams
from the single-stream reference against the fleet's own spec
(:func:`precompute_goldens`), then periodically injects those prompts as
ordinary requests (:class:`CanaryProbe`). A replica whose answer differs
in ANY token position is wrong — not slow, wrong — so it gets a
``canary_failure`` record naming the first mismatching token and counts
toward DRAINING pressure exactly like an SLO-burning replica
(``serving/router.py``).

Canary traffic is deliberately invisible to the user-facing ledgers: it
bypasses admission control, SLO observation, and the router's request
counters, and is never failed over (a probe's job is to test THIS
replica; retrying it elsewhere would launder the evidence).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, Optional, Sequence

__all__ = ["CanaryGolden", "CanaryProbe", "precompute_goldens"]


@dataclass(frozen=True)
class CanaryGolden:
    """One golden probe: a prompt plus the token stream the single-stream
    reference produced for it. ``expected`` holds the NEW tokens only
    (the engine's done event reports generated tokens, not the prompt)."""

    name: str
    prompt: "tuple[int, ...]"
    max_new_tokens: int
    expected: "tuple[int, ...]"
    rng_seed: int = 0


def _default_prompts(vocab_size: int, count: int) -> "list[tuple[int, ...]]":
    """Deterministic synthetic prompts inside the vocabulary (token 0 is
    avoided — pad/bos conventions vary by tokenizer)."""
    span = max(2, vocab_size - 1)
    prompts = []
    for i in range(count):
        length = 5 + i
        prompts.append(tuple(1 + (3 + 7 * i + 2 * j) % span for j in range(length)))
    return prompts


def precompute_goldens(
    spec: Any,
    prompts: Optional[Iterable[Sequence[int]]] = None,
    *,
    count: int = 2,
    max_new_tokens: int = 6,
    rng_seed_base: int = 7001,
) -> "list[CanaryGolden]":
    """Run the single-stream reference over the canary prompts.

    ``spec`` is a :class:`~.replica.ReplicaSpec`: its ``build_params()`` /
    ``config()`` are deterministic, so the goldens computed here are THE
    answer every correctly-functioning replica of this fleet must
    reproduce bitwise. Greedy specs (temperature 0) use
    ``greedy_generate``; sampled specs use ``sample_generate`` with the
    same rng seed the probe will ship in the request payload — sampling is
    a pure function of (prompt, rng_seed), so the comparison stays exact.
    """
    import jax
    import numpy as np

    from .. import generation as _generation

    config = spec.config()
    params = spec.build_params()
    if prompts is None:
        prompts = _default_prompts(int(config.vocab_size), count)
    goldens: "list[CanaryGolden]" = []
    for i, prompt in enumerate(prompts):
        prompt_t = tuple(int(t) for t in prompt)
        arr = np.asarray(prompt_t, dtype=np.int32)[None]
        seed = rng_seed_base + i
        temperature = float(getattr(spec, "temperature", 0.0) or 0.0)
        if temperature > 0.0:
            ref = _generation.sample_generate(
                params, arr, config, max_new_tokens=max_new_tokens,
                temperature=temperature,
                top_k=int(getattr(spec, "top_k", 0) or 0),
                top_p=float(getattr(spec, "top_p", 1.0) or 1.0),
                rng_key=jax.random.PRNGKey(seed),
            )
        else:
            ref = _generation.greedy_generate(
                params, arr, config, max_new_tokens=max_new_tokens
            )
        expected = tuple(int(t) for t in np.asarray(ref[0])[len(prompt_t):])
        goldens.append(
            CanaryGolden(
                name=f"golden{i}",
                prompt=prompt_t,
                max_new_tokens=max_new_tokens,
                expected=expected,
                rng_seed=seed,
            )
        )
    return goldens


class CanaryProbe:
    """Schedule + verdict state for the router's canary injection.

    The router owns replica selection and request plumbing; the probe owns
    WHEN to inject (``due``/``schedule``), WHICH golden goes next
    (round-robin), and the bitwise verdict (:meth:`check` — None on an
    exact match, else a dict naming the first mismatching position)."""

    def __init__(
        self,
        goldens: "list[CanaryGolden]",
        *,
        interval_s: float = 30.0,
        drain_on_failure: bool = True,
    ):
        if not goldens:
            raise ValueError("CanaryProbe needs at least one golden")
        self.goldens = list(goldens)
        self.interval_s = float(interval_s)
        self.drain_on_failure = bool(drain_on_failure)
        self._next_due: Optional[float] = None  # None -> due immediately
        self._cursor = 0
        self.probes = 0
        self.failures = 0
        self.by_replica: "dict[str, dict]" = {}

    def due(self, now: float) -> bool:
        return self._next_due is None or now >= self._next_due

    def schedule(self, now: float) -> None:
        self._next_due = now + self.interval_s

    def next_golden(self) -> CanaryGolden:
        golden = self.goldens[self._cursor % len(self.goldens)]
        self._cursor += 1
        return golden

    @staticmethod
    def check(golden: CanaryGolden, tokens: Sequence[int]) -> Optional[dict]:
        """Bitwise verdict: None on exact match, else the first mismatch.

        A wrong length is a mismatch too — the mismatch index is the first
        position where one stream has a token the other lacks."""
        got = [int(t) for t in tokens]
        expected = list(golden.expected)
        if got == expected:
            return None
        idx = next(
            (i for i, (e, g) in enumerate(zip(expected, got)) if e != g),
            min(len(expected), len(got)),
        )
        return {
            "golden": golden.name,
            "mismatch_index": idx,
            "expected_token": expected[idx] if idx < len(expected) else None,
            "got_token": got[idx] if idx < len(got) else None,
            "expected_len": len(expected),
            "got_len": len(got),
        }

    def record_result(self, replica: str, ok: bool) -> None:
        self.probes += 1
        ent = self.by_replica.setdefault(replica, {"probes": 0, "failures": 0})
        ent["probes"] += 1
        if not ok:
            self.failures += 1
            ent["failures"] += 1

    def stats(self) -> dict:
        return {
            "probes": self.probes,
            "failures": self.failures,
            "by_replica": {k: dict(v) for k, v in sorted(self.by_replica.items())},
        }
