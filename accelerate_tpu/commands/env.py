"""``accelerate-tpu env`` — platform/config diagnostic dump (reference ``commands/env.py``)."""

from __future__ import annotations

import argparse
import os
import platform

from .config import resolve_config_file


def _probe_jax(timeout: int = 60) -> dict:
    """Collect JAX backend facts in a KILLABLE subprocess.

    Remote-tunneled TPU backends have been observed to hang INSIDE backend
    init (a C call SIGALRM cannot interrupt) — and an outage is exactly when a
    user runs ``env`` for diagnostics, so the probe must never wedge the
    diagnostic itself. ``ACCELERATE_ENV_PROBE_TIMEOUT`` overrides the budget.
    """
    import json
    import subprocess
    import sys

    code = (
        "import json, jax\n"
        "print(json.dumps({\n"
        "  'JAX version': jax.__version__,\n"
        "  'JAX backend': jax.default_backend(),\n"
        "  'JAX device count': str(jax.device_count()),\n"
        "  'JAX local devices': ', '.join(str(d) for d in jax.local_devices()[:8]),\n"
        "  'JAX process count': str(jax.process_count()),\n"
        "}))\n"
    )
    try:
        res = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=timeout
        )
        if res.returncode == 0:
            # scan for OUR blob — a dict with the probe's key — so stray
            # JSON-formatted log lines or bare literals can't be mistaken
            # for it (or crash lines.update with a non-dict)
            for line in reversed(res.stdout.strip().splitlines()):
                try:
                    parsed = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(parsed, dict) and "JAX version" in parsed:
                    return parsed
            return {"JAX": "probe returned no parseable output"}
        # keep the field single-line: the last stderr line is the exception
        # message (e.g. "ModuleNotFoundError: No module named 'jax'")
        err_lines = res.stderr.strip().splitlines()
        detail = err_lines[-1][:300] if err_lines else f"rc={res.returncode}"
        return {"JAX": f"unavailable ({detail})"}
    except subprocess.TimeoutExpired:
        return {"JAX": f"backend init HUNG (> {timeout}s) — remote TPU tunnel likely down"}
    except Exception as e:  # pragma: no cover - defensive
        return {"JAX": f"unavailable ({e})"}


def env_command(args) -> int:
    import numpy as np

    import accelerate_tpu

    lines = {
        "`accelerate-tpu` version": accelerate_tpu.__version__,
        "Platform": platform.platform(),
        "Python version": platform.python_version(),
        "Numpy version": np.__version__,
    }
    try:
        probe_timeout = int(os.environ.get("ACCELERATE_ENV_PROBE_TIMEOUT", 60))
    except (TypeError, ValueError):  # a bad knob must not kill the diagnostic
        probe_timeout = 60
    if probe_timeout <= 0:  # 0/negative would misdiagnose a healthy backend as hung
        probe_timeout = 60
    lines.update(_probe_jax(timeout=probe_timeout))
    for mod in ("flax", "optax", "orbax.checkpoint", "torch", "transformers"):
        try:
            import importlib

            m = importlib.import_module(mod)
            lines[f"{mod} version"] = getattr(m, "__version__", "unknown")
        except Exception:
            lines[f"{mod} version"] = "not installed"
    accelerate_env = {k: v for k, v in os.environ.items()
                      if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "JAX_", "XLA_"))}
    lines["Environment variables"] = ""

    print("\nCopy-and-paste the text below in your GitHub issue\n")
    for k, v in lines.items():
        print(f"- {k}: {v}")
    for k, v in sorted(accelerate_env.items()):
        print(f"  - {k}={v}")
    path = resolve_config_file(getattr(args, "config_file", None))
    print(f"- Config file: {path or 'not found'}")
    if path and os.path.isfile(path):
        with open(path) as f:
            for line in f.read().splitlines():
                print(f"  {line}")
    return 0


def register_parser(subparsers) -> argparse.ArgumentParser:
    p = subparsers.add_parser("env", help="Print environment diagnostics")
    p.add_argument("--config_file", default=None)
    p.set_defaults(func=env_command)
    return p
