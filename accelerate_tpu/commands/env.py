"""``accelerate-tpu env`` — platform/config diagnostic dump (reference ``commands/env.py``)."""

from __future__ import annotations

import argparse
import os
import platform

from .config import resolve_config_file


def env_command(args) -> int:
    import numpy as np

    import accelerate_tpu

    lines = {
        "`accelerate-tpu` version": accelerate_tpu.__version__,
        "Platform": platform.platform(),
        "Python version": platform.python_version(),
        "Numpy version": np.__version__,
    }
    try:
        import jax

        lines["JAX version"] = jax.__version__
        lines["JAX backend"] = jax.default_backend()
        lines["JAX device count"] = str(jax.device_count())
        lines["JAX local devices"] = ", ".join(str(d) for d in jax.local_devices()[:8])
        lines["JAX process count"] = str(jax.process_count())
    except Exception as e:  # pragma: no cover - depends on runtime
        lines["JAX"] = f"unavailable ({e})"
    for mod in ("flax", "optax", "orbax.checkpoint", "torch", "transformers"):
        try:
            import importlib

            m = importlib.import_module(mod)
            lines[f"{mod} version"] = getattr(m, "__version__", "unknown")
        except Exception:
            lines[f"{mod} version"] = "not installed"
    accelerate_env = {k: v for k, v in os.environ.items()
                      if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "JAX_", "XLA_"))}
    lines["Environment variables"] = ""

    print("\nCopy-and-paste the text below in your GitHub issue\n")
    for k, v in lines.items():
        print(f"- {k}: {v}")
    for k, v in sorted(accelerate_env.items()):
        print(f"  - {k}={v}")
    path = resolve_config_file(getattr(args, "config_file", None))
    print(f"- Config file: {path or 'not found'}")
    if path and os.path.isfile(path):
        with open(path) as f:
            for line in f.read().splitlines():
                print(f"  {line}")
    return 0


def register_parser(subparsers) -> argparse.ArgumentParser:
    p = subparsers.add_parser("env", help="Print environment diagnostics")
    p.add_argument("--config_file", default=None)
    p.set_defaults(func=env_command)
    return p
