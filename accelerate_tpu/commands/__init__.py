"""Command-line interface for accelerate-tpu.

TPU-native analogue of the reference CLI (``/root/reference/src/accelerate/commands/``,
SURVEY.md §2.4): ``accelerate-tpu {config,launch,env,estimate-memory,merge-weights,
test,tpu-config}``. The launch model differs fundamentally: the reference forks one
process per accelerator (torchrun / xmp.spawn); we are SPMD — ONE process per host,
with every chip on the host visible to that process, and multi-host coordination via
``jax.distributed.initialize`` (coordinator address handed out by the launcher).
"""
