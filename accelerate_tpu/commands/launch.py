"""``accelerate-tpu launch`` — env-var protocol + process spawn.

Reference: ``commands/launch.py`` (SURVEY.md §2.4, §3.1). The reference forks one
process per accelerator (torchrun / ``xmp.spawn``) and rendezvouses over
MASTER_ADDR; under SPMD we spawn ONE process per host — single-host launch is
"set env, exec the script", and multi-host launch distributes
``ACCELERATE_COORDINATOR_ADDRESS`` / ``ACCELERATE_NUM_PROCESSES`` /
``ACCELERATE_PROCESS_ID`` (consumed by ``state.py`` →
``jax.distributed.initialize``), optionally fanning out over a TPU pod via
``gcloud compute tpus tpu-vm ssh --worker=all`` (the moral twin of the
reference's ``tpu_pod_launcher`` → ``xla_dist``, ``commands/launch.py:1117``).
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
from typing import Optional

from .config import ClusterConfig, resolve_config_file


def launch_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        p = subparsers.add_parser("launch", help="Launch a training script")
    else:
        p = argparse.ArgumentParser("accelerate-tpu launch")
    p.add_argument("--config_file", default=None)
    p.add_argument("-m", "--module", action="store_true",
                   help="Interpret the script as a python module (python -m)")
    p.add_argument("--cpu", action="store_true",
                   help="Run on simulated CPU devices (sets JAX_PLATFORMS=cpu)")
    p.add_argument("--num_processes", type=int, default=None,
                   help="With --cpu: number of simulated devices "
                        "(XLA_FLAGS=--xla_force_host_platform_device_count)")
    p.add_argument("--num_machines", type=int, default=None, help="Number of hosts")
    p.add_argument("--machine_rank", type=int, default=None, help="This host's rank")
    p.add_argument("--main_process_ip", default=None, help="Coordinator (host 0) IP")
    p.add_argument("--main_process_port", type=int, default=None)
    p.add_argument("--mixed_precision", default=None,
                   choices=("no", "bf16", "fp16", "fp8"))
    p.add_argument("--gradient_accumulation_steps", type=int, default=None)
    p.add_argument("--max_restarts", type=int, default=None,
                   help="Elastic supervision: relaunch the script up to N times on "
                        "nonzero exit (reference: torchrun --max_restarts passthrough, "
                        "commands/launch.py:998-1031). Restarted runs see "
                        "ACCELERATE_RESTART_COUNT and ACCELERATE_RESUME_FROM_CHECKPOINT=latest "
                        "so they can load_state() and continue.")
    p.add_argument("--monitor_interval", type=float, default=5.0,
                   help="Seconds to wait between a failure and the relaunch")
    p.add_argument("--elastic", action="store_true",
                   help="Full elastic supervision (resilience/supervisor.py): watch "
                        "exit codes (101 = watchdog stall abort), heartbeat-file gaps "
                        "and flight dumps; auto-resume the cohort from the last "
                        "committed checkpoint with bounded exponential backoff under "
                        "the --max_restarts budget (default 3 when --elastic); "
                        "repeated crashes at the same step stop with a poison-step "
                        "diagnosis. Arms the watchdog (ACCELERATE_WATCHDOG_ABORT) and "
                        "sets ACCELERATE_ELASTIC_RESUME so a cross-topology resume "
                        "re-shards instead of erroring.")
    p.add_argument("--heartbeat_timeout", type=float, default=0.0,
                   help="With --elastic: restart the cohort when a rank's heartbeat "
                        "file (touched by its watchdog every tick) goes stale for "
                        "this many seconds. 0 disables the file watch.")
    p.add_argument("--debug", action="store_true",
                   help="ACCELERATE_DEBUG_MODE: verify collective shapes across processes")
    # DeepSpeed-style flags (reference utils/launch.py:557-577 env protocol;
    # here they configure the native ZeRO shardings via DeepSpeedPlugin.from_env)
    p.add_argument("--use_deepspeed", action="store_true",
                   help="Signal DeepSpeed-style config: the script's Accelerator() "
                        "builds a DeepSpeedPlugin from the ACCELERATE_DEEPSPEED_* env")
    p.add_argument("--zero_stage", type=int, default=None)
    p.add_argument("--offload_optimizer_device", default=None,
                   choices=("none", "cpu", "nvme"))
    p.add_argument("--offload_param_device", default=None, choices=("none", "cpu", "nvme"))
    p.add_argument("--gradient_clipping", type=float, default=None)
    p.add_argument("--deepspeed_config_file", default=None,
                   help="Reference ds_config json; mined for stage/accum/clipping/offload")
    # Mesh axes (PARALLELISM_CONFIG_* protocol, parallelism_config.py)
    for axis in ("dp_replicate", "dp_shard", "tp", "cp", "sp", "ep", "pp"):
        p.add_argument(f"--{axis}_size", type=int, default=None)
    p.add_argument("--cp_rotate_method", default=None, choices=("allgather", "ring", "zigzag"))
    # TPU pod fan-out
    p.add_argument("--tpu_pod", action="store_true",
                   help="Fan out to every TPU-VM worker via gcloud ssh")
    p.add_argument("--tpu_name", default=None)
    p.add_argument("--tpu_zone", default=None)
    p.add_argument("--no_tpu_cluster", dest="tpu_pod", action="store_false")
    p.add_argument("training_script", help="Path to the script (or module with -m)")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    if subparsers is not None:
        p.set_defaults(func=launch_command)
    return p


def _merge_config(args) -> ClusterConfig:
    """CLI flags override config-file values (reference ``_validate_launch_command``)."""
    path = resolve_config_file(args.config_file)
    cfg = ClusterConfig.load(path) if path else ClusterConfig()
    for attr, flag in [
        ("num_machines", args.num_machines),
        ("machine_rank", args.machine_rank),
        ("main_process_ip", args.main_process_ip),
        ("main_process_port", args.main_process_port),
        ("mixed_precision", args.mixed_precision),
        ("num_processes", args.num_processes),
        ("tpu_name", args.tpu_name),
        ("tpu_zone", args.tpu_zone),
    ]:
        if flag is not None:
            setattr(cfg, attr, flag)
    for axis in ("dp_replicate", "dp_shard", "tp", "cp", "sp", "ep", "pp"):
        v = getattr(args, f"{axis}_size")
        if v is not None:
            setattr(cfg, f"{axis}_size", v)
    if args.cp_rotate_method is not None:
        cfg.cp_rotate_method = args.cp_rotate_method
    if args.gradient_accumulation_steps is not None:
        cfg.gradient_accumulation_steps = args.gradient_accumulation_steps
    if args.cpu:
        cfg.use_cpu = True
    if args.debug:
        cfg.debug = True
    return cfg


def build_launch_env(cfg: ClusterConfig) -> dict[str, str]:
    """The env-var channel (reference ``utils/launch.py:197-420``)."""
    env: dict[str, str] = {}
    env["ACCELERATE_MIXED_PRECISION"] = cfg.mixed_precision
    if cfg.gradient_accumulation_steps != 1:
        env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] = str(cfg.gradient_accumulation_steps)
    if cfg.debug:
        env["ACCELERATE_DEBUG_MODE"] = "true"
    if cfg.use_cpu:
        # platform selection happens via jax.config.update in PartialState —
        # setting JAX_PLATFORMS here can hang backend init on some TPU-plugin
        # installs, config.update never does
        env["ACCELERATE_USE_CPU"] = "true"
        n = cfg.num_processes or 8
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={n}").strip()
    if cfg.num_machines > 1:
        if not cfg.main_process_ip:
            raise ValueError("multi-host launch requires --main_process_ip (worker 0)")
        port = cfg.main_process_port or 8476
        env["ACCELERATE_COORDINATOR_ADDRESS"] = f"{cfg.main_process_ip}:{port}"
        env["ACCELERATE_NUM_PROCESSES"] = str(cfg.num_machines)
        env["ACCELERATE_PROCESS_ID"] = str(cfg.machine_rank)
    # Mesh geometry → PARALLELISM_CONFIG_* (reference utils/launch.py:396-420)
    mesh_flags = {
        "PARALLELISM_CONFIG_DP_REPLICATE_SIZE": cfg.dp_replicate_size,
        "PARALLELISM_CONFIG_DP_SHARD_SIZE": cfg.dp_shard_size,
        "PARALLELISM_CONFIG_TP_SIZE": cfg.tp_size,
        "PARALLELISM_CONFIG_CP_SIZE": cfg.cp_size,
        "PARALLELISM_CONFIG_SP_SIZE": cfg.sp_size,
        "PARALLELISM_CONFIG_EP_SIZE": cfg.ep_size,
        "PARALLELISM_CONFIG_PP_SIZE": cfg.pp_size,
    }
    if any(v not in (1, None) for v in mesh_flags.values()):
        for k, v in mesh_flags.items():
            env[k] = str(v)
        env["PARALLELISM_CONFIG_CP_ROTATE_METHOD"] = cfg.cp_rotate_method
    return env


def _script_cmd(args) -> list[str]:
    cmd = [sys.executable]
    if args.module:
        cmd.append("-m")
    cmd.append(args.training_script)
    cmd.extend(args.training_script_args)
    return cmd


_DS_FLAG_ENV = {
    "zero_stage": "ACCELERATE_DEEPSPEED_ZERO_STAGE",
    "offload_optimizer_device": "ACCELERATE_DEEPSPEED_OFFLOAD_OPTIMIZER_DEVICE",
    "offload_param_device": "ACCELERATE_DEEPSPEED_OFFLOAD_PARAM_DEVICE",
    "gradient_clipping": "ACCELERATE_GRADIENT_CLIPPING",
    "deepspeed_config_file": "ACCELERATE_DEEPSPEED_CONFIG_FILE",
}


def deepspeed_env(args) -> dict[str, str]:
    """DeepSpeed-style flags → the reference's env protocol
    (``utils/launch.py:557-577``); consumed by ``DeepSpeedPlugin.from_env``.

    DeepSpeed mode activates only on the explicit signals — ``--use_deepspeed``,
    ``--zero_stage`` or ``--deepspeed_config_file`` — never on auxiliary knobs
    alone (``--gradient_clipping 1.0`` by itself must not silently flip the
    run to ZeRO-2 sharding)."""
    values = {env: getattr(args, flag, None) for flag, env in _DS_FLAG_ENV.items()}
    active = (
        getattr(args, "use_deepspeed", False)
        or getattr(args, "zero_stage", None) is not None
        or getattr(args, "deepspeed_config_file", None) is not None
    )
    if not active:
        dropped = sorted(k for k, v in values.items() if v is not None)
        if dropped:
            print(
                f"[accelerate-tpu launch] ignoring DeepSpeed flags without "
                f"--use_deepspeed/--zero_stage: {dropped}",
                file=sys.stderr,
            )
        return {}
    env = {"ACCELERATE_USE_DEEPSPEED": "true"}
    env.update({k: str(v) for k, v in values.items() if v is not None})
    return env


def simple_launcher(args, cfg: ClusterConfig) -> int:
    """Single-host launch: set env, run the script (reference ``simple_launcher:986``).

    With ``--max_restarts N`` this doubles as the minimal elastic supervisor
    (the reference exposes torchrun's elastic agent for this,
    ``commands/launch.py:998-1031``): on nonzero exit the script is relaunched
    with ``ACCELERATE_RESTART_COUNT`` and
    ``ACCELERATE_RESUME_FROM_CHECKPOINT=latest`` set, so a training loop that
    calls ``accelerator.load_state()`` when that env var is present resumes
    from its newest checkpoint instead of restarting cold.
    """
    import time

    env = {**os.environ, **build_launch_env(cfg), **deepspeed_env(args)}
    # make accelerate_tpu importable in the child even for uninstalled checkouts
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_parent, env.get("PYTHONPATH")) if p
    )
    max_restarts = max(0, getattr(args, "max_restarts", None) or 0)
    monitor_interval = max(0.0, getattr(args, "monitor_interval", 5.0))
    rc = 1
    for attempt in range(max_restarts + 1):
        env["ACCELERATE_RESTART_COUNT"] = str(attempt)
        if attempt > 0:
            env["ACCELERATE_RESUME_FROM_CHECKPOINT"] = "latest"
        proc = subprocess.run(_script_cmd(args), env=env)
        rc = proc.returncode
        if rc == 0:
            return 0
        if attempt < max_restarts:
            print(
                f"[accelerate-tpu launch] script exited rc={rc}; restart "
                f"{attempt + 1}/{max_restarts} in {monitor_interval}s",
                file=sys.stderr,
            )
            time.sleep(monitor_interval)
    return rc


def tpu_pod_launcher(args, cfg: ClusterConfig) -> int:
    """Fan out to every pod worker over gcloud ssh (reference ``tpu_pod_launcher:1117``).

    Each worker re-invokes ``accelerate-tpu launch`` WITHOUT --tpu_pod and with its
    own ``--machine_rank``; jax.distributed handles rendezvous at the coordinator.
    """
    if not cfg.tpu_name:
        raise ValueError("--tpu_pod requires --tpu_name (and usually --tpu_zone)")
    if not cfg.main_process_ip:
        # every worker must agree on ONE coordinator — resolving it per-worker
        # (e.g. hostname -i) would rendezvous nowhere
        raise ValueError(
            "--tpu_pod requires --main_process_ip set to worker 0's internal IP "
            "(gcloud compute tpus tpu-vm describe <name> --format='value("
            "networkEndpoints[0].ipAddress)')"
        )
    inner = [
        "accelerate-tpu", "launch",
        "--num_machines", str(cfg.num_machines),
        "--main_process_ip", cfg.main_process_ip,
        "--main_process_port", str(cfg.main_process_port or 8476),
        "--mixed_precision", cfg.mixed_precision,
        "--gradient_accumulation_steps", str(cfg.gradient_accumulation_steps),
        "--cp_rotate_method", cfg.cp_rotate_method,
    ]
    for axis in ("dp_replicate", "dp_shard", "tp", "cp", "sp", "ep", "pp"):
        inner += [f"--{axis}_size", str(getattr(cfg, f"{axis}_size"))]
    # NOTE: --max_restarts is deliberately NOT forwarded to the inner
    # launchers. One worker restarting alone cannot rejoin the running SPMD
    # collective (the other hosts are blocked inside the old incarnation's
    # collectives) — multi-host restart must re-fan-out the WHOLE pod, which
    # is handled by the pod-level supervision loop below.
    if cfg.debug:
        inner.append("--debug")
    if getattr(args, "use_deepspeed", False):
        inner.append("--use_deepspeed")
    for flag in _DS_FLAG_ENV:
        v = getattr(args, flag, None)
        if v is not None:
            inner += [f"--{flag}", str(v)]
    if args.module:
        inner.append("-m")
    script_part = [args.training_script, *args.training_script_args]
    # gcloud sets no rank env; each worker reads its index from the TPU
    # metadata server (the xla_dist-equivalent rank channel). --machine_rank
    # must precede the script positional or REMAINDER swallows it.
    rank_probe = (
        "RANK=$(curl -s -H 'Metadata-Flavor: Google' "
        "http://metadata.google.internal/computeMetadata/v1/instance/attributes/agent-worker-number); "
    )
    remote = (rank_probe + shlex.join(inner) + " --machine_rank=$RANK "
              + shlex.join(script_part))
    cmd = [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", cfg.tpu_name,
        "--worker=all", f"--command={remote}",
    ]
    if cfg.tpu_zone:
        cmd.insert(6, f"--zone={cfg.tpu_zone}")
    # pod-level elastic supervision: if ANY worker exits nonzero (gcloud
    # propagates it) the whole pod is re-fanned-out together, with resume-from-
    # latest hints injected into every worker's env — the multi-host analogue
    # of simple_launcher's restart loop (all hosts must restart as one
    # incarnation to rendezvous)
    import time

    max_restarts = max(0, getattr(args, "max_restarts", None) or 0)
    monitor_interval = max(0.0, getattr(args, "monitor_interval", 5.0))
    rc = 1
    base_remote = cmd[-1] if cmd[-1].startswith("--command=") else None
    for attempt in range(max_restarts + 1):
        run_cmd = list(cmd)
        if base_remote is not None and attempt > 0:
            hint = (
                f"export ACCELERATE_RESTART_COUNT={attempt} "
                "ACCELERATE_RESUME_FROM_CHECKPOINT=latest; "
            )
            run_cmd[-1] = "--command=" + hint + base_remote[len("--command="):]
        print("Running:", shlex.join(run_cmd))
        rc = subprocess.run(run_cmd).returncode
        if rc == 0:
            return 0
        if attempt < max_restarts:
            print(
                f"[accelerate-tpu launch] pod exited rc={rc}; re-fan-out "
                f"{attempt + 1}/{max_restarts} in {monitor_interval}s",
                file=sys.stderr,
            )
            time.sleep(monitor_interval)
    return rc


def elastic_launcher(args, cfg: ClusterConfig) -> int:
    """``accelerate-tpu launch --elastic``: the per-host spawn wrapped in the
    resilience supervisor (``resilience/supervisor.py``) — exit-code
    classification, heartbeat-file gap watch, bounded-backoff auto-resume
    from the last committed checkpoint, poison-step diagnosis, and restart
    telemetry for the report CLI's "restarts" section."""
    import time

    from ..resilience.supervisor import RestartPolicy, supervise_command

    env = {**os.environ, **build_launch_env(cfg), **deepspeed_env(args)}
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_parent, env.get("PYTHONPATH")) if p
    )
    # one run id across incarnations so telemetry streams merge into one story
    env.setdefault("ACCELERATE_RUN_ID", f"elastic-{int(time.time())}-{os.getpid()}")
    # a stalled rank must turn into a restartable exit: arm the watchdog with
    # the abort path unless the operator configured it explicitly
    env.setdefault("ACCELERATE_WATCHDOG_TIMEOUT", "300")
    env.setdefault("ACCELERATE_WATCHDOG_ABORT", "1")
    telemetry_dir = env.setdefault("ACCELERATE_TELEMETRY_DIR", "telemetry")
    axis_sizes = {
        axis: int(getattr(cfg, f"{axis}_size") or 1)
        for axis in ("dp_replicate", "dp_shard", "tp", "cp", "sp", "ep", "pp")
    }
    axis_sizes = {a: s for a, s in axis_sizes.items() if s > 1}
    policy = RestartPolicy(
        # None = unset -> elastic default 3; an EXPLICIT 0 means "supervise,
        # classify, but never auto-restart" and must be honored
        max_restarts=3 if args.max_restarts is None else max(0, args.max_restarts),
        backoff_base_s=max(0.0, args.monitor_interval),
        heartbeat_timeout_s=max(0.0, getattr(args, "heartbeat_timeout", 0.0)),
    )
    return supervise_command(
        _script_cmd(args), env=env, policy=policy,
        telemetry_dir=telemetry_dir, axis_sizes=axis_sizes or None,
    )


def launch_command(args) -> int:
    cfg = _merge_config(args)
    if args.tpu_pod:
        if getattr(args, "elastic", False):
            # pod fan-out keeps its own whole-pod restart loop; the full
            # supervisor (exit classification, heartbeat watch, poison-step
            # diagnosis) does not apply through gcloud ssh — say so instead
            # of silently downgrading
            print(
                "[accelerate-tpu launch] --elastic is not supported with "
                "--tpu_pod; using the pod-level re-fan-out loop "
                "(--max_restarts) instead. Run --elastic per-host inside the "
                "pod for full supervision.",
                file=sys.stderr,
            )
        return tpu_pod_launcher(args, cfg)
    if getattr(args, "elastic", False):
        return elastic_launcher(args, cfg)
    return simple_launcher(args, cfg)


def register_parser(subparsers) -> argparse.ArgumentParser:
    return launch_command_parser(subparsers)


def main():
    parser = launch_command_parser()
    args = parser.parse_args()
    raise SystemExit(launch_command(args))


if __name__ == "__main__":
    main()
