"""``accelerate-tpu estimate-memory`` — model memory estimator.

Reference: ``commands/estimate.py`` pulls a Hub model, builds it under
``init_empty_weights``, and prints per-dtype sizes. Here the zero-RAM build is
``jax.eval_shape`` (``utils/modeling.abstract_params``); sources are (a) the
built-in model zoo (``llama``, ``bert`` at any geometry), (b) a local
safetensors/npz checkpoint (sizes from headers, no tensor data read), (c) a Hub
id via ``transformers`` when installed and reachable.

Training estimate follows the reference's rule of thumb: Adam training ≈ 4×
parameter bytes (params + grads + 2 optimizer moments).
"""

from __future__ import annotations

import argparse
import json
import os

DTYPES = ("float32", "bfloat16", "float16", "int8", "int4")


def _sizes_from_builtin(model: str, args) -> dict:
    import jax.numpy as jnp

    from ..models import BertConfig, LlamaConfig, init_bert, init_llama
    from ..utils.modeling import abstract_params, total_byte_size

    if model == "llama":
        # CLI flag names follow the HF convention; map onto LlamaConfig fields
        rename = {
            "vocab_size": "vocab_size",
            "hidden_size": "dim",
            "num_layers": "n_layers",
            "num_heads": "n_heads",
            "intermediate_size": "ffn_dim",
        }
        overrides = {
            field: getattr(args, flag)
            for flag, field in rename.items()
            if getattr(args, flag, None) is not None
        }
        if overrides:
            import dataclasses

            if "n_heads" in overrides and "n_kv_heads" not in overrides:
                overrides["n_kv_heads"] = overrides["n_heads"]
            cfg = dataclasses.replace(LlamaConfig(), **overrides)
        else:
            cfg = LlamaConfig()
        import jax.random as jr

        params = abstract_params(lambda: init_llama(cfg, jr.PRNGKey(0)))
    elif model == "bert":
        cfg = BertConfig.base()
        import jax.random as jr

        params = abstract_params(lambda: init_bert(cfg, jr.PRNGKey(0)))
    else:
        raise ValueError(f"unknown builtin model {model!r}; use llama|bert or a path/hub id")
    import numpy as np

    from ..utils.modeling import named_parameters

    # "largest layer" = largest unsplittable unit (reference get_max_layer_size):
    # stacked-layer subtrees (every leaf carries leading dim L) count PER LAYER,
    # everything else (embeddings, heads) as a whole top-level subtree
    flat = named_parameters(params)
    L = cfg.n_layers
    by_top: dict = {}
    for path, leaf in flat.items():
        by_top.setdefault(path.split("/")[0], []).append(leaf)
    largest = 0
    for leaves in by_top.values():
        elems = sum(int(np.prod(x.shape)) for x in leaves if hasattr(x, "shape"))
        stacked = L > 0 and all(
            getattr(x, "ndim", 0) >= 1 and x.shape[0] == L for x in leaves
        )
        largest = max(largest, elems // L if stacked else elems)
    out = {d: total_byte_size(params, getattr(jnp, d, None) if d not in ("int8", "int4") else d)
           for d in DTYPES}
    out["_largest_elems"] = largest
    return out


def _sizes_from_checkpoint(path: str) -> dict:
    """Parameter bytes from safetensors headers / npz metadata — no tensor
    reads. Headers carry no module structure, so the largest-layer column
    reports the largest single TENSOR here (a lower bound on the layer
    reserve the structured sources report)."""
    import numpy as np

    total_f32_elems = 0
    largest = 0
    files = []
    if os.path.isdir(path):
        files = [os.path.join(path, f) for f in sorted(os.listdir(path))
                 if f.endswith((".safetensors", ".npz"))]
    elif os.path.isfile(path):
        files = [path]
    if not files:
        raise FileNotFoundError(f"no .safetensors/.npz files under {path}")
    for f in files:
        if f.endswith(".safetensors"):
            import struct

            with open(f, "rb") as fh:
                n = struct.unpack("<Q", fh.read(8))[0]
                header = json.loads(fh.read(n))
            for name, meta in header.items():
                if name == "__metadata__":
                    continue
                elems = 1
                for s in meta["shape"]:
                    elems *= s
                total_f32_elems += elems
                largest = max(largest, elems)
        else:
            with np.load(f) as z:
                for name in z.files:
                    elems = int(np.prod(z[name].shape))
                    total_f32_elems += elems
                    largest = max(largest, elems)
    out = _sizes_from_numel(total_f32_elems)
    out["_largest_elems"] = largest
    return out


def _sizes_from_numel(n: int) -> dict:
    """Per-dtype byte sizes for ``n`` parameters — the single multiplier table
    shared by the checkpoint-header and hub-config paths."""
    return {
        "float32": n * 4,
        "bfloat16": n * 2,
        "float16": n * 2,
        "int8": n,
        "int4": n // 2,
    }


def _sizes_from_hub(model_id: str, trust_remote_code: bool = False) -> dict:
    """Any Hub model id (reference ``commands/estimate.py:316``): download the
    CONFIG only, build the architecture on torch's meta device (zero RAM, zero
    weight download — the reference's ``init_empty_weights`` moral twin) and
    count parameters + buffers. Also works fully offline on a local directory
    holding a ``config.json``."""
    try:
        import torch
        import transformers
        from transformers import AutoConfig, AutoModel
    except ImportError as e:  # pragma: no cover - both installed in CI image
        raise SystemExit(f"hub estimation needs transformers+torch ({e})")
    try:
        cfg = AutoConfig.from_pretrained(model_id, trust_remote_code=trust_remote_code)
    except Exception as e:
        raise SystemExit(
            f"could not load a config for {model_id!r} ({type(e).__name__}: {e}). "
            "Offline? Use a builtin model (llama|bert), a local checkpoint "
            "path, or a local directory containing config.json."
        )
    try:
        model = None
        # the TASK class (config.architectures) counts untied heads the bare
        # AutoModel base would miss — the reference picks it the same way
        arch = (getattr(cfg, "architectures", None) or [None])[0]
        cls = getattr(transformers, arch, None) if isinstance(arch, str) else None
        with torch.device("meta"):
            model = cls(cfg) if cls is not None else AutoModel.from_config(
                cfg, trust_remote_code=trust_remote_code
            )
    except Exception as e:
        raise SystemExit(
            f"could not build {model_id!r} from its config ({type(e).__name__}: {e})"
        )
    n = sum(p.numel() for p in model.parameters())
    n += sum(b.numel() for b in model.buffers())
    out = _sizes_from_numel(n)

    # largest unsplittable unit: an element of a repeated block (ModuleList
    # item) or a leaf module (embedding/head) — params AND buffers counted,
    # matching the reference's get_max_layer_size semantics
    def _module_elems(m):
        return sum(p.numel() for p in m.parameters()) + sum(b.numel() for b in m.buffers())

    largest = 0
    for mod in model.modules():
        if isinstance(mod, torch.nn.ModuleList):
            for item in mod:
                largest = max(largest, _module_elems(item))
        elif not any(True for _ in mod.children()):
            largest = max(largest, _module_elems(mod))
    out["_largest_elems"] = largest
    return out


def _fmt(nbytes: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(nbytes) < 1024 or unit == "TB":
            return f"{nbytes:.2f} {unit}"
        nbytes /= 1024
    return f"{nbytes:.2f} TB"


def estimate_command(args) -> int:
    model = args.model_name
    if model in ("llama", "bert"):
        sizes = _sizes_from_builtin(model, args)
    elif os.path.exists(model):
        try:
            sizes = _sizes_from_checkpoint(model)
        except FileNotFoundError:
            # a model DIRECTORY without weight files may still carry a
            # config.json — estimate from the architecture alone
            sizes = _sizes_from_hub(model, trust_remote_code=getattr(args, "trust_remote_code", False))
    else:
        sizes = _sizes_from_hub(model, trust_remote_code=getattr(args, "trust_remote_code", False))
    largest_elems = sizes.pop("_largest_elems", 0)
    from ..utils.modeling import dtype_byte_size

    wanted = args.dtypes or list(DTYPES)
    rows = []
    for d in wanted:
        total = sizes[d]
        largest = int(largest_elems * dtype_byte_size(d))
        rows.append((d, largest, total,
                     total * 4 if d in ("float32", "bfloat16", "float16") else None))
    if args.json:
        print(json.dumps({d: {"largest_layer_bytes": lg, "inference_bytes": t,
                              "adam_training_bytes": tr}
                          for d, lg, t, tr in rows}))
        return 0
    name_w = max(len(r[0]) for r in rows)
    print(f"Memory usage for `{model}`:\n")
    print(f"{'dtype':<{name_w}}  {'largest layer':>14}  {'inference':>12}  {'Adam training':>14}")
    for d, largest, total, train in rows:
        print(f"{d:<{name_w}}  {_fmt(largest):>14}  {_fmt(total):>12}  "
              f"{(_fmt(train) if train else '-'):>14}")
    return 0


def register_parser(subparsers) -> argparse.ArgumentParser:
    p = subparsers.add_parser("estimate-memory", help="Estimate model memory per dtype")
    p.add_argument(
        "model_name",
        help="builtin model (llama|bert), checkpoint path, Hub model id, or a "
             "directory containing config.json",
    )
    p.add_argument("--dtypes", nargs="+", choices=DTYPES, default=None)
    p.add_argument("--json", action="store_true")
    p.add_argument("--trust_remote_code", action="store_true",
                   help="allow custom modeling code from the Hub config")
    for k in ("vocab_size", "hidden_size", "num_layers", "num_heads", "intermediate_size"):
        p.add_argument(f"--{k}", type=int, default=None)
    p.set_defaults(func=estimate_command)
    return p
