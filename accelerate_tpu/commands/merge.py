"""``accelerate-tpu merge-weights`` — consolidate a sharded checkpoint into one file.

Reference: ``commands/merge.py`` → ``merge_fsdp_weights`` (``utils/fsdp_utils.py:360``)
turns a torch DCP sharded dir into a single state dict. Our sharded artifacts are
(a) ``save_model`` output dirs (``model-00001-of-000NN.safetensors`` + index.json)
and (b) ``save_state`` checkpoint dirs (``model.npz``). Output: one
``model.safetensors`` (or ``.npz`` with ``--unsafe_serialization``).
"""

from __future__ import annotations

import argparse
import json
import os


def _load_flat_dir(path: str) -> dict:
    import numpy as np

    from ..sharded_checkpoint import consolidate_sharded, is_sharded_checkpoint

    if is_sharded_checkpoint(path, "model"):
        # per-process sharded save_state dir (the reference's DCP-sharded FSDP
        # checkpoints → merge_fsdp_weights path)
        return consolidate_sharded(path, "model")

    flat: dict = {}
    index = os.path.join(path, "model.safetensors.index.json")
    if os.path.isfile(index):
        from safetensors.numpy import load_file

        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        for shard in sorted(set(weight_map.values())):
            flat.update(load_file(os.path.join(path, shard)))
        return flat
    for f in sorted(os.listdir(path)):
        full = os.path.join(path, f)
        if f.endswith(".safetensors") and f.startswith("model"):
            from safetensors.numpy import load_file

            flat.update(load_file(full))
        elif f == "model.npz":
            with np.load(full) as z:
                flat.update({k: z[k] for k in z.files})
    if not flat:
        raise FileNotFoundError(f"no model shards (safetensors/npz) found in {path}")
    return flat


def merge_command(args) -> int:
    flat = _load_flat_dir(args.checkpoint_dir)
    os.makedirs(args.output_path, exist_ok=True)
    if args.unsafe_serialization:
        import numpy as np

        out = os.path.join(args.output_path, "model.npz")
        np.savez(out, **flat)
    else:
        from safetensors.numpy import save_file

        from ..checkpointing import _safetensors_compat

        out = os.path.join(args.output_path, "model.safetensors")
        save_file(_safetensors_compat(flat), out)
    print(f"merged {len(flat)} tensors from {args.checkpoint_dir} into {out}")
    return 0


def register_parser(subparsers) -> argparse.ArgumentParser:
    p = subparsers.add_parser("merge-weights",
                              help="Merge sharded model weights into a single file")
    p.add_argument("checkpoint_dir", help="Directory holding model shards")
    p.add_argument("output_path", help="Directory to write the merged file into")
    p.add_argument("--unsafe_serialization", action="store_true",
                   help="Write .npz instead of safetensors")
    p.set_defaults(func=merge_command)
    return p
