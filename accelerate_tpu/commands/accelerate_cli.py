"""Main CLI entry point (reference ``commands/accelerate_cli.py:28-50``)."""

from __future__ import annotations

import argparse

from . import config, env, estimate, launch, merge, test, to_fsdp2, tpu


def main():
    parser = argparse.ArgumentParser(
        "accelerate-tpu",
        usage="accelerate-tpu <command> [<args>]",
        allow_abbrev=False,
    )
    subparsers = parser.add_subparsers(dest="command", required=True)
    for mod in (config, launch, env, estimate, merge, test, to_fsdp2, tpu):
        mod.register_parser(subparsers)
    args = parser.parse_args()
    raise SystemExit(args.func(args))


if __name__ == "__main__":
    main()
