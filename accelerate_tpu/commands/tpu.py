"""``accelerate-tpu tpu-config`` — run setup commands on every TPU pod worker
(reference ``commands/tpu.py:29-157``: gcloud ssh fan-out of install/setup lines)."""

from __future__ import annotations

import argparse
import shlex
import subprocess

from .config import ClusterConfig, resolve_config_file


def tpu_command(args) -> int:
    cfg_path = resolve_config_file(args.config_file)
    cfg = ClusterConfig.load(cfg_path) if cfg_path else ClusterConfig()
    tpu_name = args.tpu_name or cfg.tpu_name
    tpu_zone = args.tpu_zone or cfg.tpu_zone
    if not tpu_name:
        raise SystemExit("--tpu_name required (or set tpu_name in the config file)")
    commands = list(args.command or [])
    if args.command_file:
        with open(args.command_file) as f:
            commands += [line.strip() for line in f if line.strip()]
    if args.install_accelerate:
        commands.insert(0, "pip install accelerate-tpu")
    if not commands:
        raise SystemExit("nothing to run: pass --command/--command_file/--install_accelerate")
    remote = "; ".join(commands)
    cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
           "--worker=all", f"--command={remote}"]
    if tpu_zone:
        cmd.append(f"--zone={tpu_zone}")
    print("Running:", shlex.join(cmd))
    if args.debug:
        return 0
    return subprocess.run(cmd).returncode


def register_parser(subparsers) -> argparse.ArgumentParser:
    p = subparsers.add_parser("tpu-config", help="Run setup commands on all pod workers")
    p.add_argument("--config_file", default=None)
    p.add_argument("--tpu_name", default=None)
    p.add_argument("--tpu_zone", default=None)
    p.add_argument("--command", action="append", default=None)
    p.add_argument("--command_file", default=None)
    p.add_argument("--install_accelerate", action="store_true")
    p.add_argument("--debug", action="store_true", help="Print the gcloud command, don't run")
    p.set_defaults(func=tpu_command)
    return p
