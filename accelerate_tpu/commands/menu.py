"""Arrow-key selection menu for the interactive questionnaire.

Counterpart of the reference's ``commands/menu/`` package (cursor-driven
selection in ``accelerate config``), reimplemented minimally: a raw-mode
cursor menu on ANSI terminals with a numbered-``input()`` fallback whenever
stdin is not a TTY (CI, pipes, tests) — the questionnaire must never hang on
a non-interactive stream.

Keys: Up/Down (or k/j) move, digits jump, Enter confirms, q/Esc cancels back
to the default.
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence

_UP = "up"
_DOWN = "down"
_ENTER = "enter"
_CANCEL = "cancel"


def _pending_input(stream, timeout: float = 0.05) -> bool:
    """True when more bytes are already queued on ``stream`` — distinguishes a
    bare Esc press from the head of an arrow escape sequence without blocking
    the read. Streams without a selectable fd (StringIO in tests) report
    whatever read() yields, which is non-blocking there anyway."""
    try:
        import select

        r, _, _ = select.select([stream], [], [], timeout)
        return bool(r)
    except (ValueError, OSError, TypeError):
        return True


def _read_key(stream) -> str:
    """Decode one keypress from ``stream`` into a symbolic name. Separated
    from the terminal handling so the escape-sequence parsing is testable on
    plain strings."""
    ch = stream.read(1)
    if not ch:
        return _CANCEL
    if ch in ("\r", "\n"):
        return _ENTER
    if ch in ("q", "Q", "\x03"):  # q / Ctrl-C
        return _CANCEL
    if ch == "\x1b":  # escape sequence (arrows) or bare Esc
        if not _pending_input(stream):
            return _CANCEL  # a lone Esc press: nothing follows
        nxt = stream.read(1)
        if nxt != "[":
            return _CANCEL
        final = stream.read(1)
        return {"A": _UP, "B": _DOWN}.get(final, "")
    if ch in ("k", "K"):
        return _UP
    if ch in ("j", "J"):
        return _DOWN
    if ch.isdigit():
        return ch
    return ""


def _next_index(key: str, index: int, n: int) -> int:
    """Pure cursor arithmetic (wrap-around; digit keys jump 1-based)."""
    if key == _UP:
        return (index - 1) % n
    if key == _DOWN:
        return (index + 1) % n
    if key.isdigit():
        j = int(key) - 1
        if 0 <= j < n:
            return j
    return index


def _render(options: Sequence[str], index: int, first: bool) -> None:
    out = sys.stdout
    if not first:
        out.write(f"\x1b[{len(options)}A")  # cursor back up over the options
    for i, opt in enumerate(options):
        marker = "➤" if i == index else " "
        style = ("\x1b[7m", "\x1b[0m") if i == index else ("", "")
        out.write(f"\x1b[2K {marker} {style[0]}{opt}{style[1]}\n")
    out.flush()


class _FdStream:
    """Unbuffered reader over a file descriptor. sys.stdin's text layer
    buffers the '[A' tail of an arrow escape sequence after read(1), which
    makes select() report nothing pending and a real arrow press look like a
    bare Esc — raw os.read never over-reads, so the fd state stays honest."""

    def __init__(self, fd: int):
        self._fd = fd

    def fileno(self) -> int:
        return self._fd

    def read(self, n: int = 1) -> str:
        import os

        return os.read(self._fd, n).decode("utf-8", errors="ignore")


def _interactive_select(prompt: str, options: Sequence[str], default_index: int) -> int:
    import termios
    import tty

    fd = sys.stdin.fileno()
    try:
        saved = termios.tcgetattr(fd)
    except termios.error as e:  # isatty lied (restricted pty/IDE console)
        raise OSError(str(e))  # -> select() falls back to the numbered menu
    index = default_index
    print(f"{prompt} (arrows + Enter; q for default)")
    _render(options, index, first=True)
    stream = _FdStream(fd)
    try:
        try:
            tty.setcbreak(fd)
        except termios.error as e:
            raise OSError(str(e))
        while True:
            key = _read_key(stream)
            if key == _ENTER:
                return index
            if key == _CANCEL:
                index = default_index
                _render(options, index, first=False)
                return index
            new = _next_index(key, index, len(options))
            if new != index:
                index = new
                _render(options, index, first=False)
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, saved)


def _fallback_select(prompt: str, options: Sequence[str], default_index: int) -> int:
    print(prompt)
    for i, opt in enumerate(options):
        marker = "*" if i == default_index else " "
        print(f" {marker} {i + 1}) {opt}")
    raw = input(f"choose 1-{len(options)} [{default_index + 1}]: ").strip()
    if not raw:
        return default_index
    try:
        j = int(raw) - 1
    except ValueError:
        return default_index
    return j if 0 <= j < len(options) else default_index


def select(prompt: str, options: Sequence[str], default: Optional[str] = None) -> str:
    """Pick one of ``options``; returns the chosen string. Arrow-key cursor on
    a TTY, numbered fallback otherwise."""
    options = list(options)
    if not options:
        raise ValueError("select() needs at least one option")
    default_index = options.index(default) if default in options else 0
    try:
        interactive = sys.stdin.isatty() and sys.stdout.isatty()
    except (ValueError, OSError):  # closed/replaced streams
        interactive = False
    if interactive:
        try:
            return options[_interactive_select(prompt, options, default_index)]
        except (ImportError, OSError):  # no termios (non-POSIX) / odd terminal
            pass
    return options[_fallback_select(prompt, options, default_index)]
