"""``accelerate-tpu to-fsdp2`` (reference ``commands/to_fsdp2.py:172`` rewrites
FSDP1 config keys to FSDP2). Here the FSDP1/FSDP2 distinction does not exist —
both collapse to a NamedSharding over ``dp_shard`` under GSPMD — so there is
nothing to migrate; the command exists to SAY so instead of being an unknown
command or an ImportError."""

from __future__ import annotations


def to_fsdp2_command(args) -> int:
    print(
        "to-fsdp2 is not needed on this framework: FSDP1 and FSDP2 collapse "
        "into the same GSPMD sharding (docs/concept_guides/fsdp_gspmd.md). "
        "Your existing config works as-is — `fsdp_config:` keys map through "
        "FullyShardedDataParallelPlugin unchanged."
    )
    return 0


def register_parser(subparsers) -> None:
    p = subparsers.add_parser(
        "to-fsdp2", help="(not needed here: FSDP1/2 collapse under GSPMD)"
    )
    p.add_argument("--config_file", default=None, help="accepted for parity; unused")
    p.add_argument("--output_file", default=None, help="accepted for parity; unused")
    p.add_argument("--overwrite", action="store_true", help="accepted for parity; unused")
    p.set_defaults(func=to_fsdp2_command)
