"""``accelerate-tpu test`` — run the bundled sanity script through the launcher
(reference ``commands/test.py`` → ``test_utils/scripts/test_script.py``)."""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def test_command(args) -> int:
    import accelerate_tpu.test_utils as tu

    script = os.path.join(os.path.dirname(tu.__file__), "scripts", "test_script.py")
    cmd = [sys.executable, "-m", "accelerate_tpu.commands.launch"]
    if args.config_file:
        cmd += ["--config_file", args.config_file]
    if args.cpu:
        cmd += ["--cpu", "--num_processes", str(args.num_processes)]
    cmd.append(script)
    print("Running:", " ".join(cmd))
    rc = subprocess.run(cmd).returncode
    if rc == 0:
        print("Test is a success! You are ready for your distributed training!")
    return rc


def register_parser(subparsers) -> argparse.ArgumentParser:
    p = subparsers.add_parser("test", help="Run the bundled end-to-end sanity check")
    p.add_argument("--config_file", default=None)
    p.add_argument("--cpu", action="store_true", help="Run on a simulated CPU mesh")
    p.add_argument("--num_processes", type=int, default=8)
    p.set_defaults(func=test_command)
    return p
