"""``accelerate-tpu config`` — interactive questionnaire + YAML config file.

Mirrors the reference's ``commands/config/`` package (``cluster.py:58``
``get_cluster_input``, ``config_args.py:40-77`` load/save, default path
``~/.cache/huggingface/accelerate/default_config.yaml``) in one module: our
config surface is smaller because SPMD collapses the per-accelerator process
zoo — what remains is the mesh (dp_replicate/dp_shard/tp/cp/sp/ep/pp), mixed
precision, hosts, and launch defaults.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Optional

import yaml

default_config_dir = os.path.join(
    os.path.expanduser(os.environ.get("XDG_CACHE_HOME", "~/.cache")), "accelerate_tpu"
)
default_config_file = os.path.join(default_config_dir, "default_config.yaml")


def resolve_config_file(explicit: Optional[str] = None) -> Optional[str]:
    """Config-file precedence: explicit flag > $ACCELERATE_TPU_CONFIG_FILE > default."""
    if explicit:
        return explicit
    env = os.environ.get("ACCELERATE_TPU_CONFIG_FILE")
    if env:
        return env
    if os.path.isfile(default_config_file):
        return default_config_file
    return None


@dataclass
class ClusterConfig:
    """On-disk launch configuration (reference ``config_args.py:179`` ClusterConfig)."""

    compute_environment: str = "LOCAL_MACHINE"
    distributed_type: str = "TPU"  # TPU | MULTI_TPU_POD | CPU | NO
    mixed_precision: str = "bf16"
    num_machines: int = 1
    machine_rank: int = 0
    main_process_ip: Optional[str] = None
    main_process_port: Optional[int] = None
    num_processes: Optional[int] = None  # CPU-simulation device count; None = all chips
    # Mesh axis sizes (1 = not enabled; -1 = infer remaining devices). All-1
    # means "no mesh configured" → the runtime picks its default (pure DP).
    dp_replicate_size: int = 1
    dp_shard_size: int = 1
    tp_size: int = 1
    cp_size: int = 1
    sp_size: int = 1
    ep_size: int = 1
    pp_size: int = 1
    cp_rotate_method: str = "allgather"
    gradient_accumulation_steps: int = 1
    # TPU pod metadata (for `accelerate-tpu launch --tpu_pod` / tpu-config)
    tpu_name: Optional[str] = None
    tpu_zone: Optional[str] = None
    debug: bool = False
    use_cpu: bool = False
    downcast_bf16: bool = False
    main_training_function: str = "main"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            if path.endswith(".json"):
                json.dump(self.to_dict(), f, indent=2)
            else:
                yaml.safe_dump(self.to_dict(), f, sort_keys=False)

    @classmethod
    def load(cls, path: str) -> "ClusterConfig":
        with open(path) as f:
            data = json.load(f) if path.endswith(".json") else yaml.safe_load(f)
        if data is None:  # empty/comment-only YAML
            data = {}
        if str(data.get("compute_environment", "")).upper() == "AMAZON_SAGEMAKER":
            # a reference SageMakerConfig must not be misread as a cluster
            # config (its keys overlap enough to half-work); the exclusion is
            # deliberate and documented — docs/launching.md, api_boundary.py
            raise ValueError(
                f"{path} is a SageMaker config (compute_environment: "
                "AMAZON_SAGEMAKER). The SageMaker launch route is deliberately "
                "not supported on this TPU framework — see docs/launching.md. "
                "Target GCP TPU VMs, or use the reference package on AWS."
            )
        known = {f.name for f in dataclasses.fields(cls)}
        extra = set(data) - known
        if extra:
            raise ValueError(
                f"Unknown keys in config file {path}: {sorted(extra)}. "
                f"Known keys: {sorted(known)}"
            )
        return cls(**data)


def _ask(prompt: str, default, cast=str, choices=None):
    if choices is not None:
        import sys

        try:
            tty = sys.stdin.isatty() and sys.stdout.isatty()
        except (ValueError, OSError):
            tty = False
        if tty:
            # arrow-key cursor menu (reference commands/menu/); plain input()
            # keeps working for pipes/CI via the fallback below
            from .menu import select

            return cast(select(prompt, [str(c) for c in choices], default=str(default)))
    suffix = f" [{default}]" if default is not None else ""
    while True:
        raw = input(f"{prompt}{suffix}: ").strip()
        if not raw:
            return default
        try:
            value = cast(raw)
        except (TypeError, ValueError):
            print(f"  could not parse {raw!r} as {cast.__name__}, try again")
            continue
        if choices is not None and value not in choices:
            print(f"  pick one of {choices}")
            continue
        return value


def _ask_bool(prompt: str, default: bool) -> bool:
    raw = _ask(prompt + " (yes/no)", "yes" if default else "no")
    return str(raw).lower() in ("yes", "y", "true", "1")


def get_cluster_input() -> ClusterConfig:
    """Interactive questionnaire (reference ``commands/config/cluster.py:58``)."""
    cfg = ClusterConfig()
    cfg.distributed_type = _ask(
        "Compute environment (TPU = this host's chips, MULTI_TPU_POD = multi-host pod, "
        "CPU = simulated devices, NO = single device)",
        "TPU",
        str,
        ("TPU", "MULTI_TPU_POD", "CPU", "NO"),
    )
    if cfg.distributed_type == "MULTI_TPU_POD":
        cfg.num_machines = _ask("How many hosts (TPU VM workers)", 2, int)
        cfg.main_process_ip = _ask("Coordinator (worker 0) IP", None)
        cfg.main_process_port = _ask("Coordinator port", 8476, int)
        cfg.tpu_name = _ask("TPU name (for gcloud ssh)", None)
        cfg.tpu_zone = _ask("TPU zone", None)
    elif cfg.distributed_type == "CPU":
        cfg.use_cpu = True
        cfg.num_processes = _ask("How many simulated devices", 8, int)
    cfg.dp_shard_size = _ask("dp_shard (FSDP) axis size (-1 = all remaining devices)", -1, int)
    cfg.dp_replicate_size = _ask("dp_replicate axis size", 1, int)
    cfg.tp_size = _ask("Tensor-parallel axis size", 1, int)
    cfg.cp_size = _ask("Context-parallel axis size", 1, int)
    cfg.sp_size = _ask("Ulysses sequence-parallel axis size", 1, int)
    cfg.ep_size = _ask("Expert-parallel axis size", 1, int)
    cfg.pp_size = _ask("Pipeline-parallel axis size", 1, int)
    cfg.mixed_precision = _ask(
        "Mixed precision", "bf16", str, ("no", "bf16", "fp16", "fp8")
    )
    cfg.gradient_accumulation_steps = _ask("Gradient accumulation steps", 1, int)
    cfg.debug = _ask_bool("Enable debug mode (cross-process collective shape checks)", False)
    return cfg


def write_basic_config(mixed_precision: str = "bf16", save_location: Optional[str] = None):
    """Non-interactive default config for notebooks/CI (reference
    ``commands/config/default.py`` ``write_basic_config``, re-exported from
    ``accelerate.utils``). Refuses to clobber: returns ``False`` if the file
    already exists (delete it or pass another ``save_location``); otherwise
    writes a single-host config with the requested precision and returns the
    path."""
    from ..utils.dataclasses import PrecisionType

    mixed_precision = str(mixed_precision).lower()  # reference lowercases too
    valid = [p.value for p in PrecisionType]
    if mixed_precision not in valid:
        raise ValueError(f"mixed_precision must be one of {valid}, got {mixed_precision!r}")
    path = save_location or default_config_file
    if os.path.isfile(path):
        print(
            f"Config file already exists at {path}; not overwriting. Delete it or "
            "pass save_location to write elsewhere."
        )
        return False
    ClusterConfig(mixed_precision=mixed_precision).save(path)
    return path


def config_command(args) -> int:
    if args.default:
        cfg = ClusterConfig()
    else:
        cfg = get_cluster_input()
    path = args.config_file or default_config_file
    cfg.save(path)
    print(f"accelerate-tpu configuration saved at {path}")
    return 0


def register_parser(subparsers) -> argparse.ArgumentParser:
    p = subparsers.add_parser("config", help="Create the launch configuration file")
    p.add_argument("--config_file", default=None, help="Where to save (default: "
                   f"{default_config_file})")
    p.add_argument("--default", action="store_true",
                   help="Write the default config without asking questions")
    p.set_defaults(func=config_command)
    return p
