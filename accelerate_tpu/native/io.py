"""Native chunked-file IO: the byte-moving layer under sharded checkpoints.

The reference's sharded checkpoint path delegates to
``torch.distributed.checkpoint``'s C++ FileSystemWriter/Reader
(``/root/reference/src/accelerate/utils/fsdp_utils.py:103-414``); this is the
TPU-native equivalent (``src/io.cc``): a thread team does pwrite/pread off the
GIL with per-chunk CRC32. Pure-Python fallback (same format, zlib crc32) when
no compiler is available.

Format is owned by the caller (``sharded_checkpoint.py``): one flat binary
file per process, chunks at 64-byte-aligned offsets, layout recorded in the
caller's JSON index.
"""

from __future__ import annotations

import ctypes
import os
import zlib
from typing import Optional, Sequence

import numpy as np

ALIGN = 64


def _default_threads() -> int:
    """IO thread-team size. Default 1: on a single local disk concurrent
    pwrite at different offsets thrashes (measured 88 MB/s sequential vs
    24 MB/s with 8 threads on this class of fs); parallel filesystems
    (GCS/NFS on TPU pods) DO scale with threads — raise via
    ``ACCELERATE_TPU_IO_THREADS`` there."""
    try:
        return max(1, int(os.environ.get("ACCELERATE_TPU_IO_THREADS", "1")))
    except ValueError:
        return 1


def _lib():
    from . import _load

    lib = _load()
    if lib is None:
        return None
    if not getattr(lib, "_atpu_io_bound", False):
        try:
            lib.atpu_io_write_chunks.restype = ctypes.c_int32
            lib.atpu_io_write_chunks.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_int32,
            ]
            lib.atpu_io_read_chunks.restype = ctypes.c_int32
            lib.atpu_io_read_chunks.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_int32,
            ]
            lib._atpu_io_bound = True
        except AttributeError:  # stale .so without the io entry points
            return None
    return lib


def plan_layout(nbytes_list: Sequence[int]) -> tuple[list[int], int]:
    """64B-aligned offsets for a chunk sequence; returns (offsets, total)."""
    offsets, pos = [], 0
    for nb in nbytes_list:
        offsets.append(pos)
        pos += int(nb)
        pos = (pos + ALIGN - 1) // ALIGN * ALIGN
    return offsets, pos


def write_chunks(path: str, arrays: Sequence[np.ndarray],
                 num_threads: Optional[int] = None) -> tuple[list[int], list[int], list[int]]:
    """Write arrays as raw chunks; returns (offsets, nbytes, crc32s)."""
    if num_threads is None:
        num_threads = _default_threads()
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = [a.nbytes for a in arrays]
    offsets, _total = plan_layout(sizes)
    lib = _lib()
    if lib is not None and arrays:
        n = len(arrays)
        srcs = (ctypes.c_void_p * n)(*[a.ctypes.data_as(ctypes.c_void_p) for a in arrays])
        c_sizes = (ctypes.c_int64 * n)(*sizes)
        c_offsets = (ctypes.c_int64 * n)(*offsets)
        crcs = (ctypes.c_uint32 * n)()
        rc = lib.atpu_io_write_chunks(path.encode(), n, srcs, c_sizes, c_offsets,
                                      crcs, num_threads)
        if rc == 0:
            return offsets, sizes, list(crcs)
        # fall through to the python path on native IO failure
    crc_list = []
    with open(path, "wb") as f:
        for a, off in zip(arrays, offsets):
            f.seek(off)
            buf = a.tobytes()
            f.write(buf)
            crc_list.append(zlib.crc32(buf) & 0xFFFFFFFF)
        # durability parity with the native path (which fsyncs and fails on
        # error): a crash right after "save succeeded" must not leave a
        # truncated container behind a CRC-carrying index
        f.flush()
        os.fsync(f.fileno())
    return offsets, sizes, crc_list


def read_chunks(path: str, offsets: Sequence[int], nbytes: Sequence[int],
                crcs: Optional[Sequence[int]] = None,
                num_threads: Optional[int] = None) -> list[np.ndarray]:
    """Read raw chunks back as uint8 arrays (zero extra copies — callers wrap
    them with ``np.frombuffer``); verifies CRC32 when provided."""
    if num_threads is None:
        num_threads = _default_threads()
    n = len(offsets)
    bufs = [np.empty(int(nb), dtype=np.uint8) for nb in nbytes]
    lib = _lib()
    if lib is not None and n:
        dsts = (ctypes.c_void_p * n)(*[b.ctypes.data_as(ctypes.c_void_p) for b in bufs])
        c_sizes = (ctypes.c_int64 * n)(*[int(x) for x in nbytes])
        c_offsets = (ctypes.c_int64 * n)(*[int(x) for x in offsets])
        c_crcs = (ctypes.c_uint32 * n)(*[int(c) for c in crcs]) if crcs is not None else None
        rc = lib.atpu_io_read_chunks(path.encode(), n, dsts, c_sizes, c_offsets,
                                     c_crcs, num_threads)
        if rc == 0:
            return bufs
        if rc == -2:
            raise ValueError(f"checkpoint chunk CRC mismatch in {path} (corrupt file?)")
        # rc == -1: fall through to the python path
    with open(path, "rb") as f:
        for i, (off, nb, buf) in enumerate(zip(offsets, nbytes, bufs)):
            f.seek(int(off))
            got = f.readinto(memoryview(buf))
            if got != int(nb):
                raise IOError(f"short read in {path} at offset {off}")
            if crcs is not None and (zlib.crc32(buf) & 0xFFFFFFFF) != int(crcs[i]):
                raise ValueError(f"checkpoint chunk CRC mismatch in {path} (corrupt file?)")
    return bufs
