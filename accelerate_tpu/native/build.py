"""Lazy native-library build: compile ``src/pipeline.cc`` with g++ on first use,
cache the .so next to the package, fall back silently (callers use the
pure-Python path) when no toolchain is available."""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading

_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src")
_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib")
_LIB = os.path.join(_LIB_DIR, "libatpu_pipeline.so")
_lock = threading.Lock()


def _sources() -> list[str]:
    return sorted(
        os.path.join(_SRC_DIR, name)
        for name in os.listdir(_SRC_DIR)
        if name.endswith(".cc")
    )


def _needs_build() -> bool:
    if not os.path.isfile(_LIB):
        return True
    lib_mtime = os.path.getmtime(_LIB)
    return any(lib_mtime < os.path.getmtime(src) for src in _sources())


def build_library(verbose: bool = False) -> str | None:
    """Return the path to the compiled library, building it if stale. None if
    the build fails (no compiler, sandboxed, …)."""
    with _lock:
        if not _needs_build():
            return _LIB
        try:
            os.makedirs(_LIB_DIR, exist_ok=True)
            # build to a temp name then rename: concurrent importers never see
            # a half-written .so
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_LIB_DIR)
            os.close(fd)
        except OSError as e:  # read-only install → silent numpy fallback
            if verbose:
                print(f"native build unavailable: {e}")
            return None
        cmd = [
            os.environ.get("CXX", "g++"),
            "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            *_sources(), "-o", tmp,
        ]
        try:
            res = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
            if res.returncode != 0:
                if verbose:
                    print(f"native build failed:\n{res.stderr}")
                os.unlink(tmp)
                return None
            os.replace(tmp, _LIB)
            return _LIB
        except (OSError, subprocess.SubprocessError) as e:
            if verbose:
                print(f"native build failed: {e}")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
