"""Native host-side data pipeline (C++ via ctypes).

The reference's hot host paths live in external C++ engines — torch's
DataLoader worker pool, pinned-memory collation (SURVEY.md §2.3). This package
is the TPU-native equivalent: ``pipeline.cc`` does record IO, shuffling, and
batch assembly off the GIL; Python sees numpy arrays ready for
``jax.device_put``. Everything degrades to a pure-numpy fallback when no
compiler is available (``is_native_available()`` reports which path is live).

Public surface:
- ``parallel_collate(samples) -> np.ndarray`` — stack N same-shape samples.
- ``gather_rows(src, indices) -> np.ndarray`` — shuffled batch gather.
- ``TokenDataset(path, seq_len, dtype)`` — memory-mapped fixed-length record
  shard (LM pretraining format).
- ``NativeDataLoader(dataset, batch_size, ...)`` — threaded prefetching batch
  iterator over a TokenDataset.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_lib = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("ACCELERATE_TPU_DISABLE_NATIVE", "").lower() in ("1", "true", "yes"):
        return None
    from .build import build_library

    path = build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.atpu_abi_version.restype = ctypes.c_int32
    if lib.atpu_abi_version() != 1:
        return None
    lib.atpu_collate.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int32,
    ]
    lib.atpu_gather_rows.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.atpu_dataset_open.restype = ctypes.c_void_p
    lib.atpu_dataset_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.atpu_dataset_len.restype = ctypes.c_int64
    lib.atpu_dataset_len.argtypes = [ctypes.c_void_p]
    lib.atpu_dataset_close.argtypes = [ctypes.c_void_p]
    lib.atpu_loader_new.restype = ctypes.c_void_p
    lib.atpu_loader_new.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.atpu_loader_num_batches.restype = ctypes.c_int64
    lib.atpu_loader_num_batches.argtypes = [ctypes.c_void_p]
    lib.atpu_loader_next.restype = ctypes.c_int64
    lib.atpu_loader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.atpu_loader_next_epoch.argtypes = [ctypes.c_void_p]
    lib.atpu_loader_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def is_native_available() -> bool:
    return _load() is not None


def is_native_ready() -> bool:
    """True only if the library is already loaded — never triggers a build.
    Hot paths (collate) use this so batch 0 never blocks on a g++ compile."""
    return _lib is not None


def warm_build() -> None:
    """Kick off the (possibly slow) first-time compile on a background thread.
    Called from DataLoader/Accelerator construction so the library is ready by
    the time the hot path asks for it."""
    if _lib_tried:
        return
    import threading

    threading.Thread(target=_load, name="atpu-native-build", daemon=True).start()


# ------------------------------------------------------------------ collate --
def parallel_collate(samples: list, num_threads: int = 4) -> np.ndarray:
    """Stack N same-shape/same-dtype arrays into (N, *shape). Native memcpy
    team when available; ``np.stack`` otherwise."""
    first = np.ascontiguousarray(samples[0])
    lib = _load()
    if lib is None:
        return np.stack([np.asarray(s) for s in samples])
    arrs = [np.ascontiguousarray(s) for s in samples]
    # native path only for uniform shape AND dtype — mixed dtypes must get
    # np.stack's type promotion, not a silent cast to samples[0]'s dtype
    if any(a.shape != first.shape or a.dtype != first.dtype for a in arrs):
        return np.stack(arrs)
    out = np.empty((len(arrs),) + first.shape, dtype=first.dtype)
    ptrs = (ctypes.c_void_p * len(arrs))(
        *[a.ctypes.data_as(ctypes.c_void_p) for a in arrs]
    )
    lib.atpu_collate(ptrs, len(arrs), first.nbytes,
                     out.ctypes.data_as(ctypes.c_void_p), num_threads)
    return out


def gather_rows(src: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """``src[indices]`` for 2D+ contiguous src — native strided memcpy."""
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    lib = _load()
    # numpy handles empty/negative/out-of-range with proper IndexError
    # semantics; the native memcpy would read arbitrary memory
    if lib is None or len(src) == 0 or len(idx) == 0 or idx.min() < 0 or idx.max() >= len(src):
        return src[idx]
    row_bytes = src[0].nbytes
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    lib.atpu_gather_rows(src.ctypes.data_as(ctypes.c_void_p),
                         idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                         len(idx), row_bytes, out.ctypes.data_as(ctypes.c_void_p))
    return out


# ------------------------------------------------------------------ dataset --
class TokenDataset:
    """Memory-mapped shard of fixed-length token records: a flat binary file of
    ``seq_len`` tokens per record (the standard LM-pretraining pack format).

    Native path mmaps in C++; fallback uses ``np.memmap``.
    """

    def __init__(self, path: str, seq_len: int, dtype=np.uint16):
        self.path = path
        self.seq_len = int(seq_len)
        self.dtype = np.dtype(dtype)
        self.record_bytes = self.seq_len * self.dtype.itemsize
        self._lib = _load()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.atpu_dataset_open(
                path.encode(), self.record_bytes
            )
        if self._handle:
            self._len = self._lib.atpu_dataset_len(self._handle)
            self._mm = None
        else:
            self._mm = np.memmap(path, dtype=self.dtype, mode="r")
            self._len = self._mm.shape[0] // self.seq_len
            self._mm = self._mm[: self._len * self.seq_len].reshape(self._len, self.seq_len)

    def __len__(self) -> int:
        return int(self._len)

    def _view(self) -> np.ndarray:
        """Lazy numpy view for random access (native mode mmaps in C++ for the
        loader but python-side __getitem__ still wants an array view)."""
        if self._mm is None:
            mm = np.memmap(self.path, dtype=self.dtype, mode="r")
            self._mm = mm[: self._len * self.seq_len].reshape(self._len, self.seq_len)
        return self._mm

    def __getitem__(self, i: int) -> np.ndarray:
        return np.asarray(self._view()[i])

    def close(self):
        if self._handle and self._lib is not None:
            self._lib.atpu_dataset_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


class NativeDataLoader:
    """Prefetching batch iterator over a :class:`TokenDataset`.

    Worker threads assemble shuffled batches into a bounded reorder window in
    C++; iteration yields ``np.ndarray`` of shape ``(batch, seq_len)`` in a
    deterministic order given ``seed``. Falls back to synchronous numpy
    assembly without the native library.
    """

    def __init__(self, dataset: TokenDataset, batch_size: int, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = True, num_workers: int = 2,
                 prefetch_depth: int = 4):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.num_workers = num_workers
        self.prefetch_depth = prefetch_depth
        self._lib = _load()
        self._loader = None
        self._epoch = 0
        self._started = False
        if self._lib is not None and dataset._handle:
            self._loader = self._lib.atpu_loader_new(
                dataset._handle, self.batch_size, int(shuffle), seed,
                int(drop_last), num_workers, prefetch_depth,
            )

    def __len__(self) -> int:
        if self._loader:
            return int(self._lib.atpu_loader_num_batches(self._loader))
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self):
        # epoch state advances at iterator START, not on generator completion:
        # an abandoned partially-consumed iterator (e.g. a peek) must not leak
        # mid-epoch position into the next epoch
        if self._started:
            self._epoch += 1
            if self._loader:
                self._lib.atpu_loader_next_epoch(self._loader)
        self._started = True
        if self._loader:
            out = np.empty((self.batch_size, self.dataset.seq_len), self.dataset.dtype)
            for _ in range(len(self)):
                got = self._lib.atpu_loader_next(
                    self._loader, out.ctypes.data_as(ctypes.c_void_p)
                )
                if got < 0:
                    break
                yield out.copy()
            return
        # fallback: synchronous numpy
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(order)
        for b in range(len(self)):
            pos = (np.arange(b * self.batch_size, (b + 1) * self.batch_size)) % n
            yield gather_rows(np.asarray(self.dataset._view()), order[pos])

    def close(self):
        if self._loader and self._lib is not None:
            self._lib.atpu_loader_free(self._loader)
            self._loader = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
