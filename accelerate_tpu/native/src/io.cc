// Native checkpoint IO for accelerate-tpu.
//
// The reference's sharded-checkpoint path rides torch.distributed.checkpoint's
// C++ FileSystemWriter/Reader (SURVEY.md §2.3, fsdp_utils.py:103-414). This is
// the TPU-native equivalent: per-process shard files are written/read as raw
// chunk regions with a thread team doing pwrite/pread off the GIL, with
// per-chunk CRC32 integrity. The Python side (sharded_checkpoint.py) owns the
// format/index; this layer only moves bytes fast and checksums them.
//
// C ABI (ctypes):
//   atpu_io_write_chunks — preallocate (ftruncate) then parallel pwrite of n
//     chunks at caller-chosen offsets; emits per-chunk CRC32.
//   atpu_io_read_chunks  — parallel pread of n chunks; optional CRC verify.
// Return: 0 ok; -1 open/io failure; -2 crc mismatch (reads).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Table-driven CRC32 (IEEE, zlib-compatible). IO-bound workloads don't need
// hardware CRC; this keeps the library dependency-free.
uint32_t crc_table[256];
std::once_flag crc_once;

void crc_init() {
  // call_once: two ctypes callers can hit first use concurrently (the GIL is
  // released during the call) — a plain bool flag would race on the table
  std::call_once(crc_once, []() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  });
}

uint32_t crc32_of(const void* data, int64_t n) {
  const unsigned char* p = static_cast<const unsigned char*>(data);
  uint32_t c = 0xFFFFFFFFu;
  for (int64_t i = 0; i < n; ++i) c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

bool pwrite_all(int fd, const void* buf, int64_t n, int64_t off) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = pwrite(fd, p, static_cast<size_t>(n), static_cast<off_t>(off));
    if (w <= 0) return false;
    p += w; off += w; n -= w;
  }
  return true;
}

bool pread_all(int fd, void* buf, int64_t n, int64_t off) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = pread(fd, p, static_cast<size_t>(n), static_cast<off_t>(off));
    if (r <= 0) return false;
    p += r; off += r; n -= r;
  }
  return true;
}

}  // namespace

extern "C" {

int32_t atpu_io_write_chunks(const char* path, int64_t n, const void** srcs,
                             const int64_t* sizes, const int64_t* offsets,
                             uint32_t* crcs_out, int32_t num_threads) {
  crc_init();
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t end = offsets[i] + sizes[i];
    if (end > total) total = end;
  }
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) { close(fd); return -1; }
  std::atomic<int64_t> next(0);
  std::atomic<int32_t> failed(0);
  auto work = [&]() {
    int64_t i;
    while ((i = next.fetch_add(1)) < n && !failed.load()) {
      if (crcs_out) crcs_out[i] = crc32_of(srcs[i], sizes[i]);
      if (!pwrite_all(fd, srcs[i], sizes[i], offsets[i])) failed.store(1);
    }
  };
  if (num_threads <= 1 || n <= 1) {
    work();
  } else {
    std::vector<std::thread> team;
    int32_t nt = num_threads < n ? num_threads : static_cast<int32_t>(n);
    team.reserve(nt);
    for (int32_t t = 0; t < nt; ++t) team.emplace_back(work);
    for (auto& th : team) th.join();
  }
  bool ok = !failed.load() && fsync(fd) == 0;
  close(fd);
  return ok ? 0 : -1;
}

int32_t atpu_io_read_chunks(const char* path, int64_t n, void** dsts,
                            const int64_t* sizes, const int64_t* offsets,
                            const uint32_t* crcs, int32_t num_threads) {
  crc_init();
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  std::atomic<int64_t> next(0);
  std::atomic<int32_t> status(0);  // 0 ok, -1 io, -2 crc
  auto work = [&]() {
    int64_t i;
    while ((i = next.fetch_add(1)) < n && !status.load()) {
      if (!pread_all(fd, dsts[i], sizes[i], offsets[i])) { status.store(-1); return; }
      if (crcs && crc32_of(dsts[i], sizes[i]) != crcs[i]) { status.store(-2); return; }
    }
  };
  if (num_threads <= 1 || n <= 1) {
    work();
  } else {
    std::vector<std::thread> team;
    int32_t nt = num_threads < n ? num_threads : static_cast<int32_t>(n);
    team.reserve(nt);
    for (int32_t t = 0; t < nt; ++t) team.emplace_back(work);
    for (auto& th : team) th.join();
  }
  close(fd);
  return status.load();
}

}  // extern "C"
