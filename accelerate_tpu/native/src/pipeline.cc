// Native data-pipeline runtime for accelerate-tpu.
//
// The reference framework leans on external C++ engines for its host-side hot
// paths (torch's C++ DataLoader worker pool and pinned-memory collation;
// SURVEY.md §2.3). This is the TPU-native equivalent: the host-side work that
// feeds the chip — record IO, shuffling, batch assembly — runs here, off the
// GIL, double-buffered ahead of the training step so the device never waits on
// Python.
//
// Components (all exposed through a C ABI consumed via ctypes):
//   1. atpu_collate_*  — parallel memcpy batch assembly: gather N sample
//      buffers into one contiguous (N, sample_bytes) output using a thread
//      pool. Replaces torch's `default_collate` C++ path.
//   2. atpu_dataset_* / atpu_loader_* — memory-mapped fixed-record dataset
//      (token shards for LM pretraining) + a prefetching loader: worker
//      threads assemble whole batches (epoch shuffling with a seeded PRNG,
//      drop-last or wraparound) into a bounded ring of reusable staging
//      buffers; the consumer pops completed batches.
//
// Build: g++ -O3 -march=native -shared -fPIC (driven by ../build.py, cached
// next to the source; pure-Python fallback if no compiler).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------- collation --

// Copy n_samples buffers (each sample_bytes long, addresses in srcs[]) into
// dst, which must hold n_samples*sample_bytes. Parallelized over a transient
// thread team; for small batches the spawn cost dominates, so run inline below
// a threshold.
void atpu_collate(const void** srcs, int64_t n_samples, int64_t sample_bytes,
                  void* dst, int32_t num_threads) {
  const int64_t total = n_samples * sample_bytes;
  if (num_threads <= 1 || total < (1 << 20)) {
    for (int64_t i = 0; i < n_samples; ++i) {
      memcpy(static_cast<char*>(dst) + i * sample_bytes, srcs[i], sample_bytes);
    }
    return;
  }
  std::vector<std::thread> team;
  team.reserve(num_threads);
  std::atomic<int64_t> next(0);
  for (int32_t t = 0; t < num_threads; ++t) {
    team.emplace_back([&]() {
      int64_t i;
      while ((i = next.fetch_add(1, std::memory_order_relaxed)) < n_samples) {
        memcpy(static_cast<char*>(dst) + i * sample_bytes, srcs[i],
               sample_bytes);
      }
    });
  }
  for (auto& th : team) th.join();
}

// Strided gather: pick rows indices[0..n) from a (num_rows, row_bytes) source
// matrix into dst — the inner loop of shuffled in-memory batch assembly.
void atpu_gather_rows(const void* src, const int64_t* indices, int64_t n,
                      int64_t row_bytes, void* dst) {
  for (int64_t i = 0; i < n; ++i) {
    memcpy(static_cast<char*>(dst) + i * row_bytes,
           static_cast<const char*>(src) + indices[i] * row_bytes, row_bytes);
  }
}

// ------------------------------------------------------------------ dataset --

struct AtpuDataset {
  int fd = -1;
  const char* data = nullptr;  // mmap base
  int64_t file_bytes = 0;
  int64_t record_bytes = 0;
  int64_t num_records = 0;
};

AtpuDataset* atpu_dataset_open(const char* path, int64_t record_bytes) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  madvise(base, st.st_size, MADV_WILLNEED);
  auto* ds = new AtpuDataset();
  ds->fd = fd;
  ds->data = static_cast<const char*>(base);
  ds->file_bytes = st.st_size;
  ds->record_bytes = record_bytes;
  ds->num_records = st.st_size / record_bytes;
  return ds;
}

int64_t atpu_dataset_len(const AtpuDataset* ds) { return ds->num_records; }

void atpu_dataset_close(AtpuDataset* ds) {
  if (!ds) return;
  if (ds->data) munmap(const_cast<char*>(ds->data), ds->file_bytes);
  if (ds->fd >= 0) close(ds->fd);
  delete ds;
}

// ------------------------------------------------------------------- loader --

// Bounded multi-producer prefetch loader. Worker threads claim batch indices
// in order, assemble each batch into a staging buffer, and hand completed
// buffers to the consumer through a small reorder window so batches arrive in
// deterministic order regardless of worker scheduling.

struct Batch {
  std::vector<char> buf;
  int64_t id = -1;
};

struct AtpuLoader {
  const AtpuDataset* ds = nullptr;
  int64_t batch_size = 0;
  int64_t batch_bytes = 0;
  int64_t num_batches = 0;  // per epoch
  bool drop_last = true;
  bool shuffle = false;
  uint64_t seed = 0;
  int64_t epoch = 0;

  std::vector<int64_t> order;  // shuffled record indices for current epoch

  std::vector<std::thread> workers;
  std::atomic<int64_t> next_batch{0};  // producer claim counter
  std::atomic<bool> stop{false};

  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  std::deque<Batch> ready;      // completed batches (reordered on pop)
  int64_t next_out = 0;         // id the consumer must receive next
  int64_t max_ready = 0;        // lookahead window (ids < next_out + max_ready)
  int32_t num_workers = 2;

  void reshuffle() {
    order.resize(ds->num_records);
    for (int64_t i = 0; i < ds->num_records; ++i) order[i] = i;
    if (shuffle) {
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
      for (int64_t i = ds->num_records - 1; i > 0; --i) {
        int64_t j = static_cast<int64_t>(rng() % static_cast<uint64_t>(i + 1));
        std::swap(order[i], order[j]);
      }
    }
  }

  void work() {
    while (!stop.load(std::memory_order_acquire)) {
      int64_t id = next_batch.fetch_add(1, std::memory_order_relaxed);
      if (id >= num_batches) return;
      Batch b;
      b.id = id;
      b.buf.resize(batch_bytes);
      for (int64_t k = 0; k < batch_size; ++k) {
        // wraparound for the final uneven batch when drop_last is off
        // (reference even_batches wraparound, data_loader.py:236-262)
        int64_t pos = id * batch_size + k;
        int64_t rec = order[pos % ds->num_records];
        memcpy(b.buf.data() + k * ds->record_bytes,
               ds->data + rec * ds->record_bytes, ds->record_bytes);
      }
      std::unique_lock<std::mutex> lock(mu);
      // Admission by id, not queue occupancy: waiting on "queue has space"
      // deadlocks when out-of-order completions fill the window while the
      // consumer still needs an older id. With id-bounded lookahead every id
      // in [next_out, next_out+max_ready) is admissible, so the consumer's
      // next batch always gets in.
      cv_produce.wait(lock, [&] {
        return stop.load(std::memory_order_acquire) ||
               id < next_out + max_ready;
      });
      if (stop.load(std::memory_order_acquire)) return;
      ready.push_back(std::move(b));
      cv_consume.notify_all();
    }
  }
};

AtpuLoader* atpu_loader_new(const AtpuDataset* ds, int64_t batch_size,
                            int32_t shuffle, uint64_t seed, int32_t drop_last,
                            int32_t num_workers, int32_t prefetch_depth) {
  if (!ds || batch_size <= 0 || ds->num_records == 0) return nullptr;
  auto* ld = new AtpuLoader();
  ld->ds = ds;
  ld->batch_size = batch_size;
  ld->batch_bytes = batch_size * ds->record_bytes;
  ld->drop_last = drop_last != 0;
  ld->shuffle = shuffle != 0;
  ld->seed = seed;
  ld->num_batches = ld->drop_last
                        ? ds->num_records / batch_size
                        : (ds->num_records + batch_size - 1) / batch_size;
  ld->max_ready = prefetch_depth > 0 ? prefetch_depth : 2;
  ld->num_workers = num_workers > 0 ? num_workers : 2;
  // the lookahead window must admit one in-flight batch per worker
  if (ld->max_ready < ld->num_workers) ld->max_ready = ld->num_workers;
  ld->reshuffle();
  for (int32_t i = 0; i < ld->num_workers; ++i)
    ld->workers.emplace_back(&AtpuLoader::work, ld);
  return ld;
}

int64_t atpu_loader_num_batches(const AtpuLoader* ld) {
  return ld->num_batches;
}

// Pop the next in-order batch into dst (batch_bytes). Returns the batch id,
// or -1 when the epoch is exhausted.
int64_t atpu_loader_next(AtpuLoader* ld, void* dst) {
  if (ld->next_out >= ld->num_batches) return -1;
  std::unique_lock<std::mutex> lock(ld->mu);
  for (;;) {
    for (auto it = ld->ready.begin(); it != ld->ready.end(); ++it) {
      if (it->id == ld->next_out) {
        memcpy(dst, it->buf.data(), ld->batch_bytes);
        ld->ready.erase(it);
        ld->next_out++;
        ld->cv_produce.notify_all();  // window advanced — admit new ids
        return ld->next_out - 1;
      }
    }
    ld->cv_consume.wait(lock);
  }
}

// Start the next epoch: reshuffles (seed+epoch) and restarts the workers.
void atpu_loader_next_epoch(AtpuLoader* ld) {
  // drain workers
  ld->stop.store(true, std::memory_order_release);
  ld->cv_produce.notify_all();
  for (auto& th : ld->workers) th.join();
  ld->workers.clear();
  ld->stop.store(false, std::memory_order_release);
  ld->ready.clear();
  ld->next_out = 0;
  ld->next_batch.store(0, std::memory_order_relaxed);
  ld->epoch += 1;
  ld->reshuffle();
  for (int32_t i = 0; i < ld->num_workers; ++i)
    ld->workers.emplace_back(&AtpuLoader::work, ld);
}

void atpu_loader_free(AtpuLoader* ld) {
  if (!ld) return;
  ld->stop.store(true, std::memory_order_release);
  ld->cv_produce.notify_all();
  ld->cv_consume.notify_all();
  for (auto& th : ld->workers) th.join();
  delete ld;
}

int32_t atpu_abi_version() { return 1; }

}  // extern "C"
