"""Hook runtime for staged/paged execution.

TPU-native counterpart of the reference's ``hooks.py``
(``/root/reference/src/accelerate/hooks.py`` — ``ModelHook:43``,
``add_hook_to_module:132``, ``AlignDevicesHook:227``, ``SequentialHook``,
``CpuOffload:693``, ``LayerwiseCastingHook:757``).

Architecture shift: torch hooks monkeypatch ``module.forward``; jax models are
``fn(params, x)`` stage functions, so a hook wraps the *call* — it can reshape,
re-place or substitute the params the stage sees and post-process its outputs.
The paging hooks pull per-stage params from an :class:`~accelerate_tpu.utils.offload.
OffloadedWeightsLoader`-style mapping and ``jax.device_put`` them to the compute
device; ``device_put`` is async, so :class:`AlignDevicesHook` can prefetch stage
``i+1`` while stage ``i`` computes — double-buffering the reference's
synchronous page-in loop.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Mapping, Optional, Sequence

import numpy as np


class ModelHook:
    """Pre/post hooks around one stage call (reference ``ModelHook:43``)."""

    def init_hook(self, stage_name: str, params):
        """Called once when the hook is attached; may transform stored params."""
        return params

    def pre_forward(self, params, *args, **kwargs):
        """Return (params, args, kwargs) the stage should actually see."""
        return params, args, kwargs

    def post_forward(self, params, output):
        """Return the (possibly transformed) output."""
        return output

    def detach_hook(self, params):
        return params


class SequentialHook(ModelHook):
    """Compose hooks in order (reference ``SequentialHook:112``)."""

    def __init__(self, *hooks: ModelHook):
        self.hooks = list(hooks)

    def init_hook(self, stage_name, params):
        for h in self.hooks:
            params = h.init_hook(stage_name, params)
        return params

    def pre_forward(self, params, *args, **kwargs):
        for h in self.hooks:
            params, args, kwargs = h.pre_forward(params, *args, **kwargs)
        return params, args, kwargs

    def post_forward(self, params, output):
        for h in self.hooks:
            output = h.post_forward(params, output)
        return output

    def detach_hook(self, params):
        for h in self.hooks:
            params = h.detach_hook(params)
        return params


def add_hook_to_fn(fn: Callable, hook: ModelHook, stage_name: str = "") -> Callable:
    """Wrap ``fn(params, *args, **kwargs)`` with a hook (reference
    ``add_hook_to_module:132`` replaces ``module.forward``). The wrapped fn
    carries ``_at_hook`` so :func:`remove_hook_from_fn` can unwrap."""
    if getattr(fn, "_at_hook", None) is not None:
        hook = SequentialHook(fn._at_hook, hook)
        fn = fn._at_original

    @functools.wraps(fn)
    def wrapped(params, *args, **kwargs):
        params, args, kwargs = hook.pre_forward(params, *args, **kwargs)
        output = fn(params, *args, **kwargs)
        return hook.post_forward(params, output)

    wrapped._at_hook = hook
    wrapped._at_original = fn
    wrapped._at_stage = stage_name
    return wrapped


def remove_hook_from_fn(fn: Callable) -> Callable:
    """Unwrap (reference ``remove_hook_from_module:196``)."""
    return getattr(fn, "_at_original", fn)


class AlignDevicesHook(ModelHook):
    """Page a stage's params onto the execution device before the call and drop
    the HBM copy afterwards (reference ``AlignDevicesHook:227``:
    ``pre_forward:331`` loads from ``weights_map``, ``post_forward:377``
    re-offloads). ``weights_map`` is any mapping ``path → np/jax array`` (e.g.
    ``OffloadedWeightsLoader``); paths are relative to the stage subtree."""

    def __init__(
        self,
        execution_device=None,
        offload: bool = True,
        weights_map: Optional[Mapping[str, Any]] = None,
        tied_params_map: Optional[dict[int, Any]] = None,
    ):
        import jax

        self.execution_device = (
            execution_device if execution_device is not None else _default_device()
        )
        self.offload = offload
        self.weights_map = weights_map
        # id(host array) → device copy, shared across hooks so tied weights are
        # transferred once (reference tied_params_map, hooks.py:258-266)
        self.tied_params_map = tied_params_map if tied_params_map is not None else {}
        self._jax = jax

    def init_hook(self, stage_name, params):
        self.stage_name = stage_name
        return params

    def _put(self, leaf):
        if leaf is None:
            return None
        # hold the host array in the entry so its id cannot be recycled while
        # the cache is alive (ids of freed arrays are reused by CPython)
        key = id(leaf)
        entry = self.tied_params_map.get(key)
        if entry is not None and entry[0] is leaf:
            return entry[1]
        placed = self._jax.device_put(leaf, self.execution_device)
        self.tied_params_map[key] = (leaf, placed)
        return placed

    def pre_forward(self, params, *args, **kwargs):
        from .utils.modeling import named_parameters, unflatten_parameters

        flat = named_parameters(params)
        loaded = {}
        for path, leaf in flat.items():
            if leaf is None and self.weights_map is not None:
                leaf = self.weights_map[path]
            loaded[path] = self._put(leaf)
        args = tuple(
            self._jax.device_put(a, self.execution_device) if _is_arraylike(a) else a for a in args
        )
        if isinstance(params, Mapping):
            return unflatten_parameters(loaded), args, kwargs
        # bare-leaf params flatten to {'': leaf}
        return loaded.get("", loaded), args, kwargs

    def post_forward(self, params, output):
        if self.offload:
            self.tied_params_map.clear()
        return output


class PrefetchingLoader:
    """Iterate ``(stage_name, stage_fn, host_params)`` triples yielding
    device-resident params one stage ahead of compute. ``jax.device_put`` is
    async: the H2D copy of stage i+1 overlaps stage i's math — the
    double-buffered upgrade of the reference's page-in loop
    (``hooks.py:331-376``)."""

    def __init__(self, stages: Sequence[tuple], execution_device=None):
        self.stages = list(stages)
        self.execution_device = execution_device or _default_device()

    def __iter__(self):
        import jax

        pending = None
        for i, (name, fn, host_params) in enumerate(self.stages):
            placed = pending if pending is not None else jax.device_put(
                host_params, self.execution_device
            )
            if i + 1 < len(self.stages):
                pending = jax.device_put(self.stages[i + 1][2], self.execution_device)
            else:
                pending = None
            yield name, fn, placed


class CpuOffloadHook(ModelHook):
    """Keep params on host between calls; page to device per call (reference
    ``CpuOffload:693``). With ``prev_hook`` chaining, offload of stage i-1
    happens when stage i starts."""

    def __init__(self, execution_device=None, prev_hook: Optional["CpuOffloadHook"] = None):
        self.execution_device = execution_device or _default_device()
        self.prev_hook = prev_hook
        self._device_copy = None

    def pre_forward(self, params, *args, **kwargs):
        import jax

        if self.prev_hook is not None:
            self.prev_hook.release()
        self._device_copy = jax.device_put(params, self.execution_device)
        return self._device_copy, args, kwargs

    def release(self):
        self._device_copy = None


class LayerwiseCastingHook(ModelHook):
    """Store params in ``storage_dtype``; upcast to ``compute_dtype`` per call
    (reference ``LayerwiseCastingHook:757`` — fp8/bf16 storage, bf16/fp32
    compute)."""

    def __init__(self, storage_dtype, compute_dtype):
        self.storage_dtype = storage_dtype
        self.compute_dtype = compute_dtype

    def init_hook(self, stage_name, params):
        import jax

        return jax.tree_util.tree_map(
            lambda x: x.astype(self.storage_dtype) if _is_floating(x) else x, params
        )

    def pre_forward(self, params, *args, **kwargs):
        import jax

        cast = jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype) if _is_floating(x) else x, params
        )
        return cast, args, kwargs


def _default_device():
    import jax

    accel = [d for d in jax.local_devices() if d.platform != "cpu"]
    return accel[0] if accel else jax.local_devices()[0]


def _is_arraylike(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _is_floating(x) -> bool:
    try:
        return np.issubdtype(np.asarray(x).dtype, np.floating) or "bfloat16" in str(x.dtype)
    except Exception:
        return False
