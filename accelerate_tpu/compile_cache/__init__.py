"""Zero-cold-start recovery: a crash-safe persistent cache of serialized AOT
executables (ROADMAP item 5).

:mod:`~accelerate_tpu.compile_cache.cache` is the content-addressed on-disk
store (keys, the staged-fsync-CRC-manifest-rename commit protocol,
quarantine-on-corruption reads, size-capped eviction);
:mod:`~accelerate_tpu.compile_cache.runtime` is the consumer surface (env
knobs, telemetry, the load-or-compile helpers the Accelerator, the serving
engine warmup and the elastic supervisor call).

See ``docs/compile_cache.md`` for layout, crash/corruption semantics,
cross-host sharing and the knobs; ``benchmarks/compile_time/`` measures the
restart-to-first-step and replica-boot-to-first-token wins (``make
bench-compile``).
"""

from .cache import (
    LAST_HIT_NAME,
    MANIFEST_NAME,
    PAYLOAD_NAME,
    QUARANTINE_DIRNAME,
    SCHEMA_VERSION,
    CacheKey,
    CompileCache,
    LoadResult,
    StoreResult,
    compile_flags,
    environment_fingerprint,
    key_from_lowered,
)
from .runtime import (
    CACHE_DIR_ENV_VAR,
    CACHE_ENV_VAR,
    CACHE_FN_QUOTA_MB_ENV_VAR,
    CACHE_MAX_MB_ENV_VAR,
    aot_compile,
    cache_enabled,
    call_with_fallback,
    configured_cache_dir,
    get_cache,
    maybe_export,
    maybe_load_executable,
    preship,
    pretouch,
)

__all__ = [
    "CACHE_ENV_VAR",
    "CACHE_DIR_ENV_VAR",
    "CACHE_FN_QUOTA_MB_ENV_VAR",
    "CACHE_MAX_MB_ENV_VAR",
    "LAST_HIT_NAME",
    "MANIFEST_NAME",
    "PAYLOAD_NAME",
    "QUARANTINE_DIRNAME",
    "SCHEMA_VERSION",
    "CacheKey",
    "CompileCache",
    "LoadResult",
    "StoreResult",
    "aot_compile",
    "cache_enabled",
    "call_with_fallback",
    "compile_flags",
    "configured_cache_dir",
    "environment_fingerprint",
    "get_cache",
    "key_from_lowered",
    "maybe_export",
    "maybe_load_executable",
    "preship",
    "pretouch",
]
