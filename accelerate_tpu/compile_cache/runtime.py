"""Runtime surface of the executable cache: env knobs, telemetry, and the
load-or-compile helpers the consumers call.

Three consumers define recovery time, and each gets a one-call integration:

- the :class:`~accelerate_tpu.accelerator.Accelerator` probes the cache
  before its first step on restart generations >= 1
  (:func:`maybe_load_executable` — load-only, never compiles: a miss just
  means the jit path pays the compile as today, and
  ``telemetry/perf.py``'s cost capture then *exports* the executable so the
  NEXT generation hits);
- the serving engine's warmup AOT-compiles every lattice point through
  :func:`aot_compile` (hit → load in milliseconds, miss → compile once and
  export), so a replacement replica boots warm;
- the elastic supervisor calls :func:`pretouch` before every (re)spawn so a
  missing or read-only cache directory degrades to a VISIBLE cold start
  instead of a silent one.

Every outcome is one ``compile_cache`` telemetry record
(hit/miss/corrupt/fallback/store/... + bytes + load seconds — schema in
``docs/telemetry.md``); the report CLI aggregates them into a "compile
cache" section.

Knobs: ``ACCELERATE_COMPILE_CACHE=0`` kills the whole feature (byte-identical
behavior to an uncached build); ``ACCELERATE_COMPILE_CACHE_DIR`` names the
(shareable) directory — **unset means disabled** (the cache never writes
anywhere the operator didn't point it); ``ACCELERATE_COMPILE_CACHE_MAX_MB``
caps the directory size (least-recently-hit entries evicted first);
``ACCELERATE_COMPILE_CACHE_FN_QUOTA_MB`` caps each function's share so one
model's lattice cannot evict another fleet's entries.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

from ..logging import get_logger
from ..telemetry import events as tel
from .cache import MANIFEST_NAME, CacheKey, CompileCache, LoadResult, key_from_lowered

logger = get_logger(__name__)

CACHE_ENV_VAR = "ACCELERATE_COMPILE_CACHE"
CACHE_DIR_ENV_VAR = "ACCELERATE_COMPILE_CACHE_DIR"
CACHE_MAX_MB_ENV_VAR = "ACCELERATE_COMPILE_CACHE_MAX_MB"
CACHE_FN_QUOTA_MB_ENV_VAR = "ACCELERATE_COMPILE_CACHE_FN_QUOTA_MB"

_FALSY = ("0", "false", "no", "off")


def cache_enabled() -> bool:
    """The kill switch: ``ACCELERATE_COMPILE_CACHE=0`` disables everything —
    no directory access, no telemetry, no behavior change anywhere."""
    return os.environ.get(CACHE_ENV_VAR, "").strip().lower() not in _FALSY


def configured_cache_dir(env: Optional[dict] = None) -> Optional[str]:
    """The cache directory from the environment, or ``None`` (= disabled:
    the cache never invents a location the operator didn't configure)."""
    source = os.environ if env is None else env
    path = source.get(CACHE_DIR_ENV_VAR, "").strip()
    return path or None


def get_cache(directory: Optional[str] = None) -> Optional[CompileCache]:
    """The :class:`CompileCache` for ``directory`` (default: the env dir), or
    ``None`` when the feature is off, unconfigured, or the directory cannot
    be created (logged — an unusable cache degrades to cold compiles, it
    never breaks a restart)."""
    if not cache_enabled():
        return None
    directory = directory or configured_cache_dir()
    if not directory:
        return None
    try:
        return CompileCache(directory)
    except OSError as exc:
        logger.warning(f"compile cache dir {directory} unusable ({exc}); cold-starting")
        return None


def _emit(event: str, fn: str, key: Optional[CacheKey] = None, **fields: Any) -> None:
    from ..telemetry import metrics as _metrics

    # the streaming-metrics plane counts every cache outcome too (scrapable
    # hit/miss/corrupt rates per fn); one None-check when metrics are off
    _metrics.inc("accelerate_compile_cache_events_total", event=event, fn=fn)
    if not tel.is_enabled():
        return
    tel.emit(
        "compile_cache",
        event=event,
        fn=fn,
        key=key.entry_id if key is not None else None,
        **fields,
    )


def _emit_load(fn: str, key: CacheKey, res: LoadResult) -> None:
    if res.outcome == "hit":
        _emit("hit", fn, key, bytes=res.nbytes, load_s=res.seconds)
    elif res.outcome == "corrupt":
        _emit(
            "corrupt", fn, key, reason=res.reason,
            quarantined_to=res.quarantined_to,
        )
        _emit("fallback", fn, key, reason="corrupt entry — compiling fresh")
    else:
        _emit("miss", fn, key, reason=res.reason)


# -------------------------------------------------------------- consumers ----
def maybe_load_executable(
    name: str,
    fn: Any,
    args: tuple,
    kwargs: Optional[dict] = None,
    *,
    mesh: Optional[Any] = None,
    directory: Optional[str] = None,
) -> "tuple[Optional[Any], Optional[CacheKey]]":
    """Load-only probe for a jitted ``fn`` at ``args``: trace (no XLA
    compile), key, and return the cached executable on a hit — or ``None``
    on miss/corrupt/disabled, in which case the caller's normal jit path
    compiles exactly as today. Never raises."""
    cache = get_cache(directory)
    if cache is None or not hasattr(fn, "lower"):
        return None, None
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
        key = key_from_lowered(name, lowered, mesh=mesh)
    except Exception as exc:
        logger.warning(f"compile cache probe for {name} failed to trace: {exc}")
        return None, None
    res = cache.load(key)
    _emit_load(name, key, res)
    return res.executable, key


def aot_compile(
    name: str,
    fn: Any,
    args: tuple,
    kwargs: Optional[dict] = None,
    *,
    mesh: Optional[Any] = None,
    directory: Optional[str] = None,
    cache: Optional[CompileCache] = None,
) -> "tuple[Optional[Any], str]":
    """Load-or-compile one program point: returns ``(executable, outcome)``
    where outcome is ``hit`` / ``miss`` (freshly compiled + exported) /
    ``corrupt`` (quarantined, freshly compiled) / ``uncached`` (cache off —
    freshly compiled, not exported) / ``error`` (could not even compile:
    executable is ``None``; the caller falls back to its plain jit path)."""
    if not hasattr(fn, "lower"):
        return None, "error"
    if cache is None:
        cache = get_cache(directory)
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
    except Exception as exc:
        logger.warning(f"AOT lowering of {name} failed: {exc}")
        return None, "error"
    key = None
    if cache is not None:
        try:
            key = key_from_lowered(name, lowered, mesh=mesh)
        except Exception:
            key = None
        if key is not None:
            res = cache.load(key)
            _emit_load(name, key, res)
            if res.outcome == "hit":
                return res.executable, "hit"
            outcome = res.outcome  # miss or corrupt(→fallback compile)
        else:
            outcome = "miss"
    else:
        outcome = "uncached"
    try:
        compiled = lowered.compile()
    except Exception as exc:
        logger.warning(f"AOT compile of {name} failed: {exc}")
        return None, "error"
    if cache is not None and key is not None:
        store = cache.store(key, compiled)
        _emit(
            f"store_{store.outcome}" if store.outcome != "stored" else "store",
            name, key, bytes=store.nbytes, store_s=store.seconds,
            reason=store.reason, evicted=len(store.evicted) or None,
        )
    return compiled, outcome


def maybe_export(
    name: str,
    lowered: Any,
    compiled: Any,
    *,
    mesh: Optional[Any] = None,
    directory: Optional[str] = None,
) -> Optional[str]:
    """Export an already-compiled executable (the perf cost capture's AOT
    compile — free to serialize since the compile is already paid). Returns
    the store outcome or ``None`` when the cache is off. Never raises."""
    cache = get_cache(directory)
    if cache is None:
        return None
    try:
        key = key_from_lowered(name, lowered, mesh=mesh)
    except Exception as exc:
        logger.warning(f"compile cache export of {name} failed to key: {exc}")
        return None
    res = cache.store(key, compiled)
    _emit(
        f"store_{res.outcome}" if res.outcome != "stored" else "store",
        name, key, bytes=res.nbytes, store_s=res.seconds,
        reason=res.reason, evicted=len(res.evicted) or None,
    )
    return res.outcome


def pretouch(
    directory: Optional[str] = None, env: Optional[dict] = None
) -> "dict[str, Any]":
    """Supervisor pre-spawn probe: is the cache there and writable for the
    next generation? Returns ``{"status": "ok" | "disabled" | "unconfigured"
    | "readonly" | "missing", "dir": ...}``; anything not ``ok``/
    ``disabled``/``unconfigured`` means the respawn will cold-start — the
    caller logs and emits so that shows up in the restart record instead of
    silently doubling MTTR."""
    if env is not None:
        enabled = str(env.get(CACHE_ENV_VAR, "")).strip().lower() not in _FALSY
    else:
        enabled = cache_enabled()
    if not enabled:
        return {"status": "disabled", "dir": None}
    directory = directory or configured_cache_dir(env)
    if not directory:
        return {"status": "unconfigured", "dir": None}
    info: "dict[str, Any]" = {"dir": directory}
    if not os.path.isdir(directory):
        try:
            os.makedirs(directory, exist_ok=True)
        except OSError as exc:
            info.update(status="missing", error=str(exc))
            return info
    probe = os.path.join(directory, f".pretouch-{os.getpid()}-{os.urandom(3).hex()}")
    try:
        with open(probe, "w") as f:
            f.write("ok")
        os.unlink(probe)
    except OSError as exc:
        info.update(status="readonly", error=str(exc))
        return info
    try:
        cache = CompileCache(directory)
        info.update(status="ok", **{k: v for k, v in cache.stats().items() if k != "dir"})
    except OSError as exc:
        info.update(status="missing", error=str(exc))
    return info


def preship(
    src_dir: str,
    dst_dir: str,
    *,
    fns: Optional["set[str]"] = None,
    fn_prefixes: "tuple[str, ...]" = ("serving_",),
) -> "dict[str, Any]":
    """Warm a JOINER's cache before it boots (the autoscaler's scale-up
    path): copy committed entries from ``src_dir`` into ``dst_dir`` so the
    joining replica's warmup is all hits — zero compiles on join.

    Only entries whose manifest ``fn`` matches ship: the exact names in
    ``fns`` when given (the joiner's warmup lattice), else any
    ``fn_prefixes`` match — a training fleet's entries never ride along.
    Each entry is staged (``.tmp-``, invisible to :meth:`CompileCache.
    entries`) and atomically renamed, so a concurrently booting reader
    never sees a half-copied entry; entries already present are left
    alone. Returns ``{"shipped", "skipped", "already", "bytes"}`` and
    emits one ``compile_cache`` ``preship`` telemetry record."""
    import shutil

    out: "dict[str, Any]" = {"shipped": 0, "skipped": 0, "already": 0, "bytes": 0}
    src = CompileCache(src_dir)
    os.makedirs(dst_dir, exist_ok=True)
    for path in src.entries():
        fn = src._entry_fn(path)
        wanted = (fn in fns) if fns is not None else fn.startswith(tuple(fn_prefixes))
        if not wanted:
            out["skipped"] += 1
            continue
        dst_entry = os.path.join(dst_dir, os.path.basename(path))
        if os.path.isfile(os.path.join(dst_entry, MANIFEST_NAME)):
            out["already"] += 1
            continue
        staging = dst_entry + f".tmp-preship-{os.getpid()}-{os.urandom(3).hex()}"
        try:
            shutil.copytree(path, staging)
            os.rename(staging, dst_entry)
        except OSError:
            # a concurrent shipper won the rename, or the filesystem is sick:
            # either way the boot degrades to a compile, never to a crash
            shutil.rmtree(staging, ignore_errors=True)
            out["skipped"] += 1
            continue
        out["shipped"] += 1
        out["bytes"] += CompileCache._dir_bytes(dst_entry)
    _emit("preship", "*", src_dir=src_dir, dst_dir=dst_dir, **out)
    return out


def call_with_fallback(
    name: str,
    executable: Any,
    fallback_fn: Any,
    args: tuple,
    key: Optional[CacheKey] = None,
) -> "tuple[Any, bool]":
    """Call a cache-loaded executable, falling back to the live jit path if
    the call itself rejects (avals/shardings drifted since export — possible
    when a restart changes an input dtype the key's HLO didn't see).

    Returns ``(result, executable_still_usable)``. Only the PRE-execution
    rejections AOT input checking raises (``TypeError``/``ValueError``) are
    caught — at that point no donated buffer has been consumed, so re-running
    the fallback on the same arrays is safe. A failure from inside execution
    (backend runtime error, OOM) propagates: the inputs may already be
    donated away, and silently re-running would mask the real failure."""
    try:
        return executable(*args), True
    except (TypeError, ValueError) as exc:
        logger.warning(
            f"cached executable for {name} rejected its inputs "
            f"({type(exc).__name__}: {exc}); falling back to fresh compile"
        )
        _emit("fallback", name, key, reason=f"call rejected: {type(exc).__name__}")
        return fallback_fn(*args), False
