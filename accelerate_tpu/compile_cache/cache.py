"""Crash-safe persistent cache of serialized AOT executables.

Every elastic restart (PR 10), serving-replica replacement (PR 12) and
autoscale boot pays a full XLA compile from scratch — recovery time after a
preemption is DOMINATED by recompilation (the r04 bench round died with 8/8
probes hung in exactly that window). This module makes the compile a
once-per-fleet cost: the first process to compile a program serializes the
executable into a content-addressed on-disk entry, and every later process
generation — a supervisor respawn, a replacement replica, a new autoscaled
worker sharing the directory — loads it back in milliseconds instead of
recompiling.

**Keying.** An entry is addressed by :class:`CacheKey`: the SHA-256 of the
traced program's StableHLO text plus everything that changes what XLA would
produce for it — the mesh axis→size map, the device kind and visible device
count, the jax/jaxlib/backend versions, and the XLA compile flags. Any
difference lands on a different entry id, so a version bump or topology
change is a clean *miss*, never a wrong load.

**Crash consistency** (the PR 5 checkpoint protocol, applied to executables):
a writer serializes into a private ``<entry>.tmp-<pid>-<nonce>`` staging
directory, fsyncs every file, writes the CRC32-carrying ``MANIFEST.json``
*last*, fsyncs the staging dir, then atomically ``os.rename``s it onto the
final entry name. A ``kill -9`` at ANY point leaves either a fully committed
entry or an orphaned staging dir (swept on a later store) — never a torn
entry under the committed name. Concurrent writers race benignly: the first
rename wins, losers discard their staging.

**Defensive reads.** A poisoned cache must never crash a restart or load the
wrong executable. Every load re-validates the manifest (parseable, schema,
every key field equal to the *requested* key — a swapped manifest or a
tampered version/topology field fails here) and the payload CRC32 before
deserializing; any failure **quarantines** the entry (moved aside for the
operator, so the next restart does not re-trip on it) and reports a corrupt
outcome — the caller falls back to a fresh compile with a warning.

**Eviction.** ``ACCELERATE_COMPILE_CACHE_MAX_MB`` bounds the directory;
least-recently-HIT entries go first — every successful load touches the
entry's ``LAST_HIT`` stamp, so the executables a fleet actually reloads stay
resident while write-once-never-read entries age out (never-hit entries fall
back to their write time). ``ACCELERATE_COMPILE_CACHE_FN_QUOTA_MB`` bounds
each *function*'s share on top (the manifest ``fn`` field groups entries):
one model's serving lattice filling the directory evicts its OWN stale
points, not another fleet's step executables. Either way, an entry another
process currently holds a shared ``flock`` on (it is mid-load) is skipped —
eviction can never yank an executable out from under a reader.

The payload is a pickle of :func:`jax.experimental.serialize_executable.
serialize` output; like JAX's own persistent compilation cache, the
directory must be trusted (treat it with the same care as the checkpoint
dir it usually sits next to).
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import pickle
import shutil
import time
import zlib
from dataclasses import asdict, dataclass, field
from typing import Any, Optional

from ..logging import get_logger

logger = get_logger(__name__)

SCHEMA_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
PAYLOAD_NAME = "executable.bin"
LAST_HIT_NAME = "LAST_HIT"
QUARANTINE_DIRNAME = "quarantine"

#: Orphaned staging dirs (a writer killed mid-write) older than this are
#: swept by the next store; younger ones may belong to a live writer.
STALE_STAGING_AGE_S = 15 * 60.0


def _chaos_inject(point: str) -> None:
    # lazy import, same pattern as serving/engine.py: the cache must not pay
    # for (or cyclically import) the resilience stack at module load
    from ..resilience import chaos as _chaos

    _chaos.maybe_inject(point)


def _fsync_path(path: str) -> None:
    """fsync a file or directory by path (directory fsync is how a rename /
    create is made durable — same helper contract as checkpointing.py)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


# ------------------------------------------------------------------ keys ----
def environment_fingerprint() -> "dict[str, Any]":
    """The environment half of every cache key: anything that changes what
    XLA would compile for the same StableHLO. Collected defensively — a field
    an old jaxlib cannot report becomes ``"?"`` (still part of the key, so
    two processes disagree only if their environments actually differ)."""
    import jax
    import jaxlib

    try:
        try:
            from jax.extend.backend import get_backend
        except ImportError:  # older jax spells it differently
            from jax.lib.xla_bridge import get_backend
        backend_version = str(
            getattr(get_backend(), "platform_version", "?")
        ).strip()
    except Exception:
        backend_version = "?"
    try:
        devices = jax.devices()
        device_kind = str(getattr(devices[0], "device_kind", "?") or "?")
        num_devices = len(devices)
    except Exception:
        device_kind, num_devices = "?", 0
    return {
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "backend_version": backend_version,
        "device_kind": device_kind,
        "num_devices": num_devices,
        "flags": compile_flags(),
    }


def compile_flags() -> str:
    """Canonicalized XLA compile flags (order-independent): flag strings that
    differ only in token order must not split the cache."""
    return " ".join(sorted(os.environ.get("XLA_FLAGS", "").split()))


@dataclass(frozen=True)
class CacheKey:
    """Content address of one executable. ``fn`` is informational only (two
    identically-traced functions share an entry); every OTHER field is hashed
    into :attr:`entry_id` and re-verified against the manifest at load time."""

    fn: str
    fingerprint: str  # sha256 of the lowered StableHLO text
    mesh_axes: "tuple[tuple[str, int], ...]" = ()
    device_kind: str = "?"
    num_devices: int = 0
    jax_version: str = "?"
    jaxlib_version: str = "?"
    backend_version: str = "?"
    flags: str = ""

    def identity(self) -> "dict[str, Any]":
        """The hashed/verified fields (everything except ``fn``)."""
        out = asdict(self)
        out.pop("fn")
        out["mesh_axes"] = [[a, int(s)] for a, s in self.mesh_axes]
        return out

    @property
    def entry_id(self) -> str:
        digest = hashlib.sha256(
            json.dumps(self.identity(), sort_keys=True).encode()
        ).hexdigest()
        return digest[:24]


def key_from_lowered(name: str, lowered: Any, mesh: Optional[Any] = None) -> CacheKey:
    """Build the :class:`CacheKey` for a ``jax.stages.Lowered`` program.

    The StableHLO text embeds the traced computation including shardings, so
    its hash is stable across processes for the same program (proven by the
    cross-process key test); the mesh axis→size map is keyed explicitly on
    top because two meshes can produce the same module text for trivially
    replicated programs while compiling differently."""
    text = lowered.as_text()
    mesh_axes: "tuple[tuple[str, int], ...]" = ()
    if mesh is not None:
        try:
            mesh_axes = tuple((str(a), int(s)) for a, s in dict(mesh.shape).items())
        except Exception:
            mesh_axes = ()
    env = environment_fingerprint()
    return CacheKey(
        fn=name,
        fingerprint=hashlib.sha256(text.encode()).hexdigest(),
        mesh_axes=mesh_axes,
        **env,
    )


# --------------------------------------------------------------- results ----
@dataclass
class LoadResult:
    """Outcome of one :meth:`CompileCache.load`.

    ``outcome``: ``hit`` | ``miss`` | ``corrupt`` (validation failed, entry
    quarantined) — a corrupt outcome NEVER carries an executable; the caller
    must fall back to a fresh compile."""

    outcome: str
    executable: Optional[Any] = None
    reason: Optional[str] = None
    nbytes: int = 0
    seconds: float = 0.0
    quarantined_to: Optional[str] = None


@dataclass
class StoreResult:
    """Outcome of one :meth:`CompileCache.store`: ``stored`` | ``raced``
    (another writer committed first — benign) | ``error`` (serialization or
    IO failed; the cache stays as it was)."""

    outcome: str
    reason: Optional[str] = None
    nbytes: int = 0
    seconds: float = 0.0
    evicted: "list[str]" = field(default_factory=list)


class CompileCacheCorrupt(RuntimeError):
    """Internal: entry failed validation (caught inside :meth:`load`)."""


# ----------------------------------------------------------------- cache ----
class CompileCache:
    """One on-disk executable cache directory (shareable across hosts).

    All methods are safe against concurrent readers/writers in other
    processes and against being killed at any point; none of them raise on a
    sick filesystem or a poisoned entry — degraded outcomes are returned, not
    thrown (the one exception: the constructor raises ``OSError`` if the
    directory cannot be created, which :func:`~accelerate_tpu.compile_cache.
    runtime.pretouch` turns into a visible cold-start warning)."""

    def __init__(
        self,
        directory: str,
        max_mb: Optional[float] = None,
        fn_quota_mb: Optional[float] = None,
    ):
        self.directory = os.path.abspath(directory)
        self.max_mb = max_mb
        self.fn_quota_mb = fn_quota_mb
        os.makedirs(self.directory, exist_ok=True)

    # -- layout ---------------------------------------------------------------
    def entry_dir(self, key: CacheKey) -> str:
        return os.path.join(self.directory, key.entry_id)

    def quarantine_dir(self) -> str:
        return os.path.join(self.directory, QUARANTINE_DIRNAME)

    def entries(self) -> "list[str]":
        """Committed entry dirs (manifest present), least-recently-hit first
        (a never-hit entry's recency is its write time)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for n in names:
            p = os.path.join(self.directory, n)
            if n == QUARANTINE_DIRNAME or ".tmp-" in n:
                continue
            if os.path.isfile(os.path.join(p, MANIFEST_NAME)):
                out.append(p)
        return sorted(out, key=lambda p: self._last_hit(p))

    @staticmethod
    def _mtime(path: str) -> float:
        try:
            return os.path.getmtime(path)
        except OSError:
            return 0.0

    def _last_hit(self, path: str) -> float:
        """Eviction recency: the ``LAST_HIT`` stamp a load touches, falling
        back to the entry's write time for entries never read back."""
        try:
            return os.path.getmtime(os.path.join(path, LAST_HIT_NAME))
        except OSError:
            return self._mtime(path)

    @staticmethod
    def _touch_last_hit(entry: str) -> None:
        """Stamp read recency after a validated load (best effort, no fsync:
        recency is advisory — losing a stamp to a crash just demotes the
        entry to write-time order, it can never corrupt the entry)."""
        try:
            with open(os.path.join(entry, LAST_HIT_NAME), "w") as f:
                f.write(f"{time.time():.3f}\n")
        except OSError:
            pass

    def _entry_fn(self, path: str) -> str:
        """The manifest's ``fn`` label (the per-function quota group);
        unreadable manifests group under ``"?"`` — they still count against
        SOME quota rather than escaping accounting."""
        try:
            with open(os.path.join(path, MANIFEST_NAME)) as f:
                fn = json.load(f).get("fn")
            return str(fn) if fn else "?"
        except (OSError, ValueError):
            return "?"

    @staticmethod
    def _dir_bytes(path: str) -> int:
        total = 0
        try:
            for n in os.listdir(path):
                try:
                    total += os.path.getsize(os.path.join(path, n))
                except OSError:
                    pass
        except OSError:
            pass
        return total

    def total_bytes(self) -> int:
        return sum(self._dir_bytes(p) for p in self.entries())

    # -- store ----------------------------------------------------------------
    def store(self, key: CacheKey, compiled: Any) -> StoreResult:
        """Serialize ``compiled`` (a ``jax.stages.Compiled``) and commit it
        under ``key`` with the staged-fsync-manifest-rename protocol."""
        t0 = time.monotonic()
        final_dir = self.entry_dir(key)
        # already-committed check BEFORE serialization: a fleet of replicas
        # missing simultaneously must not all pickle a large executable just
        # to discard it (the rename race still covers the true concurrent
        # window below)
        if os.path.isfile(os.path.join(final_dir, MANIFEST_NAME)):
            return StoreResult("raced", reason="already committed")
        try:
            from jax.experimental import serialize_executable as _se

            payload = pickle.dumps(_se.serialize(compiled), protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as exc:
            return StoreResult("error", reason=f"serialize: {type(exc).__name__}: {exc}")
        self._sweep_stale_staging()
        staging = f"{final_dir}.tmp-{os.getpid()}-{os.urandom(4).hex()}"
        try:
            os.makedirs(staging)
            payload_path = os.path.join(staging, PAYLOAD_NAME)
            with open(payload_path, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            # chaos fault point: a seeded ``kill -9`` lands HERE — payload on
            # disk, manifest not yet committed; the restart must see only
            # committed entries (resilience/chaos.py, one None-check disarmed)
            _chaos_inject("compile_cache_store")
            manifest = {
                "schema": SCHEMA_VERSION,
                "key": key.identity(),
                "fn": key.fn,
                "payload": {
                    "file": PAYLOAD_NAME,
                    "bytes": len(payload),
                    "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
                },
                "created_unix": round(time.time(), 3),
            }
            manifest_path = os.path.join(staging, MANIFEST_NAME)
            with open(manifest_path, "w") as f:
                json.dump(manifest, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            _fsync_path(staging)
            try:
                os.rename(staging, final_dir)  # first writer wins
            except OSError:
                # a concurrent writer committed first — discard our staging
                shutil.rmtree(staging, ignore_errors=True)
                return StoreResult(
                    "raced", reason="concurrent writer committed first",
                    nbytes=len(payload), seconds=round(time.monotonic() - t0, 6),
                )
            _fsync_path(self.directory)
        except OSError as exc:
            shutil.rmtree(staging, ignore_errors=True)
            return StoreResult("error", reason=f"io: {exc}")
        evicted = self.evict(protect=(final_dir,))
        return StoreResult(
            "stored", nbytes=len(payload),
            seconds=round(time.monotonic() - t0, 6), evicted=evicted,
        )

    def _sweep_stale_staging(self, max_age_s: float = STALE_STAGING_AGE_S) -> "list[str]":
        """Remove orphaned ``*.tmp-*`` staging dirs older than ``max_age_s``
        (a writer killed mid-store). Never touches young staging — it may
        belong to a live writer racing us."""
        swept = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return swept
        now = time.time()
        for n in names:
            if ".tmp-" not in n:
                continue
            p = os.path.join(self.directory, n)
            if now - self._mtime(p) >= max_age_s:
                shutil.rmtree(p, ignore_errors=True)
                swept.append(p)
        return swept

    # -- load -----------------------------------------------------------------
    def load(self, key: CacheKey) -> LoadResult:
        """Validate-then-deserialize the entry for ``key``.

        NEVER raises and never returns a wrong executable: any validation or
        deserialization failure quarantines the entry and reports
        ``corrupt`` so the caller compiles fresh."""
        t0 = time.monotonic()
        entry = self.entry_dir(key)
        manifest_path = os.path.join(entry, MANIFEST_NAME)
        try:
            f = open(manifest_path, "rb")
        except OSError:
            return LoadResult("miss", reason="no committed entry")
        try:
            # shared lock: eviction (LOCK_EX | LOCK_NB) skips entries a
            # reader currently holds — a load can never lose its payload
            try:
                fcntl.flock(f.fileno(), fcntl.LOCK_SH)
            except OSError:
                pass  # exotic fs without flock: proceed unlocked
            try:
                executable, nbytes = self._validate_and_load(key, entry, f)
            except CompileCacheCorrupt as exc:
                qpath = self._quarantine(entry, str(exc))
                return LoadResult(
                    "corrupt", reason=str(exc), quarantined_to=qpath,
                    seconds=round(time.monotonic() - t0, 6),
                )
            except Exception as exc:  # unpickle/deserialize blew up
                qpath = self._quarantine(entry, f"deserialize: {type(exc).__name__}")
                return LoadResult(
                    "corrupt",
                    reason=f"deserialize: {type(exc).__name__}: {exc}",
                    quarantined_to=qpath,
                    seconds=round(time.monotonic() - t0, 6),
                )
        finally:
            f.close()  # releases the flock
        self._touch_last_hit(entry)
        return LoadResult(
            "hit", executable=executable, nbytes=nbytes,
            seconds=round(time.monotonic() - t0, 6),
        )

    def _validate_and_load(self, key: CacheKey, entry: str, manifest_file) -> "tuple[Any, int]":
        try:
            manifest = json.load(manifest_file)
        except ValueError as exc:
            raise CompileCacheCorrupt(f"manifest unparseable: {exc}")
        if not isinstance(manifest, dict) or manifest.get("schema") != SCHEMA_VERSION:
            raise CompileCacheCorrupt(
                f"manifest schema {manifest.get('schema') if isinstance(manifest, dict) else '?'}"
                f" != {SCHEMA_VERSION}"
            )
        want = key.identity()
        got = manifest.get("key")
        if not isinstance(got, dict):
            raise CompileCacheCorrupt("manifest carries no key")
        for fname, wanted in want.items():
            if got.get(fname) != wanted:
                # a swapped/tampered manifest: version, topology and
                # fingerprint mismatches all land here (an honestly different
                # environment hashes to a different entry and misses instead)
                raise CompileCacheCorrupt(
                    f"key field {fname!r} mismatch: entry has {got.get(fname)!r}, "
                    f"this process needs {wanted!r}"
                )
        spec = manifest.get("payload") or {}
        payload_path = os.path.join(entry, str(spec.get("file") or PAYLOAD_NAME))
        try:
            size = os.path.getsize(payload_path)
        except OSError:
            raise CompileCacheCorrupt("payload file missing")
        if size != spec.get("bytes"):
            raise CompileCacheCorrupt(
                f"payload truncated: {size} bytes on disk, manifest says {spec.get('bytes')}"
            )
        if _file_crc32(payload_path) != spec.get("crc32"):
            raise CompileCacheCorrupt("payload CRC32 mismatch")
        with open(payload_path, "rb") as pf:
            blob = pf.read()
        from jax.experimental import serialize_executable as _se

        executable = _se.deserialize_and_load(*pickle.loads(blob))
        return executable, size

    def _quarantine(self, entry: str, reason: str) -> Optional[str]:
        """Move a failed entry aside so the NEXT restart misses cleanly
        instead of re-validating the same poison; keeps the evidence for the
        operator. Best-effort — an unmovable entry is deleted, and a failure
        to do even that still must not break the fallback compile."""
        qdir = self.quarantine_dir()
        dest = None
        try:
            os.makedirs(qdir, exist_ok=True)
            base = os.path.basename(entry)
            dest = os.path.join(qdir, f"{base}-{os.getpid()}-{os.urandom(3).hex()}")
            os.rename(entry, dest)
            with open(os.path.join(dest, "QUARANTINE_REASON"), "w") as f:
                f.write(reason + "\n")
        except OSError:
            try:
                shutil.rmtree(entry, ignore_errors=True)
            except OSError:
                pass
            dest = None
        logger.warning(
            f"compile cache entry {os.path.basename(entry)} failed validation "
            f"({reason}); quarantined{f' to {dest}' if dest else ''} — falling "
            "back to a fresh compile"
        )
        return dest

    # -- eviction -------------------------------------------------------------
    def evict(self, max_mb: Optional[float] = None, protect: "tuple[str, ...]" = ()) -> "list[str]":
        """Delete least-recently-HIT committed entries until every function's
        share fits the per-fn quota (``fn_quota_mb`` /
        ``ACCELERATE_COMPILE_CACHE_FN_QUOTA_MB``) and the whole directory
        fits ``max_mb`` (default: the instance/env cap). No cap and no quota
        → no-op. The quota pass runs FIRST, so under directory pressure the
        function that overfilled the cache sheds its own stale entries before
        the global pass can touch anyone else's. Entries in ``protect`` and
        entries another process holds a read lock on are skipped."""
        entries = self.entries()  # least-recently-hit first
        sizes = {p: self._dir_bytes(p) for p in entries}
        evicted: "list[str]" = []

        def drop(p: str) -> bool:
            if p in protect or not self._try_evict_one(p):
                return False  # protected, or a reader holds it open
            evicted.append(p)
            return True

        quota_mb = self._fn_quota_mb()
        # no group can exceed the quota when the WHOLE directory fits it —
        # skip the per-entry manifest parses (store() calls evict after every
        # commit; a fleet-shared directory should not pay them every time)
        if quota_mb is not None and sum(sizes.values()) > int(quota_mb * 1024 * 1024):
            quota_bytes = int(quota_mb * 1024 * 1024)
            groups: "dict[str, list[str]]" = {}
            for p in entries:
                groups.setdefault(self._entry_fn(p), []).append(p)
            for group in groups.values():
                total = sum(sizes[p] for p in group)
                for p in group:  # this fn's least-recently-hit first
                    if total <= quota_bytes:
                        break
                    if drop(p):
                        total -= sizes[p]
        cap_mb = max_mb if max_mb is not None else self._cap_mb()
        if cap_mb is None:
            return evicted
        cap_bytes = int(cap_mb * 1024 * 1024)
        remaining = [p for p in entries if p not in evicted]
        total = sum(sizes[p] for p in remaining)
        for p in remaining:
            if total <= cap_bytes:
                break
            if drop(p):
                total -= sizes[p]
        return evicted

    def _cap_mb(self) -> Optional[float]:
        if self.max_mb is not None:
            return self.max_mb
        from ..utils.environment import parse_optional_float_from_env

        from .runtime import CACHE_MAX_MB_ENV_VAR

        return parse_optional_float_from_env(CACHE_MAX_MB_ENV_VAR)

    def _fn_quota_mb(self) -> Optional[float]:
        if self.fn_quota_mb is not None:
            return self.fn_quota_mb
        from ..utils.environment import parse_optional_float_from_env

        from .runtime import CACHE_FN_QUOTA_MB_ENV_VAR

        return parse_optional_float_from_env(CACHE_FN_QUOTA_MB_ENV_VAR)

    def _try_evict_one(self, entry: str) -> bool:
        manifest_path = os.path.join(entry, MANIFEST_NAME)
        try:
            f = open(manifest_path, "rb")
        except OSError:
            return False
        try:
            try:
                fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                return False  # open for read somewhere — never delete it
            shutil.rmtree(entry, ignore_errors=True)
            return not os.path.exists(entry)
        finally:
            f.close()

    # -- introspection --------------------------------------------------------
    def stats(self) -> dict:
        entries = self.entries()
        qdir = self.quarantine_dir()
        try:
            quarantined = len(os.listdir(qdir))
        except OSError:
            quarantined = 0
        return {
            "dir": self.directory,
            "entries": len(entries),
            "bytes": sum(self._dir_bytes(p) for p in entries),
            "quarantined": quarantined,
        }
