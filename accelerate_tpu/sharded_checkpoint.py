"""Sharded (per-host) checkpoint I/O: save FSDP/TP-sharded state without any
host ever materializing the full model, and reload onto a different mesh.

TPU-native counterpart of the reference's distributed-checkpoint path
(``/root/reference/src/accelerate/utils/fsdp_utils.py`` — ``save_fsdp_model:103``
/ ``save_fsdp_optimizer:233`` via ``torch.distributed.checkpoint`` sharded
writers, and the offline consolidation tool ``merge_fsdp_weights:360-414``).

Design (no torch DCP, no tensorstore — raw chunk files + JSON indices, moved
by the native threaded IO engine ``native/io.py`` / ``native/src/io.cc`` with
per-chunk CRC32; ``ACCELERATE_TPU_CKPT_FORMAT=npz`` keeps the legacy npz
container, and npz shard sets remain loadable either way):

- **Save**: every process walks its *addressable* shards of each ``jax.Array``
  leaf and writes exactly the chunks whose ``replica_id == 0`` (each distinct
  region of the global array has exactly one replica-0 copy cluster-wide, so
  every byte is written once, by the host that already holds it in RAM). One
  ``{prefix}-shard-{proc:05d}.bin`` (raw aligned chunks; ``.npz`` under the
  legacy format) + ``.index.json`` per process; the index records each chunk's
  global start/stop coordinates, byte offset/length/CRC32 (bin format), the
  leaf's global shape, dtype, and PartitionSpec. Host memory high-water mark = one process's shard,
  never the full array — the property the reference gets from DCP's
  ``FileSystemWriter``.
- **Load**: read every index in the directory (shared-filesystem assumption,
  same as the reference's DCP dirs), then for each leaf build the target array
  with ``jax.make_array_from_callback`` against the *live* template's sharding:
  each device's callback assembles its region from whichever chunks intersect
  it. Because assembly is coordinate-based, the saving and loading meshes can
  factor the devices differently (fsdp=4 → fsdp=2×tp=2, np=2 → np=1, ...).
- **Consolidate**: offline merge of a shard set into one full (numpy) dict —
  drives ``accelerate merge-weights`` for sharded dirs (reference
  ``merge_fsdp_weights``).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from .logging import get_logger

logger = get_logger(__name__)

_SHARD_RE = re.compile(r"(?P<prefix>.+)-shard-(?P<proc>\d{5})\.index\.json")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed integrity validation (CRC mismatch, short
    read, torn container, unparseable index). Carries ``path`` naming the
    offending file so operators know exactly what to delete/restore instead
    of silently assembling garbage from a torn write."""

    def __init__(self, message: str, path: Optional[str] = None):
        super().__init__(message)
        self.path = path


class CheckpointTopologyError(RuntimeError):
    """A checkpoint was written under a different mesh topology than the one
    loading it, and the caller did not ask for an elastic re-shard. Carries
    ``saved``/``current`` axis→size maps so the message (and any tooling)
    names both shapes instead of letting the load die of a deep jax shape
    error."""

    def __init__(self, message: str, saved: Optional[dict] = None,
                 current: Optional[dict] = None):
        super().__init__(message)
        self.saved = saved
        self.current = current


def resize_padded_bucket(value: np.ndarray, target_len: int, key: str = "?") -> np.ndarray:
    """Re-pad a 1-D ZeRO-1 bucket for a different replicate width.

    Buckets are ``ceil(fill/N)*N`` long (``parallel/weight_update.py``): the
    first ``fill`` elements are real, the tail is zero padding whose optimizer
    moments stay zero for the whole run (padding grads are zero). Resizing to
    ``ceil(fill/M)*M`` is therefore: keep the common prefix, zero the new
    tail — and refuse loudly if truncation would drop a nonzero element
    (the leaf was NOT a padded bucket, and "re-sharding" it would corrupt
    state silently).
    """
    n = int(value.shape[0])
    target_len = int(target_len)
    if target_len == n:
        return value
    if target_len < n and np.any(value[target_len:]):
        raise ValueError(
            f"cannot elastically resize leaf {key!r} from {n} to {target_len}: "
            f"the would-be-dropped tail contains nonzero elements, so this is "
            "not ZeRO-1 bucket padding (topology change touched a non-bucket "
            "leaf)"
        )
    out = np.zeros((target_len,), dtype=value.dtype)
    out[: min(n, target_len)] = value[: min(n, target_len)]
    return out


def _ckpt_format() -> str:
    fmt = os.environ.get("ACCELERATE_TPU_CKPT_FORMAT", "bin").strip().lower()
    return fmt if fmt in ("bin", "npz") else "bin"


def _leaf_key(path) -> str:
    """'/'-joined pytree path — must match ``checkpointing.flatten_pytree``."""
    return (
        "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))) for p in path)
        or "_root"
    )


def _index_to_coords(index, shape) -> tuple[list[int], list[int]]:
    """Normalize a jax shard index (tuple of slices) to explicit start/stop lists."""
    start, stop = [], []
    for sl, dim in zip(index, shape):
        s = 0 if sl.start is None else int(sl.start)
        e = dim if sl.stop is None else int(sl.stop)
        start.append(s)
        stop.append(e)
    # 0-d arrays: index is (), shape is ()
    return start, stop


def _spec_to_json(sharding) -> Optional[list]:
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None

    def _axis(a):
        if a is None:
            return None
        if isinstance(a, (tuple, list)):
            return list(a)
        return str(a)

    return [_axis(a) for a in spec]


@dataclass
class ShardedTreeSnapshot:
    """Host-side capture of one process's replica-0 chunks of a pytree.

    The **snapshot** half of a sharded save: every array region this process
    must write is already copied to host numpy (``chunks``), with the
    coordinate/layout metadata (``leaves_meta``) the index file needs. After
    construction nothing references device memory — serialization can happen
    on another thread, arbitrarily later, against mutated live arrays.
    """

    process_index: int
    num_processes: int
    chunks: "dict[str, np.ndarray]" = field(default_factory=dict)
    leaves_meta: "dict[str, dict]" = field(default_factory=dict)
    mesh_shape: "Optional[dict[str, int]]" = None  # writing mesh's axis→size

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.chunks.values())


def snapshot_sharded_pytree(tree) -> ShardedTreeSnapshot:
    """Device→host capture of this process's replica-0 chunks (called on EVERY
    process). The fast phase of a sharded save: only the addressable shards
    this host already owns are copied — no collectives, no file IO.

    Non-``jax.Array`` leaves (host numpy/scalars, replicated by construction)
    are captured by process 0 only, as a single full chunk.
    """
    import jax

    proc = jax.process_index()
    nproc = jax.process_count()

    snap = ShardedTreeSnapshot(process_index=proc, num_processes=nproc)
    chunks = snap.chunks
    leaves_meta = snap.leaves_meta
    counter = 0

    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _leaf_key(path)
        if snap.mesh_shape is None and isinstance(leaf, jax.Array):
            # record the writing topology (cross-topology resume guard):
            # every NamedSharding leaf carries the mesh
            mesh = getattr(leaf.sharding, "mesh", None)
            if mesh is not None and hasattr(mesh, "shape"):
                try:
                    snap.mesh_shape = {str(k): int(v) for k, v in dict(mesh.shape).items()}
                except TypeError:
                    pass
        if (
            isinstance(leaf, jax.Array)
            and hasattr(leaf, "addressable_shards")
            and not (leaf.is_fully_addressable and proc != 0)
        ):
            # A fully-addressable leaf is HOST-LOCAL in a multi-process run:
            # every host's single-device shard is its own replica 0, so without
            # this gate all N processes would write the same coordinates and
            # load would silently keep whichever file sorts last. Process 0's
            # copy is canonical (the reference saves rank-0 state too); truly
            # global (non-addressable) leaves still dedup by replica_id below.
            meta = {
                "shape": list(leaf.shape),
                "dtype": str(np.dtype(leaf.dtype)) if leaf.dtype != jax.numpy.bfloat16 else "bfloat16",
                "spec": _spec_to_json(leaf.sharding),
                "chunks": [],
            }
            written_regions = set()
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                start, stop = _index_to_coords(shard.index, leaf.shape)
                region = (tuple(start), tuple(stop))
                if region in written_regions:
                    # two addressable devices can hold replica-0 of the same
                    # region only if the region itself is degenerate; be safe
                    continue
                written_regions.add(region)
                ckey = f"c{counter:07d}"
                counter += 1
                # explicit copy: on the CPU backend np.asarray can alias the
                # device buffer, and a donated buffer mutates under an async
                # writer — the snapshot must own its bytes
                data = np.array(shard.data, copy=True)
                if data.dtype.kind not in "fiub" or str(data.dtype) == "bfloat16":
                    data = data.astype(np.float32)
                chunks[ckey] = data
                meta["chunks"].append({"key": ckey, "start": start, "stop": stop})
            if meta["chunks"]:
                leaves_meta[key] = meta
            # else: replica-0 copies of every region live on other processes;
            # their indices will carry this leaf
        else:
            if proc == 0:
                arr = np.array(leaf, copy=True)
                ckey = f"c{counter:07d}"
                counter += 1
                if arr.dtype.kind not in "fiub" or str(arr.dtype) == "bfloat16":
                    arr = arr.astype(np.float32)
                chunks[ckey] = arr
                leaves_meta[key] = {
                    "shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                    "spec": None,
                    "chunks": [{"key": ckey, "start": [0] * arr.ndim, "stop": list(arr.shape)}],
                }
    return snap


def write_sharded_snapshot(
    snap: ShardedTreeSnapshot,
    directory: str,
    prefix: str = "model",
    heartbeat=None,
) -> "dict[str, dict]":
    """Serialize a :class:`ShardedTreeSnapshot` — the **write** half of a
    sharded save; pure file IO, safe on a background thread. Returns
    ``{filename: {"bytes": n, "crc32": c | None}}`` for the commit manifest.
    ``heartbeat`` (if given) is called once per file written so a watchdog can
    tell a hung filesystem from a large save.
    """
    os.makedirs(directory, exist_ok=True)
    proc = snap.process_index
    chunks = snap.chunks
    leaves_meta = snap.leaves_meta

    fmt = _ckpt_format()
    index_file = os.path.join(directory, f"{prefix}-shard-{proc:05d}.index.json")
    if fmt == "npz":
        shard_file = os.path.join(directory, f"{prefix}-shard-{proc:05d}.npz")
        np.savez(shard_file, **chunks)
    else:
        # raw chunk file written by the native threaded IO engine (per-chunk
        # CRC32 verified on load); chunk layout goes into the index
        from .native import io as native_io

        shard_file = os.path.join(directory, f"{prefix}-shard-{proc:05d}.bin")
        keys = list(chunks.keys())
        arrays = [chunks[k] for k in keys]
        offsets, sizes, crcs = native_io.write_chunks(shard_file, arrays)
        layout = {
            k: {"offset": o, "nbytes": s, "crc32": c,
                "dtype": str(a.dtype), "shape": list(a.shape)}
            for k, o, s, c, a in zip(keys, offsets, sizes, crcs, arrays)
        }
        for meta in leaves_meta.values():
            for chunk in meta["chunks"]:
                chunk.update(layout[chunk["key"]])
    if heartbeat is not None:
        heartbeat(os.path.basename(shard_file))
    with open(index_file, "w") as f:
        json.dump(
            {
                "process_index": proc,
                "num_processes": snap.num_processes,
                "mesh": snap.mesh_shape,
                "leaves": leaves_meta,
            },
            f,
        )
    if heartbeat is not None:
        heartbeat(os.path.basename(index_file))
    logger.info(f"wrote {len(chunks)} chunks to {shard_file}")
    return {
        os.path.basename(shard_file): {"bytes": os.path.getsize(shard_file)},
        os.path.basename(index_file): {"bytes": os.path.getsize(index_file)},
    }


def save_sharded_pytree(tree, directory: str, prefix: str = "model") -> str:
    """Write this process's chunks of ``tree`` (called on EVERY process):
    :func:`snapshot_sharded_pytree` + :func:`write_sharded_snapshot` run
    back-to-back on the caller thread."""
    snap = snapshot_sharded_pytree(tree)
    written = write_sharded_snapshot(snap, directory, prefix=prefix)
    shard = next(n for n in written if not n.endswith(".index.json"))
    return os.path.join(directory, shard)


def read_saved_mesh(directory: str, prefix: str = "model") -> "Optional[dict[str, int]]":
    """The mesh axis→size map recorded in a shard set's indices (first one
    found), or None for pre-topology-record checkpoints."""
    if not os.path.isdir(directory):
        return None
    for name in sorted(os.listdir(directory)):
        m = _SHARD_RE.fullmatch(name)
        if not m or m.group("prefix") != prefix:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                mesh = json.load(f).get("mesh")
        except (OSError, ValueError):
            continue
        if mesh:
            return {str(k): int(v) for k, v in mesh.items()}
    return None


def is_sharded_checkpoint(directory: str, prefix: str = "model") -> bool:
    return os.path.isdir(directory) and any(
        m and m.group("prefix") == prefix
        for m in (_SHARD_RE.fullmatch(name) for name in os.listdir(directory))
    )


def _read_indices(directory: str, prefix: str) -> dict[str, dict]:
    """Merge all per-process indices → leafkey → {shape,dtype,chunks:[...+file]}."""
    merged: dict[str, dict] = {}
    found = False
    for name in sorted(os.listdir(directory)):
        m = _SHARD_RE.fullmatch(name)
        if not m or m.group("prefix") != prefix:
            continue
        found = True
        try:
            with open(os.path.join(directory, name)) as f:
                index = json.load(f)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise CheckpointCorruptError(
                f"unparseable shard index {os.path.join(directory, name)}: {e} "
                "(torn write? delete this checkpoint and resume from an older one)",
                path=os.path.join(directory, name),
            ) from e
        stem = os.path.join(directory, name[: -len(".index.json")])
        for key, meta in index["leaves"].items():
            entry = merged.setdefault(
                key, {"shape": meta["shape"], "dtype": meta["dtype"], "spec": meta.get("spec"), "chunks": []}
            )
            if entry["shape"] != meta["shape"]:
                raise ValueError(
                    f"inconsistent shapes for {key!r} across shard indices: "
                    f"{entry['shape']} vs {meta['shape']}"
                )
            for chunk in meta["chunks"]:
                # container chosen PER CHUNK: a byte offset marks the raw .bin
                # format; anything else is a legacy npz entry. (A directory can
                # legitimately hold a stale file of the other format — routing
                # by which file exists would misread a valid checkpoint.)
                container = stem + (".bin" if "offset" in chunk else ".npz")
                entry["chunks"].append({**chunk, "file": container})
    if not found:
        raise FileNotFoundError(f"no '{prefix}-shard-*.index.json' under {directory}")
    return merged


class _ChunkReader:
    """Reads chunk arrays on demand: raw .bin chunks go through the native IO
    engine (CRC-verified); legacy npz containers stay supported.

    ``read_many`` batches a request set into ONE threaded ``read_chunks`` call
    per file — no open+pread per chunk — and caches decoded arrays so a chunk
    intersecting several device regions is read and CRC-checked once. Only
    REQUESTED chunks are ever read (a resharding load that needs one slice of
    a multi-GB shard file must not pull the whole file into host RAM).
    ``close()`` frees the cache.
    """

    def __init__(self, merged: Optional[dict] = None):
        self._open: dict[str, Any] = {}
        self._bin_cache: dict[tuple[str, int], np.ndarray] = {}

    def read_many(self, chunks: list[dict]) -> None:
        """Warm the cache for a request set, one batched IO call per file."""
        from .native import io as native_io

        by_file: dict[str, list[dict]] = {}
        for c in chunks:
            if "offset" in c and (c["file"], c["offset"]) not in self._bin_cache:
                by_file.setdefault(c["file"], []).append(c)
        for file, want in by_file.items():
            seen: set[int] = set()
            want = [c for c in want if not (c["offset"] in seen or seen.add(c["offset"]))]
            try:
                bufs = native_io.read_chunks(
                    file,
                    [c["offset"] for c in want],
                    [c["nbytes"] for c in want],
                    [c["crc32"] for c in want] if all("crc32" in c for c in want) else None,
                )
            except (ValueError, IOError) as e:
                # CRC mismatch or short read: a torn/corrupt chunk container.
                # Name the file so the operator knows what to discard.
                raise CheckpointCorruptError(
                    f"corrupt checkpoint chunk file {file}: {e}", path=file
                ) from e
            for c, buf in zip(want, bufs):
                self._bin_cache[(file, c["offset"])] = np.frombuffer(
                    buf, dtype=np.dtype(c["dtype"])
                ).reshape(c["shape"])

    def read(self, chunk: dict) -> np.ndarray:
        file = chunk["file"]
        if "offset" in chunk:
            key = (file, chunk["offset"])
            if key not in self._bin_cache:
                self.read_many([chunk])
            return self._bin_cache[key]
        if file not in self._open:
            try:
                self._open[file] = np.load(file, allow_pickle=False)
            except Exception as e:  # torn zip container
                raise CheckpointCorruptError(
                    f"corrupt checkpoint shard file {file}: {e}", path=file
                ) from e
        return self._open[file][chunk["key"]]

    def close(self):
        for handle in self._open.values():
            handle.close()
        self._open.clear()
        self._bin_cache.clear()


def _assemble_region(meta: dict, start: list[int], stop: list[int], reader: _ChunkReader,
                     dtype) -> np.ndarray:
    """Assemble global region [start, stop) of a leaf from intersecting chunks."""
    out_shape = [e - s for s, e in zip(start, stop)]
    out = np.empty(out_shape, dtype=dtype)
    filled = 0

    def _intersection(chunk):
        i_start = [max(a, b) for a, b in zip(start, chunk["start"])]
        i_stop = [min(a, b) for a, b in zip(stop, chunk["stop"])]
        if any(a >= b for a, b in zip(i_start, i_stop)):
            return None
        return i_start, i_stop

    hits = [(c, inter) for c in meta["chunks"] if (inter := _intersection(c))]
    reader.read_many([c for c, _ in hits])  # one threaded IO call per file
    for chunk, (inter_start, inter_stop) in hits:
        c_start = chunk["start"]
        data = reader.read(chunk)
        src = tuple(
            slice(a - cs, b - cs) for a, b, cs in zip(inter_start, inter_stop, c_start)
        )
        dst = tuple(slice(a - s, b - s) for a, b, s in zip(inter_start, inter_stop, start))
        out[dst] = data[src]
        filled += int(np.prod([b - a for a, b in zip(inter_start, inter_stop)]))
    expected = int(np.prod(out_shape)) if out_shape else 1
    if not meta["chunks"] and expected == 0:
        return out
    if filled != expected:
        kind = "incomplete (gap)" if filled < expected else (
            "over-covered (overlapping chunks — stale shard files from a "
            "previous save with a different process count/mesh in this dir?)"
        )
        raise ValueError(
            f"sharded checkpoint {kind}: region {start}..{stop} has "
            f"{filled}/{expected} elements covered"
        )
    return out


def load_sharded_pytree(template, directory: str, prefix: str = "model", plan=None,
                        elastic: bool = False):
    """Restore a sharded checkpoint into the structure/shardings of ``template``.

    ``template`` leaves that are ``jax.Array`` are rebuilt with
    ``jax.make_array_from_callback`` against their live sharding — each device
    pulls only its own region, so resharding to a different mesh factorization
    is just different callback indices. Non-array leaves are read whole.

    ``plan`` (a ``parallel.sharding.ShardingPlan``) lets ``jax.ShapeDtypeStruct``
    template leaves restore WITHOUT live arrays: their target sharding is
    rebuilt from the PartitionSpec recorded in the shard index via
    ``plan.sharding_from_saved_spec`` — the resume-onto-a-fresh-mesh path,
    where only shapes (not placed buffers) exist before the load.

    ``elastic=True`` additionally re-pads 1-D leaves whose saved length
    differs from the template's: ZeRO-1 buckets are padded to a multiple of
    the replicate width, so a dp=N→dp=M resume changes their global length
    (see :func:`resize_padded_bucket` — truncation that would drop nonzero
    data still raises).
    """
    import jax

    merged = _read_indices(directory, prefix)
    reader = _ChunkReader()

    def _restore(path, leaf):
        key = _leaf_key(path)
        if key not in merged:
            raise KeyError(f"sharded checkpoint missing leaf {key!r}")
        meta = merged[key]
        is_live = isinstance(leaf, jax.Array)
        is_spec_leaf = (
            not is_live
            and plan is not None
            and not isinstance(leaf, np.ndarray)
            and hasattr(leaf, "shape")
            and hasattr(leaf, "dtype")
        )
        if is_live or is_spec_leaf:
            np_dtype = np.float32 if meta["dtype"] == "bfloat16" else np.dtype(meta["dtype"])
            sharding = (
                leaf.sharding if is_live else plan.sharding_from_saved_spec(
                    meta.get("spec"), drop_unknown_axes=elastic
                )
            )
            if list(leaf.shape) != list(meta["shape"]):
                if not (elastic and len(meta["shape"]) == 1
                        and getattr(leaf, "ndim", None) == 1):
                    raise ValueError(
                        f"shape mismatch for {key!r}: live {leaf.shape} vs saved "
                        f"{meta['shape']}"
                        + (
                            "" if elastic else
                            " (a topology change? elastic resume re-pads 1-D "
                            "ZeRO-1 buckets — see docs/resilience.md)"
                        )
                    )
                full = _assemble_region(
                    meta, [0], list(meta["shape"]), reader, np_dtype
                )
                data = resize_padded_bucket(full, int(leaf.shape[0]), key)
                return jax.device_put(data.astype(leaf.dtype), sharding)

            def cb(index, _meta=meta, _dtype=np_dtype, _shape=tuple(leaf.shape)):
                start, stop = _index_to_coords(index, _shape)
                return _assemble_region(_meta, start, stop, reader, _dtype)

            arr = jax.make_array_from_callback(tuple(leaf.shape), sharding, cb)
            if arr.dtype != leaf.dtype:
                arr = jax.device_put(arr.astype(leaf.dtype), sharding)
            return arr
        start = [0] * len(meta["shape"])
        value = _assemble_region(meta, start, list(meta["shape"]), reader,
                                 np.dtype(meta["dtype"]) if meta["dtype"] != "bfloat16" else np.float32)
        return np.asarray(value, dtype=getattr(leaf, "dtype", None))

    try:
        return jax.tree_util.tree_map_with_path(_restore, template)
    finally:
        reader.close()


def consolidate_sharded(directory: str, prefix: str = "model") -> dict[str, np.ndarray]:
    """Offline merge: full numpy dict keyed by '/'-joined leaf paths (the
    counterpart of the reference's ``merge_fsdp_weights`` offline tool)."""
    merged = _read_indices(directory, prefix)
    reader = _ChunkReader()
    try:
        out = {}
        for key, meta in merged.items():
            dtype = np.float32 if meta["dtype"] == "bfloat16" else np.dtype(meta["dtype"])
            out[key] = _assemble_region(meta, [0] * len(meta["shape"]), meta["shape"], reader, dtype)
        return out
    finally:
        reader.close()


def merge_sharded_checkpoint(directory: str, output_path: str, prefix: str = "model",
                             safe_serialization: bool = True) -> str:
    """Consolidate a shard set into one file (safetensors or npz)."""
    flat = consolidate_sharded(directory, prefix)
    if safe_serialization and not output_path.endswith(".npz"):
        from safetensors.numpy import save_file

        if not output_path.endswith(".safetensors"):
            output_path = output_path + ".safetensors"
        save_file(flat, output_path)
    else:
        if not output_path.endswith(".npz"):
            output_path = output_path + ".npz"
        np.savez(output_path, **flat)
    logger.info(f"consolidated {len(flat)} leaves → {output_path}")
    return output_path


# ---------------------------------------------------------------------------
# reference utils/fsdp_utils.py spellings: the DCP-style sharded save/load
# entry points, mapped onto the native per-host shard format


def _fsdp_prefix(base: str, index: int) -> str:
    return base if index == 0 else f"{base}_{index}"


def save_fsdp_model(fsdp_plugin, accelerator, model, output_dir: str, model_index: int = 0,
                    adapter_only: bool = False) -> str:
    """reference ``save_fsdp_model utils/fsdp_utils.py:103``: sharded save of a
    (possibly multi-host-sharded) param pytree; no host materializes the full
    state. ``fsdp_plugin`` is accepted for signature parity — sharding layout
    comes from the arrays themselves under GSPMD."""
    return save_sharded_pytree(model, output_dir, prefix=_fsdp_prefix("model", model_index))


def load_fsdp_model(fsdp_plugin, accelerator, model, input_dir: str, model_index: int = 0,
                    adapter_only: bool = False):
    """reference ``load_fsdp_model``: reload onto the live tree's shardings
    (works across a different mesh factorization — resharding reads only the
    needed chunk regions)."""
    return load_sharded_pytree(model, input_dir, prefix=_fsdp_prefix("model", model_index))


def save_fsdp_optimizer(fsdp_plugin, accelerator, optimizer, model, output_dir: str,
                        optimizer_index: int = 0) -> str:
    """reference ``save_fsdp_optimizer utils/fsdp_utils.py:233``."""
    opt_state = getattr(optimizer, "opt_state", optimizer)
    return save_sharded_pytree(
        opt_state, output_dir, prefix=_fsdp_prefix("optimizer", optimizer_index)
    )


def load_fsdp_optimizer(fsdp_plugin, accelerator, optimizer, model, input_dir: str,
                        optimizer_index: int = 0, adapter_only: bool = False):
    """reference ``load_fsdp_optimizer``: restores into the wrapper's live
    ``opt_state`` (and returns it)."""
    template = getattr(optimizer, "opt_state", optimizer)
    state = load_sharded_pytree(
        template, input_dir, prefix=_fsdp_prefix("optimizer", optimizer_index)
    )
    if hasattr(optimizer, "opt_state"):
        optimizer.opt_state = state
    return state
