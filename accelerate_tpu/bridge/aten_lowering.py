"""torch.export (ATen graph) → JAX lowering: the decoder-capable bridge path.

``fx_lowering`` interprets a *symbolic* fx trace — shape-agnostic and fast, but
it depends on ``transformers.utils.fx``, whose supported-model list no longer
includes decoder families (GPT-2, Llama) after the 4.5x attention/masking
refactor (vmap-based ``create_causal_mask`` and proxy-hostile shape unpacking).

``torch.export`` sidesteps all of that: it runs the real model once with
example inputs, specializing python control flow, and emits a closed graph of
ATen ops with params/buffers lifted to placeholders. Interpreting THAT graph
needs a finite handler table (torch.export's IR is pre-dispatch ATen — the
high-level ops like ``aten.linear``/``aten.scaled_dot_product_attention``/
``aten.layer_norm`` survive, so handlers stay readable) and works for any
exportable model — the GPT-2/Llama route the round-2 verdict asked for.

Reference contract: same as fx_lowering — ``prepare_model accelerator.py:1735``
driving unmodified torch training scripts; plus big-model decoder inference
(``/root/reference/benchmarks/big_model_inference``).

Trade-off vs fx_lowering: shapes are baked at export time, so ``fn`` must be
called with the example shapes (pad batches to fixed shape — standard TPU
practice anyway).
"""

from __future__ import annotations

import math
import operator
from typing import Any, Callable, Optional

from .fx_lowering import (
    LoweringError,
    _Ctx,
    _cross_entropy,
    _scaled_dot_product_attention,
    _to_jnp_dtype,
    _traceable_masking,
)

__all__ = ["lower_module_aten"]


def _aten_handlers() -> dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    H: dict[str, Callable] = {}

    def reg(names, fn):
        for n in names if isinstance(names, (list, tuple)) else [names]:
            H[n] = fn
        return fn

    # -- structural / no-ops --------------------------------------------------
    ident = lambda ctx, x, *a, **k: x
    reg(
        ["aten.alias.default", "aten.contiguous.default", "aten.clone.default",
         "aten.detach.default", "aten.lift_fresh_copy.default",
         "aten._assert_tensor_metadata.default", "aten.positive.default"],
        ident,
    )
    reg("<built-in function getitem>", lambda ctx, seq, idx: seq[idx])

    def _view(ctx, x, shape):
        return jnp.reshape(x, [int(s) for s in shape])

    reg(["aten.view.default", "aten.reshape.default", "aten._unsafe_view.default"], _view)
    reg("aten.flatten.using_ints", lambda ctx, x, start=0, end=-1: _flatten(x, start, end))
    reg("aten.transpose.int", lambda ctx, x, d0, d1: jnp.swapaxes(x, d0, d1))
    reg("aten.t.default", lambda ctx, x: x.T)
    reg("aten.permute.default", lambda ctx, x, dims: jnp.transpose(x, dims))
    reg("aten.unsqueeze.default", lambda ctx, x, dim: jnp.expand_dims(x, dim))
    reg("aten.squeeze.dim", lambda ctx, x, dim: jnp.squeeze(x, dim))
    reg("aten.squeeze.default", lambda ctx, x: jnp.squeeze(x))

    def _expand(ctx, x, sizes, implicit=False):
        # -1 keeps the existing size; dims align from the right (torch expand)
        out_ndim = len(sizes)
        xs = (1,) * (out_ndim - x.ndim) + tuple(x.shape)
        full = [xs[i] if int(s) == -1 else int(s) for i, s in enumerate(sizes)]
        return jnp.broadcast_to(jnp.reshape(x, xs), full)

    reg("aten.expand.default", _expand)

    def _slice(ctx, x, dim=0, start=None, end=None, step=1):
        idx = [slice(None)] * x.ndim
        end = None if end is not None and end >= 2**62 else end
        idx[dim] = slice(start, end, step)
        return x[tuple(idx)]

    reg("aten.slice.Tensor", _slice)

    def _select(ctx, x, dim, index):
        idx = [slice(None)] * x.ndim
        idx[dim] = index
        return x[tuple(idx)]

    reg("aten.select.int", _select)
    reg("aten.index.Tensor", lambda ctx, x, indices: x[tuple(
        (slice(None) if i is None else i) for i in indices)])
    reg("aten.cat.default", lambda ctx, xs, dim=0: jnp.concatenate(xs, axis=dim))
    reg("aten.stack.default", lambda ctx, xs, dim=0: jnp.stack(xs, axis=dim))

    def _split(ctx, x, size, dim=0):
        n = x.shape[dim]
        if isinstance(size, int):
            cuts = list(range(size, n, size))
        else:
            cuts, acc = [], 0
            for s in size[:-1]:
                acc += s
                cuts.append(acc)
        return tuple(jnp.split(x, cuts, axis=dim))

    reg(["aten.split.Tensor", "aten.split_with_sizes.default"], _split)
    reg("aten.chunk.default", lambda ctx, x, chunks, dim=0: tuple(
        jnp.array_split(x, chunks, axis=dim)))

    def _pad(ctx, x, pad, mode="constant", value=None):
        if mode != "constant":
            raise LoweringError(f"aten.pad mode={mode!r} not supported (constant only)")
        # torch pad: last-dim-first pairs
        cfg = [(0, 0)] * x.ndim
        for i in range(len(pad) // 2):
            cfg[x.ndim - 1 - i] = (int(pad[2 * i]), int(pad[2 * i + 1]))
        return jnp.pad(x, cfg, constant_values=value or 0)

    reg(["aten.pad.default", "aten.constant_pad_nd.default"], _pad)

    # -- elementwise -----------------------------------------------------------
    def binop(fn):
        def h(ctx, a, b, *, alpha=None, **kw):
            if alpha is not None and alpha != 1:
                b = b * alpha
            return fn(a, b)

        return h

    reg(["aten.add.Tensor", "aten.add.Scalar"], binop(lambda a, b: a + b))
    reg(["aten.sub.Tensor", "aten.sub.Scalar"], binop(lambda a, b: a - b))

    def _rsub(ctx, a, b, *, alpha=None, **kw):
        # torch: other - alpha * input (alpha scales INPUT, unlike add/sub)
        if alpha is not None and alpha != 1:
            a = a * alpha
        return b - a

    reg(["aten.rsub.Scalar", "aten.rsub.Tensor"], _rsub)
    reg(["aten.mul.Tensor", "aten.mul.Scalar"], binop(lambda a, b: a * b))
    reg(["aten.div.Tensor", "aten.div.Scalar"], binop(lambda a, b: a / b))
    reg("aten.floor_divide.default", binop(lambda a, b: a // b))
    reg(["aten.pow.Tensor_Scalar", "aten.pow.Tensor_Tensor"], binop(lambda a, b: a**b))
    reg(["aten.remainder.Scalar", "aten.remainder.Tensor"], binop(lambda a, b: a % b))
    for name, fn in {
        "neg": jnp.negative, "abs": jnp.abs, "exp": jnp.exp, "log": jnp.log,
        "sqrt": jnp.sqrt, "rsqrt": jax.lax.rsqrt, "tanh": jnp.tanh,
        "sigmoid": jax.nn.sigmoid, "silu": jax.nn.silu, "relu": jax.nn.relu,
        "erf": jax.scipy.special.erf, "sin": jnp.sin, "cos": jnp.cos,
        "bitwise_not": jnp.invert,  # ~x: bitwise for ints, logical for bools
        "logical_not": jnp.logical_not,
        "floor": jnp.floor, "ceil": jnp.ceil, "round": jnp.round,
        "reciprocal": jnp.reciprocal, "sign": jnp.sign, "isnan": jnp.isnan,
        "isinf": jnp.isinf,
    }.items():
        reg(f"aten.{name}.default", (lambda f: lambda ctx, x, *a, **k: f(x))(fn))

    def _gelu(ctx, x, approximate="none"):
        return jax.nn.gelu(x, approximate=approximate == "tanh")

    reg("aten.gelu.default", _gelu)
    reg("aten.clamp.default", lambda ctx, x, lo=None, hi=None: jnp.clip(x, lo, hi))
    reg(["aten.clamp_min.default"], lambda ctx, x, lo: jnp.maximum(x, lo))
    reg(["aten.clamp_max.default"], lambda ctx, x, hi: jnp.minimum(x, hi))
    for name, fn in {"eq": jnp.equal, "ne": jnp.not_equal, "lt": jnp.less,
                     "gt": jnp.greater, "le": jnp.less_equal, "ge": jnp.greater_equal}.items():
        reg([f"aten.{name}.Tensor", f"aten.{name}.Scalar"],
            (lambda f: lambda ctx, a, b: f(a, b))(fn))
    reg(["aten.bitwise_and.Tensor", "aten.logical_and.default"],
        lambda ctx, a, b: jnp.logical_and(a, b))
    reg(["aten.bitwise_or.Tensor", "aten.logical_or.default"],
        lambda ctx, a, b: jnp.logical_or(a, b))
    reg("aten.where.self", lambda ctx, c, a, b: jnp.where(c, a, b))
    reg(["aten.masked_fill.Scalar", "aten.masked_fill.Tensor"],
        lambda ctx, x, mask, value: jnp.where(mask, value, x))
    reg("aten.tril.default", lambda ctx, x, diagonal=0: jnp.tril(x, k=diagonal))
    reg("aten.triu.default", lambda ctx, x, diagonal=0: jnp.triu(x, k=diagonal))
    reg("aten.cumsum.default", lambda ctx, x, dim, dtype=None: jnp.cumsum(
        x, axis=dim, dtype=_to_jnp_dtype(dtype) if dtype is not None else None))

    # -- reductions -------------------------------------------------------------
    def _mean(ctx, x, dim=None, keepdim=False, dtype=None):
        return jnp.mean(x, axis=_dims(dim), keepdims=keepdim,
                        dtype=_to_jnp_dtype(dtype) if dtype is not None else None)

    reg(["aten.mean.default", "aten.mean.dim"], _mean)

    def _sum(ctx, x, dim=None, keepdim=False, dtype=None):
        return jnp.sum(x, axis=_dims(dim), keepdims=keepdim,
                       dtype=_to_jnp_dtype(dtype) if dtype is not None else None)

    reg(["aten.sum.default", "aten.sum.dim_IntList"], _sum)
    reg("aten.amax.default", lambda ctx, x, dim=None, keepdim=False: jnp.max(
        x, axis=_dims(dim), keepdims=keepdim))
    reg("aten.amin.default", lambda ctx, x, dim=None, keepdim=False: jnp.min(
        x, axis=_dims(dim), keepdims=keepdim))
    reg("aten.argmax.default", lambda ctx, x, dim=None, keepdim=False: jnp.argmax(
        x, axis=dim, keepdims=keepdim))
    reg("aten.max.dim", lambda ctx, x, dim, keepdim=False: (
        jnp.max(x, axis=dim, keepdims=keepdim), jnp.argmax(x, axis=dim, keepdims=keepdim)))
    reg("aten.min.dim", lambda ctx, x, dim, keepdim=False: (
        jnp.min(x, axis=dim, keepdims=keepdim), jnp.argmin(x, axis=dim, keepdims=keepdim)))
    # elementwise two-operand min/max (torch.min(a, b) — T5's relative-position
    # bucketing uses this) + full reductions
    reg(["aten.minimum.default", "aten.min.other"], lambda ctx, a, b: jnp.minimum(a, b))
    reg(["aten.maximum.default", "aten.max.other"], lambda ctx, a, b: jnp.maximum(a, b))
    reg("aten.max.default", lambda ctx, x: jnp.max(x))
    reg("aten.min.default", lambda ctx, x: jnp.min(x))
    reg("aten.var.correction", lambda ctx, x, dim=None, *, correction=1, keepdim=False:
        jnp.var(x, axis=_dims(dim), ddof=int(correction), keepdims=keepdim))

    # -- matmuls ---------------------------------------------------------------
    reg(["aten.mm.default", "aten.bmm.default", "aten.matmul.default"],
        lambda ctx, a, b: jnp.matmul(a, b))
    reg("aten.addmm.default", lambda ctx, bias, a, b, *, beta=1, alpha=1:
        beta * bias + alpha * (a @ b))
    reg("aten.linear.default", lambda ctx, x, w, b=None:
        (x @ w.T + b) if b is not None else x @ w.T)
    reg("aten.einsum.default", lambda ctx, eq, operands, path=None: jnp.einsum(eq, *operands))
    reg("aten.baddbmm.default", lambda ctx, inp, a, b, *, beta=1, alpha=1:
        beta * inp + alpha * jnp.matmul(a, b))

    # -- nn ops ------------------------------------------------------------------
    def _embedding(ctx, weight, ids, padding_idx=-1, scale_grad=False, sparse=False):
        return weight[ids]

    reg("aten.embedding.default", _embedding)

    def _layer_norm(ctx, x, shape, weight=None, bias=None, eps=1e-5, *a):
        axes = tuple(range(x.ndim - len(shape), x.ndim))
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        out = ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
        if weight is not None:
            out = out * weight
        if bias is not None:
            out = out + bias
        return out

    reg("aten.layer_norm.default", _layer_norm)

    def _rms_norm(ctx, x, shape, weight=None, eps=None):
        axes = tuple(range(x.ndim - len(shape), x.ndim))
        xf = x.astype(jnp.float32)
        eps = 1e-6 if eps is None else eps
        out = (xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=axes, keepdims=True) + eps)).astype(x.dtype)
        return out * weight if weight is not None else out

    reg("aten.rms_norm.default", _rms_norm)

    def _dropout(ctx, x, p=0.5, train=False):
        if ctx.train and p:
            return ctx.dropout(x, p)
        return x

    reg("aten.dropout.default", _dropout)

    # returns (output, keep_mask) — consumers read it via getitem; the RNG
    # stream is ctx.dropout's so aten.dropout and native_dropout stay in sync
    reg("aten.native_dropout.default", lambda ctx, x, p=0.5, train=False: ctx.dropout(
        x, p if ctx.train else 0.0, return_mask=True))
    reg("aten.softmax.int", lambda ctx, x, dim=-1, dtype=None: jax.nn.softmax(
        x.astype(_to_jnp_dtype(dtype)) if dtype is not None else x, axis=dim))
    reg("aten._softmax.default", lambda ctx, x, dim, half_to_float: jax.nn.softmax(x, axis=dim))
    reg("aten.log_softmax.int", lambda ctx, x, dim=-1, dtype=None: jax.nn.log_softmax(
        x.astype(_to_jnp_dtype(dtype)) if dtype is not None else x, axis=dim))

    def _sdpa(ctx, q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
              scale=None, enable_gqa=False):
        return _scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=dropout_p, is_causal=is_causal,
            scale=scale, enable_gqa=enable_gqa, ctx=ctx,
        )

    reg("aten.scaled_dot_product_attention.default", _sdpa)

    _CE_RED = {0: "none", 1: "mean", 2: "sum"}

    def _ce(ctx, logits, labels, weight=None, reduction=1, ignore_index=-100,
            label_smoothing=0.0):
        if weight is not None or label_smoothing:
            raise LoweringError("cross_entropy with class weights/smoothing not lowered")
        red = _CE_RED.get(reduction, reduction) if isinstance(reduction, int) else reduction
        return _cross_entropy(logits, labels, ignore_index=ignore_index, reduction=red)

    reg("aten.cross_entropy_loss.default", _ce)

    def _reduce_loss(err, reduction, out_dtype):
        # torch reduction codes: 0=none, 1=mean, 2=sum. Scalars stay f32;
        # 'none' keeps the input dtype (torch parity)
        if reduction in (1, "mean"):
            return jnp.mean(err)
        if reduction in (2, "sum"):
            return jnp.sum(err)
        if reduction in (0, "none"):
            return err.astype(out_dtype)
        raise LoweringError(f"unknown loss reduction {reduction!r}")

    def _elementwise_loss(op):
        def handler(ctx, pred, target, reduction=1):
            err = op(pred.astype(jnp.float32), target.astype(jnp.float32))
            return _reduce_loss(err, reduction, pred.dtype)

        return handler

    _l1 = _elementwise_loss(lambda p, t: jnp.abs(p - t))
    reg("aten.mse_loss.default", _elementwise_loss(lambda p, t: (p - t) ** 2))
    reg("aten.l1_loss.default", _l1)

    def _smooth_l1(ctx, pred, target, reduction=1, beta=1.0):
        if beta == 0:  # torch: beta=0 IS l1 (and /beta would NaN the grads)
            return _l1(ctx, pred, target, reduction)
        d = pred.astype(jnp.float32) - target.astype(jnp.float32)
        err = jnp.where(
            jnp.abs(d) < beta, 0.5 * d * d / beta, jnp.abs(d) - 0.5 * beta
        )
        return _reduce_loss(err, reduction, pred.dtype)

    reg("aten.smooth_l1_loss.default", _smooth_l1)

    # -- factories / dtype --------------------------------------------------------
    def _factory_kw(kw):
        dtype = kw.get("dtype")
        return {"dtype": _to_jnp_dtype(dtype) if dtype is not None else None}

    reg("aten.arange.default", lambda ctx, end, **kw: jnp.arange(end, **_factory_kw(kw)))
    reg("aten.arange.start", lambda ctx, start, end, **kw: jnp.arange(
        start, end, **_factory_kw(kw)))
    reg("aten.arange.start_step", lambda ctx, start, end, step, **kw: jnp.arange(
        start, end, step, **_factory_kw(kw)))
    reg("aten.full.default", lambda ctx, size, value, **kw: jnp.full(
        [int(s) for s in size], value, **_factory_kw(kw)))
    def _like_dtype(x, kw):
        dtype = kw.get("dtype")
        return _to_jnp_dtype(dtype) if dtype is not None else x.dtype

    reg("aten.full_like.default", lambda ctx, x, value, **kw: jnp.full_like(
        x, value, dtype=_like_dtype(x, kw)))
    reg("aten.zeros.default", lambda ctx, size, **kw: jnp.zeros(
        [int(s) for s in size], **_factory_kw(kw)))
    reg("aten.ones.default", lambda ctx, size, **kw: jnp.ones(
        [int(s) for s in size], **_factory_kw(kw)))
    reg("aten.zeros_like.default", lambda ctx, x, **kw: jnp.zeros_like(
        x, dtype=_like_dtype(x, kw)))
    reg("aten.ones_like.default", lambda ctx, x, **kw: jnp.ones_like(
        x, dtype=_like_dtype(x, kw)))
    reg("aten.empty_like.default", lambda ctx, x, **kw: jnp.zeros_like(
        x, dtype=_like_dtype(x, kw)))
    reg("aten.scalar_tensor.default", lambda ctx, v, **kw: jnp.asarray(v, **_factory_kw(kw)))
    # x.new_zeros(size) family: fresh tensor of given size, inheriting x's
    # dtype unless overridden
    reg("aten.new_zeros.default", lambda ctx, x, size, **kw: jnp.zeros(
        [int(s) for s in size], dtype=_like_dtype(x, kw)))
    reg("aten.new_ones.default", lambda ctx, x, size, **kw: jnp.ones(
        [int(s) for s in size], dtype=_like_dtype(x, kw)))
    reg("aten.new_full.default", lambda ctx, x, size, value, **kw: jnp.full(
        [int(s) for s in size], value, dtype=_like_dtype(x, kw)))

    def _to(ctx, x, *args, **kw):
        import torch

        dtype = kw.get("dtype")
        for a in args:
            if isinstance(a, torch.dtype):
                dtype = a
        if dtype is not None:
            return x.astype(_to_jnp_dtype(dtype))
        return x

    reg(["aten.to.dtype", "aten.to.dtype_layout", "aten.to.device",
         "aten._to_copy.default"], _to)
    reg("aten.type_as.default", lambda ctx, x, other: x.astype(other.dtype))

    reg("aten.gather.default", lambda ctx, x, dim, index: jnp.take_along_axis(
        x, index, axis=dim))
    reg("aten.index_select.default", lambda ctx, x, dim, index: jnp.take(
        x, index, axis=dim))
    reg("aten.repeat.default", lambda ctx, x, repeats: jnp.tile(x, repeats))
    reg("aten.roll.default", lambda ctx, x, shifts, dims=None: jnp.roll(
        x, shifts, axis=tuple(dims) if dims else None))
    reg("aten.flip.default", lambda ctx, x, dims: jnp.flip(x, axis=tuple(dims)))

    # -- convolution / pooling / batch-norm / resize (CV family) ---------------
    # Closes the bridge's CV hole (VERDICT r03 item 4): the reference's CV
    # acceptance surface (examples/cv_example.py, ResNet-50) crosses here.
    from jax import lax

    def _spatial(v, nd: int) -> tuple:
        if isinstance(v, (list, tuple)):
            vals = [int(x) for x in v]
            if len(vals) == 1:
                vals = vals * nd
            return tuple(vals[:nd])
        return (int(v),) * nd

    def _conv_letters(nd: int) -> str:
        return "DHW"[3 - nd :]

    def _convolution(ctx, x, w, bias=None, stride=1, padding=0, dilation=1,
                     transposed=False, output_padding=0, groups=1):
        nd = x.ndim - 2
        letters = _conv_letters(nd)
        s = _spatial(stride, nd)
        d = _spatial(dilation, nd)
        groups = int(groups)
        if not transposed:
            if isinstance(padding, str):
                pad = padding.upper()  # torch "same"/"valid"
            else:
                p = _spatial(padding, nd)
                pad = [(pi, pi) for pi in p]
            dn = lax.conv_dimension_numbers(
                x.shape, w.shape, ("NC" + letters, "OI" + letters, "NC" + letters)
            )
            out = lax.conv_general_dilated(
                x, w.astype(x.dtype), window_strides=s, padding=pad,
                rhs_dilation=d, dimension_numbers=dn, feature_group_count=groups,
            )
        else:
            # ConvTranspose: torch weight is (Cin, Cout/g, *k). Express as a
            # regular conv with lhs_dilation=stride on a spatially-flipped,
            # (I,O)-swapped kernel; torch's output size contract
            # (in-1)*s - 2p + d*(k-1) + output_padding + 1 fixes the padding.
            p = _spatial(padding if not isinstance(padding, str) else 0, nd)
            op = _spatial(output_padding, nd)
            k = w.shape[2:]
            cin, cout_g = w.shape[0], w.shape[1]
            wg = w.reshape((groups, cin // groups, cout_g) + k)
            wg = jnp.swapaxes(wg, 1, 2)  # (g, Cout/g, Cin/g, *k)
            wg = wg.reshape((groups * cout_g, cin // groups) + k)
            wg = jnp.flip(wg, axis=tuple(range(2, 2 + nd)))
            pad = [
                (d[i] * (k[i] - 1) - p[i], d[i] * (k[i] - 1) - p[i] + op[i])
                for i in range(nd)
            ]
            dn = lax.conv_dimension_numbers(
                x.shape, wg.shape, ("NC" + letters, "OI" + letters, "NC" + letters)
            )
            out = lax.conv_general_dilated(
                x, wg.astype(x.dtype), window_strides=(1,) * nd, padding=pad,
                lhs_dilation=s, rhs_dilation=d, dimension_numbers=dn,
                feature_group_count=groups,
            )
        if bias is not None:
            out = out + bias.astype(out.dtype).reshape((1, -1) + (1,) * nd)
        return out

    reg("aten.convolution.default", _convolution)
    reg(
        ["aten.conv1d.default", "aten.conv2d.default", "aten.conv3d.default"],
        lambda ctx, x, w, bias=None, stride=1, padding=0, dilation=1, groups=1:
            _convolution(ctx, x, w, bias, stride, padding, dilation, False, 0, groups),
    )
    reg(
        ["aten.conv_transpose1d.default", "aten.conv_transpose2d.input",
         "aten.conv_transpose3d.input"],
        lambda ctx, x, w, bias=None, stride=1, padding=0, output_padding=0,
               groups=1, dilation=1:
            _convolution(ctx, x, w, bias, stride, padding, dilation, True,
                         output_padding, groups),
    )

    def _bn_stats(x):
        axes = (0,) + tuple(range(2, x.ndim))
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)  # biased, as torch normalizes with
        return mean, var

    def _bn_apply(x, mean, var, weight, bias, eps):
        shape = (1, -1) + (1,) * (x.ndim - 2)
        inv = lax.rsqrt(var.astype(jnp.float32) + eps).reshape(shape)
        out = (x.astype(jnp.float32) - mean.astype(jnp.float32).reshape(shape)) * inv
        if weight is not None:
            out = out * weight.astype(jnp.float32).reshape(shape)
        if bias is not None:
            out = out + bias.astype(jnp.float32).reshape(shape)
        return out.astype(x.dtype)

    def _batch_norm(ctx, x, weight=None, bias=None, running_mean=None,
                    running_var=None, training=False, momentum=0.1, eps=1e-5,
                    cudnn_enabled=True):
        if training or running_mean is None:
            mean, var = _bn_stats(x)
        else:
            mean, var = running_mean, running_var
        return _bn_apply(x, mean, var, weight, bias, eps)

    reg("aten.batch_norm.default", _batch_norm)

    def _group_norm_stats(x, num_groups, weight, bias, eps):
        # [N, C, *spatial] normalized per (sample, group) — the UNet-family
        # norm (GroupNorm is batch-independent: same math train and eval).
        # Returns (out, mean[N,g], rstd[N,g]).
        N, C = x.shape[:2]
        g = int(num_groups)
        xf = x.astype(jnp.float32).reshape((N, g, C // g) + x.shape[2:])
        axes = tuple(range(2, xf.ndim))
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        rstd = lax.rsqrt(var + eps)
        out = ((xf - mean) * rstd).reshape(x.shape)
        shape = (1, C) + (1,) * (x.ndim - 2)
        if weight is not None:
            out = out * weight.astype(jnp.float32).reshape(shape)
        if bias is not None:
            out = out + bias.astype(jnp.float32).reshape(shape)
        return out.astype(x.dtype), mean.reshape(N, g), rstd.reshape(N, g)

    reg(
        "aten.group_norm.default",
        lambda ctx, x, num_groups, weight=None, bias=None, eps=1e-5,
               cudnn_enabled=True:
            _group_norm_stats(x, num_groups, weight, bias, eps)[0],
    )
    reg(
        "aten.native_group_norm.default",
        # decomposed form: (x, weight, bias, N, C, HxW, group, eps) ->
        # (out, mean[N,g], rstd[N,g])
        lambda ctx, x, weight, bias, N, C, HxW, group, eps:
            _group_norm_stats(x, group, weight, bias, eps),
    )
    reg(
        "aten.broadcast_tensors.default",
        lambda ctx, tensors: list(jnp.broadcast_arrays(*tensors)),
    )

    def _bn_legit_functional(ctx, x, weight, bias, running_mean, running_var,
                             training, momentum, eps):
        # functionalized train-mode BN: returns new running stats as extra
        # outputs (the BUFFER_MUTATION channel threads them back to the user)
        if training:
            mean, var = _bn_stats(x)
            n = x.size // x.shape[1]
            unbiased = var * (n / max(n - 1, 1))  # torch tracks UNBIASED var
            new_mean = (1 - momentum) * running_mean.astype(jnp.float32) + momentum * mean
            new_var = (1 - momentum) * running_var.astype(jnp.float32) + momentum * unbiased
        else:
            mean, var = running_mean, running_var
            new_mean, new_var = running_mean, running_var
        out = _bn_apply(x, mean, var, weight, bias, eps)
        save_rstd = lax.rsqrt(var.astype(jnp.float32) + eps)
        return (out, mean.astype(jnp.float32), save_rstd,
                new_mean.astype(running_mean.dtype), new_var.astype(running_var.dtype))

    reg("aten._native_batch_norm_legit_functional.default", _bn_legit_functional)
    reg(
        "aten._native_batch_norm_legit_no_training.default",
        lambda ctx, x, weight, bias, running_mean, running_var, momentum, eps: (
            _bn_apply(x, running_mean, running_var, weight, bias, eps),
            jnp.zeros((0,), jnp.float32),
            jnp.zeros((0,), jnp.float32),
        ),
    )

    def _pool_dims(in_sz, k, s, p, d, ceil_mode):
        """Per-dim (out, lo_pad, hi_pad, keep) following torch's pooling shape
        contract: ceil-mode windows must START within input+lo padding."""
        eff_k = d * (k - 1) + 1
        if ceil_mode:
            out = -(-(in_sz + 2 * p - eff_k) // s) + 1
            if (out - 1) * s >= in_sz + p:
                out -= 1
        else:
            out = (in_sz + 2 * p - eff_k) // s + 1
        needed = (out - 1) * s + eff_k - p  # input cols the windows touch
        keep = min(in_sz, needed)  # floor mode may leave a dead tail: slice it
        hi = needed - keep
        return out, p, hi, keep

    def _reduce_pool(x, init, op, k, s, pads, d):
        nd = len(k)
        return lax.reduce_window(
            x, init, op,
            window_dimensions=(1, 1) + k,
            window_strides=(1, 1) + s,
            padding=((0, 0), (0, 0)) + pads,
            window_dilation=(1, 1) + d,
        )

    def _max_pool(ctx, x, kernel_size, stride=None, padding=0, dilation=1,
                  ceil_mode=False):
        nd = x.ndim - 2
        k = _spatial(kernel_size, nd)
        s = _spatial(stride, nd) if stride not in (None, []) else k
        p = _spatial(padding, nd)
        d = _spatial(dilation, nd)
        dims = [
            _pool_dims(x.shape[2 + i], k[i], s[i], p[i], d[i], bool(ceil_mode))
            for i in range(nd)
        ]
        x = x[(slice(None), slice(None)) + tuple(slice(0, dm[3]) for dm in dims)]
        pads = tuple((dm[1], dm[2]) for dm in dims)
        # init must be a CONCRETE scalar — a traced init breaks reduce_window's
        # autodiff linearization
        neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
               else int(jnp.iinfo(x.dtype).min))
        return _reduce_pool(x, neg, lax.max, k, s, pads, d)

    reg(["aten.max_pool1d.default", "aten.max_pool2d.default",
         "aten.max_pool3d.default"], _max_pool)

    def _avg_pool(ctx, x, kernel_size, stride=None, padding=0, ceil_mode=False,
                  count_include_pad=True, divisor_override=None):
        nd = x.ndim - 2
        k = _spatial(kernel_size, nd)
        s = _spatial(stride, nd) if stride not in (None, []) else k
        p = _spatial(padding, nd)
        d = (1,) * nd
        dims = [
            _pool_dims(x.shape[2 + i], k[i], s[i], p[i], 1, bool(ceil_mode))
            for i in range(nd)
        ]
        x = x[(slice(None), slice(None)) + tuple(slice(0, dm[3]) for dm in dims)]
        pads = tuple((dm[1], dm[2]) for dm in dims)
        total = _reduce_pool(x.astype(jnp.float32), 0.0, lax.add, k, s, pads, d)
        if divisor_override:
            div = jnp.asarray(float(divisor_override), jnp.float32)
        else:
            # divisor = window overlap with the COUNTED region: the real input
            # plus (when count_include_pad) the symmetric padding — never the
            # ceil-mode tail beyond it (torch semantics)
            spatial = tuple(dm[3] for dm in dims)
            if count_include_pad:
                ones = jnp.ones((1, 1) + tuple(sz + 2 * pp for sz, pp in zip(spatial, p)),
                                jnp.float32)
                cpads = tuple((0, max(dm[2] - dm[1], 0)) for dm in dims)
            else:
                ones = jnp.ones((1, 1) + spatial, jnp.float32)
                cpads = pads
            div = _reduce_pool(ones, 0.0, lax.add, k, s, cpads, d)
        return (total / div).astype(x.dtype)

    reg(["aten.avg_pool1d.default", "aten.avg_pool2d.default",
         "aten.avg_pool3d.default"], _avg_pool)

    def _adaptive_avg_pool(ctx, x, output_size):
        nd = x.ndim - 2
        out_sz = _spatial(output_size, nd)
        for i in range(nd):
            axis = 2 + i
            in_sz = x.shape[axis]
            o = out_sz[i]
            if o == in_sz:
                continue
            if in_sz % o == 0:
                r = in_sz // o
                shape = x.shape[:axis] + (o, r) + x.shape[axis + 1 :]
                x = x.reshape(shape).mean(axis=axis + 1)
            else:
                # torch windows: [floor(j*in/o), ceil((j+1)*in/o)) — separable,
                # one static slice per output position
                pieces = []
                for j in range(o):
                    lo = (j * in_sz) // o
                    hi = -(-((j + 1) * in_sz) // o)
                    sl = (slice(None),) * axis + (slice(lo, hi),)
                    pieces.append(x[sl].mean(axis=axis, keepdims=True))
                x = jnp.concatenate(pieces, axis=axis)
        return x

    reg(["aten.adaptive_avg_pool1d.default", "aten.adaptive_avg_pool2d.default",
         "aten.adaptive_avg_pool3d.default"], _adaptive_avg_pool)

    def _resize_sizes(x, output_size, scale_factors):
        nd = x.ndim - 2
        if output_size not in (None, []):
            return tuple(int(v) for v in output_size)
        sf = scale_factors if isinstance(scale_factors, (list, tuple)) else [scale_factors] * nd
        return tuple(int(math.floor(x.shape[2 + i] * float(sf[i]))) for i in range(nd))

    def _upsample_nearest(ctx, x, output_size=None, scale_factors=None, exact=False):
        sizes = _resize_sizes(x, output_size, scale_factors)
        for i, o in enumerate(sizes):
            axis = 2 + i
            in_sz = x.shape[axis]
            if o == in_sz:
                continue
            scale = in_sz / o
            if exact:
                idx = jnp.floor((jnp.arange(o) + 0.5) * scale).astype(jnp.int32)
            else:
                idx = jnp.floor(jnp.arange(o) * scale).astype(jnp.int32)
            x = jnp.take(x, jnp.clip(idx, 0, in_sz - 1), axis=axis)
        return x

    reg(["aten.upsample_nearest1d.vec", "aten.upsample_nearest2d.vec",
         "aten.upsample_nearest3d.vec"],
        lambda ctx, x, output_size=None, scale_factors=None:
            _upsample_nearest(ctx, x, output_size, scale_factors, exact=False))
    reg(["aten._upsample_nearest_exact1d.vec", "aten._upsample_nearest_exact2d.vec",
         "aten._upsample_nearest_exact3d.vec"],
        lambda ctx, x, output_size=None, scale_factors=None:
            _upsample_nearest(ctx, x, output_size, scale_factors, exact=True))

    def _interp_linear_dim(x, axis, o, align_corners):
        in_sz = x.shape[axis]
        if o == in_sz:
            return x
        if align_corners:
            # o == 1: torch clamps the scale to 0 and samples index 0
            scale = (in_sz - 1) / (o - 1) if o > 1 else 0.0
            src = jnp.arange(o, dtype=jnp.float32) * scale
        else:
            src = jnp.clip((jnp.arange(o, dtype=jnp.float32) + 0.5) * (in_sz / o) - 0.5,
                           0.0, in_sz - 1)
        lo = jnp.floor(src).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, in_sz - 1)
        w = (src - lo).astype(jnp.float32)
        bshape = [1] * x.ndim
        bshape[axis] = o
        w = w.reshape(bshape)
        xf = x.astype(jnp.float32)
        return (jnp.take(xf, lo, axis=axis) * (1 - w)
                + jnp.take(xf, hi, axis=axis) * w).astype(x.dtype)

    def _upsample_linear(ctx, x, output_size=None, align_corners=False, scale_factors=None):
        sizes = _resize_sizes(x, output_size, scale_factors)
        for i, o in enumerate(sizes):
            x = _interp_linear_dim(x, 2 + i, o, bool(align_corners))
        return x

    reg(["aten.upsample_linear1d.vec", "aten.upsample_bilinear2d.vec",
         "aten.upsample_trilinear3d.vec"], _upsample_linear)

    # -- functionalized mutation ops -------------------------------------------
    # In-place ops (aten.add_, aten.copy_ on slice VIEWS, ...) cannot be
    # interpreted per-node — a copy_ writing through a view mutates its BASE
    # tensor, invisible to a functional interpreter. lower_module_aten detects
    # mutating graphs and functionalizes them (ep.run_decompositions), after
    # which mutation appears as these pure scatter/copy ops instead. Seen in
    # the wild: T5's _shift_right (labels → decoder_input_ids).
    def _slice_scatter(ctx, base, src, dim=0, start=None, end=None, step=1):
        dim = dim % base.ndim
        size = base.shape[dim]
        # ATen: negative indices shift by size, then clamp to [0, size] — a
        # still-negative value (e.g. end=-5 on size 4) means an EMPTY slice,
        # not Python's from-the-back reinterpretation
        start = 0 if start is None else min(max(start + size if start < 0 else start, 0), size)
        end = size if end is None else min(max(end + size if end < 0 else end, 0), size)
        idx = (slice(None),) * dim + (slice(int(start), int(end), int(step or 1)),)
        return base.at[idx].set(src)

    def _select_scatter(ctx, base, src, dim, index):
        dim = dim % base.ndim
        idx = (slice(None),) * dim + (int(index),)
        return base.at[idx].set(src)

    reg("aten.slice_scatter.default", _slice_scatter)
    reg("aten.select_scatter.default", _select_scatter)
    reg("aten.copy.default", lambda ctx, dst, src, non_blocking=False: jnp.broadcast_to(
        jnp.asarray(src).astype(dst.dtype), dst.shape))
    reg(["aten.fill.Tensor", "aten.fill.Scalar"], lambda ctx, x, value: jnp.full_like(
        x, jnp.asarray(value)))

    return H


def _flatten(x, start_dim=0, end_dim=-1):
    import jax.numpy as jnp

    nd = x.ndim
    start = start_dim % nd
    end = end_dim % nd
    shape = x.shape[:start] + (-1,) + x.shape[end + 1:]
    return jnp.reshape(x, shape)


def _dims(dim):
    if dim is None:
        return None
    return tuple(dim) if isinstance(dim, (list, tuple)) else dim


def _graph_mutates(graph_module) -> bool:
    """True when the exported program contains in-place ATen ops (trailing
    underscore, e.g. ``aten.copy_``) whose buffer mutation a per-node
    functional interpreter cannot express. Scans EVERY fx graph, including
    higher-order-op subgraphs (no_grad/autocast bodies live in nested
    GraphModules, not the top-level graph)."""
    import torch.fx

    for gm in graph_module.modules():
        if not isinstance(gm, torch.fx.GraphModule):
            continue
        for node in gm.graph.nodes:
            if node.op != "call_function":
                continue
            name = str(node.target)
            parts = name.split(".")
            if len(parts) >= 2:
                op = parts[1] if parts[0] == "aten" else parts[-2]
                if op.endswith("_") and not op.startswith("__"):
                    return True
    return False


def lower_module_aten(model, example_inputs: dict, train_mode: bool = False):
    """Lower ``model`` via ``torch.export`` → ``(fn, params, buffers)``.

    ``example_inputs``: dict of example kwargs (numpy or torch tensors) fixing
    the traced shapes. Returned ``fn(params, buffers, inputs, train=False,
    rng=None)`` is pure/jittable; params/buffers are flat dot-path dicts of
    jax arrays (DLPack-shared with the module, same contract as
    ``fx_lowering.lower_module``).

    ``train_mode=True`` exports the TRAIN-mode graph: batch-norm normalizes by
    batch statistics and dropout ops appear (driven by ``fn``'s ``train``/
    ``rng`` args). Mutated buffers (BN running stats) come back through
    ``fn(..., with_buffer_updates=True)`` → ``(out, {buffer_name: new_value})``;
    the mutated names are listed on ``fn.mutated_buffers``.
    """
    import numpy as np
    import torch

    from .dlpack import module_params_to_jax

    example = {
        k: (torch.from_numpy(np.asarray(v)) if not isinstance(v, torch.Tensor) else v)
        for k, v in example_inputs.items()
    }
    was_training = model.training
    model.train(train_mode)
    prior_use_cache = None
    if getattr(model, "config", None) is not None and getattr(model.config, "use_cache", None):
        prior_use_cache = model.config.use_cache
        model.config.use_cache = False  # DynamicCache outputs are not exportable
    try:
        with _traceable_masking(), torch.no_grad():
            ep = torch.export.export(model, (), example, strict=False)
    finally:
        model.train(was_training)
        if prior_use_cache is not None:
            model.config.use_cache = prior_use_cache

    if _graph_mutates(ep.graph_module):
        # in-place ops writing through views (T5 _shift_right's
        # `shifted[:, 1:] = labels[:, :-1]`) are not interpretable per-node;
        # functionalize — mutation becomes slice_scatter/select_scatter/copy
        ep = ep.run_decompositions({})

    sig = ep.graph_signature
    params, buffers = module_params_to_jax(model)

    # tied weights: the export signature uses each alias's own fqn (e.g. BOTH
    # transformer.wte.weight and lm_head.weight) while the flat param dict is
    # deduped — canonicalize aliases to the first-seen name
    def _canonical_names(named_iter):
        seen: dict[int, str] = {}
        table: dict[str, str] = {}
        for name, t in named_iter:
            tid = id(t)
            seen.setdefault(tid, name)
            table[name] = seen[tid]
        return table

    param_alias = _canonical_names(model.named_parameters(remove_duplicate=False))
    buffer_alias = _canonical_names(model.named_buffers(remove_duplicate=False))

    inputs_to_params = {
        k: param_alias.get(v, v) for k, v in sig.inputs_to_parameters.items()
    }
    inputs_to_buffers = {
        k: buffer_alias.get(v, v) for k, v in sig.inputs_to_buffers.items()
    }
    user_inputs = {
        s.arg.name: s.target if s.target is not None else s.arg.name
        for s in sig.input_specs
        if s.kind.name == "USER_INPUT" and hasattr(s.arg, "name")
    }
    # tensor constants lifted by export (e.g. baked masks)
    constants = {}
    for name, value in getattr(ep, "constants", {}).items():
        if isinstance(value, torch.Tensor):
            constants[name] = np.asarray(value.detach().cpu())
    inputs_to_constants = dict(getattr(sig, "inputs_to_lifted_tensor_constants", {}) or {})

    out_spec = None
    call_spec = getattr(ep, "call_spec", None)
    if call_spec is not None:
        out_spec = getattr(call_spec, "out_spec", None)

    handlers = _aten_handlers()
    root_gm = ep.graph_module

    import torch.fx

    # higher-order ops wrap subgraphs (e.g. the no_grad rotary-embedding region
    # exports as wrap_with_set_grad_enabled(flag, submod, *args)); args before
    # the subgraph operand are config scalars to drop
    _HOP_SKIP = {"wrap_with_set_grad_enabled": 1, "wrap_with_autocast": 4}

    mutated_buffer_names = [
        buffer_alias.get(s.target, s.target)
        for s in sig.output_specs
        if s.kind.name == "BUFFER_MUTATION"
    ]

    def fn(params, buffers, inputs, train: bool = False, rng=None,
           with_buffer_updates: bool = False):
        import jax.numpy as jnp

        ctx = _Ctx(train, rng)

        def resolve_placeholder_root(node):
            if node.name in inputs_to_params:
                return params[inputs_to_params[node.name]]
            if node.name in inputs_to_buffers:
                return buffers[inputs_to_buffers[node.name]]
            if node.name in inputs_to_constants:
                return jnp.asarray(constants[inputs_to_constants[node.name]])
            key = user_inputs.get(node.name, node.name)
            val = inputs.get(key, inputs.get(node.name))
            return jnp.asarray(val) if val is not None else None

        def run_graph(gm, positional_args=None):
            env: dict = {}
            arg_iter = iter(positional_args) if positional_args is not None else None

            def lookup(n):
                return env[n.name]

            for node in gm.graph.nodes:
                if node.op == "placeholder":
                    val = next(arg_iter) if arg_iter is not None else resolve_placeholder_root(node)
                elif node.op == "get_attr":
                    target = str(node.target)
                    sub = getattr(gm, target, None)
                    if isinstance(sub, torch.fx.GraphModule):
                        val = sub
                    elif target in buffers:
                        val = buffers[target]
                    elif target in params:
                        val = params[target]
                    elif target in constants:
                        val = jnp.asarray(constants[target])
                    elif isinstance(sub, torch.Tensor):
                        val = jnp.asarray(np.asarray(sub.detach().cpu()))
                    else:
                        raise LoweringError(f"get_attr target {target!r} not found")
                elif node.op == "call_function":
                    name = str(node.target)
                    opname = getattr(node.target, "__name__", name)
                    args = torch.fx.node.map_arg(node.args, lookup)
                    kwargs = torch.fx.node.map_arg(node.kwargs, lookup)
                    if opname in _HOP_SKIP:
                        skip = _HOP_SKIP[opname]
                        sub_gm = args[skip]
                        val = run_graph(sub_gm, positional_args=list(args[skip + 1:]))
                    else:
                        handler = handlers.get(name)
                        if handler is None:
                            raise LoweringError(f"no ATen lowering for {name!r}")
                        val = handler(ctx, *args, **kwargs)
                elif node.op == "output":
                    out_args = node.args[0]
                    mapped = torch.fx.node.map_arg(out_args, lookup)
                    return list(mapped) if isinstance(mapped, (list, tuple)) else [mapped]
                else:  # pragma: no cover
                    raise LoweringError(f"unknown export op {node.op}")
                env[node.name] = val
            raise LoweringError("graph had no output node")

        mapped = run_graph(root_gm)
        # root output order matches output_specs; split user outputs from
        # buffer mutations (BN running stats — returned when asked for)
        buf_updates: dict = {}
        if len(mapped) == len(sig.output_specs):
            flat_out = []
            for v, s in zip(mapped, sig.output_specs):
                if s.kind.name == "USER_OUTPUT":
                    flat_out.append(v)
                elif s.kind.name == "BUFFER_MUTATION":
                    buf_updates[buffer_alias.get(s.target, s.target)] = v
        else:
            flat_out = mapped

        def _finish(result):
            return (result, buf_updates) if with_buffer_updates else result

        if out_spec is not None:
            try:
                import torch.utils._pytree as torch_pytree

                rebuilt = torch_pytree.tree_unflatten(flat_out, out_spec)
                if hasattr(rebuilt, "items"):
                    return _finish({k: v for k, v in rebuilt.items() if v is not None})
                return _finish(rebuilt)
            except Exception:
                pass
        if len(flat_out) == 1:
            return _finish(flat_out[0])
        return _finish(tuple(flat_out))

    fn.mutated_buffers = mutated_buffer_names
    return fn, params, buffers
