"""torch-interop bridge: run torch-style training scripts on the TPU-native core.

The reference's north star is that ``examples/nlp_example.py`` — a *torch*
script built on ``Accelerator.prepare(model, optimizer, dl, scheduler)`` +
``accelerator.backward(loss)`` (reference ``src/accelerate/accelerator.py:1735
prepare_model``, ``:2770 backward``) — runs with minimal modification. This
package provides that:

- :mod:`dlpack` — zero-copy ``torch.Tensor`` ↔ ``jax.Array`` exchange.
- :mod:`fx_lowering` — ``torch.fx`` graph → pure JAX function. The model's
  *math* is re-expressed in jnp/lax and compiled by XLA; torch never executes
  on the hot path.
- :mod:`module` — :class:`BridgedModule` / :class:`BridgedOptimizer`: the
  torch-style objects returned by ``prepare`` whose ``model(**batch)`` /
  ``optimizer.step()`` drive one fused jitted forward+backward under the hood.
"""

from .dlpack import torch_to_jax, jax_to_torch, module_params_to_jax, write_back_to_module
from .fx_lowering import lower_module, LoweringError
from .module import BridgedModule, BridgedOptimizer

__all__ = [
    "torch_to_jax",
    "jax_to_torch",
    "module_params_to_jax",
    "write_back_to_module",
    "lower_module",
    "LoweringError",
    "BridgedModule",
    "BridgedOptimizer",
]
