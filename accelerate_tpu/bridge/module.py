"""BridgedModule / BridgedOptimizer: torch-style training objects whose hot path
is one fused jitted JAX step.

The reference's torch loop (``examples/nlp_example.py``) is::

    model, optimizer, dl, sched = accelerator.prepare(model, optimizer, dl, sched)
    for batch in dl:
        outputs = model(**batch)
        accelerator.backward(outputs.loss)
        optimizer.step(); sched.step(); optimizer.zero_grad()

Bridged semantics (TPU-first redesign of ``prepare_model accelerator.py:1735`` +
``backward :2770``):

- ``model(**batch)`` in train mode runs ONE jitted ``value_and_grad`` of the
  fx-lowered function — forward and backward fused, XLA/GSPMD handles layout and
  collectives. Gradients are cached on the module.
- ``accelerator.backward(loss)`` moves the cached grads into the optimizer's
  accumulator (so torch-style gradient accumulation — several backwards, one
  step — works naturally: grads are averaged at ``step()``).
- ``optimizer.step()`` applies an optax update matched to the torch optimizer's
  type/hyperparams. The learning rate is read live from
  ``param_groups[0]["lr"]`` each step, so *unmodified torch LR schedulers* work:
  they mutate the torch optimizer, we observe it (``optax.inject_hyperparams``
  keeps it a traced scalar — no recompile per LR value).
"""

from __future__ import annotations

from typing import Any, Optional

__all__ = ["BridgedModule", "BridgedOptimizer", "BridgedOutput"]


class BridgedOutput(dict):
    """Mapping + attribute access, like transformers' ModelOutput."""

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None


class BridgedModule:
    """An ``nn.Module`` lowered to JAX; callable with torch-script semantics."""

    def __init__(self, torch_module, accelerator=None, rng_seed: int = 0):
        self.torch_module = torch_module
        self.accelerator = accelerator
        self.training = torch_module.training
        from .dlpack import module_params_to_jax

        self.params, self.buffers = module_params_to_jax(torch_module)
        self._fn = None
        self._input_names: Optional[tuple] = None
        self._aten_shapes: Optional[tuple] = None  # set when on the export path
        self._aten_cache: dict = {}  # shapes-signature → lowered fn
        self._fx_failed = False  # fx trace known-unsupported: go straight to export
        self._train_step = None
        self._train_fwd = None
        self._eval_step = None
        self._pending_grads = None
        self._pending_loss = None
        self._rng_seed = rng_seed
        self._call_count = 0

    # -- torch Module API surface -------------------------------------------
    def train(self, mode: bool = True):
        self.training = mode
        self.torch_module.train(mode)
        return self

    def eval(self):
        return self.train(False)

    def parameters(self):
        """jax leaves (for introspection; optimization goes through the bridge)."""
        return list(self.params.values())

    def named_parameters(self):
        return list(self.params.items())

    def state_dict(self):
        import numpy as np
        import jax

        return {k: np.asarray(jax.device_get(v)) for k, v in self.params.items()}

    def load_state_dict(self, state: dict, strict: bool = True):
        import jax
        import jax.numpy as jnp

        missing = [k for k in self.params if k not in state]
        if strict and missing:
            raise KeyError(f"missing keys in state_dict: {missing[:5]}...")
        for k, v in state.items():
            if k in self.params:
                old = self.params[k]
                self.params[k] = jax.device_put(
                    jnp.asarray(v, dtype=old.dtype), getattr(old, "sharding", None)
                )

    def sync_to_torch(self):
        """Copy live jax params AND buffers (BN running stats update during
        training) back into the wrapped ``nn.Module`` (for torch-side
        save/export — reference ``get_state_dict:3947``)."""
        from .dlpack import write_back_to_module

        write_back_to_module(self.torch_module, self.params, self.buffers)
        return self.torch_module

    # -- lowering / compilation ---------------------------------------------
    def _shape_key(self, example_batch):
        """ATen-cache key: batch shapes + train/eval mode (the export bakes
        mode-dependent semantics — train-mode BN normalizes by batch stats)."""
        import numpy as np

        if example_batch is None:
            return None
        return (
            bool(self.training),
            tuple((k, tuple(np.shape(example_batch[k]))) for k in sorted(example_batch)),
        )

    def _ensure_lowered(self, input_names, example_batch=None):
        key = tuple(sorted(input_names))
        shapes = self._shape_key(example_batch)
        if self._fn is not None and self._input_names == key and (
            self._aten_shapes is None or self._aten_shapes == shapes
        ):
            return
        if self._fx_failed:
            # fx is known-unsupported for this module: go straight to export
            # (shape-keyed cache — alternating train/eval shapes must not
            # re-lower every call)
            self._fn = self._lower_aten(example_batch, shapes)
            self._input_names = key
            self._train_step = None
            self._eval_step = None
            return
        from .fx_lowering import lower_module

        try:
            fn, _, _ = lower_module(self.torch_module, list(input_names))
            self._aten_shapes = None
        except Exception:
            # decoder families (GPT-2, Llama, ...) are no longer symbolically
            # traceable through transformers.utils.fx — fall back to the
            # torch.export ATen path (shape-specialized; re-lowers on a new
            # batch shape)
            if example_batch is None:
                raise
            self._fx_failed = True
            fn = self._lower_aten(example_batch, shapes)
        self._fn = fn
        self._input_names = key
        self._train_step = None
        self._eval_step = None

    def _lower_aten(self, example_batch, shapes):
        fn = self._aten_cache.get(shapes)
        if fn is None:
            from .aten_lowering import lower_module_aten

            fn, _, _ = lower_module_aten(
                self.torch_module, example_batch, train_mode=bool(self.training)
            )
            self._aten_cache[shapes] = fn
        self._aten_shapes = shapes
        return fn

    def _policy(self):
        if self.accelerator is not None:
            return self.accelerator.state.mixed_precision_policy
        from ..utils.dataclasses import MixedPrecisionPolicy

        return MixedPrecisionPolicy(None, None, None)

    def _build_steps(self):
        import jax

        fn = self._fn
        policy = self._policy()
        # export-path fns report mutated buffers (BN running stats); thread
        # them out of the jitted step so self.buffers stays live across steps
        has_buffer_updates = bool(getattr(fn, "mutated_buffers", None))
        mutated = frozenset(getattr(fn, "mutated_buffers", ()) or ())

        def cast_buffers(buffers):
            # mutated buffers (running statistics) stay at storage precision:
            # a bf16 compute policy must not quantize the momentum blend —
            # torch keeps BN stats fp32 under autocast too
            cast = policy.cast_to_compute(
                {k: v for k, v in buffers.items() if k not in mutated}
            )
            return {**cast, **{k: buffers[k] for k in mutated if k in buffers}}

        def train_loss(params, buffers, batch, rng):
            import jax.numpy as jnp

            cast = (
                policy.cast_to_compute(params),
                cast_buffers(buffers),
                policy.cast_to_compute(batch),
            )
            if has_buffer_updates:
                out, buf_updates = fn(*cast, train=True, rng=rng, with_buffer_updates=True)
            else:
                out, buf_updates = fn(*cast, train=True, rng=rng), {}
            loss = out["loss"] if isinstance(out, dict) else out[0]
            return loss.astype(jnp.float32), (out, buf_updates)

        grad_fn = jax.value_and_grad(train_loss, has_aux=True)

        def train_step(params, buffers, batch, rng):
            (loss, (out, buf_updates)), grads = grad_fn(params, buffers, batch, rng)
            return loss, out, grads, buf_updates

        def train_forward(params, buffers, batch, rng):
            # train-mode forward WITHOUT loss (no labels): torch still updates
            # BN running stats on such a call — so must we
            cast = (
                policy.cast_to_compute(params),
                cast_buffers(buffers),
                policy.cast_to_compute(batch),
            )
            if has_buffer_updates:
                return fn(*cast, train=True, rng=rng, with_buffer_updates=True)
            return fn(*cast, train=True, rng=rng), {}

        def eval_step(params, buffers, batch):
            return fn(
                policy.cast_to_compute(params),
                cast_buffers(buffers),
                policy.cast_to_compute(batch),
                train=False,
                rng=None,
            )

        self._train_step = jax.jit(train_step)
        self._train_fwd = jax.jit(train_forward)
        self._eval_step = jax.jit(eval_step)

    # -- the call ------------------------------------------------------------
    def __call__(self, **batch) -> BridgedOutput:
        import jax
        import numpy as np

        batch = {k: v for k, v in batch.items() if v is not None}
        raw_batch = dict(batch)
        self._ensure_lowered(batch.keys(), example_batch=raw_batch)
        if self._train_step is None:
            self._build_steps()
        batch = {k: _to_jax(v) for k, v in batch.items()}

        def _run():
            # no module state is mutated until the step succeeds, so the
            # LoweringError retry below cannot leave stale grads/rng behind
            if self.training and "labels" in batch:
                rng = jax.random.fold_in(jax.random.PRNGKey(self._rng_seed), self._call_count)
                loss, out, grads, buf_updates = self._train_step(
                    self.params, self.buffers, batch, rng
                )
                out = dict(out) if isinstance(out, dict) else {"loss": loss, "logits": out[1]}
                out["loss"] = loss
                self._call_count += 1
                self._pending_grads = grads
                self._apply_buffer_updates(buf_updates)
                return out
            if self.training:
                # train-mode logits probe (no labels): running stats update,
                # no grads
                rng = jax.random.fold_in(jax.random.PRNGKey(self._rng_seed), self._call_count)
                out, buf_updates = self._train_fwd(self.params, self.buffers, batch, rng)
                self._call_count += 1
                self._apply_buffer_updates(buf_updates)
                if not isinstance(out, dict):
                    out = {"logits": out if not isinstance(out, (tuple, list)) else out[0]}
                return out
            out = self._eval_step(self.params, self.buffers, batch)
            if not isinstance(out, dict):
                out = {"logits": out if not isinstance(out, (tuple, list)) else out[0]}
            return out

        from .fx_lowering import LoweringError

        try:
            out = _run()
        except LoweringError:
            # the symbolic-fx fn is interpreted lazily, so a missing handler
            # only surfaces on first execution — retry once through the
            # export/ATen path. Genuine runtime errors (shape bugs, OOM, user
            # mistakes) propagate unmasked.
            if self._aten_shapes is not None:
                raise
            self._fx_failed = True
            self._fn = self._lower_aten(raw_batch, self._shape_key(raw_batch))
            self._train_step = None
            self._eval_step = None
            self._build_steps()
            out = _run()
        return BridgedOutput({k: _TensorView.wrap(v) for k, v in out.items()})

    def _apply_buffer_updates(self, buf_updates):
        if not buf_updates:
            return
        self.buffers = {
            **self.buffers,
            **{
                k: v.astype(self.buffers[k].dtype)
                for k, v in buf_updates.items()
                if k in self.buffers
            },
        }

    def pop_pending_grads(self):
        grads, self._pending_grads = self._pending_grads, None
        return grads

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 32,
        eos_token_id=None,
        pad_token_id: Optional[int] = None,
        attention_mask=None,
    ):
        """Greedy decoding for bridged decoder models (GPT-2, Llama, ...).

        Fixed-shape full forwards: ids are padded once to
        ``S + max_new_tokens`` so the export/ATen path compiles exactly one
        graph; under causal attention the not-yet-generated tail cannot
        influence earlier positions, so each step's argmax at the current
        position is exact. (For the cache-based native path see
        ``accelerate_tpu.generation.greedy_generate``.)

        Ragged (right-padded) batches: pass ``attention_mask``. Each distinct
        prompt length decodes in its own exact forward (continuation starts at
        the row's true length, pads never attended — HF greedy parity), so a
        ragged batch costs up to one compile + forward chain per row; the
        equal-length fast path stays batched.
        """
        import numpy as np

        was_training = self.training
        self.training = False
        if pad_token_id is None:
            pad_token_id = getattr(getattr(self.torch_module, "config", None), "pad_token_id", None)
            pad_token_id = 0 if pad_token_id is None else pad_token_id
        try:
            ids = np.asarray(input_ids)
            B, S = ids.shape
            if getattr(getattr(self.torch_module, "config", None), "is_encoder_decoder", False):
                return self._generate_seq2seq(
                    ids,
                    max_new_tokens=max_new_tokens,
                    eos_token_id=eos_token_id,
                    pad_token_id=pad_token_id,
                    attention_mask=attention_mask,
                )
            if attention_mask is not None:
                mask = np.asarray(attention_mask)
                lengths = mask.astype(np.int64).sum(axis=1)
                prefix_ones = all(bool(mask[i, : lengths[i]].all()) for i in range(B))
                if not prefix_ones or (lengths == 0).any():
                    raise ValueError(
                        "generate() supports right-padded attention_mask only "
                        "(each row a non-empty prefix of ones)"
                    )
                if (lengths != S).any():
                    rows = []
                    for i in range(B):
                        rows.append(
                            self.generate(
                                ids[i : i + 1, : lengths[i]],
                                max_new_tokens=max_new_tokens,
                                eos_token_id=eos_token_id,
                                pad_token_id=pad_token_id,
                            )[0]
                        )
                    width = max(r.shape[0] for r in rows)
                    out = np.full((B, width), pad_token_id, dtype=ids.dtype)
                    for i, r in enumerate(rows):
                        out[i, : r.shape[0]] = r
                    return out
            total = S + max_new_tokens
            padded = np.full((B, total), pad_token_id, dtype=ids.dtype)
            padded[:, :S] = ids
            finished = np.zeros((B,), bool)
            for step in range(max_new_tokens):
                cur = S + step
                out = self(
                    input_ids=padded,
                    attention_mask=np.ones((B, total), dtype=ids.dtype),
                )
                tok = _logits_np(out)[:, cur - 1].argmax(-1).astype(ids.dtype)
                if eos_token_id is not None:
                    # rows that finished EARLIER pad (HF greedy parity); the
                    # row's own first eos is kept
                    tok = np.where(finished, pad_token_id, tok)
                    finished |= _is_eos(tok, eos_token_id)
                padded[:, cur] = tok
                if eos_token_id is not None and finished.all():
                    padded = padded[:, : cur + 1]
                    break
            return padded
        finally:
            self.training = was_training

    def _generate_seq2seq(
        self,
        ids,
        max_new_tokens: int,
        eos_token_id,
        pad_token_id: int,
        attention_mask=None,
    ):
        """Greedy decoding for bridged encoder-decoder models (T5, ...).

        Same fixed-shape strategy as the decoder path: decoder ids are padded
        once to ``1 + max_new_tokens`` (starting from
        ``config.decoder_start_token_id``) so one graph compiles; the causal
        decoder makes each step's argmax at position ``t`` exact regardless of
        the unfilled tail. Every step re-runs the full encoder+decoder — the
        correctness-first bridge route (the native cached path is
        ``accelerate_tpu.generation``); encoder cost could be hoisted with an
        encoder/decoder split lowering if it ever matters.
        """
        import numpy as np

        cfg = self.torch_module.config
        start_id = cfg.decoder_start_token_id
        if start_id is None:
            raise ValueError("config.decoder_start_token_id required for seq2seq generate")
        if eos_token_id is None:
            eos_token_id = getattr(cfg, "eos_token_id", None)
        B, S = ids.shape
        enc_mask = (
            np.asarray(attention_mask).astype(ids.dtype)
            if attention_mask is not None
            else np.ones((B, S), dtype=ids.dtype)
        )
        total = 1 + max_new_tokens
        dec = np.full((B, total), pad_token_id, dtype=ids.dtype)
        dec[:, 0] = start_id
        finished = np.zeros((B,), bool)
        for step in range(max_new_tokens):
            out = self(
                input_ids=ids, attention_mask=enc_mask, decoder_input_ids=dec
            )
            tok = _logits_np(out)[:, step].argmax(-1).astype(ids.dtype)
            if eos_token_id is not None:
                tok = np.where(finished, pad_token_id, tok)
                finished |= _is_eos(tok, eos_token_id)
            dec[:, step + 1] = tok
            if eos_token_id is not None and finished.all():
                dec = dec[:, : step + 2]
                break
        return dec


def _logits_np(out):
    """BridgedOutput logits → numpy (unwraps the _TensorView)."""
    import numpy as np

    v = out["logits"]
    return np.asarray(v.array if hasattr(v, "array") else v)


def _is_eos(tok, eos_token_id):
    """Per-row bool: is ``tok`` an eos? Accepts an int OR a list of ids (HF
    configs commonly store lists) — membership, never broadcasting."""
    import numpy as np

    ids = eos_token_id if isinstance(eos_token_id, (list, tuple, set)) else [eos_token_id]
    return np.isin(tok, np.asarray(sorted(ids)))


def _to_jax(v):
    """Batch-input conversion: returns UNCOMMITTED host arrays. DLPack import
    (``torch_to_jax``) would commit to device 0, which conflicts with
    mesh-placed params inside the jitted step ("incompatible devices") — let
    jit place batch leaves to match the computation instead."""
    import numpy as np

    try:
        import torch

        if isinstance(v, torch.Tensor):
            from .dlpack import torch_tensor_to_numpy

            return torch_tensor_to_numpy(v)
    except ImportError:
        pass
    if isinstance(v, (int, float, bool, np.ndarray)):
        return np.asarray(v)
    return v


class _TensorView:
    """Thin torch-flavored view over a jax array so torch-style metric code
    (``.argmax(dim=-1)``, ``.item()``, ``.detach().float()``, ``.cpu()``,
    comparison / arithmetic) keeps working without a device round-trip until a
    value is actually needed."""

    __slots__ = ("array",)

    def __init__(self, array):
        self.array = array

    @classmethod
    def wrap(cls, value):
        return cls(value) if hasattr(value, "dtype") else value

    # conversions
    def __float__(self):
        import numpy as np

        return float(np.asarray(self.array))

    def __int__(self):
        import numpy as np

        return int(np.asarray(self.array))

    def __bool__(self):
        import numpy as np

        return bool(np.asarray(self.array))

    def item(self):
        return self.__float__() if "float" in str(self.array.dtype) else self.__int__()

    def numpy(self):
        import numpy as np

        return np.asarray(self.array)

    def __array__(self, dtype=None):
        import numpy as np

        arr = np.asarray(self.array)
        return arr.astype(dtype) if dtype is not None else arr

    def torch(self):
        from .dlpack import jax_to_torch

        return jax_to_torch(self.array)

    # torch-style methods (dim= kwargs)
    def argmax(self, dim=None, keepdim=False):
        import jax.numpy as jnp

        return _TensorView(jnp.argmax(self.array, axis=dim))

    def detach(self):
        return self

    def float(self):
        import jax.numpy as jnp

        return _TensorView(self.array.astype(jnp.float32))

    def cpu(self):
        return self

    def to(self, *a, **k):
        return self

    def view(self, *shape):
        import jax.numpy as jnp

        return _TensorView(jnp.reshape(self.array, shape))

    def repeat(self, n):
        import jax.numpy as jnp

        return _TensorView(jnp.tile(self.array, n))

    @property
    def shape(self):
        return self.array.shape

    @property
    def ndim(self):
        return self.array.ndim

    @property
    def dtype(self):
        return self.array.dtype

    def __getitem__(self, idx):
        return _TensorView.wrap(self.array[idx])

    def __len__(self):
        return self.array.shape[0]

    def __repr__(self):
        return f"_TensorView({self.array!r})"

    def _binop(self, other, op):
        other = other.array if isinstance(other, _TensorView) else other
        return _TensorView.wrap(op(self.array, other))

    def __add__(self, other):
        import operator

        return self._binop(other, operator.add)

    __radd__ = __add__

    def __sub__(self, other):
        import operator

        return self._binop(other, operator.sub)

    def __mul__(self, other):
        import operator

        return self._binop(other, operator.mul)

    __rmul__ = __mul__

    def __truediv__(self, other):
        import operator

        return self._binop(other, operator.truediv)

    def __eq__(self, other):
        import operator

        return self._binop(other, operator.eq)

    def __ne__(self, other):
        import operator

        return self._binop(other, operator.ne)

    def __hash__(self):
        return id(self)


class BridgedOptimizer:
    """Wraps a ``torch.optim.Optimizer`` into an optax update over the bridged
    params (reference ``AcceleratedOptimizer optimizer.py:38``; here the torch
    optimizer never steps — it is the *hyperparameter source*)."""

    _SUPPORTED = ("AdamW", "Adam", "SGD")

    def __init__(self, torch_optimizer, module: BridgedModule):
        self.torch_optimizer = torch_optimizer
        self.module = module
        self.opt_state = None
        self._accum = None
        self._accum_count = 0
        self._apply = None
        self._tx = None

    # torch API surface
    @property
    def param_groups(self):
        return self.torch_optimizer.param_groups

    def zero_grad(self, set_to_none: bool = True):
        self._accum = None
        self._accum_count = 0

    def accumulate_grads(self, grads):
        import jax

        if self._accum is None:
            self._accum = grads
        else:
            self._accum = jax.tree_util.tree_map(lambda a, g: a + g, self._accum, grads)
        self._accum_count += 1

    def _build(self):
        import optax

        group = self.torch_optimizer.param_groups[0]
        kind = type(self.torch_optimizer).__name__
        if kind == "AdamW":
            b1, b2 = group.get("betas", (0.9, 0.999))
            base = lambda lr: optax.adamw(
                lr, b1=b1, b2=b2, eps=group.get("eps", 1e-8),
                weight_decay=group.get("weight_decay", 1e-2),
            )
        elif kind == "Adam":
            b1, b2 = group.get("betas", (0.9, 0.999))
            base = lambda lr: optax.adam(lr, b1=b1, b2=b2, eps=group.get("eps", 1e-8))
        elif kind == "SGD":
            base = lambda lr: optax.sgd(
                lr, momentum=group.get("momentum", 0.0) or None,
                nesterov=group.get("nesterov", False),
            )
        else:
            raise NotImplementedError(
                f"BridgedOptimizer supports {self._SUPPORTED}; got {kind}. "
                "Pass an optax transform to Accelerator.prepare instead."
            )
        import optax

        self._tx = optax.inject_hyperparams(lambda learning_rate: base(learning_rate))(
            learning_rate=float(group["lr"])
        )
        self.opt_state = self._tx.init(self.module.params)

        import jax

        def apply(params, opt_state, grads, lr, count):
            grads = jax.tree_util.tree_map(lambda g: g / count, grads)
            opt_state.hyperparams["learning_rate"] = lr
            updates, new_state = self._tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_state

        # no donate_argnums on purpose: torch-interop _TensorViews hold raw
        # references to the param arrays across steps, so donating params
        # would delete buffers under live views; 2x-state HBM is the price
        # of the interop path
        self._apply = jax.jit(apply)  # jaxlint: disable=R3

    def step(self, closure=None):
        import jax.numpy as jnp

        if self._accum is None:
            return  # torch semantics: step with no grads is a no-op
        if self._apply is None:
            self._build()
        lr = jnp.float32(self.torch_optimizer.param_groups[0]["lr"])
        count = jnp.float32(max(self._accum_count, 1))
        self.module.params, self.opt_state = self._apply(
            self.module.params, self.opt_state, self._accum, lr, count
        )
        self._accum = None
        self._accum_count = 0

    def state_dict(self):
        import numpy as np
        import jax

        flat = {}
        if self.opt_state is not None:
            for i, leaf in enumerate(jax.tree_util.tree_leaves(self.opt_state)):
                flat[str(i)] = np.asarray(jax.device_get(leaf))
        return flat

    def load_state_dict(self, state: dict):
        import jax

        if self.opt_state is None:
            self._build()
        leaves, treedef = jax.tree_util.tree_flatten(self.opt_state)
        new_leaves = [state[str(i)] for i in range(len(leaves))]
        self.opt_state = jax.tree_util.tree_unflatten(treedef, new_leaves)
