"""torch.fx graph → pure JAX function.

This is the TPU-native answer to the reference's ``prepare_model``
(``/root/reference/src/accelerate/accelerator.py:1735``): instead of wrapping the
``nn.Module`` for DDP/FSDP execution *in torch*, the module's computation is
traced once with ``torch.fx`` (HuggingFace's tracer when available, so
transformers models trace cleanly) and re-expressed as a pure jnp/lax function
over a params pytree. XLA then owns the whole hot path — fusion, sharding
(GSPMD), collectives — and torch never executes per-step.

The op tables below cover the surface actually emitted by transformers
encoder/decoder models and torchvision-style convnets (Linear/Embedding/
LayerNorm/Conv/BatchNorm/pooling modules; sdpa, masks, shape ops). Lowering is
interpretation: at jit-trace time we walk the fx graph node-by-node, so shapes
stay static and XLA sees one flat computation.
"""

from __future__ import annotations

import math
import operator
from typing import Any, Callable

__all__ = ["lower_module", "LoweringError"]


class LoweringError(RuntimeError):
    """A torch op with no JAX lowering — the message names the op so users can
    extend the table or supply a handwritten ``jax_forward``."""


# ---------------------------------------------------------------------------
# dtype mapping


def _dtype_table():
    import jax.numpy as jnp
    import torch

    return {
        torch.float32: jnp.float32,
        torch.float64: jnp.float64,
        torch.float16: jnp.float16,
        torch.bfloat16: jnp.bfloat16,
        torch.int64: jnp.int64,
        torch.int32: jnp.int32,
        torch.int16: jnp.int16,
        torch.int8: jnp.int8,
        torch.uint8: jnp.uint8,
        torch.bool: jnp.bool_,
    }


def _to_jnp_dtype(dtype):
    import torch

    if isinstance(dtype, torch.dtype):
        table = _dtype_table()
        if dtype not in table:
            raise LoweringError(f"no jnp equivalent for torch dtype {dtype}")
        return table[dtype]
    return dtype


class _Finfo:
    """``torch.finfo(dtype)`` stand-in with the fields mask code touches."""

    def __init__(self, dtype):
        import numpy as np
        import ml_dtypes

        jnp_dtype = _to_jnp_dtype(dtype)
        info = (
            ml_dtypes.finfo(jnp_dtype)
            if str(np.dtype(jnp_dtype)) == "bfloat16"
            else np.finfo(np.dtype(jnp_dtype))
        )
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)


# ---------------------------------------------------------------------------
# shared op helpers


def _normalize_dims(args):
    """torch packs shapes as varargs OR a single tuple/list."""
    if len(args) == 1 and isinstance(args[0], (tuple, list)):
        return tuple(args[0])
    return tuple(args)


def _scaled_dot_product_attention(
    q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, enable_gqa=False, *, ctx=None
):
    import jax.numpy as jnp

    if enable_gqa and q.shape[-3] != k.shape[-3]:
        rep = q.shape[-3] // k.shape[-3]
        k = jnp.repeat(k, rep, axis=-3)
        v = jnp.repeat(v, rep, axis=-3)
    head_dim = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(head_dim)
    logits = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    neg = jnp.finfo(jnp.float32).min
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, neg)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    if is_causal:
        qlen, klen = q.shape[-2], k.shape[-2]
        causal = jnp.tril(jnp.ones((qlen, klen), dtype=bool), k=klen - qlen)
        logits = jnp.where(causal, logits, neg)
    weights = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    weights = weights.astype(q.dtype)
    if ctx is not None and ctx.train and dropout_p:
        weights = ctx.dropout(weights, dropout_p)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


def _cross_entropy(logits, labels, ignore_index=-100, *, reduction="mean"):
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    logz = jnp.log(jnp.sum(jnp.exp(logits - jnp.max(logits, -1, keepdims=True)), -1)) + jnp.max(
        logits, -1
    )
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    nll = logz - jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    if reduction == "mean":
        return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1)
    if reduction == "sum":
        return jnp.sum(nll)
    return nll


def _conv2d(x, weight, bias, stride, padding, dilation, groups):
    import jax.lax as lax

    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = (padding, padding) if isinstance(padding, int) else tuple(padding)
        pad = [(p[0], p[0]), (p[1], p[1])]
    out = lax.conv_general_dilated(
        x,
        weight,
        window_strides=stride,
        padding=pad,
        rhs_dilation=dilation,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
    )
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


# ---------------------------------------------------------------------------
# interpreter


class _Ctx:
    """Per-call interpreter context: train-mode flag + dropout rng stream."""

    def __init__(self, train: bool, rng):
        self.train = train
        self.rng = rng
        self._counter = 0

    def dropout(self, x, p, return_mask: bool = False):
        import jax
        import jax.numpy as jnp

        if not self.train or p == 0.0 or self.rng is None:
            # inactive (eval / p=0 / deterministic-train mode): identity
            return (x, jnp.ones(x.shape, bool)) if return_mask else x
        key = jax.random.fold_in(self.rng, self._counter)
        self._counter += 1
        keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
        out = jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))
        return (out, keep) if return_mask else out


def _module_handlers() -> dict[str, Callable]:
    import jax.numpy as jnp
    import jax.lax as lax
    import jax.nn as jnn

    def linear(mod, p, ctx, x):
        w = p["weight"]
        out = x @ w.T
        return out + p["bias"] if "bias" in p else out

    def embedding(mod, p, ctx, ids):
        # torch's padding_idx only freezes that row's *gradient*; the forward is
        # a plain lookup (the row is zero-initialized), so lower it as one
        return jnp.take(p["weight"], ids, axis=0)

    def layer_norm(mod, p, ctx, x):
        axes = tuple(range(x.ndim - len(mod.normalized_shape), x.ndim))
        mean = jnp.mean(x.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(x.astype(jnp.float32), axis=axes, keepdims=True)
        out = (x.astype(jnp.float32) - mean) / jnp.sqrt(var + mod.eps)
        if "weight" in p:
            out = out * p["weight"] + p.get("bias", 0.0)
        return out.astype(x.dtype)

    def dropout(mod, p, ctx, x):
        return ctx.dropout(x, mod.p)

    def cross_entropy_loss(mod, p, ctx, logits, labels):
        return _cross_entropy(
            logits, labels, ignore_index=mod.ignore_index, reduction=mod.reduction
        )

    def conv2d(mod, p, ctx, x):
        return _conv2d(
            x, p["weight"], p.get("bias"), mod.stride, mod.padding, mod.dilation, mod.groups
        )

    def batch_norm2d(mod, p, ctx, x):
        # KNOWN LIMITATION: running_mean/var are NOT updated during bridged
        # training (the lowered fn is pure). Train mode uses batch statistics;
        # eval uses whatever the torch module's buffers held at lowering time.
        # Fine for inference bridging and for short fine-tunes evaluated in
        # train mode; full BN-train support needs a buffers-out signature.
        if ctx.train and mod.training_stats_in_train:
            mean = jnp.mean(x, axis=(0, 2, 3))
            var = jnp.var(x, axis=(0, 2, 3))
        else:
            mean, var = p["running_mean"], p["running_var"]
        out = (x - mean[None, :, None, None]) / jnp.sqrt(var[None, :, None, None] + mod.eps)
        if "weight" in p:
            out = out * p["weight"][None, :, None, None] + p["bias"][None, :, None, None]
        return out

    def max_pool2d(mod, p, ctx, x):
        k = (mod.kernel_size,) * 2 if isinstance(mod.kernel_size, int) else tuple(mod.kernel_size)
        s = mod.stride or mod.kernel_size
        s = (s, s) if isinstance(s, int) else tuple(s)
        pad = (mod.padding, mod.padding) if isinstance(mod.padding, int) else tuple(mod.padding)
        return lax.reduce_window(
            x,
            -jnp.inf,
            lax.max,
            (1, 1) + k,
            (1, 1) + s,
            [(0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])],
        )

    def adaptive_avg_pool2d(mod, p, ctx, x):
        size = mod.output_size
        size = (size, size) if isinstance(size, int) else tuple(size)
        if size != (1, 1):
            raise LoweringError("AdaptiveAvgPool2d only lowered for output_size=1")
        return jnp.mean(x, axis=(2, 3), keepdims=True)

    def flatten(mod, p, ctx, x):
        return jnp.reshape(x, x.shape[: mod.start_dim] + (-1,))

    def act(fn):
        return lambda mod, p, ctx, x: fn(x)

    return {
        "Linear": linear,
        "Embedding": embedding,
        "LayerNorm": layer_norm,
        "Dropout": dropout,
        "CrossEntropyLoss": cross_entropy_loss,
        "Conv2d": conv2d,
        "BatchNorm2d": batch_norm2d,
        "MaxPool2d": max_pool2d,
        "AdaptiveAvgPool2d": adaptive_avg_pool2d,
        "Flatten": flatten,
        "Identity": act(lambda x: x),
        "Tanh": act(jnp.tanh),
        "ReLU": act(jnn.relu),
        "GELU": act(jnn.gelu),
        "SiLU": act(jnn.silu),
        "Sigmoid": act(jnn.sigmoid),
        "Softmax": lambda mod, p, ctx, x: jnn.softmax(x, axis=mod.dim if mod.dim is not None else -1),
        "NewGELUActivation": act(lambda x: jnn.gelu(x, approximate=True)),
        "GELUActivation": act(jnn.gelu),
        "PytorchGELUTanh": act(lambda x: jnn.gelu(x, approximate=True)),
    }


def _function_handlers() -> dict[str, Callable]:
    import jax.numpy as jnp
    import jax.nn as jnn

    def _getattr(ctx, obj, name, *default):
        if name == "shape":
            return obj.shape
        if name == "dtype":
            return obj.dtype
        if name == "device":
            return "jax"
        return getattr(obj, name, *default)

    def _to_tensor(ctx, data, dtype=None, device=None, **kw):
        return jnp.asarray(data, dtype=_to_jnp_dtype(dtype) if dtype is not None else None)

    def _arange(ctx, *args, dtype=None, device=None, **kw):
        return jnp.arange(*args, dtype=_to_jnp_dtype(dtype) if dtype is not None else None)

    def _full(ctx, size, fill, dtype=None, device=None, **kw):
        return jnp.full(tuple(size), fill, dtype=_to_jnp_dtype(dtype) if dtype is not None else None)

    def _like(fn):
        def h(ctx, x, dtype=None, device=None, **kw):
            return fn(x, dtype=_to_jnp_dtype(dtype) if dtype is not None else None)

        return h

    def _dropout_fn(ctx, x, p=0.5, training=True, inplace=False):
        return ctx.dropout(x, p) if training else x

    def _softmax(ctx, x, dim=-1, **kw):
        return jnn.softmax(x, axis=dim)

    def _cat(ctx, tensors, dim=0):
        return jnp.concatenate(tensors, axis=dim)

    def _stack(ctx, tensors, dim=0):
        return jnp.stack(tensors, axis=dim)

    def _einsum(ctx, eq, *ops):
        if len(ops) == 1 and isinstance(ops[0], (tuple, list)):
            ops = tuple(ops[0])
        return jnp.einsum(eq, *ops)

    def binop(fn):
        return lambda ctx, a, b, **kw: fn(a, b)

    def unop(fn):
        return lambda ctx, x, **kw: fn(x)

    table: dict[str, Callable] = {
        "add": binop(operator.add),
        "sub": binop(operator.sub),
        "mul": binop(operator.mul),
        "truediv": binop(operator.truediv),
        "div": binop(operator.truediv),
        "floordiv": binop(operator.floordiv),
        "mod": binop(operator.mod),
        "pow": binop(operator.pow),
        "matmul": binop(operator.matmul),
        "bmm": binop(operator.matmul),
        "eq": binop(operator.eq),
        "ne": binop(operator.ne),
        "lt": binop(operator.lt),
        "le": binop(operator.le),
        "gt": binop(operator.gt),
        "ge": binop(operator.ge),
        "and_": binop(operator.and_),
        "or_": binop(operator.or_),
        "getitem": binop(operator.getitem),
        "neg": unop(operator.neg),
        "invert": unop(operator.invert),
        "getattr": _getattr,
        "finfo": lambda ctx, dtype: _Finfo(dtype),
        "tensor": _to_tensor,
        "as_tensor": _to_tensor,
        "arange": _arange,
        "full": _full,
        "ones": lambda ctx, *a, dtype=None, device=None, **kw: jnp.ones(
            _normalize_dims(a), dtype=_to_jnp_dtype(dtype) if dtype else None
        ),
        "zeros": lambda ctx, *a, dtype=None, device=None, **kw: jnp.zeros(
            _normalize_dims(a), dtype=_to_jnp_dtype(dtype) if dtype else None
        ),
        "ones_like": _like(jnp.ones_like),
        "zeros_like": _like(jnp.zeros_like),
        "full_like": lambda ctx, x, fill, dtype=None, **kw: jnp.full_like(
            x, fill, dtype=_to_jnp_dtype(dtype) if dtype else None
        ),
        "where": lambda ctx, c, a=None, b=None: jnp.where(c, a, b) if a is not None else jnp.where(c),
        "clamp": lambda ctx, x, min=None, max=None: jnp.clip(x, min, max),
        "rsqrt": unop(lambda x: 1.0 / jnp.sqrt(x)),
        "sqrt": unop(jnp.sqrt),
        "exp": unop(jnp.exp),
        "log": unop(jnp.log),
        "sin": unop(jnp.sin),
        "cos": unop(jnp.cos),
        "abs": unop(jnp.abs),
        "erf": unop(lambda x: __import__("jax").scipy.special.erf(x)),
        "mean": lambda ctx, x, dim=None, keepdim=False, **kw: jnp.mean(x, axis=dim, keepdims=keepdim),
        "sum": lambda ctx, x, dim=None, keepdim=False, **kw: jnp.sum(x, axis=dim, keepdims=keepdim),
        "cumsum": lambda ctx, x, dim=-1, **kw: jnp.cumsum(x, axis=dim),
        "argmax": lambda ctx, x, dim=None, keepdim=False: jnp.argmax(x, axis=dim),
        "softmax": _softmax,
        "log_softmax": lambda ctx, x, dim=-1, **kw: jnn.log_softmax(x, axis=dim),
        "relu": unop(jnn.relu),
        "gelu": lambda ctx, x, approximate="none": jnn.gelu(x, approximate=approximate != "none"),
        "tanh": unop(jnp.tanh),
        "sigmoid": unop(jnn.sigmoid),
        "silu": unop(jnn.silu),
        "dropout": _dropout_fn,
        "cat": _cat,
        "concat": _cat,
        "stack": _stack,
        "einsum": _einsum,
        "flatten": lambda ctx, x, start_dim=0, end_dim=-1: _flatten(x, start_dim, end_dim),
        "transpose": lambda ctx, x, a, b: jnp.swapaxes(x, a, b),
        "permute": lambda ctx, x, *dims: jnp.transpose(x, _normalize_dims(dims)),
        "unsqueeze": lambda ctx, x, dim: jnp.expand_dims(x, dim),
        "squeeze": lambda ctx, x, dim=None: jnp.squeeze(x, axis=dim),
        "scaled_dot_product_attention": lambda ctx, *a, **kw: _scaled_dot_product_attention(
            *a, **kw, ctx=ctx
        ),
        "cross_entropy": lambda ctx, logits, labels, ignore_index=-100, reduction="mean", **kw: (
            _cross_entropy(logits, labels, ignore_index=ignore_index, reduction=reduction)
        ),
        "embedding": lambda ctx, ids, weight, padding_idx=None, **kw: jnp.take(weight, ids, axis=0),
        "linear": lambda ctx, x, w, b=None: (x @ w.T + b) if b is not None else x @ w.T,
        "layer_norm": lambda ctx, x, shape, weight=None, bias=None, eps=1e-5: _layer_norm_fn(
            x, shape, weight, bias, eps
        ),
        "masked_fill": lambda ctx, x, mask, value: jnp.where(mask, value, x),
        "repeat_interleave": lambda ctx, x, repeats, dim=None, **kw: jnp.repeat(x, repeats, axis=dim),
        "split": lambda ctx, x, size, dim=0: _split(x, size, dim),
        "chunk": lambda ctx, x, chunks, dim=0: tuple(jnp.array_split(x, chunks, axis=dim)),
        "type_as": lambda ctx, x, other: x.astype(other.dtype),
        "contiguous": unop(lambda x: x),
        "clone": unop(lambda x: x),
        "detach": unop(lambda x: x),
    }
    return table


def _flatten(x, start_dim=0, end_dim=-1):
    import jax.numpy as jnp

    nd = x.ndim
    start = start_dim % nd
    end = end_dim % nd
    return jnp.reshape(x, x.shape[:start] + (-1,) + x.shape[end + 1 :])


def _layer_norm_fn(x, shape, weight, bias, eps):
    import jax.numpy as jnp

    axes = tuple(range(x.ndim - len(shape), x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + eps)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def _split(x, size, dim):
    import jax.numpy as jnp

    if isinstance(size, int):
        n = x.shape[dim]
        points = list(range(size, n, size))
    else:
        points, acc = [], 0
        for s in size[:-1]:
            acc += s
            points.append(acc)
    return tuple(jnp.split(x, points, axis=dim))


def _method_handlers() -> dict[str, Callable]:
    import jax.numpy as jnp

    fns = _function_handlers()
    extra = {
        "dim": lambda ctx, x: x.ndim,
        "size": lambda ctx, x, d=None: x.shape if d is None else x.shape[d],
        "numel": lambda ctx, x: int(x.size),
        "view": lambda ctx, x, *shape: jnp.reshape(x, _normalize_dims(shape)),
        "reshape": lambda ctx, x, *shape: jnp.reshape(x, _normalize_dims(shape)),
        "expand": lambda ctx, x, *sizes: _expand(x, _normalize_dims(sizes)),
        "expand_as": lambda ctx, x, other: jnp.broadcast_to(x, other.shape),
        "repeat": lambda ctx, x, *reps: jnp.tile(x, _normalize_dims(reps)),
        "to": _method_to,
        "float": lambda ctx, x: x.astype(jnp.float32),
        "half": lambda ctx, x: x.astype(jnp.float16),
        "long": lambda ctx, x: x.astype(jnp.int64),
        "int": lambda ctx, x: x.astype(jnp.int32),
        "bool": lambda ctx, x: x.astype(jnp.bool_),
        "item": lambda ctx, x: x,  # stays traced; concretized by the caller
        "t": lambda ctx, x: x.T,
        "masked_fill": fns["masked_fill"],
        "masked_fill_": fns["masked_fill"],
    }
    table = dict(fns)
    table.update(extra)
    return table


def _expand(x, sizes):
    import jax.numpy as jnp

    sizes = tuple(
        x.shape[i - (len(sizes) - x.ndim)] if s == -1 else s for i, s in enumerate(sizes)
    )
    return jnp.broadcast_to(x, sizes)


def _method_to(ctx, x, *args, **kwargs):
    import torch

    for a in list(args) + list(kwargs.values()):
        if isinstance(a, torch.dtype):
            return x.astype(_to_jnp_dtype(a))
        if hasattr(a, "dtype") and not isinstance(a, (str,)):
            return x.astype(a.dtype)
    return x  # device-only move: placement is GSPMD's job


def _plain_containers(obj):
    """fx emits immutable_dict/immutable_list containers, which are not JAX
    pytree types — rebuild as plain dict/list/tuple."""
    if isinstance(obj, dict):
        return {k: _plain_containers(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_plain_containers(v) for v in obj)
    if isinstance(obj, list):
        return [_plain_containers(v) for v in obj]
    return obj


# ---------------------------------------------------------------------------
# tracing + interpretation


import contextlib


@contextlib.contextmanager
def _traceable_masking():
    """Replace transformers' vmap-based mask builders with fx-traceable
    equivalents for the duration of a trace.

    ``transformers.masking_utils.create_causal_mask`` (4.5x) builds masks via
    ``torch.vmap`` over index functions — untraceable by fx (proxies are not
    vmap-able), which kills symbolic_trace for every decoder (GPT-2, Llama,
    ...). The reference patches this same function for its CP hooks
    (``/root/reference/src/accelerate/big_modeling.py:769-783``); here we swap
    in a plain triu-based additive mask, semantically equal for the standard
    causal + padding case.
    """
    try:
        import torch
        from transformers import masking_utils
    except ImportError:
        yield
        return

    def _causal(config=None, input_embeds=None, attention_mask=None, cache_position=None,
                past_key_values=None, position_ids=None, or_mask_function=None,
                and_mask_function=None, **kw):
        if or_mask_function is not None or and_mask_function is not None:
            return orig_causal(
                config=config, input_embeds=input_embeds, attention_mask=attention_mask,
                cache_position=cache_position, past_key_values=past_key_values,
                position_ids=position_ids, or_mask_function=or_mask_function,
                and_mask_function=and_mask_function, **kw,
            )
        seq = input_embeds.shape[1]
        dtype = input_embeds.dtype
        neg = torch.finfo(dtype).min
        mask = torch.full((seq, seq), neg, dtype=dtype).triu(1)[None, None]
        if attention_mask is not None:
            pad = (1.0 - attention_mask[:, None, None, :].to(dtype)) * neg
            mask = mask + pad
        return mask

    def _bidirectional(config=None, input_embeds=None, attention_mask=None, **kw):
        if attention_mask is None:
            return None
        dtype = input_embeds.dtype
        return (1.0 - attention_mask[:, None, None, :].to(dtype)) * torch.finfo(dtype).min

    patches = {}
    orig_causal = getattr(masking_utils, "create_causal_mask", None)
    for name, repl in (("create_causal_mask", _causal),
                       ("create_bidirectional_mask", _bidirectional)):
        if hasattr(masking_utils, name):
            patches[name] = getattr(masking_utils, name)
            setattr(masking_utils, name, repl)
    # model modules import these by name; patch their module globals too
    import sys

    module_patches = []
    for mod_name, mod in list(sys.modules.items()):
        if not mod_name.startswith("transformers.models."):
            continue
        for name, repl in (("create_causal_mask", _causal),
                           ("create_bidirectional_mask", _bidirectional)):
            if getattr(mod, name, None) is patches.get(name) and patches.get(name) is not None:
                module_patches.append((mod, name, getattr(mod, name)))
                setattr(mod, name, repl)
    try:
        yield
    finally:
        for name, orig in patches.items():
            setattr(masking_utils, name, orig)
        for mod, name, orig in module_patches:
            setattr(mod, name, orig)


def _trace(model, input_names):
    import torch.fx

    with _traceable_masking():
        try:
            from transformers.utils import fx as hf_fx

            try:
                return hf_fx.symbolic_trace(model, input_names=list(input_names))
            except Exception:
                pass
        except ImportError:
            pass
        return torch.fx.symbolic_trace(model)


def _collect_module_meta(gm):
    """Snapshot the python-scalar hyperparams the handlers need so the returned
    fn doesn't hold the live torch modules."""

    class Meta:
        pass

    meta = {}
    for name, sub in gm.named_modules():
        m = Meta()
        for attr in (
            "p", "eps", "dim", "padding_idx", "ignore_index", "reduction", "normalized_shape",
            "stride", "padding", "dilation", "groups", "kernel_size", "output_size",
            "start_dim", "end_dim", "inplace", "approximate",
        ):
            if hasattr(sub, attr):
                val = getattr(sub, attr)
                if isinstance(val, (int, float, str, bool, tuple, list)) or val is None:
                    setattr(m, attr, val)
        m.type_name = type(sub).__name__
        m.training_stats_in_train = True
        meta[name] = m
    return meta


def lower_module(model, input_names):
    """Lower ``model`` (an ``nn.Module``) to ``(fn, params, buffers)``.

    ``fn(params, buffers, inputs, train=False, rng=None)`` is pure/jittable;
    ``inputs`` is a dict keyed like ``input_names``. Params/buffers are flat
    dot-path-keyed dicts of jax arrays (DLPack-shared from the module).
    """
    from .dlpack import module_params_to_jax

    was_training = model.training
    model.eval()  # trace without autograd bookkeeping; train diffs via ctx
    gm = _trace(model, input_names)
    model.train(was_training)

    params, buffers = module_params_to_jax(model)
    module_meta = _collect_module_meta(gm)
    mod_handlers = _module_handlers()
    fn_handlers = _function_handlers()
    method_handlers = _method_handlers()
    nodes = list(gm.graph.nodes)

    # per-module param-name suffixes, resolved once
    module_param_names: dict[str, list[str]] = {}
    for full in list(params) + list(buffers):
        prefix, _, leaf = full.rpartition(".")
        module_param_names.setdefault(prefix, []).append(leaf)

    import torch.fx

    def fn(params, buffers, inputs, train: bool = False, rng=None):
        import jax.numpy as jnp

        ctx = _Ctx(train, rng)
        env: dict = {}

        def lookup(n):
            return env[n.name]

        for node in nodes:
            if node.op == "placeholder":
                if node.target in inputs:
                    val = inputs[node.target]
                    val = jnp.asarray(val) if not hasattr(val, "dtype") else val
                else:
                    val = node.args[0] if node.args else None
            elif node.op == "get_attr":
                if node.target in buffers:
                    val = buffers[node.target]
                elif node.target in params:
                    val = params[node.target]
                else:
                    raise LoweringError(f"get_attr target {node.target!r} not found")
            elif node.op == "call_module":
                meta = module_meta[node.target]
                handler = mod_handlers.get(meta.type_name)
                if handler is None:
                    raise LoweringError(f"no lowering for module type {meta.type_name}")
                sub_params = {
                    leaf: (params.get(f"{node.target}.{leaf}") if f"{node.target}.{leaf}" in params
                           else buffers.get(f"{node.target}.{leaf}"))
                    for leaf in module_param_names.get(node.target, [])
                }
                args = torch.fx.node.map_arg(node.args, lookup)
                kwargs = torch.fx.node.map_arg(node.kwargs, lookup)
                val = handler(meta, sub_params, ctx, *args, **kwargs)
            elif node.op in ("call_function", "call_method"):
                if node.op == "call_function":
                    name = getattr(node.target, "__name__", str(node.target))
                    handler = fn_handlers.get(name)
                else:
                    name = node.target
                    handler = method_handlers.get(name)
                if handler is None:
                    raise LoweringError(f"no lowering for {node.op} {name!r}")
                args = torch.fx.node.map_arg(node.args, lookup)
                kwargs = torch.fx.node.map_arg(node.kwargs, lookup)
                val = handler(ctx, *args, **kwargs)
            elif node.op == "output":
                return _plain_containers(torch.fx.node.map_arg(node.args[0], lookup))
            else:  # pragma: no cover
                raise LoweringError(f"unknown fx op {node.op}")
            env[node.name] = val
        raise LoweringError("fx graph had no output node")

    return fn, params, buffers
