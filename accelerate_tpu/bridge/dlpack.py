"""Zero-copy torch ↔ jax array exchange via DLPack.

The reference moves params with ``model.to(device)`` (``accelerator.py:1833``);
here the torch module's (host) storage is shared into JAX without a copy, then
``device_put`` with a ``NamedSharding`` is the single H2D hop that also shards
(the FSDP/TP "wrap" collapsed into placement — SURVEY.md §7).
"""

from __future__ import annotations

from typing import Any


def torch_tensor_to_numpy(tensor):
    """torch.Tensor → host numpy array, UNCOMMITTED (no jax device). bf16 goes
    through a bit-reinterpret (numpy itself has no bfloat16; ml_dtypes does).
    The one shared implementation for batch conversion (bridge/module.py) and
    HF-checkpoint conversion (models/convert.py)."""
    import torch

    t = tensor.detach()
    if t.device.type != "cpu":
        t = t.cpu()
    t = t.contiguous()
    if t.dtype == torch.bfloat16:
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def torch_to_jax(tensor):
    """torch.Tensor → jax.Array, zero-copy when host-resident and contiguous."""
    import jax
    import numpy as np
    import torch

    t = tensor.detach()
    if t.device.type != "cpu":
        t = t.cpu()
    if not t.is_contiguous():
        t = t.contiguous()
    if t.dtype == torch.bfloat16:
        # numpy has no bf16; DLPack handles it directly
        return jax.numpy.asarray(jax.dlpack.from_dlpack(t))
    try:
        return jax.dlpack.from_dlpack(t)
    except Exception:
        return jax.numpy.asarray(np.asarray(t))


def jax_to_torch(array):
    """jax.Array → torch.Tensor (zero-copy for host arrays, else D2H copy)."""
    import jax
    import numpy as np
    import torch

    array = jax.device_get(array) if not isinstance(array, np.ndarray) else array
    try:
        return torch.from_dlpack(array)
    except Exception:
        return torch.from_numpy(np.ascontiguousarray(array))


def module_params_to_jax(module) -> tuple[dict[str, Any], dict[str, Any]]:
    """Extract ``(params, buffers)`` flat pytrees (dot-path keyed) from an
    ``nn.Module``, sharing storage via DLPack."""
    params = {name: torch_to_jax(p) for name, p in module.named_parameters()}
    buffers = {name: torch_to_jax(b) for name, b in module.named_buffers()}
    return params, buffers


def write_back_to_module(module, params: dict[str, Any], buffers: dict[str, Any] | None = None) -> None:
    """Copy (possibly sharded) jax params — and live buffers such as BN running
    stats — back into the torch module in-place, used before torch-side
    save/export (reference ``get_state_dict:3947``)."""
    import torch

    torch_params = dict(module.named_parameters())
    torch_buffers = dict(module.named_buffers())
    with torch.no_grad():
        for name, value in params.items():
            if name in torch_params:
                torch_params[name].copy_(jax_to_torch(value).to(torch_params[name].dtype))
        for name, value in (buffers or {}).items():
            if name in torch_buffers:
                target = torch_buffers[name]
                t = jax_to_torch(value).to(target.dtype).reshape(target.shape)
                target.copy_(t)
