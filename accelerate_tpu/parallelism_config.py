"""N-D parallelism configuration → JAX device mesh.

TPU-native counterpart of the reference's ``parallelism_config.py``
(``/root/reference/src/accelerate/parallelism_config.py:33-386``): same canonical
axis order ``("dp_replicate", "dp_shard", "cp", "sp", "tp")`` (``:262``, torchtitan
convention), same flattened joint axes ``dp``, ``dp_shard_cp``, ``dp_cp``
(``build_device_mesh :211-239``), same total-size == world-size validation
(``_validate_accelerator :350-386``), plus first-class ``ep`` and ``pp`` axes (the
reference only reaches expert/pipeline parallelism through Megatron/DeepSpeed/PiPPy
engines). ``pp`` is outermost: stages are the natural unit to place across slices.

On TPU the mesh maps onto the physical interconnect: inner (rightmost) axes ride
ICI; outer axes are the ones to place across DCN slices — ``pp`` first (stage
boundaries cross slices with one activation transfer per microbatch), then
``dp_replicate`` (one param-sized allreduce per step). Device order comes from
``mesh_utils.create_device_mesh`` so collectives ride ICI rings.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# Canonical axis order — mirror of reference parallelism_config.py:262.
MESH_AXIS_NAMES = ("pp", "dp_replicate", "dp_shard", "cp", "sp", "tp", "ep")

# Flattened logical axes: PartitionSpec accepts tuples of mesh axis names, so the
# reference's flattened sub-meshes (``dp``, ``dp_shard_cp``, ``dp_cp``) become spec
# aliases rather than separately-constructed meshes.
DP_AXES = ("dp_replicate", "dp_shard")
DP_SHARD_CP_AXES = ("dp_shard", "cp")
DP_CP_AXES = ("dp_replicate", "dp_shard", "cp")
BATCH_AXES = ("dp_replicate", "dp_shard", "cp", "sp")  # axes a global batch is split over


@dataclass
class ParallelismConfig:
    """Sizes for each mesh axis. ``dp_shard_size=-1`` infers from the device count.

    Mirrors reference ``ParallelismConfig`` fields (``parallelism_config.py:61-66``):
    dp_replicate/dp_shard/cp/sp/tp, with ``ep`` added. ``cp_rotate_method`` mirrors
    ``TorchContextParallelConfig.set_rotate_method`` (``utils/dataclasses.py:2186``):
    ``"allgather"`` gathers KV once, ``"ring"`` (= reference ``alltoall``) rotates KV
    blocks with ``lax.ppermute``.
    """

    pp_size: int = 1
    dp_replicate_size: int = 1
    dp_shard_size: int = 1
    cp_size: int = 1
    sp_size: int = 1
    tp_size: int = 1
    ep_size: int = 1
    cp_rotate_method: str = "allgather"  # "allgather" | "ring" | "zigzag"

    def __post_init__(self):
        for name in ("pp_size", "dp_replicate_size", "cp_size", "sp_size", "tp_size", "ep_size"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1, got {getattr(self, name)}")
        if self.dp_shard_size == 0 or self.dp_shard_size < -1:
            raise ValueError(f"dp_shard_size must be -1 (infer) or >= 1, got {self.dp_shard_size}")
        if self.cp_size > 1 and self.sp_size > 1:
            # Reference makes CP and SP mutually exclusive (parallelism_config.py:323-329).
            raise ValueError("cp_size and sp_size cannot both be > 1 (pick ring-CP or Ulysses-SP)")
        if self.cp_rotate_method not in ("allgather", "ring", "zigzag"):
            raise ValueError(
                f"cp_rotate_method must be 'allgather', 'ring' or 'zigzag', got {self.cp_rotate_method}"
            )

    # -- size/enabled properties (reference parallelism_config.py properties) ----
    @property
    def non_dp_shard_size(self) -> int:
        return (self.pp_size * self.dp_replicate_size * self.cp_size * self.sp_size
                * self.tp_size * self.ep_size)

    def infer_dp_shard(self, num_devices: int) -> int:
        if self.dp_shard_size != -1:
            return self.dp_shard_size
        rest = self.non_dp_shard_size
        if num_devices % rest != 0:
            raise ValueError(
                f"cannot infer dp_shard_size: {num_devices} devices not divisible by "
                f"product of other axes {rest}"
            )
        return num_devices // rest

    def total_size(self, num_devices: Optional[int] = None) -> int:
        dp_shard = self.dp_shard_size
        if dp_shard == -1:
            if num_devices is None:
                raise ValueError("dp_shard_size=-1 needs num_devices to infer")
            dp_shard = self.infer_dp_shard(num_devices)
        return self.non_dp_shard_size * dp_shard

    @property
    def dp_enabled(self) -> bool:
        return self.dp_replicate_size > 1 or self.dp_shard_size == -1 or self.dp_shard_size > 1

    @property
    def fsdp_enabled(self) -> bool:
        return self.dp_shard_size == -1 or self.dp_shard_size > 1

    @property
    def hsdp_enabled(self) -> bool:
        return self.fsdp_enabled and self.dp_replicate_size > 1

    @property
    def tp_enabled(self) -> bool:
        return self.tp_size > 1

    @property
    def cp_enabled(self) -> bool:
        return self.cp_size > 1

    @property
    def sp_enabled(self) -> bool:
        return self.sp_size > 1

    @property
    def ep_enabled(self) -> bool:
        return self.ep_size > 1

    @property
    def pp_enabled(self) -> bool:
        return self.pp_size > 1

    # -- env protocol (reference parallelism_config.py:269-284 reads
    #    PARALLELISM_CONFIG_* written by utils/launch.py:396-420) ---------------
    @classmethod
    def from_env(cls) -> "ParallelismConfig":
        def _get(name: str, default: int) -> int:
            return int(os.environ.get(f"PARALLELISM_CONFIG_{name}", default))

        return cls(
            pp_size=_get("PP_SIZE", 1),
            dp_replicate_size=_get("DP_REPLICATE_SIZE", 1),
            dp_shard_size=_get("DP_SHARD_SIZE", 1),
            cp_size=_get("CP_SIZE", 1),
            sp_size=_get("SP_SIZE", 1),
            tp_size=_get("TP_SIZE", 1),
            ep_size=_get("EP_SIZE", 1),
            cp_rotate_method=os.environ.get("PARALLELISM_CONFIG_CP_ROTATE_METHOD", "allgather"),
        )

    def to_env(self) -> dict[str, str]:
        return {
            "PARALLELISM_CONFIG_PP_SIZE": str(self.pp_size),
            "PARALLELISM_CONFIG_DP_REPLICATE_SIZE": str(self.dp_replicate_size),
            "PARALLELISM_CONFIG_DP_SHARD_SIZE": str(self.dp_shard_size),
            "PARALLELISM_CONFIG_CP_SIZE": str(self.cp_size),
            "PARALLELISM_CONFIG_SP_SIZE": str(self.sp_size),
            "PARALLELISM_CONFIG_TP_SIZE": str(self.tp_size),
            "PARALLELISM_CONFIG_EP_SIZE": str(self.ep_size),
            "PARALLELISM_CONFIG_CP_ROTATE_METHOD": self.cp_rotate_method,
        }

    # -- mesh construction (reference build_device_mesh :211-239) ---------------
    def mesh_shape(self, num_devices: int) -> tuple[int, ...]:
        dp_shard = self.infer_dp_shard(num_devices)
        shape = (
            self.pp_size,
            self.dp_replicate_size,
            dp_shard,
            self.cp_size,
            self.sp_size,
            self.tp_size,
            self.ep_size,
        )
        total = int(np.prod(shape))
        if total != num_devices:
            raise ValueError(
                f"mesh {dict(zip(MESH_AXIS_NAMES, shape))} has size {total} but "
                f"{num_devices} devices are available"
            )
        return shape

    # -- multi-slice (DCN) topology ---------------------------------------------
    @staticmethod
    def _num_slices(devices) -> int:
        """Distinct ``slice_index`` values across ``devices`` (1 when the
        attribute is absent — single-slice or CPU/virtual devices)."""
        ids = {getattr(d, "slice_index", None) for d in devices}
        return 1 if None in ids else len(ids)

    def dcn_mesh_shapes(
        self, num_devices: int, num_slices: int
    ) -> "tuple[tuple[int, ...], tuple[int, ...]]":
        """Factor the global mesh into ``(per_slice_shape, dcn_shape)``.

        The DCN factor lands on the OUTERMOST axes first — ``pp`` (one
        activation transfer per microbatch crosses the slice boundary), then
        ``dp_replicate`` (one param-sized allreduce per step) — exactly the
        placement the reference's multi-node rendezvous achieves by rank
        ordering (``/root/reference/src/accelerate/state.py:753-812``); inner
        axes (dp_shard/cp/sp/tp/ep) stay intra-slice on ICI.
        ``ACCELERATE_DCN_MESH_SHAPE`` (comma-separated 7-tuple in
        ``MESH_AXIS_NAMES`` order) overrides the factorization, e.g. to push
        ``dp_shard`` across DCN when cross-slice FSDP is intended.
        """
        shape = self.mesh_shape(num_devices)
        explicit = os.environ.get("ACCELERATE_DCN_MESH_SHAPE", "").strip()
        if explicit:
            dcn = tuple(int(x) for x in explicit.split(","))
            if len(dcn) != len(shape):
                raise ValueError(
                    f"ACCELERATE_DCN_MESH_SHAPE needs {len(shape)} comma-separated sizes "
                    f"(axes {MESH_AXIS_NAMES}), got {explicit!r}"
                )
        else:
            import math

            dcn_list = [1] * len(shape)
            remaining = num_slices
            for idx in (0, 1):  # pp, dp_replicate — the DCN-tolerant axes
                if remaining == 1:
                    break
                f = math.gcd(shape[idx], remaining)
                dcn_list[idx] = f
                remaining //= f
            if remaining != 1:
                raise ValueError(
                    f"cannot place {num_slices} slices across the outer mesh axes: "
                    f"pp={shape[0]} x dp_replicate={shape[1]} does not absorb the slice "
                    f"count. Raise pp_size/dp_replicate_size to a multiple of the slice "
                    f"count, or set ACCELERATE_DCN_MESH_SHAPE to place another axis "
                    f"(e.g. dp_shard) across DCN explicitly."
                )
            dcn = tuple(dcn_list)
        if int(np.prod(dcn)) != num_slices:
            raise ValueError(
                f"dcn mesh shape {dcn} has size {int(np.prod(dcn))} but there are "
                f"{num_slices} slices"
            )
        bad = [
            MESH_AXIS_NAMES[i]
            for i, (s, d) in enumerate(zip(shape, dcn))
            if d < 1 or s % d != 0
        ]
        if bad:
            raise ValueError(
                f"dcn factor does not divide the mesh axis size for {bad} "
                f"(mesh {shape}, dcn {dcn})"
            )
        per_slice = tuple(s // d for s, d in zip(shape, dcn))
        return per_slice, dcn

    def build_mesh(self, devices=None):
        """Build a ``jax.sharding.Mesh`` with canonical axis names.

        Single-slice: device placement uses ``mesh_utils.create_device_mesh``
        so inner mesh axes map to physically-adjacent chips (ICI rings).
        Multi-slice (``slice_index`` differs across devices, e.g. a multislice
        TPU pod): ``mesh_utils.create_hybrid_device_mesh`` places the
        DCN-tolerant outer axes (``pp``, ``dp_replicate``) across slices and
        keeps the bandwidth-hungry inner axes on ICI — see
        :meth:`dcn_mesh_shapes`. ``ACCELERATE_HYBRID_MESH_GRANULE=process``
        treats processes (not slices) as the DCN unit, for platforms that
        don't expose ``slice_index``. Falls back to a plain reshape of device
        order (fine for CPU/virtual meshes).
        """
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        requested = self.total_size(len(devices))
        if requested > len(devices):
            raise ValueError(
                f"parallelism config needs {requested} devices but only {len(devices)} available"
            )
        if requested < len(devices):
            # run on a subset (single-chip debugging on a multi-chip host)
            devices = devices[:requested]
        shape = self.mesh_shape(len(devices))
        granule = os.environ.get("ACCELERATE_HYBRID_MESH_GRANULE", "slice").strip().lower()
        if granule == "process":
            num_slices = len({getattr(d, "process_index", 0) for d in devices})
        else:
            num_slices = self._num_slices(devices)
        try:
            from jax.experimental import mesh_utils

            if num_slices > 1:
                per_slice, dcn = self.dcn_mesh_shapes(len(devices), num_slices)
                device_array = mesh_utils.create_hybrid_device_mesh(
                    per_slice,
                    dcn,
                    devices=devices,
                    process_is_granule=(granule == "process"),
                    allow_split_physical_axes=True,
                )
            else:
                device_array = mesh_utils.create_device_mesh(
                    shape, devices=devices, allow_split_physical_axes=True
                )
        except (ValueError, NotImplementedError, AssertionError) as e:
            import warnings

            if num_slices > 1:
                # a multi-slice topology that cannot be factored must NOT be
                # silently flattened: a plain reshape would put tp/dp_shard
                # collectives on DCN, a silent order-of-magnitude slowdown
                raise
            warnings.warn(
                f"mesh_utils.create_device_mesh failed ({e}); falling back to plain "
                "device-order reshape — collectives may not ride optimal ICI rings.",
                stacklevel=2,
            )
            device_array = np.asarray(devices).reshape(shape)
        return Mesh(device_array, axis_names=MESH_AXIS_NAMES)

    def describe(self, num_devices: Optional[int] = None) -> str:
        if num_devices is not None:
            shape = self.mesh_shape(num_devices)
        else:
            shape = (
                self.pp_size,
                self.dp_replicate_size,
                self.dp_shard_size,
                self.cp_size,
                self.sp_size,
                self.tp_size,
                self.ep_size,
            )
        return " x ".join(f"{n}={s}" for n, s in zip(MESH_AXIS_NAMES, shape))


def get_1d_dp_config(num_devices: int) -> ParallelismConfig:
    """Pure data parallelism over every device (the reference's DDP default)."""
    return ParallelismConfig(dp_replicate_size=num_devices)


def get_fsdp_config(num_devices: int) -> ParallelismConfig:
    """Full parameter sharding over every device (reference FSDP full_shard)."""
    return ParallelismConfig(dp_shard_size=num_devices)
