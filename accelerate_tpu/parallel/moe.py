"""Expert parallelism: capacity-based MoE routing over the ``ep`` mesh axis.

TPU-native counterpart of the reference's expert-parallel reach-through
(SURVEY.md §2.2 EP row: Megatron ``expert_model_parallel_size`` /
``utils/launch.py:367-378`` and DeepSpeed MoE leaf modules
``accelerator.py:2244-2245`` — the reference has no in-repo MoE math; both
engines do CUDA all-to-all token routing).

Here routing is the Switch/GShard einsum formulation: top-k gating with a fixed
per-expert capacity, dispatch/combine as one-hot einsums, and expert weights
carrying a leading ``[E, ...]`` axis sharded over ``ep``
(``P('ep', None, ...)``). With tokens sharded over dp and experts over ep, XLA
lowers the dispatch einsum to the same all-to-all the engines hand-code — but
fused, overlapped, and differentiable. Static capacity keeps every shape fixed
(jit-friendly); dropped tokens pass through the residual, and the standard
load-balance auxiliary loss keeps the router honest.

Routing is *grouped* (GShard "groups"): tokens are split into fixed-size blocks
that each get their own capacity and intra-group cumsum, so dispatch memory is
``O(N · E · capacity_per_group)`` — linear in N — and the position-assignment
cumsum vectorizes over groups instead of serializing across the global batch.
"""

from __future__ import annotations

import numpy as np


def init_moe_ffn(key, d_model: int, d_ff: int, num_experts: int, dtype=None):
    """Params for an expert-parallel FFN: router + per-expert MLP stacks."""
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.float32
    k_r, k_i, k_o = jax.random.split(key, 3)
    scale_in = 1.0 / np.sqrt(d_model)
    scale_out = 1.0 / np.sqrt(d_ff)
    return {
        "router": {"kernel": (jax.random.normal(k_r, (d_model, num_experts)) * scale_in).astype(dtype)},
        "wi": {"kernel": (jax.random.normal(k_i, (num_experts, d_model, d_ff)) * scale_in).astype(dtype)},
        "wo": {"kernel": (jax.random.normal(k_o, (num_experts, d_ff, d_model)) * scale_out).astype(dtype)},
    }


def moe_shard_rules():
    """Sharding rules for MoE params: experts over ``ep``, router replicated.
    Compose with the model family's base rules (first match wins)."""
    from jax.sharding import PartitionSpec as P

    from .sharding import ShardingRules

    return ShardingRules(
        [
            (r"router/kernel", P()),
            (r"wi/kernel", P("ep", None, "tp")),
            (r"wo/kernel", P("ep", "tp", None)),
        ]
    )


def moe_ffn(
    params,
    x,
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    mesh=None,
    ep_axis: str = "ep",
    activation=None,
    group_size: int = 4096,
):
    """Mixture-of-experts FFN on ``x: [B, S, D]`` → ``(y, aux_loss)``.

    Tokens are routed in groups of ``group_size`` (each group has its own
    capacity ``ceil(top_k · cf · g / E)``), keeping dispatch memory linear in
    the token count. ``aux_loss`` is the GShard/Switch load-balance term
    ``E * Σ_e fraction_tokens(e) · mean_prob(e)`` — add it (scaled ~1e-2) to the
    training loss.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if activation is None:
        activation = jax.nn.gelu

    B, S, D = x.shape
    E = params["router"]["kernel"].shape[-1]
    N = B * S
    g = min(group_size, N)
    # shape-specialization is intended here: the divisor search runs at trace
    # time and the program is compiled per (B, S) bucket anyway
    while N % g != 0:  # shrink to a divisor; worst case g=1 never happens for 2^k shapes  # jaxlint: disable=R2
        g -= 1
    G = N // g
    capacity = max(int(np.ceil(top_k * capacity_factor * g / E)), 1)

    x_grp = x.reshape(G, g, D)
    router_logits = jnp.einsum(
        "gnd,de->gne", x_grp.astype(jnp.float32), params["router"]["kernel"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [G, g, E]

    # --- top-k assignment with per-group, per-expert capacity ---------------
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)  # [G, g, k]
    # renormalize the chosen gates (standard top-2 practice)
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((G, g, E, capacity), dtype=x.dtype)
    combine = jnp.zeros((G, g, E, capacity), dtype=jnp.float32)
    # running count of tokens already admitted per expert, built choice-major so
    # the 1st choice wins capacity over 2nd choices (GShard ordering)
    expert_fill = jnp.zeros((G, E), dtype=jnp.int32)
    for k in range(top_k):
        e_k = gate_idx[..., k]  # [G, g]
        onehot = jax.nn.one_hot(e_k, E, dtype=jnp.int32)  # [G, g, E]
        pos_within = jnp.cumsum(onehot, axis=1) - 1 + expert_fill[:, None, :]  # [G, g, E]
        pos = jnp.take_along_axis(pos_within, e_k[..., None], axis=2)[..., 0]  # [G, g]
        keep = pos < capacity
        pos_onehot = jax.nn.one_hot(pos, capacity, dtype=x.dtype) * keep[..., None]
        contrib = onehot[..., None].astype(x.dtype) * pos_onehot[:, :, None, :]  # [G, g, E, C]
        dispatch = dispatch + contrib
        combine = combine + contrib.astype(jnp.float32) * gate_vals[..., k][..., None, None]
        expert_fill = expert_fill + onehot.sum(axis=1)

    # --- expert compute (ep-sharded) ---------------------------------------
    expert_in = jnp.einsum("gnec,gnd->egcd", dispatch, x_grp)  # [E, G, C, D]
    if mesh is not None and mesh.shape.get(ep_axis, 1) > 1:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(ep_axis, None, None, None))
        )
    h = activation(jnp.einsum("egcd,edf->egcf", expert_in, params["wi"]["kernel"]))
    expert_out = jnp.einsum("egcf,efd->egcd", h, params["wo"]["kernel"])  # [E, G, C, D]
    if mesh is not None and mesh.shape.get(ep_axis, 1) > 1:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(ep_axis, None, None, None))
        )
    y_grp = jnp.einsum("gnec,egcd->gnd", combine.astype(expert_out.dtype), expert_out)

    # --- load-balance auxiliary loss ---------------------------------------
    # fraction of tokens whose FIRST choice is e, and mean router prob for e
    first_choice = jax.nn.one_hot(gate_idx[..., 0].reshape(-1), E, dtype=jnp.float32)
    fraction = first_choice.mean(axis=0)
    mean_prob = probs.reshape(-1, E).mean(axis=0)
    aux_loss = E * jnp.sum(fraction * mean_prob)

    return y_grp.reshape(B, S, D).astype(x.dtype), aux_loss
