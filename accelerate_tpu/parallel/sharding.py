"""Parameter sharding rules: pytree → PartitionSpec tree over the named mesh.

This replaces the reference's per-engine "prepare = wrap the module" flows with
"prepare = assign shardings" (SURVEY.md §7):

- FSDP/HSDP — reference ``_prepare_fsdp2`` (``accelerator.py:1643-1733``) +
  ``fsdp2_prepare_model`` (``utils/fsdp_utils.py:607-722``): params sharded on dim 0
  over the joint ``(dp_shard, cp)`` axes (the reference's ``dp_shard_cp`` flat mesh,
  ``parallelism_config.py:211-239``); XLA all-gathers forward, reduce-scatters
  backward — the GSPMD twin of FSDP2's DTensor flow.
- TP — reference ``_prepare_tp`` (``accelerator.py:1572-1626``) + transformers
  ``tp_plan`` tables: a module-pattern → PartitionSpec rule list.
- The optimizer state inherits param shardings (reference FSDP2's optimizer
  param-swap trick ``utils/fsdp_utils.py:543`` becomes: optax state is a pytree of
  param-shaped leaves, shard it with the same specs).
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence

import numpy as np

from ..parallelism_config import ParallelismConfig

FSDP_AXES = ("dp_shard", "cp")  # reference joint dp_shard_cp mesh


def _path_str(path) -> str:
    """jax tree path → 'a/b/0/c' string for regex matching."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


class ShardingRules:
    """Ordered (pattern → PartitionSpec) table, first match wins.

    The TPU-native analogue of transformers' ``tp_plan`` / Megatron's per-layer
    parallel maps. Patterns are regexes over '/'-joined param paths.
    """

    def __init__(self, rules: Sequence[tuple[str, Any]] = ()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]

    def match(self, path: str):
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return None

    def __add__(self, other: "ShardingRules") -> "ShardingRules":
        merged = ShardingRules()
        merged.rules = list(self.rules) + list(other.rules)
        return merged


def _merge_fsdp_into_spec(spec, shape, fsdp_axes: tuple, fsdp_size: int, axis_sizes: dict):
    """Add FSDP axes to a (possibly TP-sharded) spec.

    Strategy: shard the largest dimension not already claimed by the spec whose
    size divides evenly by the FSDP world; if dim 0 is claimed by TP, compose FSDP
    into the same dim tuple when the joint product divides. Non-divisible params
    stay as-is (replicated over the FSDP axes) — ``jax.device_put`` requires even
    shards outside jit.
    """
    from jax.sharding import PartitionSpec

    dims = list(spec) if spec is not None else []
    while len(dims) < len(shape):
        dims.append(None)
    candidates = [
        i for i, d in enumerate(dims) if d is None and shape[i] >= 2 and shape[i] % fsdp_size == 0
    ]
    if not candidates:
        # compose onto dim 0's existing axes (e.g. TP row-parallel + FSDP)
        if dims and dims[0] is not None:
            existing = dims[0] if isinstance(dims[0], tuple) else (dims[0],)
            existing_size = int(np.prod([axis_sizes.get(a, 1) for a in existing]))
            if shape[0] % (fsdp_size * existing_size) == 0:
                dims[0] = tuple(fsdp_axes) + existing
        return PartitionSpec(*dims)
    target = 0 if 0 in candidates else max(candidates, key=lambda i: shape[i])
    dims[target] = tuple(fsdp_axes) if len(fsdp_axes) > 1 else fsdp_axes[0]
    return PartitionSpec(*dims)


def infer_param_specs(
    params,
    mesh,
    parallelism_config: Optional[ParallelismConfig] = None,
    rules: Optional[ShardingRules] = None,
    min_fsdp_size: int = 2**10,
):
    """Compute a PartitionSpec pytree for ``params``.

    1. explicit ``rules`` (TP tables etc.) claim dims first;
    2. if FSDP is enabled, shard the largest free dim over ``(dp_shard, cp)``
       (params smaller than ``min_fsdp_size`` elements stay replicated — the
       moral twin of FSDP auto-wrap ``min_num_params`` policy, reference
       ``utils/dataclasses.py:1566+``);
    3. everything else is replicated.
    """
    import jax
    from jax.sharding import PartitionSpec

    pc = parallelism_config
    fsdp_on = pc is not None and pc.fsdp_enabled
    fsdp_axes = tuple(a for a in FSDP_AXES if mesh.shape.get(a, 1) > 1)
    fsdp_size = int(np.prod([mesh.shape[a] for a in fsdp_axes])) if fsdp_axes else 1

    def _spec(path, value):
        path_s = _path_str(path)
        shape = np.shape(value)
        base = rules.match(path_s) if rules is not None else None
        if base is None:
            base = PartitionSpec()
        if fsdp_on and fsdp_size > 1 and int(np.prod(shape or (1,))) >= min_fsdp_size:
            return _merge_fsdp_into_spec(base, shape, fsdp_axes, fsdp_size, dict(mesh.shape))
        # pad spec to rank
        dims = list(base)
        while len(dims) < len(shape):
            dims.append(None)
        return PartitionSpec(*dims)

    return jax.tree_util.tree_map_with_path(_spec, params)


def shard_params(params, mesh, specs=None, parallelism_config=None, rules=None, donate: bool = False):
    """Place every param on the mesh per its spec (the "prepare model" moment —
    reference ``prepare_model accelerator.py:1735`` collapses to this device_put)."""
    import jax
    from jax.sharding import NamedSharding

    if specs is None:
        specs = infer_param_specs(params, mesh, parallelism_config, rules)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: x is None,
    ), specs


def tree_specs_like(tree, params, param_specs):
    """Spec pytree for an arbitrary state tree (e.g. optax state): any subtree whose
    structure matches the params pytree inherits ``param_specs``; all other leaves
    are replicated (``P()``). Reference counterpart: optimizer state inheriting
    FSDP shardings (``utils/fsdp_utils.py:543`` param-swap trick)."""
    import jax
    from jax.sharding import PartitionSpec
    from jax.tree_util import default_registry

    params_treedef = jax.tree_util.tree_structure(params)

    def _walk(node):
        if node is None:
            return None
        try:
            if jax.tree_util.tree_structure(node) == params_treedef:
                return param_specs
        except Exception:
            pass
        if jax.tree_util.all_leaves([node]):
            return PartitionSpec()
        one_level = jax.tree_util.tree_structure(node, is_leaf=lambda x: x is not node)
        children, _ = default_registry.flatten_one_level(node)
        return jax.tree_util.tree_unflatten(one_level, [_walk(c) for c in children])

    return _walk(tree)


def shard_like_params(tree, mesh, params, param_specs, zero1_axis: Optional[str] = None):
    """Device-put ``tree`` with shardings inherited from params where structures
    match (see :func:`tree_specs_like`). ``zero1_axis`` additionally applies
    :func:`zero1_state_specs` — optimizer-state sharding over a replicate
    axis."""
    import jax
    from jax.sharding import NamedSharding

    specs = tree_specs_like(tree, params, param_specs)
    if zero1_axis is not None:
        specs = zero1_state_specs(tree, specs, mesh, axis=zero1_axis)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )


def zero1_state_specs(state, specs, mesh, axis: str = "dp_replicate"):
    """Shard otherwise-replicated optimizer-state leaves over the data-parallel
    REPLICATE axis (ZeRO-1 as a GSPMD sharding — the technique of "Automatic
    Cross-Replica Sharding of Weight Update in Data-Parallel Training", Xu et
    al. 2020, arXiv:2004.13336: annotate the moment buffers sharded, let XLA
    partition the elementwise optimizer math and insert the gathers).

    Params and grads stay replicated (pure DP); only the optimizer state —
    2× params for Adam — splits across replicas, so each chip stores
    ``state/dp_replicate``. Leaves already sharded by FSDP/TP rules, scalars,
    and dims not divisible by the axis size are left unchanged.
    """
    import jax
    from jax.sharding import PartitionSpec

    axis_size = dict(mesh.shape).get(axis, 1)
    if axis_size <= 1:
        return specs

    def _maybe(leaf, spec):
        if any(ax is not None for ax in tuple(spec)):
            return spec  # FSDP/TP already shard this leaf
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1 and shape[0] > 0 and shape[0] % axis_size == 0:
            return PartitionSpec(axis)
        return spec

    return jax.tree_util.tree_map(_maybe, state, specs)


def replicate(tree, mesh):
    """Fully replicate a pytree over the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


# ---------------------------------------------------------------------------
# Canonical TP rule builders (used by models/; mirrors transformers tp_plan)


def llama_tp_rules() -> ShardingRules:
    """Megatron-style TP for a Llama/GPT decoder: column-parallel QKV/up, row-
    parallel out/down, vocab-parallel embedding (reference: Megatron TP via
    ``utils/megatron_lm.py``; transformers ``tp_plan="auto"`` validated in
    ``accelerator.py:1856-1865``)."""
    from jax.sharding import PartitionSpec as P

    return ShardingRules(
        [
            (r"(wq|wk|wv|q_proj|k_proj|v_proj|qkv)/kernel", P(None, "tp")),
            (r"(wo|o_proj|out_proj)/kernel", P("tp", None)),
            (r"(w1|gate_proj|up_proj|w3|fc1)/kernel", P(None, "tp")),
            (r"(w2|down_proj|fc2)/kernel", P("tp", None)),
            (r"(embed_tokens|wte|embedding)/(embedding|kernel)", P("tp", None)),
            (r"lm_head/kernel", P(None, "tp")),
        ]
    )


# ---------------------------------------------------------------------------
# Optimizer-state host offload (ZeRO-Offload / FSDP cpu_offload parity)
#
# Reference: DeepSpeedPlugin offload_optimizer_device ("cpu"/"nvme") hands the
# optimizer partition to the DeepSpeed CPU Adam engine; torch-FSDP
# CPUOffload(offload_params=True) pages flat-params to host. The TPU-native
# mechanism is XLA memory kinds: optimizer-state arrays live in host RAM
# (``pinned_host``) between steps, and the compiled step stages them into HBM
# on entry and commits them back on exit — the transfers are inside ONE XLA
# program, so they overlap with compute instead of round-tripping through
# Python. Frees sizeof(opt_state) of HBM (2× params for Adam).

_HOST_KIND = "pinned_host"
_host_offload_support: Optional[bool] = None


def host_offload_supported() -> bool:
    """True when this backend can compile memory-kind annotated programs (TPU
    yes; the CPU emulation backend lacks the annotate_device_placement custom
    call). Probed once with a tiny jit."""
    global _host_offload_support
    if _host_offload_support is None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding

        try:
            dev = jax.devices()[0]
            host = SingleDeviceSharding(dev, memory_kind=_HOST_KIND)
            devk = SingleDeviceSharding(dev, memory_kind="device")
            x = jax.device_put(jnp.zeros((8,)), host)
            # the full offload round trip: H2D stage, compute, D2H commit —
            # the commit half is what unsupported backends fail to compile
            y = jax.jit(
                lambda a: jax.device_put(jax.device_put(a, devk) * 2, host)
            )(x)
            jax.block_until_ready(y)
            # some backends (CPU emulation) compile but silently DROP the
            # D2H placement — the round trip must actually land in host memory
            _host_offload_support = getattr(y.sharding, "memory_kind", None) == _HOST_KIND
        except Exception as e:
            # cache the verdict only for the known can't-compile signatures;
            # a transient runtime error must not pin False for the process
            msg = str(e)
            definitive = any(
                sig in msg
                for sig in ("annotate_device_placement", "memory kind", "Memory kind", "memory_kind")
            ) or type(e).__name__ in ("NotImplementedError",)
            if definitive:
                _host_offload_support = False
            return False
    return _host_offload_support


def _with_memory_kind(sharding, kind: str):
    return sharding.with_memory_kind(kind)


def offload_tree_shardings(tree, mesh=None):
    """For a tree of live arrays return ``(host_shardings, device_shardings)``
    trees derived from each leaf's current sharding.

    With ``mesh`` given, leaves whose sharding does not span the mesh's device
    set (e.g. an optax ``count`` scalar committed to one device before
    prepare) are normalized to mesh-replicated — one jit cannot mix
    single-device and mesh-wide operands."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh_devices = set(mesh.devices.flat) if mesh is not None else None

    def _base(x):
        s = x.sharding
        if mesh_devices is not None and set(s.device_set) != mesh_devices:
            return NamedSharding(mesh, PartitionSpec())
        return s

    host = jax.tree_util.tree_map(lambda x: _with_memory_kind(_base(x), _HOST_KIND), tree)
    dev = jax.tree_util.tree_map(lambda x: _with_memory_kind(_base(x), "device"), tree)
    return host, dev


def offload_to_host(tree, mesh=None):
    """Commit a tree of arrays to host memory (keeping their logical
    shardings). Returns the host-resident tree."""
    import jax

    host, _ = offload_tree_shardings(tree, mesh=mesh)
    return jax.device_put(tree, host)


def make_host_offloaded_step(base_step, opt_state, donate: bool = True, mesh=None):
    """Wrap ``base_step(params, opt_state, batch) -> (params, opt_state,
    metrics)`` so the optimizer state lives in ``pinned_host`` between steps.

    ``opt_state`` must be the LIVE (device-resident) state; it is committed to
    host here and the matching host-resident state is returned alongside the
    compiled step: ``(step, host_opt_state)``. Inside the jitted step the
    state is staged HBM-ward (H2D), updated, and committed back (D2H) — both
    transfers are part of the XLA program. Pass ``mesh`` so stray
    single-device leaves are normalized onto it.
    """
    import jax

    host_s, dev_s = offload_tree_shardings(opt_state, mesh=mesh)
    host_state = jax.device_put(opt_state, host_s)

    def step(params, opt_state, batch):
        staged = jax.device_put(opt_state, dev_s)
        new_params, new_opt, metrics = base_step(params, staged, batch)
        new_opt = jax.device_put(new_opt, host_s)
        return new_params, new_opt, metrics

    jit_step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return jit_step, host_state
