"""THE sharding decision surface: pytree → PartitionSpec/placement over the mesh.

This replaces the reference's per-engine "prepare = wrap the module" flows with
"prepare = assign shardings" (SURVEY.md §7), and — since ISSUE 9 — concentrates
every spec decision behind ONE entry point, :func:`make_sharding_plan`
(SimpleFSDP's trace-and-reshard architecture, arXiv:2411.00284: a single
function of (mesh, parallelism_config) decides param/grad/opt-state/
update-slice shardings; engines consume the plan instead of re-deriving specs):

- ``Accelerator.prepare_model`` builds a :class:`ShardingPlan` and places params
  through it;
- ``AcceleratedOptimizer.init`` consumes ``plan.init_optimizer_state`` — which
  routes ZeRO-1 through the fused bucketed weight update
  (``parallel/weight_update.py``, arXiv:2004.13336) when the layout allows, and
  through the GSPMD annotation path (:func:`zero1_state_specs`) otherwise;
- host-offload staging shardings come from ``plan.offload_shardings``;
- sharded checkpointing restores template-less leaves through
  ``plan.sharding_from_saved_spec``.

Specs are CANONICALIZED (trailing ``None`` dims trimmed) in exactly one place,
:func:`canonicalize_spec`. This is load-bearing: a jitted step's outputs carry
GSPMD-normalized (trimmed) NamedShardings, and any placed input whose sharding
compares unequal to the matching output re-specializes the step's C++ fastpath
cache at step 1 — the bert-tiny "1 recompile at step 1" signal PR 7 recorded.

Sharding strategy per engine (unchanged semantics):

- FSDP/HSDP — params sharded on dim 0 over the joint ``(dp_shard, cp)`` axes
  (the reference's ``dp_shard_cp`` flat mesh); XLA all-gathers forward,
  reduce-scatters backward.
- TP — a module-pattern → PartitionSpec rule list (transformers ``tp_plan``).
- Optimizer state inherits param shardings (reference FSDP2's param-swap trick
  becomes: optax state is a pytree of param-shaped leaves, shard it alike).
- ZeRO-1 — fused bucketed reduce-scatter/update/all-gather inside the jitted
  step (see ``weight_update.py``); annotation-mode fallback for composite
  meshes and non-elementwise transforms.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..parallelism_config import ParallelismConfig

FSDP_AXES = ("dp_shard", "cp")  # reference joint dp_shard_cp mesh


def _path_str(path) -> str:
    """jax tree path → 'a/b/0/c' string for regex matching."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def canonicalize_spec(spec, axis_sizes: Optional[dict] = None):
    """Normalize a PartitionSpec to the form GSPMD hands back on jitted-step
    OUTPUTS: size-1 mesh axes dropped (sharding over them IS replication) and
    trailing ``None`` dims trimmed — ``P(None, None, 'tp')`` on a tp=1 mesh
    → ``P()``, ``P('dp_shard', None)`` → ``P('dp_shard')``.

    This is load-bearing, not cosmetic: placing inputs in any equal-meaning
    but unequal-COMPARING form makes the step's C++ dispatch cache
    re-specialize on its second call (the input's sharding no longer matches
    the previous step's output's).
    """
    from jax.sharding import PartitionSpec

    dims = []
    for d in (list(spec) if spec is not None else []):
        if d is None:
            dims.append(None)
            continue
        axes = tuple(d) if isinstance(d, (tuple, list)) else (d,)
        if axis_sizes is not None:
            # unknown axes default to "keep": device_put will then error
            # loudly instead of this helper silently eating a typo
            axes = tuple(a for a in axes if axis_sizes.get(a, 2) > 1)
        if not axes:
            dims.append(None)
        elif len(axes) == 1:
            dims.append(axes[0])
        else:
            dims.append(axes)
    while dims and dims[-1] is None:
        dims.pop()
    return PartitionSpec(*dims)


class ShardingRules:
    """Ordered (pattern → PartitionSpec) table, first match wins.

    The TPU-native analogue of transformers' ``tp_plan`` / Megatron's per-layer
    parallel maps. Patterns are regexes over '/'-joined param paths.
    """

    def __init__(self, rules: Sequence[tuple[str, Any]] = ()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]

    def match(self, path: str):
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return None

    def __add__(self, other: "ShardingRules") -> "ShardingRules":
        merged = ShardingRules()
        merged.rules = list(self.rules) + list(other.rules)
        return merged


def _merge_fsdp_into_spec(spec, shape, fsdp_axes: tuple, fsdp_size: int, axis_sizes: dict):
    """Add FSDP axes to a (possibly TP-sharded) spec.

    Strategy: shard the largest dimension not already claimed by the spec whose
    size divides evenly by the FSDP world; if dim 0 is claimed by TP, compose FSDP
    into the same dim tuple when the joint product divides. Non-divisible params
    stay as-is (replicated over the FSDP axes) — ``jax.device_put`` requires even
    shards outside jit.
    """
    dims = list(spec) if spec is not None else []
    while len(dims) < len(shape):
        dims.append(None)
    candidates = [
        i for i, d in enumerate(dims) if d is None and shape[i] >= 2 and shape[i] % fsdp_size == 0
    ]
    if not candidates:
        # compose onto dim 0's existing axes (e.g. TP row-parallel + FSDP)
        if dims and dims[0] is not None:
            existing = dims[0] if isinstance(dims[0], tuple) else (dims[0],)
            existing_size = int(np.prod([axis_sizes.get(a, 1) for a in existing]))
            if shape[0] % (fsdp_size * existing_size) == 0:
                dims[0] = tuple(fsdp_axes) + existing
        return canonicalize_spec(dims, axis_sizes)
    target = 0 if 0 in candidates else max(candidates, key=lambda i: shape[i])
    dims[target] = tuple(fsdp_axes) if len(fsdp_axes) > 1 else fsdp_axes[0]
    return canonicalize_spec(dims, axis_sizes)


def infer_param_specs(
    params,
    mesh,
    parallelism_config: Optional[ParallelismConfig] = None,
    rules: Optional[ShardingRules] = None,
    min_fsdp_size: int = 2**10,
):
    """Compute a (canonical) PartitionSpec pytree for ``params``.

    1. explicit ``rules`` (TP tables etc.) claim dims first;
    2. if FSDP is enabled, shard the largest free dim over ``(dp_shard, cp)``
       (params smaller than ``min_fsdp_size`` elements stay replicated — the
       moral twin of FSDP auto-wrap ``min_num_params`` policy, reference
       ``utils/dataclasses.py:1566+``);
    3. everything else is replicated.
    """
    import jax

    pc = parallelism_config
    fsdp_on = pc is not None and pc.fsdp_enabled
    fsdp_axes = tuple(a for a in FSDP_AXES if mesh.shape.get(a, 1) > 1)
    fsdp_size = int(np.prod([mesh.shape[a] for a in fsdp_axes])) if fsdp_axes else 1

    axis_sizes = dict(mesh.shape)

    def _spec(path, value):
        path_s = _path_str(path)
        shape = np.shape(value)
        base = rules.match(path_s) if rules is not None else None
        if fsdp_on and fsdp_size > 1 and int(np.prod(shape or (1,))) >= min_fsdp_size:
            return _merge_fsdp_into_spec(base, shape, fsdp_axes, fsdp_size, axis_sizes)
        return canonicalize_spec(base, axis_sizes)

    return jax.tree_util.tree_map_with_path(_spec, params)


def shard_params(params, mesh, specs=None, parallelism_config=None, rules=None, donate: bool = False):
    """Place every param on the mesh per its spec (the "prepare model" moment —
    reference ``prepare_model accelerator.py:1735`` collapses to this device_put)."""
    import jax
    from jax.sharding import NamedSharding

    if specs is None:
        specs = infer_param_specs(params, mesh, parallelism_config, rules)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: x is None,
    ), specs


def tree_specs_like(tree, params, param_specs):
    """Spec pytree for an arbitrary state tree (e.g. optax state): any subtree whose
    structure matches the params pytree inherits ``param_specs``; all other leaves
    are replicated (``P()``). Reference counterpart: optimizer state inheriting
    FSDP shardings (``utils/fsdp_utils.py:543`` param-swap trick)."""
    import jax
    from jax.sharding import PartitionSpec
    from jax.tree_util import default_registry

    params_treedef = jax.tree_util.tree_structure(params)

    def _walk(node):
        if node is None:
            return None
        try:
            if jax.tree_util.tree_structure(node) == params_treedef:
                return param_specs
        except Exception:
            pass
        if jax.tree_util.all_leaves([node]):
            return PartitionSpec()
        one_level = jax.tree_util.tree_structure(node, is_leaf=lambda x: x is not node)
        children, _ = default_registry.flatten_one_level(node)
        return jax.tree_util.tree_unflatten(one_level, [_walk(c) for c in children])

    return _walk(tree)


def shard_like_params(tree, mesh, params, param_specs, zero1_axis: Optional[str] = None):
    """Device-put ``tree`` with shardings inherited from params where structures
    match (see :func:`tree_specs_like`). ``zero1_axis`` additionally applies
    :func:`zero1_state_specs` — annotation-mode optimizer-state sharding over a
    replicate axis (the fused bucketed path lives in ``plan.init_optimizer_state``)."""
    import jax
    from jax.sharding import NamedSharding

    specs = tree_specs_like(tree, params, param_specs)
    if zero1_axis is not None:
        specs = zero1_state_specs(tree, specs, mesh, axis=zero1_axis)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )


def zero1_state_specs(state, specs, mesh, axis: str = "dp_replicate"):
    """ANNOTATION-mode ZeRO-1: shard otherwise-replicated optimizer-state leaves
    over the data-parallel replicate axis and let GSPMD partition the
    elementwise update math (arXiv:2004.13336's original formulation).

    This is the fallback for composite meshes (ZeRO-1 stacked on TP/FSDP-sharded
    leaves) and non-elementwise transforms; pure-DP meshes take the fused
    bucketed path in ``parallel/weight_update.py`` instead (deterministic, with
    explicit reduce-scatter/all-gather and 1/N update math).

    Params and grads stay replicated (pure DP); only the optimizer state —
    2× params for Adam — splits across replicas, so each chip stores
    ``state/dp_replicate``. Leaves already sharded by FSDP/TP rules, scalars,
    and dims not divisible by the axis size are left unchanged.
    """
    import jax
    from jax.sharding import PartitionSpec

    axis_size = dict(mesh.shape).get(axis, 1)
    if axis_size <= 1:
        return specs

    def _maybe(leaf, spec):
        if any(ax is not None for ax in tuple(spec)):
            return spec  # FSDP/TP already shard this leaf
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1 and shape[0] > 0 and shape[0] % axis_size == 0:
            return PartitionSpec(axis)
        return spec

    return jax.tree_util.tree_map(_maybe, state, specs)


def replicate(tree, mesh):
    """Fully replicate a pytree over the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


# ---------------------------------------------------------------------------
# The single spec-decision entry point (ISSUE 9 / SimpleFSDP arXiv:2411.00284)


@dataclass
class ShardingPlan:
    """One resolved sharding decision for a prepared model.

    Built by :func:`make_sharding_plan`; consumed by ``Accelerator`` (param
    placement), ``AcceleratedOptimizer`` (state init/placement, fused ZeRO-1
    update), host-offload staging, and sharded checkpointing. Holds the spec
    set for params/grads (identical), optimizer state, and — when fused ZeRO-1
    is active — the bucketed update-slice layout.
    """

    mesh: Any
    parallelism_config: Optional[ParallelismConfig]
    rules: Optional[ShardingRules]
    param_specs: Any
    zero1_axis: Optional[str] = None
    zero1: Optional[Any] = None  # Zero1BucketPlan when the fused path is active

    # ------------------------------------------------------------------ params --
    @property
    def grad_specs(self):
        """Gradients of a mean loss share the param layout (GSPMD reduces them
        in the backward pass)."""
        return self.param_specs

    @property
    def fused_zero1(self) -> bool:
        return self.zero1 is not None

    def named_sharding(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, spec)

    def place_params(self, params):
        """Device-put ``params`` per the plan's specs (the "prepare model"
        moment)."""
        placed, _ = shard_params(params, self.mesh, specs=self.param_specs)
        return placed

    # ------------------------------------------------------------- opt state --
    def opt_state_specs(self, state, params):
        """Spec tree for an optimizer state over the UNBUCKETED params:
        param-shaped subtrees inherit param specs; annotation-mode ZeRO-1
        applies only when the fused path is off (fused state is bucketed and
        never sees this)."""
        specs = tree_specs_like(state, params, self.param_specs)
        if self.zero1_axis is not None and not self.fused_zero1:
            specs = zero1_state_specs(state, specs, self.mesh, axis=self.zero1_axis)
        return specs

    def place_opt_state(self, state, params):
        # one placement implementation: delegate to shard_like_params (fused
        # ZeRO-1 never reaches here — its bucketed state is placed by
        # init_fused_optimizer_state — so the annotation axis applies only
        # when the fused path is off)
        return shard_like_params(
            state, self.mesh, params, self.param_specs,
            zero1_axis=None if self.fused_zero1 else self.zero1_axis,
        )

    def init_fused_optimizer_state(self, tx, params):
        """Initialize BUCKETED, 1/N-per-replica optimizer state for ``tx`` and
        the matching fused update — or None when fused ZeRO-1 is off for this
        plan or ``tx`` materializes state the bucket layout cannot shard (the
        plan then demotes itself to the annotation path, and the caller
        proceeds with ``tx.init`` + :meth:`place_opt_state`).

        Returns ``(opt_state, update_fn)`` where
        ``update_fn(grads, opt_state, params) -> (new_params, new_opt_state)``
        replaces the plain ``tx.update`` + ``apply_updates`` pair inside the
        jitted train step.
        """
        if not self.fused_zero1:
            return None
        from .weight_update import (
            FusedZero1Incompatible,
            init_bucketed_opt_state,
            make_fused_zero1_update,
        )

        try:
            state, state_specs = init_bucketed_opt_state(
                tx, params, self.zero1, self.mesh
            )
            update_fn = make_fused_zero1_update(tx, self.zero1, self.mesh, state_specs)
            return state, update_fn
        except FusedZero1Incompatible as e:
            import warnings

            warnings.warn(str(e), stacklevel=2)
            self.zero1 = None
            return None

    # ----------------------------------------------------------- host offload --
    def offload_shardings(self, tree):
        """``(host, device)`` sharding trees for staging ``tree`` between host
        RAM and HBM inside a compiled step (ZeRO-Offload)."""
        return offload_tree_shardings(tree, mesh=self.mesh)

    # ------------------------------------------------------------ checkpoints --
    def sharding_from_saved_spec(self, spec_json, drop_unknown_axes: bool = False):
        """NamedSharding for a spec recorded in a sharded-checkpoint index
        (``sharded_checkpoint._spec_to_json`` format: a list of axis names,
        axis-name lists, or None per dim; or None for replicated). Lets a
        resume restore onto this plan's mesh without live template arrays.

        ``drop_unknown_axes=True`` (the elastic cross-topology path) treats
        axis names this plan's mesh does not have as replication instead of
        keeping them for a loud ``device_put`` failure — a checkpoint written
        on a richer mesh factorization restores replicated over the missing
        axes and re-chunks over the surviving ones."""
        from jax.sharding import PartitionSpec

        if spec_json is None:
            return self.named_sharding(PartitionSpec())
        axis_sizes = dict(self.mesh.shape)
        dims = []
        for axis in spec_json:
            if axis is None:
                dims.append(None)
            elif isinstance(axis, (list, tuple)):
                axes = tuple(str(a) for a in axis)
                if drop_unknown_axes:
                    axes = tuple(a for a in axes if a in axis_sizes)
                dims.append(axes if axes else None)
            else:
                name = str(axis)
                if drop_unknown_axes and name not in axis_sizes:
                    name = None
                dims.append(name)
        return self.named_sharding(canonicalize_spec(dims, axis_sizes))

    # -------------------------------------------------------------- telemetry --
    def zero1_collective_bytes(self) -> "Optional[dict[str, int]]":
        """Per-step compiled-collective payload of the fused weight update
        (feeds the telemetry comms counters), or None when not fused."""
        if not self.fused_zero1:
            return None
        n = self.zero1.collective_bytes
        return {"reduce_scatter": n, "all_gather": n}


def make_sharding_plan(
    params,
    mesh,
    parallelism_config: Optional[ParallelismConfig] = None,
    rules: Optional[ShardingRules] = None,
    zero1_axis: Optional[str] = None,
    zero1_fused: Optional[bool] = None,
    zero1_bucket_bytes: Optional[int] = None,
    min_fsdp_size: int = 2**10,
    param_specs=None,
) -> ShardingPlan:
    """THE spec-decision entry point: given mesh + parallelism intent, resolve
    the full sharding plan for params/grads/opt-state/update-slices.

    Fused ZeRO-1 engages when ``zero1_axis`` names a >1-sized mesh axis, every
    param is a floating array, and the params are fully replicated under the
    resolved specs (pure data parallelism — ZeRO-1 composed with TP/FSDP keeps
    the annotation path). ``zero1_fused=False`` (or env
    ``ACCELERATE_ZERO1_FUSED=0``) forces the annotation path.
    """
    import jax

    axis_sizes = dict(mesh.shape)
    if param_specs is None:
        param_specs = infer_param_specs(
            params, mesh, parallelism_config, rules, min_fsdp_size=min_fsdp_size
        )
    else:
        # user-supplied specs get the same canonical form as inferred ones —
        # a padded/size-1-axis spec would re-specialize the jitted step at
        # step 1 and could wrongly read as "not replicated" below
        param_specs = jax.tree_util.tree_map(
            lambda s: None if s is None else canonicalize_spec(s, axis_sizes),
            param_specs,
            is_leaf=lambda s: s is None,
        )
    plan = ShardingPlan(
        mesh=mesh,
        parallelism_config=parallelism_config,
        rules=rules,
        param_specs=param_specs,
        zero1_axis=zero1_axis,
    )
    if zero1_axis is None:
        return plan
    axis_size = dict(mesh.shape).get(zero1_axis, 1)
    if axis_size <= 1:
        return plan
    if zero1_fused is None:
        from ..utils.environment import parse_flag_from_env

        zero1_fused = parse_flag_from_env("ACCELERATE_ZERO1_FUSED", default=True)
    if not zero1_fused:
        return plan
    spec_leaves = jax.tree_util.tree_leaves(param_specs)  # PartitionSpec is a leaf
    all_replicated = all(
        not any(ax is not None for ax in tuple(s)) for s in spec_leaves
    )
    if not all_replicated:
        return plan  # composite mesh: ZeRO-1 annotations compose with FSDP/TP
    from .weight_update import build_bucket_plan

    # fp8 delayed-scaling meta leaves are replace-with-cotangent side state,
    # not optimized params: they bypass the buckets (and the optimizer tx)
    # as passthrough slots, so dtype_recipe="fp8" keeps the fused path
    # engaged instead of demoting to the annotation path
    from ..ops.fp8 import META_KEY

    try:
        plan.zero1 = build_bucket_plan(
            params, zero1_axis, axis_size, bucket_bytes=zero1_bucket_bytes,
            passthrough=lambda path: META_KEY in path.split("/"),
        )
    except ValueError:
        plan.zero1 = None  # non-floating leaves: annotation path
    return plan


# ---------------------------------------------------------------------------
# Canonical TP rule builders (used by models/; mirrors transformers tp_plan)


def llama_tp_rules() -> ShardingRules:
    """Megatron-style TP for a Llama/GPT decoder: column-parallel QKV/up, row-
    parallel out/down, vocab-parallel embedding (reference: Megatron TP via
    ``utils/megatron_lm.py``; transformers ``tp_plan="auto"`` validated in
    ``accelerator.py:1856-1865``)."""
    from jax.sharding import PartitionSpec as P

    return ShardingRules(
        [
            (r"(wq|wk|wv|q_proj|k_proj|v_proj|qkv)/kernel", P(None, "tp")),
            (r"(wo|o_proj|out_proj)/kernel", P("tp", None)),
            (r"(w1|gate_proj|up_proj|w3|fc1)/kernel", P(None, "tp")),
            (r"(w2|down_proj|fc2)/kernel", P("tp", None)),
            (r"(embed_tokens|wte|embedding)/(embedding|kernel)", P("tp", None)),
            (r"lm_head/kernel", P(None, "tp")),
        ]
    )


# ---------------------------------------------------------------------------
# Optimizer-state host offload (ZeRO-Offload / FSDP cpu_offload parity)
#
# Reference: DeepSpeedPlugin offload_optimizer_device ("cpu"/"nvme") hands the
# optimizer partition to the DeepSpeed CPU Adam engine; torch-FSDP
# CPUOffload(offload_params=True) pages flat-params to host. The TPU-native
# mechanism is XLA memory kinds: optimizer-state arrays live in host RAM
# (``pinned_host`` on TPU) between steps, and the compiled step stages them
# into HBM on entry and commits them back on exit — the transfers are inside
# ONE XLA program, so they overlap with compute instead of round-tripping
# through Python. Frees sizeof(opt_state) of HBM (2× params for Adam).

_host_offload_support: Optional[bool] = None
_offload_kinds: Optional[tuple] = None  # resolved (host_kind, device_kind); () = none


def host_memory_kind() -> Optional[str]:
    """The host-RAM memory kind this backend's devices expose: ``pinned_host``
    on TPU; some CPU builds expose ``unpinned_host``. None when the device
    reports no host tier at all."""
    kinds = offload_memory_kinds()
    return kinds[0] if kinds else None


def offload_memory_kinds() -> Optional[tuple]:
    """``(host_kind, device_kind)`` when this backend exposes BOTH a host-RAM
    tier and a distinct device tier (the precondition for optimizer-state
    offload), else None. The CPU emulation backend addresses only
    ``unpinned_host`` — host RAM *is* its device memory, so there is nothing
    to offload from and this returns None. Probed once per process."""
    global _offload_kinds
    if _offload_kinds is None:
        import jax
        from jax.sharding import SingleDeviceSharding

        resolved: tuple = ()
        try:
            dev = jax.devices()[0]
            try:
                kinds = [m.kind for m in dev.addressable_memories()]
            except Exception:
                # old jax without memory introspection: assume the TPU layout
                kinds = ["device", "pinned_host"]
            host = next((k for k in ("pinned_host", "unpinned_host") if k in kinds), None)
            if host is not None and "device" in kinds:
                # both tiers must be constructible as shardings
                SingleDeviceSharding(dev, memory_kind=host)
                SingleDeviceSharding(dev, memory_kind="device")
                resolved = (host, "device")
        except Exception:
            resolved = ()
        _offload_kinds = resolved
    return _offload_kinds or None


def host_offload_supported() -> bool:
    """True when this backend can compile memory-kind annotated programs (TPU
    yes; the CPU emulation backend exposes no separate device tier and most
    CPU builds lack the annotate_device_placement custom call). Probed once
    with a tiny jit."""
    global _host_offload_support
    if _host_offload_support is None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding

        kinds = offload_memory_kinds()
        if kinds is None:
            _host_offload_support = False
            return False
        host_kind, device_kind = kinds
        try:
            dev = jax.devices()[0]
            host = SingleDeviceSharding(dev, memory_kind=host_kind)
            devk = SingleDeviceSharding(dev, memory_kind=device_kind)
            x = jax.device_put(jnp.zeros((8,)), host)
            # the full offload round trip: H2D stage, compute, D2H commit —
            # the commit half is what unsupported backends fail to compile
            y = jax.jit(
                lambda a: jax.device_put(jax.device_put(a, devk) * 2, host)
            )(x)
            jax.block_until_ready(y)
            # some backends compile but silently DROP the D2H placement — the
            # round trip must actually land in host memory
            _host_offload_support = getattr(y.sharding, "memory_kind", None) == host_kind
        except Exception as e:
            # cache the verdict only for the known can't-compile signatures;
            # a transient runtime error must not pin False for the process
            msg = str(e)
            definitive = any(
                sig in msg
                for sig in ("annotate_device_placement", "memory kind", "Memory kind", "memory_kind")
            ) or type(e).__name__ in ("NotImplementedError",)
            if definitive:
                _host_offload_support = False
            return False
    return _host_offload_support


def _with_memory_kind(sharding, kind: str):
    return sharding.with_memory_kind(kind)


def offload_tree_shardings(tree, mesh=None):
    """For a tree of live arrays return ``(host_shardings, device_shardings)``
    trees derived from each leaf's current sharding (memory kinds resolved by
    :func:`offload_memory_kinds` — ``pinned_host``/``device`` on TPU).

    With ``mesh`` given, leaves whose sharding does not span the mesh's device
    set (e.g. an optax ``count`` scalar committed to one device before
    prepare) are normalized to mesh-replicated — one jit cannot mix
    single-device and mesh-wide operands."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    kinds = offload_memory_kinds()
    if kinds is None:
        raise RuntimeError(
            "this backend exposes no separate host/device memory tiers "
            f"(host offload needs both; see offload_memory_kinds)"
        )
    host_kind, device_kind = kinds
    mesh_devices = set(mesh.devices.flat) if mesh is not None else None

    def _base(x):
        s = x.sharding
        if mesh_devices is not None and set(s.device_set) != mesh_devices:
            return NamedSharding(mesh, PartitionSpec())
        return s

    host = jax.tree_util.tree_map(lambda x: _with_memory_kind(_base(x), host_kind), tree)
    dev = jax.tree_util.tree_map(lambda x: _with_memory_kind(_base(x), device_kind), tree)
    return host, dev


def offload_to_host(tree, mesh=None):
    """Commit a tree of arrays to host memory (keeping their logical
    shardings). Returns the host-resident tree."""
    import jax

    host, _ = offload_tree_shardings(tree, mesh=mesh)
    return jax.device_put(tree, host)


def make_host_offloaded_step(base_step, opt_state, donate: bool = True, mesh=None, plan=None):
    """Wrap ``base_step(params, opt_state, batch) -> (params, opt_state,
    metrics)`` so the optimizer state lives in host memory between steps.

    ``opt_state`` must be the LIVE (device-resident) state; it is committed to
    host here and the matching host-resident state is returned alongside the
    compiled step: ``(step, host_opt_state)``. Inside the jitted step the
    state is staged HBM-ward (H2D), updated, and committed back (D2H) — both
    transfers are part of the XLA program. Pass ``plan`` (or ``mesh``) so
    stray single-device leaves are normalized onto the mesh.
    """
    import jax

    if plan is not None:
        host_s, dev_s = plan.offload_shardings(opt_state)
    else:
        host_s, dev_s = offload_tree_shardings(opt_state, mesh=mesh)
    host_state = jax.device_put(opt_state, host_s)

    def step(params, opt_state, batch):
        staged = jax.device_put(opt_state, dev_s)
        new_params, new_opt, metrics = base_step(params, staged, batch)
        new_opt = jax.device_put(new_opt, host_s)
        return new_params, new_opt, metrics

    jit_step = jax.jit(step, donate_argnums=(0, 1) if donate else ())
    return jit_step, host_state
