"""Long-context attention parallelism: ring attention (CP) and Ulysses (SP).

TPU-native counterpart of the reference's two mutually-exclusive long-context
backends (SURVEY.md §5 "Long-context / sequence parallelism"):

- **CP / ring attention** — reference ``_prepare_cp`` (``accelerator.py:1628``) +
  ``maybe_context_parallel`` (``:4056-4120``) wrap torch's experimental
  ``context_parallel`` with allgather/alltoall KV rotation. Here: the sequence
  dim is sharded over the ``cp`` mesh axis; K/V blocks rotate around the ICI
  ring with ``lax.ppermute`` inside ``shard_map`` while a flash-style online
  softmax accumulates — O(S/cp) memory per chip, fully overlapped
  compute/communication, differentiable end-to-end. ``rotate="allgather"``
  instead gathers KV once (better for short rings).
- **SP / Ulysses** — reference DeepSpeed ALST path (``accelerator.py:2344-2456``):
  head-sharded attention via all-to-all. Here: ``lax.all_to_all`` reshards
  seq-sharded QKV to head-sharded, runs full-sequence attention locally, and
  reshards back.

Both produce an ``attention_fn(q, k, v, causal=...)`` over GLOBAL [B, S, H, D]
arrays, drop-in for ``models``' pluggable attention hook.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallelism_config import DP_AXES


NEG_INF = -1e30


def _block_attn(q, k, v, mask, scale):
    """One flash block: returns (unnormalized out, row max, row sumexp).

    q: [B, Hq, Sq, D]; k,v: [B, Hq, Skv, D]; mask: [Sq, Skv] bool or None.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    return o, m, l


def _merge_blocks(o, m, l, o_new, m_new, l_new):
    """Online-softmax merge of two partial attention results."""
    m_tot = jnp.maximum(m, m_new)
    c_old = jnp.exp(m - m_tot)
    c_new = jnp.exp(m_new - m_tot)
    o = o * c_old[..., None].astype(o.dtype) + o_new * c_new[..., None].astype(o.dtype)
    l = l * c_old + l_new * c_new
    return o, m_tot, l


def _local_ring_attention(q, k, v, *, axis_name: str, axis_size: int, causal: bool, scale: float):
    """Runs INSIDE shard_map: q,k,v are the local seq shards [B, S_loc, H, D]."""
    cp = axis_size
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    # head-major layout for the block kernel
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    if kh.shape[1] != qh.shape[1]:  # GQA: replicate kv heads
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)

    perm = [(i, (i + 1) % cp) for i in range(cp)]
    rows = jnp.arange(S)

    def _mask_for(src):
        if not causal:
            return None
        # global positions: q at idx*S + row, kv at src*S + col
        q_pos = idx * S + rows[:, None]
        k_pos = src * S + rows[None, :]
        return q_pos >= k_pos

    # step 0 is the resident (diagonal) block: no rotation needed, and doing it
    # first means the scan issues exactly cp-1 ppermutes — the final rotation
    # would only restore the starting layout, which nobody reads.
    o, m, l = _block_attn(qh, kh, vh, _mask_for(idx), scale)

    def body(carry, step):
        o, m, l, k_cur, v_cur = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (idx - step) % cp  # global chunk index held after `step` rotations
        o_new, m_new, l_new = _block_attn(qh, k_cur, v_cur, _mask_for(src), scale)
        o, m, l = _merge_blocks(o, m, l, o_new, m_new, l_new)
        return (o, m, l, k_cur, v_cur), None

    if cp > 1:
        (o, m, l, _, _), _ = jax.lax.scan(body, (o, m, l, kh, vh), jnp.arange(1, cp))
    out = o / jnp.maximum(l, 1e-30)[..., None].astype(o.dtype)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _local_allgather_attention(q, k, v, *, axis_name: str, axis_size: int, causal: bool, scale: float):
    """CP with one-shot KV allgather (reference rotate_method='allgather')."""
    cp = axis_size
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    k_full = jax.lax.all_gather(k, axis_name, axis=1, tiled=True)  # [B, S*cp, Hkv, D]
    v_full = jax.lax.all_gather(v, axis_name, axis=1, tiled=True)
    qh = q.transpose(0, 2, 1, 3)
    kh = k_full.transpose(0, 2, 1, 3)
    vh = v_full.transpose(0, 2, 1, 3)
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    mask = None
    if causal:
        q_pos = idx * S + jnp.arange(S)[:, None]
        k_pos = jnp.arange(S * cp)[None, :]
        mask = q_pos >= k_pos
    o, m, l = _block_attn(qh, kh, vh, mask, scale)
    out = o / jnp.maximum(l, 1e-30)[..., None].astype(o.dtype)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _local_ulysses_attention(q, k, v, *, axis_name: str, axis_size: int, causal: bool, scale: float):
    """Runs INSIDE shard_map over the sp axis: local [B, S_loc, H, D] →
    all-to-all → [B, S, H_loc, D] → full-seq attention → all-to-all back
    (reference UlyssesSPAttentionHF head-sharding, accelerator.py:2344-2390)."""
    from ..ops.attention import _xla_attention

    def seq_to_head(x):
        # split heads across the axis, concat sequence
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def head_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    q_h, k_h, v_h = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    out = _xla_attention(q_h, k_h, v_h, causal=causal, mask=None, scale=scale)
    return head_to_seq(out)


def make_context_parallel_attention(
    mesh,
    strategy: str = "ring",  # "ring" | "allgather" | "ulysses"
    axis_name: Optional[str] = None,
    batch_axes: tuple = DP_AXES,
    head_axis: str = "tp",
):
    """Build an attention_fn over GLOBAL [B, S, H, D] arrays that parallelizes the
    sequence dim over ``cp`` (ring/allgather) or ``sp`` (ulysses).

    The returned function is jit-compatible and differentiable; it is the
    ``attention_fn`` hook of the model family (the moral twin of the reference's
    ``maybe_context_parallel`` buffer-sharding context, ``accelerator.py:4056``).
    """
    from jax import shard_map

    if axis_name is None:
        axis_name = "sp" if strategy == "ulysses" else "cp"
    axis_size = mesh.shape.get(axis_name, 1)
    head_axis_in_mesh = head_axis if mesh.shape.get(head_axis, 1) > 1 else None

    local_fn = {
        "ring": _local_ring_attention,
        "allgather": _local_allgather_attention,
        "ulysses": _local_ulysses_attention,
    }[strategy]

    def attention_fn(q, k, v, causal: bool = True, scale: Optional[float] = None):
        if axis_size <= 1:
            from ..ops.attention import dot_product_attention

            return dot_product_attention(q, k, v, causal=causal, scale=scale)
        scale_v = 1.0 / math.sqrt(q.shape[-1]) if scale is None else scale
        if strategy == "ulysses" and (
            q.shape[2] % axis_size != 0 or k.shape[2] % axis_size != 0
        ):
            raise ValueError(
                f"Ulysses SP needs q heads ({q.shape[2]}) and kv heads ({k.shape[2]}) "
                f"divisible by sp size ({axis_size}); use ring CP for more chips than heads"
            )
        spec = P(batch_axes, axis_name, head_axis_in_mesh, None)
        fn = shard_map(
            partial(
                local_fn, axis_name=axis_name, axis_size=axis_size, causal=causal, scale=scale_v
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)

    return attention_fn


def sequence_parallel_attention(mesh, **kwargs):
    """Ulysses attention_fn (reference ALST/UlyssesSP path)."""
    return make_context_parallel_attention(mesh, strategy="ulysses", **kwargs)
