"""Long-context attention parallelism: ring attention (CP) and Ulysses (SP).

TPU-native counterpart of the reference's two mutually-exclusive long-context
backends (SURVEY.md §5 "Long-context / sequence parallelism"):

- **CP / ring attention** — reference ``_prepare_cp`` (``accelerator.py:1628``) +
  ``maybe_context_parallel`` (``:4056-4120``) wrap torch's experimental
  ``context_parallel`` with allgather/alltoall KV rotation. Here: the sequence
  dim is sharded over the ``cp`` mesh axis; K/V blocks rotate around the ICI
  ring with ``lax.ppermute`` inside ``shard_map`` while a flash-style online
  softmax accumulates — O(S/cp) memory per chip, fully overlapped
  compute/communication, differentiable end-to-end. ``rotate="allgather"``
  instead gathers KV once (better for short rings).
- **SP / Ulysses** — reference DeepSpeed ALST path (``accelerator.py:2344-2456``):
  head-sharded attention via all-to-all. Here: ``lax.all_to_all`` reshards
  seq-sharded QKV to head-sharded, runs full-sequence attention locally, and
  reshards back.

Both produce an ``attention_fn(q, k, v, causal=...)`` over GLOBAL [B, S, H, D]
arrays, drop-in for ``models``' pluggable attention hook.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallelism_config import DP_AXES


NEG_INF = -1e30


def _block_attn(q, k, v, mask, scale):
    """One flash block: returns (unnormalized out, row max, row sumexp).

    q: [B, Hq, Sq, D]; k,v: [B, Hq, Skv, D]; mask: [Sq, Skv] bool or None.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    return o, m, l


def _merge_blocks(o, m, l, o_new, m_new, l_new):
    """Online-softmax merge of two partial attention results."""
    m_tot = jnp.maximum(m, m_new)
    c_old = jnp.exp(m - m_tot)
    c_new = jnp.exp(m_new - m_tot)
    o = o * c_old[..., None].astype(o.dtype) + o_new * c_new[..., None].astype(o.dtype)
    l = l * c_old + l_new * c_new
    return o, m_tot, l


def _local_ring_attention(q, k, v, *, axis_name: str, axis_size: int, causal: bool, scale: float):
    """Runs INSIDE shard_map: q,k,v are the local seq shards [B, S_loc, H, D]."""
    cp = axis_size
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    # head-major layout for the block kernel
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    if kh.shape[1] != qh.shape[1]:  # GQA: replicate kv heads
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)

    perm = [(i, (i + 1) % cp) for i in range(cp)]
    rows = jnp.arange(S)

    def _mask_for(src):
        if not causal:
            return None
        # global positions: q at idx*S + row, kv at src*S + col
        q_pos = idx * S + rows[:, None]
        k_pos = src * S + rows[None, :]
        return q_pos >= k_pos

    # step 0 is the resident (diagonal) block: no rotation needed, and doing it
    # first means the scan issues exactly cp-1 ppermutes — the final rotation
    # would only restore the starting layout, which nobody reads.
    o, m, l = _block_attn(qh, kh, vh, _mask_for(idx), scale)

    def body(carry, step):
        o, m, l, k_cur, v_cur = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (idx - step) % cp  # global chunk index held after `step` rotations
        o_new, m_new, l_new = _block_attn(qh, k_cur, v_cur, _mask_for(src), scale)
        o, m, l = _merge_blocks(o, m, l, o_new, m_new, l_new)
        return (o, m, l, k_cur, v_cur), None

    if cp > 1:
        (o, m, l, _, _), _ = jax.lax.scan(body, (o, m, l, kh, vh), jnp.arange(1, cp))
    out = o / jnp.maximum(l, 1e-30)[..., None].astype(o.dtype)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _zigzag_perms(cp: int):
    """Static lane permutations for the zig-zag exchange.

    Global HALF-chunks are numbered 0..2cp-1; contiguous layout puts chunks
    (2i, 2i+1) on rank i, zig-zag layout puts (i, 2cp-1-i) on rank i. Chunk c's
    zig-zag home is ``c if c < cp else 2cp-1-c``. Routing lane A (each rank's
    first half, chunk 2i) and lane B (second half, 2i+1) separately makes each
    lane's routing a bijection on ranks → one ``ppermute`` per lane."""
    home = lambda c: c if c < cp else 2 * cp - 1 - c
    perm_a = [(i, home(2 * i)) for i in range(cp)]
    perm_b = [(i, home(2 * i + 1)) for i in range(cp)]
    inv_a = [(dst, src) for src, dst in perm_a]
    inv_b = [(dst, src) for src, dst in perm_b]
    # chunk id arriving in each lane at rank r (for low/high normalization)
    lane_a_chunk = [0] * cp
    lane_b_chunk = [0] * cp
    for i in range(cp):
        lane_a_chunk[home(2 * i)] = 2 * i
        lane_b_chunk[home(2 * i + 1)] = 2 * i + 1
    return perm_a, perm_b, inv_a, inv_b, lane_a_chunk, lane_b_chunk


def _local_zigzag_attention(q, k, v, *, axis_name: str, axis_size: int, causal: bool, scale: float):
    """Load-balanced causal ring attention (zig-zag chunk placement).

    The contiguous ring computes every (q-shard × kv-shard) block and masks the
    upper-triangle half away — wasted MXU work that also skews per-rank useful
    FLOPs (SURVEY §7 hard part: "load-balancing zig-zag order"; same trick as
    llama3/ring-flash-attention's striped layout). Re-placing half-chunks so
    rank i holds global half-chunks ``(i, 2cp-1-i)`` makes every rotation step
    need exactly TWO half-blocks of UNMASKED attention on every rank —
    half the block-FLOPs of the contiguous schedule, perfectly balanced.

    Data stays contiguous outside: the exchange (2 ppermutes in, 2 out) is
    internal. The rotation loop is unrolled (cp is static, the per-step
    operand selection is a cheap ``where``); fully-masked blocks are simply
    never computed.
    """
    cp = axis_size
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    if S % 2 != 0:
        raise ValueError(f"zigzag CP needs an even local sequence shard, got {S}")
    half = S // 2
    perm_a, perm_b, inv_a, inv_b, lane_a_chunk, lane_b_chunk = _zigzag_perms(cp)
    lane_a_chunk = jnp.asarray(lane_a_chunk)
    lane_b_chunk = jnp.asarray(lane_b_chunk)

    def heads_major(x):
        return x.transpose(0, 2, 1, 3)  # [B, H, S, D]

    qh, kh, vh = heads_major(q), heads_major(k), heads_major(v)
    if kh.shape[1] != qh.shape[1]:  # GQA
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)

    def exchange(x):  # contiguous halves → zigzag lanes
        a = jax.lax.ppermute(x[:, :, :half], axis_name, perm_a)
        b = jax.lax.ppermute(x[:, :, half:], axis_name, perm_b)
        return a, b

    qa, qb = exchange(qh)
    ka, kb = exchange(kh)
    va, vb = exchange(vh)
    # normalize lanes to (low chunk = idx, high chunk = 2cp-1-idx)
    a_is_low = (lane_a_chunk[idx] < lane_b_chunk[idx])[None, None, None, None]

    def pick(low_first, a, b):
        cond = a_is_low if low_first else ~a_is_low
        return jnp.where(cond, a, b)

    q_lo, q_hi = pick(True, qa, qb), pick(False, qa, qb)
    k_lo, k_hi = pick(True, ka, kb), pick(False, ka, kb)
    v_lo, v_hi = pick(True, va, vb), pick(False, va, vb)

    tril = jnp.tril(jnp.ones((half, half), dtype=bool))
    # resident step: q_lo×kv_lo and q_hi×kv_hi are causal diagonals;
    # q_hi×kv_lo is a full block (high chunk id > low chunk id always)
    o_lo, m_lo, l_lo = _block_attn(q_lo, k_lo, v_lo, tril, scale)
    o_hi, m_hi, l_hi = _block_attn(q_hi, k_hi, v_hi, tril, scale)
    o_hi, m_hi, l_hi = _merge_blocks(o_hi, m_hi, l_hi, *_block_attn(q_hi, k_lo, v_lo, None, scale))

    shift = [(i, (i + 1) % cp) for i in range(cp)]

    def body(carry, step):
        (o_lo, m_lo, l_lo, o_hi, m_hi, l_hi, k_lo_c, k_hi_c, v_lo_c, v_hi_c) = carry
        k_lo_c = jax.lax.ppermute(k_lo_c, axis_name, shift)
        k_hi_c = jax.lax.ppermute(k_hi_c, axis_name, shift)
        v_lo_c = jax.lax.ppermute(v_lo_c, axis_name, shift)
        v_hi_c = jax.lax.ppermute(v_hi_c, axis_name, shift)
        j = (idx - step) % cp  # low chunk id of the kv pair now held
        pred = (j < idx)[None, None, None, None]
        # j < idx: needed blocks are (q_lo, kv_lo) and (q_hi, kv_lo)
        # j > idx: needed blocks are (q_hi, kv_lo) and (q_hi, kv_hi)
        # — always two FULL (unmasked) half-blocks; see _zigzag_perms docstring
        qa_sel = jnp.where(pred, q_lo, q_hi)
        ob_a, mb_a, lb_a = _block_attn(qa_sel, k_lo_c, v_lo_c, None, scale)
        kv_sel_k = jnp.where(pred, k_lo_c, k_hi_c)
        kv_sel_v = jnp.where(pred, v_lo_c, v_hi_c)
        ob_b, mb_b, lb_b = _block_attn(q_hi, kv_sel_k, kv_sel_v, None, scale)
        # block A merges into acc_lo when j<idx, else into acc_hi
        pm = pred[..., 0]  # [1,1,1] broadcast over [B,H,Sq]
        n_lo = _merge_blocks(o_lo, m_lo, l_lo, ob_a, mb_a, lb_a)
        n_hi = _merge_blocks(o_hi, m_hi, l_hi, ob_a, mb_a, lb_a)
        o_lo = jnp.where(pred, n_lo[0], o_lo)
        m_lo = jnp.where(pm, n_lo[1], m_lo)
        l_lo = jnp.where(pm, n_lo[2], l_lo)
        o_hi = jnp.where(pred, o_hi, n_hi[0])
        m_hi = jnp.where(pm, m_hi, n_hi[1])
        l_hi = jnp.where(pm, l_hi, n_hi[2])
        # block B always belongs to acc_hi
        o_hi, m_hi, l_hi = _merge_blocks(o_hi, m_hi, l_hi, ob_b, mb_b, lb_b)
        return (o_lo, m_lo, l_lo, o_hi, m_hi, l_hi, k_lo_c, k_hi_c, v_lo_c, v_hi_c), None

    if cp > 1:
        (o_lo, m_lo, l_lo, o_hi, m_hi, l_hi, *_), _ = jax.lax.scan(
            body,
            (o_lo, m_lo, l_lo, o_hi, m_hi, l_hi, k_lo, k_hi, v_lo, v_hi),
            jnp.arange(1, cp),
        )

    out_lo = o_lo / jnp.maximum(l_lo, 1e-30)[..., None].astype(o_lo.dtype)
    out_hi = o_hi / jnp.maximum(l_hi, 1e-30)[..., None].astype(o_hi.dtype)
    # restore lanes, then un-exchange back to the contiguous layout
    lane_a = jnp.where(a_is_low, out_lo, out_hi)
    lane_b = jnp.where(a_is_low, out_hi, out_lo)
    first = jax.lax.ppermute(lane_a, axis_name, inv_a)
    second = jax.lax.ppermute(lane_b, axis_name, inv_b)
    out = jnp.concatenate([first, second], axis=2)  # [B, H, S, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _local_allgather_attention(q, k, v, *, axis_name: str, axis_size: int, causal: bool, scale: float):
    """CP with one-shot KV allgather (reference rotate_method='allgather')."""
    cp = axis_size
    idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    k_full = jax.lax.all_gather(k, axis_name, axis=1, tiled=True)  # [B, S*cp, Hkv, D]
    v_full = jax.lax.all_gather(v, axis_name, axis=1, tiled=True)
    qh = q.transpose(0, 2, 1, 3)
    kh = k_full.transpose(0, 2, 1, 3)
    vh = v_full.transpose(0, 2, 1, 3)
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    mask = None
    if causal:
        q_pos = idx * S + jnp.arange(S)[:, None]
        k_pos = jnp.arange(S * cp)[None, :]
        mask = q_pos >= k_pos
    o, m, l = _block_attn(qh, kh, vh, mask, scale)
    out = o / jnp.maximum(l, 1e-30)[..., None].astype(o.dtype)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _local_ulysses_attention(q, k, v, *, axis_name: str, axis_size: int, causal: bool, scale: float):
    """Runs INSIDE shard_map over the sp axis: local [B, S_loc, H, D] →
    all-to-all → [B, S, H_loc, D] → full-seq attention → all-to-all back
    (reference UlyssesSPAttentionHF head-sharding, accelerator.py:2344-2390)."""
    from ..ops.attention import _xla_attention

    def seq_to_head(x):
        # split heads across the axis, concat sequence
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def head_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    q_h, k_h, v_h = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    out = _xla_attention(q_h, k_h, v_h, causal=causal, mask=None, scale=scale)
    return head_to_seq(out)


def make_context_parallel_attention(
    mesh,
    strategy: str = "ring",  # "ring" | "zigzag" | "allgather" | "ulysses"
    axis_name: Optional[str] = None,
    batch_axes: tuple = DP_AXES,
    head_axis: str = "tp",
):
    """Build an attention_fn over GLOBAL [B, S, H, D] arrays that parallelizes the
    sequence dim over ``cp`` (ring/allgather) or ``sp`` (ulysses).

    The returned function is jit-compatible and differentiable; it is the
    ``attention_fn`` hook of the model family (the moral twin of the reference's
    ``maybe_context_parallel`` buffer-sharding context, ``accelerator.py:4056``).
    """
    from ..utils.jax_compat import shard_map

    if axis_name is None:
        axis_name = "sp" if strategy == "ulysses" else "cp"
    axis_size = mesh.shape.get(axis_name, 1)
    head_axis_in_mesh = head_axis if mesh.shape.get(head_axis, 1) > 1 else None

    local_fn = {
        "ring": _local_ring_attention,
        "zigzag": _local_zigzag_attention,
        "allgather": _local_allgather_attention,
        "ulysses": _local_ulysses_attention,
    }[strategy]

    def attention_fn(q, k, v, causal: bool = True, scale: Optional[float] = None):
        if axis_size <= 1:
            from ..ops.attention import dot_product_attention

            return dot_product_attention(q, k, v, causal=causal, scale=scale)
        fn_local = local_fn
        if strategy == "zigzag" and not causal:
            # without causal masking every block is needed — the balanced
            # placement buys nothing; use the plain ring
            fn_local = _local_ring_attention
        scale_v = 1.0 / math.sqrt(q.shape[-1]) if scale is None else scale
        if strategy == "ulysses" and (
            q.shape[2] % axis_size != 0 or k.shape[2] % axis_size != 0
        ):
            raise ValueError(
                f"Ulysses SP needs q heads ({q.shape[2]}) and kv heads ({k.shape[2]}) "
                f"divisible by sp size ({axis_size}); use ring CP for more chips than heads"
            )
        spec = P(batch_axes, axis_name, head_axis_in_mesh, None)
        fn = shard_map(
            partial(
                fn_local, axis_name=axis_name, axis_size=axis_size, causal=causal, scale=scale_v
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)

    return attention_fn


def sequence_parallel_attention(mesh, **kwargs):
    """Ulysses attention_fn (reference ALST/UlyssesSP path)."""
    return make_context_parallel_attention(mesh, strategy="ulysses", **kwargs)
