"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

TPU-native counterpart of the reference's PiPPy integration
(``/root/reference/src/accelerate/inference.py`` — ``prepare_pippy:126``,
``build_pipeline:75`` auto-splitting by balanced size, ``pippy_forward:101``
with ``ScheduleGPipe`` microbatching) and of Megatron's training-side PP.

Architecture shift: PiPPy traces an ``nn.Module`` into per-rank graph stages
and moves microbatches over NCCL P2P. Here the model is ALREADY a stack of
homogeneous stage params (leading ``pp``-sharded axis); the schedule is a
``lax.scan`` inside ``shard_map`` whose per-tick communication is one
``lax.ppermute`` shifting activations to the next stage over ICI. The whole
schedule is one compiled function — differentiable end to end, so unlike the
reference (inference-only without Megatron) the same code trains: ``jax.grad``
through ``ppermute`` yields the reverse (backward) pipeline automatically.

Composition: ``shard_map`` is manual over ``pp`` only (``axis_names={'pp'}``);
inside a stage, arrays keep their GSPMD shardings, so tp/dp/cp compose with
pipelining the usual way.
"""

from __future__ import annotations

from typing import Any, Callable


def split_into_stages(layer_params: list, pp: int) -> Any:
    """Stack per-layer param trees ``[L entries] → leaves [pp, L//pp, ...]``
    (the analogue of reference ``build_pipeline``'s balanced split points,
    ``inference.py:75-99`` — homogeneous decoder layers split evenly)."""
    import jax
    import jax.numpy as jnp

    L = len(layer_params)
    if L % pp != 0:
        raise ValueError(f"{L} layers not divisible into {pp} pipeline stages")
    per = L // pp

    def _stack(*leaves):
        stacked = jnp.stack([jnp.asarray(x) for x in leaves], axis=0)  # [L, ...]
        return stacked.reshape((pp, per) + stacked.shape[1:])

    return jax.tree_util.tree_map(_stack, *layer_params)


def split_microbatches(batch, num_microbatches: int):
    """[B, ...] → [M, B//M, ...] on every leaf (reference GPipe ``chunks`` arg,
    ``inference.py:141``)."""
    import jax
    import jax.numpy as jnp

    def _split(x):
        B = x.shape[0]
        if B % num_microbatches != 0:
            raise ValueError(f"batch {B} not divisible into {num_microbatches} microbatches")
        return jnp.reshape(x, (num_microbatches, B // num_microbatches) + x.shape[1:])

    return jax.tree_util.tree_map(_split, batch)


def merge_microbatches(batch):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.reshape(x, (x.shape[0] * x.shape[1],) + x.shape[2:]), batch
    )


def make_pipeline_forward(
    stage_fn: Callable,
    mesh,
    num_microbatches: int,
    axis_name: str = "pp",
):
    """Build ``forward(stage_params_stack, x) -> y`` running a GPipe schedule.

    ``stage_fn(stage_params, x) -> y`` is one stage's compute (e.g. a
    ``lax.scan`` over its layer slice); activations must have the same
    shape/dtype as inputs (transformer trunk). ``stage_params_stack`` leaves
    carry a leading ``[pp, ...]`` axis sharded over ``pp``; ``x`` is the global
    ``[B, ...]`` activation batch (already embedded).

    The schedule runs ``M + pp - 1`` ticks; tick ``t`` has stage ``s`` compute
    microbatch ``t - s`` (the classic GPipe trapezoid), with one ``ppermute``
    per tick moving activations down the ring.
    """
    import jax
    import jax.numpy as jnp
    from ..utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    pp = int(mesh.shape[axis_name])
    M = num_microbatches
    if pp <= 1:
        def forward_trivial(stage_params_stack, x):
            sp = jax.tree_util.tree_map(lambda a: a[0], stage_params_stack)
            return stage_fn(sp, x)

        return forward_trivial

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def _local(stage_params, x_micro):
        # stage_params leaves [1, ...]; x_micro [M, Bm, ...] (replicated over pp)
        params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        idx = jax.lax.axis_index(axis_name)
        out_buf = jnp.zeros_like(x_micro)

        def tick(carry, t):
            cur, out_buf = carry
            # stage 0 injects microbatch t (clamped; masked-out beyond M-1)
            inject = x_micro[jnp.minimum(t, M - 1)]
            stage_in = jnp.where(idx == 0, inject, cur)
            y = stage_fn(params, stage_in)
            # last stage records microbatch t-(pp-1) once the trapezoid fills
            write_idx = jnp.clip(t - (pp - 1), 0, M - 1)
            do_write = jnp.logical_and(idx == pp - 1, t >= pp - 1)
            out_buf = jax.lax.cond(
                do_write,
                lambda b: jax.lax.dynamic_update_index_in_dim(b, y, write_idx, 0),
                lambda b: b,
                out_buf,
            )
            # shift activations to the next stage (stage pp-1 sends nowhere)
            nxt = jax.lax.ppermute(y, axis_name, fwd_perm)
            return (nxt, out_buf), None

        cur0 = jnp.zeros_like(x_micro[0])
        (cur, out_buf), _ = jax.lax.scan(tick, (cur0, out_buf), jnp.arange(M + pp - 1))
        # every stage returns its buffer; only the last stage's holds the result
        # — the caller slices [-1], which fetches just that stage's shard
        return out_buf[None]  # [1, M, Bm, ...]

    sm = shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(axis_name),
        axis_names={axis_name},
        check_vma=False,
    )

    def forward(stage_params_stack, x):
        x_micro = split_microbatches(x, M)
        stacked = sm(stage_params_stack, x_micro)  # [pp, M, Bm, ...]
        return merge_microbatches(stacked[-1])

    return forward


def prepare_pipeline(
    layer_params: list,
    stage_fn: Callable,
    mesh,
    num_microbatches: int | None = None,
    axis_name: str = "pp",
):
    """One-call pipeline prep (the reference's user entry ``prepare_pippy:126``:
    auto-split into balanced stages + a GPipe-scheduled forward). Balances the
    homogeneous layer stack over the ``pp`` mesh axis and returns
    ``(stage_params_stack, forward)`` with ``forward(stage_params_stack, x)``
    running the microbatched schedule. ``num_microbatches`` defaults to the
    pipeline degree (enough to fill the trapezoid)."""
    pp = int(mesh.shape[axis_name])
    if num_microbatches is None:
        num_microbatches = max(pp, 1)
    stacked = split_into_stages(layer_params, pp)
    forward = make_pipeline_forward(stage_fn, mesh, num_microbatches, axis_name)
    return stacked, forward


def make_pipeline_train_step_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    mesh,
    num_microbatches: int,
    axis_name: str = "pp",
):
    """1F1B training schedule: backward for a microbatch starts as soon as its
    forward clears the last stage, so each stage holds at most
    ``2·(pp-1-s)+1`` in-flight microbatch inputs instead of GPipe's all-``M``
    residuals (reference precedent to beat: ScheduleGPipe,
    ``/root/reference/src/accelerate/inference.py:101-125`` — inference-only;
    Megatron's 1F1B is the training-side shape this matches).

    Mechanics (one ``lax.scan`` inside ``shard_map``, lockstep across stages):

    - tick ``k``: stage ``s`` FORWARDS microbatch ``m_f = k - s`` (the GPipe
      trapezoid) and BACKWARDS microbatch ``m_b = k - (2·pp - 2 - s)`` — on
      the last stage these coincide (loss vjp starts immediately), upstream
      stages run ``2·(pp-1-s)`` ticks behind, which is exactly the 1F1B
      interleave.
    - residuals: only each microbatch's stage INPUT is kept, in a ring buffer
      of depth ``min(M, 2·pp-1)``; the backward recomputes the stage forward
      inside ``jax.vjp`` (remat — the standard memory/flops trade of 1F1B
      implementations).
    - per-tick comms: one fwd ``ppermute`` (activations down) and one bwd
      ``ppermute`` (input-grads up) on the ICI ring.

    ``stage_fn(stage_params, x) -> y`` as in :func:`make_pipeline_forward`;
    ``loss_fn(y, target) -> scalar`` is applied per microbatch on the last
    stage (mean over microbatches is returned). Returns
    ``step(stage_params_stack, x, targets) -> (loss, grads_stack)`` with
    ``grads_stack`` sharded ``[pp, ...]`` like the params.
    """
    import jax
    import jax.numpy as jnp
    from ..utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    pp = int(mesh.shape[axis_name])
    M = num_microbatches
    if pp <= 1:
        def step_trivial(stage_params_stack, x, targets):
            sp = jax.tree_util.tree_map(lambda a: a[0], stage_params_stack)

            def whole(p, x, t):
                return loss_fn(stage_fn(p, x), t)

            loss, grads = jax.value_and_grad(whole)(sp, x, targets)
            return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

        return step_trivial

    R = min(M, 2 * pp - 1)  # ring depth ≥ max in-flight microbatches per stage
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]
    bwd_perm = [(i + 1, i) for i in range(pp - 1)]
    T = M + 2 * pp - 2  # last tick: stage 0's backward of microbatch M-1

    def _local(stage_params, x_micro, tgt_micro):
        params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        idx = jax.lax.axis_index(axis_name)
        is_last = idx == pp - 1
        zero_x = jnp.zeros_like(x_micro[0])

        def fwd_only(p, x):
            return stage_fn(p, x)

        def tick(carry, k):
            cur_fwd, cur_bwd, ring, grads_acc, loss_acc = carry

            # ---- forward slot: microbatch m_f = k - idx --------------------
            m_f = k - idx
            fwd_valid = jnp.logical_and(m_f >= 0, m_f < M)
            x_in = jnp.where(idx == 0, x_micro[jnp.clip(m_f, 0, M - 1)], cur_fwd)
            y = stage_fn(params, x_in)
            slot_f = jnp.clip(m_f, 0, M - 1) % R
            ring = jax.lax.cond(
                fwd_valid,
                lambda r: jax.lax.dynamic_update_index_in_dim(r, x_in, slot_f, 0),
                lambda r: r,
                ring,
            )

            # ---- backward slot: microbatch m_b = k - (2pp - 2 - idx) -------
            m_b = k - (2 * pp - 2 - idx)
            bwd_valid = jnp.logical_and(m_b >= 0, m_b < M)
            slot_b = jnp.clip(m_b, 0, M - 1) % R
            x_saved = ring[slot_b]
            target = tgt_micro[jnp.clip(m_b, 0, M - 1)]

            # ONE stage vjp per tick: the cotangent is the loss grad wrt this
            # stage's OWN recomputed output on the last stage, or the grad
            # received from downstream elsewhere (lockstep SPMD — the cheap
            # loss-only grad runs masked everywhere, the expensive stage
            # backward runs once)
            y_saved, vjp = jax.vjp(fwd_only, params, x_saved)
            loss_m, dy_last = jax.value_and_grad(loss_fn)(y_saved, target)
            cot = jnp.where(is_last, dy_last, cur_bwd)
            dp, dx = vjp(cot.astype(y_saved.dtype))
            grads_acc = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(bwd_valid, g, jnp.zeros_like(g)),
                grads_acc,
                dp,
            )
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(bwd_valid, is_last), loss_m, 0.0
            )

            nxt_fwd = jax.lax.ppermute(y, axis_name, fwd_perm)
            nxt_bwd = jax.lax.ppermute(
                jnp.where(bwd_valid, dx, jnp.zeros_like(dx)), axis_name, bwd_perm
            )
            return (nxt_fwd, nxt_bwd, ring, grads_acc, loss_acc), None

        ring0 = jnp.zeros((R,) + x_micro.shape[1:], x_micro.dtype)
        grads0 = jax.tree_util.tree_map(jnp.zeros_like, params)
        carry0 = (zero_x, jnp.zeros_like(zero_x), ring0, grads0, jnp.float32(0.0))
        (_, _, _, grads_acc, loss_acc), _ = jax.lax.scan(
            tick, carry0, jnp.arange(T)
        )
        grads_acc = jax.tree_util.tree_map(lambda g: (g / M)[None], grads_acc)
        # only the last stage accumulated a nonzero loss; psum shares it, and
        # each stage emits one slot of a [pp] vector (partial-manual shard_map
        # requires outputs to carry the manual axis)
        loss = jax.lax.psum(loss_acc / M, axis_name)
        return loss[None], grads_acc

    sm = shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P()),
        out_specs=(P(axis_name), P(axis_name)),
        axis_names={axis_name},
        check_vma=False,
    )

    import functools

    @functools.partial(jax.jit)  # partial-manual shard_map requires jit context
    def step(stage_params_stack, x, targets):
        x_micro = split_microbatches(x, M)
        tgt_micro = split_microbatches(targets, M)
        loss_stack, grads = sm(stage_params_stack, x_micro, tgt_micro)
        return loss_stack[0], grads

    return step
