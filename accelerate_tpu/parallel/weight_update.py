"""Fused cross-replica weight-update sharding: real ZeRO-1 inside the jitted step.

The technique of "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al. 2020, arXiv:2004.13336), made explicit
instead of annotation-and-hope (the previous ``zero1_state_specs`` path merely
sharded the moment buffers and let GSPMD partition the update — which also let
the partitioner re-shard the forward/backward graph, reassociating reductions
and making the "ZeRO-1 matches replicated DP" comparison ulp-unstable):

1. **Bucket**: gradients are flattened and concatenated into size-bounded,
   dtype-homogeneous buckets (:class:`Zero1BucketPlan`), padded so every bucket
   splits evenly across the replicate axis.
2. **Reduce-scatter**: each replica keeps only its ``1/N`` chunk of each grad
   bucket. Gradients of a mean loss over a dp-sharded batch come out of
   ``jax.grad`` already summed (a GSPMD all-reduce); the per-replica chunk is a
   ``dynamic_slice`` keyed on the replica id, exactly the all-reduce +
   partition-slice pattern XLA's reassociation pass rewrites into a
   reduce-scatter (the CRS paper's transformation).
3. **Shard-local update**: the optimizer transform runs on the ``1/N`` chunk —
   optimizer math AND first/second-moment memory drop to ``1/N`` per replica.
4. **All-gather**: the updated param chunks are reassembled. Buckets are
   independent chains in the HLO, so XLA's latency-hiding scheduler can overlap
   the all-gather of bucket *i* with the optimizer math of bucket *i+1*.

The update region runs under ``shard_map`` (manual collectives), so no sharding
constraint leaks into the forward/backward graph: the compiled loss/grad math
is instruction-identical to the replicated-DP baseline, and the fused step's
weights match it **bitwise** on a deterministic backend.

Scope: the fused path assumes an *elementwise* optimizer transform chain
(adam/adamw/sgd/lion/MultiSteps wrappers — anything whose per-element update
depends only on that element's grad/param/state). Shape-dependent transforms
(adafactor's factored moments, per-tensor trust ratios) are detected at init
when they materialize non-bucket-shaped state and fall back to the annotation
path; stateless shape-dependent transforms cannot be detected — disable with
``ACCELERATE_ZERO1_FUSED=0`` for those.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

DEFAULT_BUCKET_BYTES = 4 * 1024 * 1024
BUCKET_BYTES_ENV = "ACCELERATE_ZERO1_BUCKET_MB"


class FusedZero1Incompatible(ValueError):
    """The optimizer transform materialized state the fused ZeRO-1 path cannot
    shard (non-bucket-shaped array leaves, e.g. adafactor's factored moments).
    Callers catch this and fall back to the GSPMD annotation path."""


def bucket_bytes_from_env(default: int = DEFAULT_BUCKET_BYTES) -> int:
    raw = os.environ.get(BUCKET_BYTES_ENV, "").strip()
    if not raw:
        return default
    try:
        return max(1, int(float(raw) * 1024 * 1024))
    except ValueError:
        return default


@dataclass(frozen=True)
class _LeafSlot:
    """Where one param/grad leaf lives inside the bucketed representation."""

    leaf_index: int  # position in tree-flatten order
    bucket: str
    offset: int  # element offset into the bucket
    size: int  # element count
    shape: tuple
    dtype: str


@dataclass
class Zero1BucketPlan:
    """Static layout of the bucketed ZeRO-1 weight update for one param tree.

    Built once (from shapes only) by :func:`build_bucket_plan`; used inside the
    jitted step to flatten grads/params into buckets and re-assemble updated
    params. Buckets are dtype-homogeneous and padded to a multiple of
    ``axis_size`` so each replica owns an equal contiguous chunk.
    """

    axis: str
    axis_size: int
    treedef: Any
    slots: "list[_LeafSlot]"
    bucket_sizes: "dict[str, int]"  # padded element counts
    bucket_dtypes: "dict[str, Any]"  # np.dtype per bucket
    n_elements: int = 0  # total unpadded bucketed param elements
    # leaf indices (tree-flatten order) excluded from buckets and carried
    # alongside them: replace-with-cotangent leaves (fp8 delayed-scaling meta)
    # whose "gradient" IS the new value, never touched by the optimizer tx
    passthrough_indices: tuple = ()

    # ------------------------------------------------------------ properties --
    @property
    def bucket_names(self) -> "list[str]":
        return list(self.bucket_sizes)

    @property
    def num_buckets(self) -> int:
        return len(self.bucket_sizes)

    def chunk_size(self, name: str) -> int:
        return self.bucket_sizes[name] // self.axis_size

    @property
    def bucket_nbytes(self) -> "dict[str, int]":
        return {
            name: size * np.dtype(self.bucket_dtypes[name]).itemsize
            for name, size in self.bucket_sizes.items()
        }

    @property
    def collective_bytes(self) -> int:
        """Bytes moved per update in ONE direction (the reduce-scatter of grad
        buckets; the all-gather of param buckets moves the same amount)."""
        return sum(self.bucket_nbytes.values())

    # ------------------------------------------------------------- transforms --
    def bucket_tree(self, tree):
        """Flatten a param-shaped pytree into ``{bucket_name: 1-D array}``.
        Trace-safe (pure jnp ops); padding elements are zeros."""
        import jax
        import jax.numpy as jnp

        leaves = jax.tree_util.tree_leaves(tree)
        planned = len(self.slots) + len(self.passthrough_indices)
        if len(leaves) != planned:
            raise ValueError(
                f"tree has {len(leaves)} leaves but the bucket plan was built "
                f"for {planned} — not the planned param structure"
            )
        parts: "dict[str, list]" = {name: [] for name in self.bucket_sizes}
        filled: "dict[str, int]" = {name: 0 for name in self.bucket_sizes}
        for slot in self.slots:
            parts[slot.bucket].append(jnp.ravel(leaves[slot.leaf_index]))
            filled[slot.bucket] += slot.size
        out = {}
        for name, pieces in parts.items():
            pad = self.bucket_sizes[name] - filled[name]
            if pad:
                pieces.append(jnp.zeros((pad,), self.bucket_dtypes[name]))
            out[name] = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
        return out

    def passthrough_leaves(self, tree) -> "list":
        """The tree's passthrough leaves, in ``passthrough_indices`` order."""
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
        return [leaves[i] for i in self.passthrough_indices]

    def unbucket_tree(self, buckets, passthrough=None):
        """Rebuild the param-shaped pytree from ``{bucket_name: 1-D array}``.
        Plans with passthrough slots need ``passthrough``: the leaf values (in
        ``passthrough_indices`` order) to splice back in."""
        import jax

        n_leaves = len(self.slots) + len(self.passthrough_indices)
        leaves: "list" = [None] * n_leaves
        for slot in self.slots:
            flat = buckets[slot.bucket]
            piece = jax.lax.slice(flat, (slot.offset,), (slot.offset + slot.size,))
            leaves[slot.leaf_index] = piece.reshape(slot.shape)
        if self.passthrough_indices:
            if passthrough is None or len(passthrough) != len(self.passthrough_indices):
                raise ValueError(
                    f"plan has {len(self.passthrough_indices)} passthrough leaves; "
                    "unbucket_tree needs their values (see passthrough_leaves)"
                )
            for i, val in zip(self.passthrough_indices, passthrough):
                leaves[i] = val
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    # ---------------------------------------------------------------- specs ----
    def bucket_specs(self):
        """``{bucket: P(axis)}`` — the update-slice shardings (each replica owns
        a 1/N chunk of every bucket)."""
        from jax.sharding import PartitionSpec

        return {name: PartitionSpec(self.axis) for name in self.bucket_sizes}

    def state_partition_specs(self, state):
        """PartitionSpec tree for an optimizer state built over the bucketed
        params: bucket-shaped subtrees get ``P(axis)``, scalars ``P()``.

        Raises :class:`FusedZero1Incompatible` for array leaves that are
        neither (the signature of a shape-dependent transform)."""
        import jax
        from jax.sharding import PartitionSpec

        sizes = {}  # padded size -> seen (dict, not set: keep R5-clean iteration)
        for s in self.bucket_sizes.values():
            sizes[s] = True

        def _spec(path, leaf):
            ndim = getattr(leaf, "ndim", None)
            if ndim is None or ndim == 0:
                return PartitionSpec()
            shape = tuple(leaf.shape)
            if len(shape) == 1 and sizes.get(shape[0]):
                return PartitionSpec(self.axis)
            raise FusedZero1Incompatible(
                f"optimizer state leaf {jax.tree_util.keystr(path)} has shape "
                f"{shape}, which is not a ZeRO-1 bucket ({list(self.bucket_sizes.values())}) "
                "or a scalar — this transform is not elementwise-bucketable "
                "(e.g. adafactor's factored moments); falling back to the "
                "GSPMD annotation path"
            )

        return jax.tree_util.tree_map_with_path(_spec, state)


def _leaf_path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(getattr(p, "name", p)))
    return "/".join(parts)


def build_bucket_plan(
    params,
    axis: str,
    axis_size: int,
    bucket_bytes: Optional[int] = None,
    passthrough: "Optional[Callable[[str], bool]]" = None,
) -> Zero1BucketPlan:
    """Assign every param leaf to a dtype-homogeneous, size-bounded bucket.

    Leaves are packed greedily in tree-flatten order (one open bucket per
    dtype); a bucket closes when adding the next leaf would exceed
    ``bucket_bytes``. Each bucket is padded to a multiple of ``axis_size``.
    Raises ``ValueError`` for non-floating leaves (their ``jax.grad`` cotangent
    is ``float0`` — callers should gate the fused path off instead).

    ``passthrough`` (a predicate over '/'-joined leaf paths) marks leaves that
    bypass the buckets entirely — replace-with-cotangent side state (fp8
    delayed-scaling meta) whose "gradient" is its updated value. Passthrough
    leaves never enter the optimizer transform or the collectives; the fused
    update installs their cotangents directly (the fused twin of
    ``ops.fp8._meta_replace_transform``).
    """
    import jax
    import jax.numpy as jnp

    if bucket_bytes is None:
        bucket_bytes = bucket_bytes_from_env()
    path_leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    slots: "list[_LeafSlot]" = []
    passthrough_indices: "list[int]" = []
    bucket_sizes: "dict[str, int]" = {}
    bucket_dtypes: "dict[str, Any]" = {}
    open_bucket: "dict[str, str]" = {}  # dtype str -> open bucket name
    fill: "dict[str, int]" = {}  # bucket name -> unpadded elements
    total = 0
    for i, (path, leaf) in enumerate(path_leaves):
        if passthrough is not None and passthrough(_leaf_path_str(path)):
            passthrough_indices.append(i)
            continue
        dtype = np.dtype(leaf.dtype)
        # np's .kind can't see extension floats (bfloat16 reports 'V')
        if not jnp.issubdtype(dtype, jnp.floating):
            raise ValueError(
                f"fused ZeRO-1 needs floating-point params; leaf {i} is {dtype}"
            )
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        total += size
        key = str(dtype)
        name = open_bucket.get(key)
        if name is not None and (fill[name] + size) * dtype.itemsize > bucket_bytes and fill[name] > 0:
            name = None  # close the full bucket
        if name is None:
            name = f"b{len(bucket_sizes):03d}"
            open_bucket[key] = name
            bucket_sizes[name] = 0
            bucket_dtypes[name] = dtype
            fill[name] = 0
        slots.append(
            _LeafSlot(
                leaf_index=i,
                bucket=name,
                offset=fill[name],
                size=size,
                shape=tuple(leaf.shape),
                dtype=str(dtype),
            )
        )
        fill[name] += size
    for name, n in fill.items():
        bucket_sizes[name] = -(-n // axis_size) * axis_size  # ceil to axis_size
    return Zero1BucketPlan(
        axis=axis,
        axis_size=axis_size,
        treedef=treedef,
        slots=slots,
        bucket_sizes=bucket_sizes,
        bucket_dtypes=bucket_dtypes,
        n_elements=total,
        passthrough_indices=tuple(passthrough_indices),
    )


def init_bucketed_opt_state(tx, params, plan: Zero1BucketPlan, mesh):
    """Initialize ``tx`` over the BUCKETED param representation and place each
    state leaf sharded ``1/N`` over the replicate axis.

    Returns ``(opt_state, state_specs)``. Raises
    :class:`FusedZero1Incompatible` when the transform materializes state the
    bucket layout cannot shard (callers fall back to annotation-mode ZeRO-1).
    """
    import jax
    from jax.sharding import NamedSharding

    bucketed = jax.device_put(
        plan.bucket_tree(params),
        {n: NamedSharding(mesh, s) for n, s in plan.bucket_specs().items()},
    )
    state = tx.init(bucketed)
    specs = plan.state_partition_specs(state)  # may raise FusedZero1Incompatible
    state = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )
    return state, specs


def make_fused_zero1_update(tx, plan: Zero1BucketPlan, mesh, state_specs) -> Callable:
    """Build ``update_fn(grads, opt_state, params) -> (new_params, new_opt_state)``.

    Runs the bucketed reduce-scatter → shard-local ``tx.update`` → all-gather
    pipeline under ``shard_map`` (manual collectives — nothing leaks into the
    caller's forward/backward partitioning). Trace-safe: call it inside the
    jitted train step. ``opt_state`` must come from
    :func:`init_bucketed_opt_state`.
    """
    import jax
    import optax
    from jax.sharding import PartitionSpec as P

    from ..utils.jax_compat import shard_map

    axis = plan.axis
    names = plan.bucket_names
    chunks = {n: plan.chunk_size(n) for n in names}
    repl_specs = {n: P() for n in names}

    def shard_update(gb, st, pb):
        # per-replica region: gb/pb arrive replicated (full buckets), st leaves
        # arrive as this replica's 1/N chunks (in_specs below)
        idx = jax.lax.axis_index(axis)
        g_sl, p_sl = {}, {}
        for n in names:
            start = idx * chunks[n]
            g_sl[n] = jax.lax.dynamic_slice(gb[n], (start,), (chunks[n],))
            p_sl[n] = jax.lax.dynamic_slice(pb[n], (start,), (chunks[n],))
        updates, new_st = tx.update(g_sl, st, p_sl)
        new_p = optax.apply_updates(p_sl, updates)
        # per-bucket all-gathers are independent of each other and of the next
        # bucket's optimizer math — XLA's latency-hiding scheduler overlaps them
        new_pb = {
            n: jax.lax.all_gather(new_p[n], axis, tiled=True) for n in names
        }
        return new_pb, new_st

    sharded = shard_map(
        shard_update,
        mesh=mesh,
        in_specs=(repl_specs, state_specs, repl_specs),
        out_specs=(repl_specs, state_specs),
        # scalar state (counts, mini_step) is replicated by construction; the
        # checker cannot prove that through lax.cond (MultiSteps) — off
        check_vma=False,
    )

    def update_fn(grads, opt_state, params):
        gb = plan.bucket_tree(grads)
        pb = plan.bucket_tree(params)
        new_pb, new_state = sharded(gb, opt_state, pb)
        # passthrough leaves (fp8 delayed-scaling meta) ride OUTSIDE the
        # shard_map: tiny, replicated, and their cotangent IS the new value
        # (the fused twin of ops.fp8._meta_replace_transform) — so the new
        # leaf is the grad leaf verbatim, every micro-step
        pt = plan.passthrough_leaves(grads) if plan.passthrough_indices else None
        return plan.unbucket_tree(new_pb, pt), new_state

    return update_fn


# ---------------------------------------------------------------------------
# Self-check (consumed by `make doctor`): build a fused step on a virtual
# multi-device mesh, lint-critical invariants aside, and prove the compiled
# program actually contains collectives moving the planned number of bytes.

_HLO_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1, "u64": 8, "u32": 4, "u16": 2,
    "u8": 1, "pred": 1,
}


def hlo_collective_bytes(hlo_text: str) -> "dict[str, int]":
    """Sum output bytes of collective ops in an HLO module text dump —
    the trace-derived cross-check that the fused step really communicates.
    Handles both single results (``= f32[2048]{0} all-gather(...)``) and the
    tuple results XLA's collective-combiner passes produce
    (``= (f32[2048], f32[256]) all-gather(...)``)."""
    import re

    out: "dict[str, int]" = {}
    shape = r"(\w+)\[([\d,]*)\]\S*"
    single = re.compile(
        rf"=\s*{shape}\s[^\n]*?\b(all-gather|reduce-scatter|all-reduce|collective-permute)\("
    )
    variadic = re.compile(
        r"=\s*\(([^)]*)\)\s[^\n]*?\b(all-gather|reduce-scatter|all-reduce|collective-permute)\("
    )
    part = re.compile(rf"{shape}")

    def _nbytes(dtype: str, dims: str) -> int:
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        return elems * _HLO_DTYPE_BYTES.get(dtype, 4)

    for dtype, dims, op in single.findall(hlo_text):
        out[op] = out.get(op, 0) + _nbytes(dtype, dims)
    for inner, op in variadic.findall(hlo_text):
        for dtype, dims in part.findall(inner):
            out[op] = out.get(op, 0) + _nbytes(dtype, dims)
    return out


def self_check(n_devices: int = 8, bucket_bytes: int = 1 << 12) -> dict:
    """Compile a fused ZeRO-1 step on ``n_devices`` virtual CPU devices and
    report plan/HLO collective accounting plus a one-step parity probe vs the
    replicated update. Run in a FRESH process (sets XLA_FLAGS before jax
    loads); ``make doctor`` invokes it via a subprocess."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(
        np.array(jax.devices()[:n_devices]).reshape(n_devices), ("dp_replicate",)
    )
    repl = NamedSharding(mesh, P())
    params = {
        "w1": jax.device_put(
            jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.1, repl
        ),
        "w2": jax.device_put(
            jax.random.normal(jax.random.PRNGKey(1), (32, 8)) * 0.1, repl
        ),
    }
    plan = build_bucket_plan(params, "dp_replicate", n_devices, bucket_bytes)
    tx = optax.adam(1e-3)
    state, specs = init_bucketed_opt_state(tx, params, plan, mesh)
    fused = make_fused_zero1_update(tx, plan, mesh, specs)

    def loss_fn(p, b):
        return jnp.mean((jnp.tanh(b @ p["w1"]) @ p["w2"]) ** 2)

    def step(p, st, b):
        loss, grads = jax.value_and_grad(loss_fn)(p, b)
        new_p, new_st = fused(grads, st, p)
        return new_p, new_st, loss

    jitted = jax.jit(step, donate_argnums=(0, 1))
    batch = jax.device_put(jnp.ones((16, 64), jnp.float32), repl)
    compiled = jitted.lower(params, state, batch).compile()
    hlo_bytes = hlo_collective_bytes(compiled.as_text())

    # one-step parity probe vs the plain replicated update
    tx2 = optax.adam(1e-3)
    base_state = jax.device_put(tx2.init(params), repl)

    def base_step(p, st, b):
        loss, grads = jax.value_and_grad(loss_fn)(p, b)
        u, st = tx2.update(grads, st, p)
        return optax.apply_updates(p, u), st, loss

    p_ref, _, _ = jax.jit(base_step)(params, base_state, batch)
    p_fused, new_state, _ = jitted(params, state, batch)
    max_delta = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(
            jax.tree_util.tree_leaves(p_fused), jax.tree_util.tree_leaves(p_ref)
        )
    )
    mu_leaf = new_state[0].mu[plan.bucket_names[0]]
    shard = next(iter(mu_leaf.addressable_shards))
    return {
        "n_devices": n_devices,
        "num_buckets": plan.num_buckets,
        "plan_collective_bytes": plan.collective_bytes,
        "hlo_collective_bytes": hlo_bytes,
        "hlo_total_collective_bytes": sum(hlo_bytes.values()),
        "opt_state_shard_fraction": shard.data.size / mu_leaf.size,
        "parity_max_abs_delta": max_delta,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(self_check()))
