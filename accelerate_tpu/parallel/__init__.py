from .long_context import make_context_parallel_attention, sequence_parallel_attention
from .sharding import (
    FSDP_AXES,
    ShardingRules,
    infer_param_specs,
    llama_tp_rules,
    replicate,
    shard_like_params,
    shard_params,
    tree_specs_like,
)
