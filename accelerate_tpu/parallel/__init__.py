from .sharding import (
    FSDP_AXES,
    ShardingRules,
    infer_param_specs,
    llama_tp_rules,
    replicate,
    shard_like_params,
    shard_params,
    tree_specs_like,
)
