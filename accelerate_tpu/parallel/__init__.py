from .long_context import make_context_parallel_attention, sequence_parallel_attention
from .moe import init_moe_ffn, moe_ffn, moe_shard_rules
from .pipeline import (
    make_pipeline_forward,
    make_pipeline_train_step_1f1b,
    merge_microbatches,
    prepare_pipeline,
    split_into_stages,
    split_microbatches,
)
from .sharding import (
    FSDP_AXES,
    ShardingPlan,
    ShardingRules,
    canonicalize_spec,
    host_memory_kind,
    host_offload_supported,
    infer_param_specs,
    llama_tp_rules,
    make_host_offloaded_step,
    make_sharding_plan,
    offload_memory_kinds,
    offload_to_host,
    offload_tree_shardings,
    replicate,
    shard_like_params,
    shard_params,
    tree_specs_like,
    zero1_state_specs,
)
from .weight_update import (
    FusedZero1Incompatible,
    Zero1BucketPlan,
    build_bucket_plan,
    init_bucketed_opt_state,
    make_fused_zero1_update,
)
