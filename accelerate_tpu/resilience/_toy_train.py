"""Deterministic toy training run — the chaos harness's workload.

``python -m accelerate_tpu.resilience._toy_train --project-dir D --steps N``
trains a tiny least-squares model through the REAL stack (Accelerator,
prepared DataLoader, jitted train step, committed checkpoints every
``--save-every`` steps) with fully deterministic batches: batch ``i`` is a
pure function of ``i``, so a run that is killed at step ``s`` and resumed
from the step-``k`` checkpoint replays batches ``k..N`` and finishes with
params BITWISE-identical to an uninterrupted run. That property is the chaos
e2e's oracle (``make chaos``, ``tests/test_resilience.py``).

Resume protocol: when the supervisor set ``ACCELERATE_RESUME_FROM_CHECKPOINT``
(``Accelerator.resume_from_checkpoint``), the script restores params,
optimizer state and the dataloader snapshot from the newest committed
checkpoint — consumed batches are skipped by the restored loader state, not
by any step arithmetic here. A first incarnation (or a crash before the first
commit) starts cold.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="toy_train")
    parser.add_argument("--project-dir", required=True)
    parser.add_argument("--steps", type=int, default=12)
    parser.add_argument("--save-every", type=int, default=3)
    parser.add_argument("--global-batch", type=int, default=32,
                        help="GLOBAL batch size (the prepared loader's "
                             "per-call batch is global/num_devices), so the "
                             "batch stream is identical across topologies — "
                             "the property cross-topology parity rests on")
    parser.add_argument("--zero-stage", type=int, default=0,
                        help="1 = shard optimizer state over dp_replicate "
                             "(fused ZeRO-1) — the state whose buckets the "
                             "cross-topology resume must re-pad")
    parser.add_argument("--out", default=None,
                        help="Where to write the final params npz "
                             "(default <project-dir>/final_params.npz)")
    args = parser.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import DataLoader
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    steps = args.steps

    class DeterministicDS:
        """item i -> a pure function of i (restart- and topology-invariant)."""

        def __len__(self):
            return steps * args.global_batch

        def __getitem__(self, i):
            rng = np.random.default_rng(1000 + i)
            return {"x": rng.normal(size=(16,)).astype(np.float32)}

    from accelerate_tpu import DeepSpeedPlugin

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=args.project_dir, automatic_checkpoint_naming=True
        ),
        deepspeed_plugin=(
            DeepSpeedPlugin(zero_stage=1) if args.zero_stage == 1 else None
        ),
    )
    bs = max(1, args.global_batch // acc.partial_state.num_devices)
    params = {"w": jnp.zeros((16, 4), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    params, opt = acc.prepare(params, optax.adam(1e-2))
    dl = acc.prepare(DataLoader(DeterministicDS(), batch_size=bs, shuffle=False))

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] + p["b"]) ** 2) + 1e-3 * jnp.mean(
            batch["x"]
        )

    step = acc.prepare_train_step(loss_fn, opt)
    opt_state = opt.opt_state

    resumed_from = None
    if acc.resume_from_checkpoint:
        try:
            params, opt_state = acc.load_state(
                acc.resume_from_checkpoint, params=params, opt_state=opt_state
            )
            resumed_from = acc.project_configuration.iteration - 1
        except FileNotFoundError:
            pass  # died before the first commit: start cold

    ran = 0
    metrics = {"loss": float("nan")}  # a resumed run may have nothing left to do
    for batch in dl:
        params, opt_state, metrics = step(params, opt_state, batch)
        ran += 1
        if args.save_every > 0 and ran % args.save_every == 0:
            acc.save_state(params=params, opt_state=opt_state)

    out = args.out or os.path.join(args.project_dir, "final_params.npz")
    np.savez(out, **{k: np.asarray(v) for k, v in params.items()})
    acc.end_training()
    print(json.dumps({
        "final_params": out,
        "batches_run_this_incarnation": ran,
        "generation": acc.restart_generation,
        "resumed_from_iteration": resumed_from,
        "loss": float(metrics["loss"]),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
