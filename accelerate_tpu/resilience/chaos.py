"""Deterministic fault injection: the chaos harness that proves the elastic
supervisor actually rides through failures.

A resilience layer that has never seen a failure is a hypothesis, not a
feature. This module turns "a host got preempted mid-epoch" into a seeded,
replayable schedule so the same SIGKILL lands at the same step in every run
of the chaos e2e (``make chaos``, ``tests/test_resilience.py``):

- :class:`ChaosSchedule` — an ordered list of :class:`Fault` entries
  ``(point, step, rank, kind, duration_s)``; built programmatically, parsed
  from JSON, or generated from a seed (:meth:`ChaosSchedule.seeded` — same
  seed, same faults, forever).
- :func:`maybe_inject` — the in-process hook, wired into the train step
  (``Accelerator._track_step``), the host collectives
  (``utils/operations.py``) and the prefetch producer (``data_loader.py``).
  It is a single ``is None`` check unless ``ACCELERATE_CHAOS_SCHEDULE`` armed
  a schedule for this process, so production hot paths pay nothing.

Fault kinds model the real pod failure modes the forensics layer (PR 4) keeps
autopsying:

``sigkill``
    Preemption: the process dies instantly, no handlers run — exactly what a
    maintenance event does to a TPU-VM host.
``sigterm``
    Polite eviction: SIGTERM triggers the flight-recorder crash dump first.
``hang``
    A rank wedges inside a collective/step for ``duration_s`` (or forever
    with ``duration_s=None``): the watchdog's blocked-phase detection and the
    supervisor's heartbeat-file gap watch are the intended catchers.
``slow``
    A persistent straggler: every matching injection sleeps ``duration_s``,
    degrading one host without killing it (feeds the straggler-mitigation
    replanner, :func:`replan_data_assignment`).
``crash``
    A plain Python exception (``ChaosFaultError``) — the generic "training
    code blew up" case.

Faults match on injection *point* (``train_step`` / ``collective`` /
``prefetch`` / ``any``), *step* (``None`` = any step), *rank* (``None`` =
every rank; rank resolution uses ``state.process_identity()`` so it works
before jax init), and *generation* (``None`` = any restart generation —
pinning a fault to generation 0 is how a test kills the first incarnation but
lets the resumed one finish).
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Optional

CHAOS_ENV_VAR = "ACCELERATE_CHAOS_SCHEDULE"

FAULT_KINDS = ("sigkill", "sigterm", "hang", "slow", "crash", "corrupt")
# "serving_decode" fires inside ServingEngine.step (serving/engine.py): a
# seeded replica kill/hang/slow lands mid-decode, which is what the router's
# failover chaos tests and `make doctor` check 13 exercise.
# "compile_cache_store" fires inside CompileCache.store (compile_cache/),
# BETWEEN the payload write and the manifest commit — a sigkill there is the
# kill-9-mid-cache-write case the cache's crash protocol must survive.
# "kv_handoff" fires inside PrefillEngine.step (serving/disagg.py), between
# the chunked prefill and the KV handoff pack: a "crash" drops the handoff
# with the prefill replica (the router must re-run prefill exactly-once), a
# "corrupt" lets the pack complete but flips payload bytes (the router's
# checksum verify must catch it), "slow"/"hang" delay/wedge the handoff.
POINTS = (
    "train_step", "collective", "prefetch", "serving_decode",
    "compile_cache_store", "kv_handoff", "any",
)


class ChaosFaultError(RuntimeError):
    """Raised by a ``crash`` fault — the injected stand-in for arbitrary
    training-code failure."""


class ChaosCorruptionError(ChaosFaultError):
    """Raised by a ``corrupt`` fault. Sites that model in-transit payload
    corruption (the ``kv_handoff`` point) catch THIS subclass and deliver a
    deliberately damaged payload instead of dying; anywhere else it behaves
    exactly like ``crash`` (a ChaosFaultError the worker reports as fatal)."""


@dataclass(frozen=True)
class Fault:
    """One scheduled fault. ``step``/``rank``/``generation`` of ``None`` match
    anything; ``point`` of ``"any"`` matches every injection site."""

    kind: str
    point: str = "any"
    step: Optional[int] = None
    rank: Optional[int] = None
    generation: Optional[int] = None
    duration_s: Optional[float] = 0.05
    once: bool = True  # fire at most once per process (slow faults set False)

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (know {FAULT_KINDS})")
        if self.point not in POINTS:
            raise ValueError(f"unknown injection point {self.point!r} (know {POINTS})")

    def matches(self, point: str, step: Optional[int], rank: int, generation: int) -> bool:
        if self.point != "any" and self.point != point:
            return False
        if self.step is not None and step != self.step:
            return False
        if self.rank is not None and rank != self.rank:
            return False
        if self.generation is not None and generation != self.generation:
            return False
        return True


@dataclass
class ChaosSchedule:
    """A deterministic, serializable fault schedule for one chaos run."""

    faults: "list[Fault]" = field(default_factory=list)
    seed: Optional[int] = None

    # ------------------------------------------------------------ construction --
    @classmethod
    def seeded(
        cls,
        seed: int,
        steps: int,
        kinds: "tuple[str, ...]" = ("sigkill", "hang"),
        n_faults: int = 2,
        ranks: int = 1,
        generation: Optional[int] = 0,
        point: str = "train_step",
    ) -> "ChaosSchedule":
        """Generate ``n_faults`` faults at distinct steps in ``[1, steps)``,
        deterministically from ``seed`` (a private ``random.Random`` — never
        the global RNG, which training code may reseed). Faults default to
        generation 0 so the restarted incarnation runs fault-free; ``point``
        picks the injection site (serving chaos uses ``"serving_decode"``)."""
        rng = random.Random(seed)
        candidates = list(range(1, max(2, steps)))
        rng.shuffle(candidates)
        faults = []
        for i in range(n_faults):
            kind = kinds[i % len(kinds)]
            # a seeded hang must actually wedge the rank (only the watchdog /
            # heartbeat watch may end it) — a finite sleep would pass the
            # chaos assertion vacuously; slow faults degrade persistently
            duration = None if kind == "hang" else (2.0 if kind == "slow" else 0.0)
            faults.append(
                Fault(
                    kind=kind,
                    point=point,
                    step=candidates[i % len(candidates)],
                    rank=rng.randrange(ranks) if ranks > 1 else None,
                    generation=generation,
                    duration_s=duration,
                    once=kind != "slow",
                )
            )
        faults.sort(key=lambda f: (f.step if f.step is not None else -1))
        return cls(faults=faults, seed=seed)

    @classmethod
    def from_json(cls, payload: str) -> "ChaosSchedule":
        """Parse ``{"seed": ..., "faults": [{...}, ...]}`` (or a bare fault
        list). ``@/path/to/file.json`` indirects through a file — schedules
        that pin many steps get long, and env values do not."""
        if payload.startswith("@"):
            with open(payload[1:]) as f:
                payload = f.read()
        data = json.loads(payload)
        if isinstance(data, list):
            data = {"faults": data}
        return cls(
            faults=[Fault(**f) for f in data.get("faults", [])],
            seed=data.get("seed"),
        )

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed, "faults": [asdict(f) for f in self.faults]})

    # ----------------------------------------------------------------- matching --
    def pending(self, point: str, step: Optional[int], rank: int, generation: int,
                fired: "set[int]") -> "list[tuple[int, Fault]]":
        return [
            (i, f)
            for i, f in enumerate(self.faults)
            if (not f.once or i not in fired) and f.matches(point, step, rank, generation)
        ]


# ---------------------------------------------------------------------------
# process-level injection hook

_SCHEDULE: Optional[ChaosSchedule] = None
_FIRED: "set[int]" = set()
_ARMED_FROM_ENV = False
# serving replicas inject from concurrent engine threads: matching and the
# once-marking must be atomic or a once-fault could fire in two replicas
_MATCH_LOCK = threading.Lock()


def arm(schedule: Optional[ChaosSchedule]) -> None:
    """Install ``schedule`` for this process (tests / __main__ drivers);
    ``None`` disarms."""
    global _SCHEDULE, _FIRED
    _SCHEDULE = schedule
    _FIRED = set()


def maybe_arm_from_env() -> Optional[ChaosSchedule]:
    """Arm from ``ACCELERATE_CHAOS_SCHEDULE`` once per process. A malformed
    schedule raises immediately — silently training without the faults a
    chaos test asked for would turn every chaos assertion vacuous."""
    global _ARMED_FROM_ENV
    if _SCHEDULE is not None or _ARMED_FROM_ENV:
        return _SCHEDULE
    _ARMED_FROM_ENV = True
    payload = os.environ.get(CHAOS_ENV_VAR, "").strip()
    if not payload:
        return None
    arm(ChaosSchedule.from_json(payload))
    return _SCHEDULE


def is_armed() -> bool:
    return _SCHEDULE is not None


def _identity() -> "tuple[int, int]":
    from ..state import process_identity
    from .membership import current_generation

    ident = process_identity()
    return int(ident.get("process_index", 0)), current_generation()


def maybe_inject(point: str, step: Optional[int] = None) -> None:
    """Fire any scheduled fault matching this (point, step, rank, generation).

    The wired-in call sites pass their natural coordinates: the train step its
    step index, collectives and the prefetch producer just their point (step
    matching then uses the flight recorder's current step, which the
    accelerator keeps fresh). Disabled cost: one ``is None`` check.
    """
    if _SCHEDULE is None:
        return
    rank, generation = _identity()
    if step is None:
        from ..telemetry import flight_recorder as _flight

        step = _flight.get_recorder().step
    with _MATCH_LOCK:  # match + mark atomically; execute OUTSIDE the lock
        # (a hang fault holds forever — other threads must stay injectable)
        hits = _SCHEDULE.pending(point, step, rank, generation, _FIRED)
        for idx, fault in hits:
            if fault.once:
                _FIRED.add(idx)
    for idx, fault in hits:
        _execute(fault, point, step)


def _execute(fault: Fault, point: str, step: Optional[int]) -> None:
    from ..logging import get_logger
    from ..telemetry import events as _tel
    from ..telemetry import flight_recorder as _flight

    desc = f"chaos: injecting {fault.kind} at point={point} step={step}"
    get_logger(__name__).warning(desc)
    _tel.emit("chaos_fault", fault=fault.kind, point=point, step=step)
    _flight.record("chaos_fault", fault=fault.kind, point=point, step=step)
    if fault.kind == "sigkill":
        _tel.hard_flush()
        os.kill(os.getpid(), signal.SIGKILL)
    elif fault.kind == "sigterm":
        _tel.hard_flush()
        os.kill(os.getpid(), signal.SIGTERM)
        # SIGTERM is asynchronous; the flight recorder's handler re-raises as
        # an exit — give it a beat rather than racing on
        time.sleep(30.0)
    elif fault.kind == "hang":
        with _flight.phase(f"chaos:hang@{point}"):
            time.sleep(1e9 if fault.duration_s is None else fault.duration_s)
    elif fault.kind == "slow":
        time.sleep(fault.duration_s or 0.05)
    elif fault.kind == "crash":
        raise ChaosFaultError(desc)
    elif fault.kind == "corrupt":
        raise ChaosCorruptionError(desc)


# ---------------------------------------------------------------------------
# straggler mitigation: turn PR 4's --by-rank skew data into a data replan

def replan_data_assignment(
    rank_step_seconds: "dict[int, float]",
    slow_factor: float = 1.5,
) -> "dict[str, Any]":
    """Decide a mitigation for a persistently slow host.

    ``rank_step_seconds`` maps rank → mean step seconds (the report CLI's
    ``--by-rank`` skew table, or ``report["ranks"]["per_rank_step_s"]``).
    A rank whose mean exceeds ``slow_factor`` × the median is a straggler;
    the replan assigns it proportionally less data (weights normalized so a
    healthy cohort is all-1.0) and names it for exclusion if the supervisor
    is about to regrow the cohort anyway.

    Returns ``{"weights": {rank: w}, "stragglers": [rank, ...],
    "exclude": [rank, ...]}`` — ``exclude`` lists ranks slower than
    2×``slow_factor`` (bad enough that dropping the host beats feeding it
    less).
    """
    if not rank_step_seconds:
        return {"weights": {}, "stragglers": [], "exclude": []}
    times = sorted(rank_step_seconds.values())
    # LOWER median: with half the cohort degraded, the upper median is already
    # polluted by the stragglers being measured
    median = times[(len(times) - 1) // 2]
    weights: "dict[int, float]" = {}
    stragglers: "list[int]" = []
    exclude: "list[int]" = []
    for rank, t in sorted(rank_step_seconds.items()):
        if median > 0 and t > slow_factor * median:
            stragglers.append(rank)
            weights[rank] = round(max(0.1, median / t), 4)
            if t > 2 * slow_factor * median:
                exclude.append(rank)
        else:
            weights[rank] = 1.0
    return {"weights": weights, "stragglers": stragglers, "exclude": exclude}


# ---------------------------------------------------------------------------
# `make chaos`: the seeded end-to-end — a fault-free reference run, then a
# supervised run under a SIGKILL schedule; final params must match bitwise.


def main(argv=None) -> int:
    import argparse
    import subprocess
    import sys
    import tempfile

    import numpy as np

    from .supervisor import RestartPolicy, Supervisor

    parser = argparse.ArgumentParser(prog="python -m accelerate_tpu.resilience.chaos")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--keep-dir", default=None,
                        help="Run under this dir (kept) instead of a tempdir")
    args = parser.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    base_env = dict(os.environ, JAX_PLATFORMS="cpu")
    base_env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, base_env.get("PYTHONPATH")) if p
    )
    base_env.pop(CHAOS_ENV_VAR, None)

    def toy_cmd(project_dir: str) -> "list[str]":
        return [
            sys.executable, "-m", "accelerate_tpu.resilience._toy_train",
            "--project-dir", project_dir, "--steps", str(args.steps),
            "--save-every", "2",
        ]

    with tempfile.TemporaryDirectory() as tmp:
        root = args.keep_dir or tmp
        os.makedirs(root, exist_ok=True)
        # 1. fault-free reference
        ref_dir = os.path.join(root, "reference")
        os.makedirs(ref_dir, exist_ok=True)
        ref = subprocess.run(toy_cmd(ref_dir), env=base_env, capture_output=True,
                             text=True, timeout=600)
        if ref.returncode != 0:
            print(f"chaos: reference run failed rc={ref.returncode}\n{ref.stderr[-2000:]}",
                  file=sys.stderr)
            return 2
        # 2. supervised run under a seeded generation-0 SIGKILL schedule
        chaos_dir = os.path.join(root, "chaos")
        tel_dir = os.path.join(chaos_dir, "telemetry")
        os.makedirs(tel_dir, exist_ok=True)
        schedule = ChaosSchedule.seeded(
            args.seed, steps=args.steps, kinds=("sigkill",), n_faults=1
        )
        env = dict(base_env)
        env[CHAOS_ENV_VAR] = schedule.to_json()
        env["ACCELERATE_TELEMETRY_DIR"] = tel_dir
        sup = Supervisor(
            [toy_cmd(chaos_dir)],
            env=env,
            policy=RestartPolicy(max_restarts=args.max_restarts,
                                 backoff_base_s=0.2, grace_period_s=2.0),
            telemetry_dir=tel_dir,
        )
        rc = sup.run()
        verdict: "dict[str, Any]" = {
            "schedule": json.loads(schedule.to_json()),
            "supervisor_rc": rc,
            "restarts": sup.restarts_used,
            "causes": [i.cause for i in sup.incidents],
        }
        match = False
        if rc == 0:
            ref_params = dict(np.load(os.path.join(ref_dir, "final_params.npz")))
            chaos_params = dict(np.load(os.path.join(chaos_dir, "final_params.npz")))
            match = set(ref_params) == set(chaos_params) and all(
                np.array_equal(ref_params[k], chaos_params[k]) for k in ref_params
            )
        verdict["final_params_bitwise_match"] = match
        ok = rc == 0 and sup.restarts_used >= 1 and match

        def _params_match(run_dir: str) -> bool:
            ref_params = dict(np.load(os.path.join(ref_dir, "final_params.npz")))
            got = dict(np.load(os.path.join(run_dir, "final_params.npz")))
            return set(ref_params) == set(got) and all(
                np.array_equal(ref_params[k], got[k]) for k in ref_params
            )

        def _cache_records(tel_root: str) -> "dict[str, int]":
            counts: "dict[str, int]" = {}
            try:
                names = os.listdir(tel_root)
            except OSError:
                return counts
            for n in names:
                if not (n.startswith("events-rank") and n.endswith(".jsonl")):
                    continue
                with open(os.path.join(tel_root, n)) as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if rec.get("kind") == "compile_cache":
                            ev = rec.get("event")
                            counts[ev] = counts.get(ev, 0) + 1
            return counts

        # 3. compile-cache leg A: kill -9 MID-CACHE-WRITE (a seeded SIGKILL at
        # the compile_cache_store point — payload written, manifest not) →
        # the restart must see only committed entries, resume, and finish
        # with bitwise-identical params
        from .. import compile_cache as _cc

        midwrite_dir = os.path.join(root, "cache-midwrite")
        midwrite_cache = os.path.join(midwrite_dir, "cache")
        midwrite_tel = os.path.join(midwrite_dir, "telemetry")
        os.makedirs(midwrite_tel, exist_ok=True)
        env = dict(base_env)
        env[CHAOS_ENV_VAR] = ChaosSchedule(
            faults=[Fault(kind="sigkill", point="compile_cache_store", generation=0)]
        ).to_json()
        env["ACCELERATE_TELEMETRY"] = "1"
        env["ACCELERATE_TELEMETRY_DIR"] = midwrite_tel
        env[_cc.CACHE_DIR_ENV_VAR] = midwrite_cache
        sup2 = Supervisor(
            [toy_cmd(midwrite_dir)], env=env,
            policy=RestartPolicy(max_restarts=args.max_restarts,
                                 backoff_base_s=0.2, grace_period_s=2.0),
            telemetry_dir=midwrite_tel,
        )
        rc2 = sup2.run()
        committed = []
        cache_obj = _cc.CompileCache(midwrite_cache) if os.path.isdir(midwrite_cache) else None
        if cache_obj is not None:
            committed = cache_obj.entries()
        verdict["midwrite"] = {
            "supervisor_rc": rc2,
            "restarts": sup2.restarts_used,
            "causes": [i.cause for i in sup2.incidents],
            "committed_entries": len(committed),
            "final_params_bitwise_match": rc2 == 0 and _params_match(midwrite_dir),
            "cache_records": _cache_records(midwrite_tel),
        }
        ok = ok and rc2 == 0 and sup2.restarts_used >= 1 and verdict["midwrite"][
            "final_params_bitwise_match"
        ]

        # 4. compile-cache leg B: POISONED entry. Populate the cache with one
        # clean run, bit-flip every payload, then run supervised under the
        # original SIGKILL schedule — the warm restart must detect the
        # corruption, quarantine, fall back to a fresh compile (recorded in
        # telemetry), and STILL finish with bitwise-identical params.
        poison_dir = os.path.join(root, "cache-poison")
        poison_cache = os.path.join(poison_dir, "cache")
        poison_tel = os.path.join(poison_dir, "telemetry")
        seed_dir = os.path.join(poison_dir, "seedrun")
        os.makedirs(seed_dir, exist_ok=True)
        os.makedirs(poison_tel, exist_ok=True)
        env = dict(base_env)
        env["ACCELERATE_TELEMETRY"] = "1"
        env["ACCELERATE_TELEMETRY_DIR"] = os.path.join(poison_dir, "telemetry-seed")
        env[_cc.CACHE_DIR_ENV_VAR] = poison_cache
        seed_run = subprocess.run(toy_cmd(seed_dir), env=env, capture_output=True,
                                  text=True, timeout=600)
        poisoned = 0
        if seed_run.returncode == 0 and os.path.isdir(poison_cache):
            for entry in _cc.CompileCache(poison_cache).entries():
                payload = os.path.join(entry, _cc.PAYLOAD_NAME)
                try:
                    blob = bytearray(open(payload, "rb").read())
                    blob[len(blob) // 2] ^= 0xFF
                    open(payload, "wb").write(bytes(blob))
                    poisoned += 1
                except OSError:
                    pass
        env = dict(env)
        env[CHAOS_ENV_VAR] = schedule.to_json()
        env["ACCELERATE_TELEMETRY_DIR"] = poison_tel
        sup3 = Supervisor(
            [toy_cmd(poison_dir)], env=env,
            policy=RestartPolicy(max_restarts=args.max_restarts,
                                 backoff_base_s=0.2, grace_period_s=2.0),
            telemetry_dir=poison_tel,
        )
        rc3 = sup3.run()
        cache_recs = _cache_records(poison_tel)
        quarantined = 0
        qdir = os.path.join(poison_cache, _cc.QUARANTINE_DIRNAME)
        try:
            quarantined = len(os.listdir(qdir))
        except OSError:
            pass
        verdict["poisoned"] = {
            "supervisor_rc": rc3,
            "restarts": sup3.restarts_used,
            "entries_poisoned": poisoned,
            "quarantined": quarantined,
            "cache_records": cache_recs,
            "final_params_bitwise_match": rc3 == 0 and _params_match(poison_dir),
        }
        ok = ok and (
            rc3 == 0 and sup3.restarts_used >= 1 and poisoned >= 1
            and quarantined >= 1 and cache_recs.get("corrupt", 0) >= 1
            and cache_recs.get("fallback", 0) >= 1
            and verdict["poisoned"]["final_params_bitwise_match"]
        )

        print(json.dumps(verdict))
        print(
            "chaos: PASS — SIGKILL auto-resume, kill-9-mid-cache-write restart, "
            "and poisoned-cache restart all finished with bitwise-identical "
            "params (corrupt entry quarantined, fallback compile recorded)" if ok
            else "chaos: FAIL — see verdict above",
            file=sys.stderr,
        )
        return 0 if ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
