"""Cross-topology checkpoint resume: the arithmetic that lets a checkpoint
written at dp=N restore onto dp=M.

The sharded checkpoint format (``sharded_checkpoint.py``) is already
coordinate-based — any leaf whose *global shape* is topology-independent
re-chunks onto a new mesh for free. Two things are NOT topology-independent,
and this module handles both:

1. **The mesh itself.** Since this PR every shard index and every
   ``_COMMITTED`` manifest records the writing mesh's axis→size map.
   :func:`check_topology` compares it against the resuming mesh and either
   waves the load through (same topology), allows it (elastic resume), or
   raises :class:`~accelerate_tpu.sharded_checkpoint.CheckpointTopologyError`
   naming both shapes — instead of the deep jax shape error a mismatched
   load used to die of.
2. **Fused ZeRO-1 optimizer state.** Bucketed moment buffers are padded to a
   multiple of the replicate width (``ceil(fill/N)*N``, PR 9), so their
   global length CHANGES with dp size. Bucket *assignment* does not — it
   depends only on param shapes and ``bucket_bytes`` — so re-sharding is a
   re-pad: the real elements occupy the common prefix, the tail is zero
   padding (grads of padding are zero, so Adam moments of padding stay zero
   for the whole run). :func:`resize_padded_bucket`
   truncates/zero-extends with a hard check that nothing nonzero is being
   dropped. The elastic load paths
   (``load_sharded_pytree(..., elastic=True)``,
   ``checkpointing.unflatten_into(..., elastic=True)``) call it for 1-D
   leaves whose saved and live lengths differ.

The dataloader needs no re-sharding: its snapshot counts *global* batches
consumed, and ``load_state`` restores ``skip_batches`` — the resumed epoch
skips exactly the batches the dead incarnation finished, whatever the new
dp width slices them into.
"""

from __future__ import annotations

from typing import Any, Optional

from ..sharded_checkpoint import (  # noqa: F401  (public re-exports)
    CheckpointTopologyError,
    read_saved_mesh,
    resize_padded_bucket,
)


def mesh_shape_dict(mesh) -> "Optional[dict[str, int]]":
    """``{axis: size}`` for a jax Mesh (or None for meshless runs)."""
    if mesh is None:
        return None
    try:
        return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except (TypeError, AttributeError):
        return None


def _effective(shape: "Optional[dict]") -> "dict[str, int]":
    """Size-1 axes are replication — drop them so ``{'dp': 2}`` matches
    ``{'dp': 2, 'tp': 1}``."""
    return {k: int(v) for k, v in (shape or {}).items() if int(v) > 1}


def topology_matches(saved: "Optional[dict]", current: "Optional[dict]") -> bool:
    """True when the two mesh shapes are equivalent (or either is unknown —
    checkpoints predating the mesh record stay loadable)."""
    if saved is None or current is None:
        return True
    return _effective(saved) == _effective(current)


def is_elastic_compatible(saved: "Optional[dict]", current: "Optional[dict]") -> bool:
    """Can the elastic path re-shard ``saved`` onto ``current``? Only the
    data-parallel replicate width may differ; model-parallel axes are baked
    into the saved layout."""
    s, c = _effective(saved), _effective(current)
    s.pop("dp_replicate", None)
    c.pop("dp_replicate", None)
    return s == c


def describe_shapes(saved: "Optional[dict]", current: "Optional[dict]") -> str:
    def _fmt(d):
        if not d:
            return "<unknown>"
        return "×".join(f"{k}={v}" for k, v in sorted(d.items()))

    return f"saved mesh {_fmt(_effective(saved))} vs current mesh {_fmt(_effective(current))}"


def check_topology(
    saved: "Optional[dict]", current: "Optional[dict]", elastic: bool = False
) -> bool:
    """Gate a load across topologies.

    Only a ``dp_replicate`` width change is *shape-affecting*: fused-ZeRO-1
    bucket lengths are padded to ``ceil(fill/N)·N``, so a dp=N checkpoint
    holds different global shapes than a dp=M template — the case that used
    to die deep inside jax. That case returns True (re-pad buckets) under
    ``elastic`` and raises :class:`CheckpointTopologyError` naming both
    shapes otherwise.

    Every OTHER factorization change (fsdp=8 → fsdp=4×tp=2, a different
    process count, dropped axes) keeps all global array shapes — the
    coordinate-based sharded loader has always handled those with live
    templates, and they pass through untouched (returns False).
    """
    if topology_matches(saved, current):
        return False
    s, c = _effective(saved), _effective(current)
    if s.get("dp_replicate", 1) == c.get("dp_replicate", 1):
        return False  # pure refactorization: global shapes invariant
    if not elastic:
        raise CheckpointTopologyError(
            f"checkpoint topology mismatch: {describe_shapes(saved, current)} — "
            "the data-parallel replicate width changed, so ZeRO-1 optimizer "
            "bucket shapes differ. Pass elastic=True to load_state (or run "
            "under `accelerate-tpu launch --elastic`, which sets "
            "ACCELERATE_ELASTIC_RESUME) to re-shard onto the current mesh, or "
            "relaunch with the saved topology.",
            saved=saved,
            current=current,
        )
    return True


def saved_topology(input_dir: str) -> "Optional[dict[str, int]]":
    """The mesh shape a checkpoint directory was written under: the
    ``_COMMITTED`` manifest's ``mesh`` entry, falling back to the shard
    indices for uncommitted/legacy layouts. None when nothing recorded."""
    import json
    import os

    from ..checkpointing import COMMITTED_MARKER

    marker = os.path.join(input_dir, COMMITTED_MARKER)
    if os.path.isfile(marker):
        try:
            with open(marker) as f:
                mesh = json.load(f).get("mesh")
            if mesh:
                return {str(k): int(v) for k, v in mesh.items()}
        except (OSError, ValueError):
            pass
    for prefix in ("model", "optimizer"):
        mesh = read_saved_mesh(input_dir, prefix)
        if mesh:
            return mesh
    return None
