"""The elastic supervisor: the loop that acts on a dead rank instead of
autopsying it.

PR 4 built the evidence chain (watchdog stall dumps, flight records, the
exit-101 abort) and PR 5 the recovery substrate (crash-consistent
``_COMMITTED`` checkpoints). This module closes the loop:
``accelerate-tpu launch --elastic`` wraps the per-host spawn in a
:class:`Supervisor` that

1. **watches** child exit codes, heartbeat-file gaps (the watchdog touches
   ``ACCELERATE_HEARTBEAT_FILE`` every tick — a stale mtime means even the
   watchdog thread is gone, the one hang class exit codes cannot report), and
   flight-recorder dumps (for step attribution);
2. **classifies** every death (:func:`classify_exit`): ``0`` → done, ``101``
   → watchdog stall-abort (restart, link the dump), signals → preemption /
   OOM-kill (restart), other nonzero → crash — where a *repeated crash at the
   same step* is a poison step and the supervisor stops with a diagnosis
   instead of burning the restart budget re-dying deterministically;
3. **tears down** the whole cohort on any failure (a half-dead SPMD cohort is
   blocked in the old incarnation's collectives; one rank cannot rejoin it),
   then **respawns** everyone under a new restart generation with bounded
   exponential backoff and a max-restart budget, injecting
   ``ACCELERATE_RESUME_FROM_CHECKPOINT=latest`` + ``ACCELERATE_ELASTIC_RESUME``
   so the training script resumes from the newest committed checkpoint;
4. **shrinks** when a host stays gone: ``available_fn`` reports who can come
   back, :mod:`.membership` renumbers the cohort and rescales
   ``dp_replicate``, and the cross-topology checkpoint loader re-shards the
   optimizer state onto the smaller mesh.

Every transition is a ``restart`` telemetry record
(``events-supervisor.jsonl`` in the telemetry dir) carrying generation,
cause, exit code, crash step, dump link and downtime seconds — the report
CLI's "restarts" section aggregates them.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..logging import get_logger
from ..telemetry.watchdog import ABORT_EXIT_CODE, HEARTBEAT_FILE_ENV_VAR
from .membership import (
    GENERATION_ENV_VAR,
    CohortSpec,
    MembershipError,
    negotiate_membership,
    publish_cohort_spec,
)

logger = get_logger(__name__)

#: Causes that indicate the environment killed us (restart is the right call).
TRANSIENT_CAUSES = ("stall_abort", "killed", "terminated", "heartbeat_gap")


def classify_exit(returncode: int) -> "tuple[str, bool]":
    """``(cause, restartable)`` for a child's exit code.

    ``101`` is RESERVED as the watchdog's stall-abort code
    (``telemetry.watchdog.ABORT_EXIT_CODE``): a rank that aborted itself
    after dumping a stall diagnosis. Negative codes are deaths by signal —
    SIGKILL is what preemption and the OOM killer both look like.
    """
    if returncode == 0:
        return "clean", False
    if returncode == ABORT_EXIT_CODE:
        return "stall_abort", True
    if returncode < 0:
        sig = -returncode
        if sig == signal.SIGKILL:
            return "killed", True  # preemption / OOM-killer
        if sig == signal.SIGTERM:
            return "terminated", True  # polite eviction
        return f"signal:{sig}", True
    return "crash", True


@dataclass
class RestartPolicy:
    """Bounds on the supervisor's persistence."""

    max_restarts: int = 3
    backoff_base_s: float = 1.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    poison_threshold: int = 3  # same-step failures before giving up
    heartbeat_timeout_s: float = 0.0  # 0 disables the mtime watch
    grace_period_s: float = 5.0  # SIGTERM → SIGKILL escalation window

    def backoff(self, attempt: int) -> float:
        """Delay before restart ``attempt`` (1-based), exponentially grown and
        capped."""
        return min(
            self.backoff_max_s,
            self.backoff_base_s * (self.backoff_factor ** max(0, attempt - 1)),
        )


@dataclass
class _Incident:
    generation: int
    cause: str
    exit_code: Optional[int]
    step: Optional[int] = None
    dump: Optional[str] = None


class Supervisor:
    """Supervise one cohort of per-host training processes.

    ``commands`` maps previous-rank → argv; single-host elastic launch passes
    one command. ``available_fn()`` (called before each respawn) returns the
    previous ranks that can come back — default: all of them. ``env`` is the
    base environment every child inherits (the launcher's env protocol).
    """

    def __init__(
        self,
        commands: "list[list[str]]",
        env: "Optional[dict[str, str]]" = None,
        policy: Optional[RestartPolicy] = None,
        telemetry_dir: Optional[str] = None,
        roster_dir: Optional[str] = None,
        available_fn: Optional[Callable[[], "list[int]"]] = None,
        axis_sizes: "Optional[dict[str, int]]" = None,
        spawn_fn: Optional[Callable[..., "subprocess.Popen"]] = None,
        status_interval_s: float = 5.0,
    ):
        if not commands:
            raise ValueError("supervisor needs at least one child command")
        self.commands = [list(c) for c in commands]
        self.env = dict(env if env is not None else os.environ)
        self.policy = policy or RestartPolicy()
        self.telemetry_dir = telemetry_dir or self.env.get(
            "ACCELERATE_TELEMETRY_DIR", "telemetry"
        )
        self.roster_dir = roster_dir or os.path.join(self.telemetry_dir, "cohort")
        self.available_fn = available_fn
        self.axis_sizes = dict(axis_sizes or {})
        self._spawn_fn = spawn_fn or subprocess.Popen
        self.generation = 0
        self.restarts_used = 0
        self.incidents: "list[_Incident]" = []
        self._children: "dict[int, subprocess.Popen]" = {}  # new-rank -> proc
        self._spawned_at = 0.0
        self._events_path = os.path.join(self.telemetry_dir, "events-supervisor.jsonl")
        self._events_opened = False
        # throttled ``supervisor`` status records from the watch loop: the
        # live hub (telemetry/hub.py) tails these for supervisor liveness,
        # current generation, and the restart budget without having to
        # infer them from restart records that may never come
        self.status_interval_s = float(status_interval_s)
        self._last_status_t = float("-inf")
        self._seen_dumps: "dict[str, float]" = {}  # path -> mtime (ranks reuse names)
        # Training-side SLO (telemetry/slo.py): ACCELERATE_SLO_RESTART_DOWNTIME_S
        # arms a restart-downtime objective — every restart's downtime_s is one
        # event, and a burn-episode entry writes an ``slo_violation`` record
        # into events-supervisor.jsonl next to the restart records. Restarts
        # are rare, so min_events=1: a single over-budget restart is a signal.
        from ..telemetry.slo import SLOMonitor, restart_downtime_slo_from_env

        downtime_slo = restart_downtime_slo_from_env()
        self._slo_monitor = (
            SLOMonitor([downtime_slo], min_events=1) if downtime_slo is not None else None
        )

    # -------------------------------------------------------------- telemetry --
    def _emit(self, kind: str, **fields: Any) -> None:
        try:
            os.makedirs(self.telemetry_dir, exist_ok=True)
            with open(self._events_path, "a") as f:
                if not self._events_opened:
                    self._events_opened = True
                    if f.tell() == 0:
                        f.write(
                            json.dumps(
                                {
                                    "kind": "meta",
                                    "schema": 1,
                                    "run_id": self.env.get("ACCELERATE_RUN_ID"),
                                    "role": "supervisor",
                                    "t": round(time.monotonic(), 6),
                                }
                            )
                            + "\n"
                        )
                f.write(
                    json.dumps({"kind": kind, "t": round(time.monotonic(), 6), **fields})
                    + "\n"
                )
        except OSError:
            pass  # supervision must not die of a full disk

    def _goodput_verdict(self) -> None:
        """On clean finish, fold the whole run's event streams into the
        goodput ledger and log the one-line verdict (also emitted as a
        supervisor ``goodput`` record so the report CLI can find it without
        re-deriving). Best-effort: a verdict failure must not fail the run."""
        try:
            from ..telemetry import goodput as _goodput
            from ..telemetry import report as _report

            events = _report.load_events([self.telemetry_dir])
            ledger = _goodput.build_ledger(events)
            if ledger is None:
                return
            logger.info(_goodput.verdict_line(ledger))
            self._emit(
                "goodput",
                final=True,
                goodput_fraction=ledger["goodput_fraction"],
                wall_s=ledger["wall_s"],
                unattributed_fraction=ledger["unattributed_fraction"],
                top_badput=ledger.get("top_badput"),
            )
        except Exception:
            logger.warning("goodput verdict failed", exc_info=True)

    # ----------------------------------------------------------------- spawn ----
    def _heartbeat_file(self, new_rank: int) -> str:
        return os.path.join(self.telemetry_dir, f"heartbeat-rank{new_rank}")

    def _pretouch_compile_cache(self, generation: int) -> None:
        """Probe the persistent compile cache the children will use BEFORE
        respawning them: a missing/readonly/unconfigured cache means the next
        generation cold-starts — that must be a visible, attributed fact in
        the supervisor record, not a silent MTTR doubling."""
        from ..compile_cache import pretouch

        try:
            info = pretouch(env=self.env)
        except Exception as exc:  # the probe must never block a respawn
            info = {"status": "error", "error": f"{type(exc).__name__}: {exc}"}
        self._emit("compile_cache", generation=generation, **info)
        if info.get("status") in ("missing", "readonly", "error"):
            logger.warning(
                f"compile cache {info.get('dir') or '?'} is {info['status']} "
                f"({info.get('error', '')}); generation {generation} will "
                "cold-start (full XLA recompile)"
            )

    def _spawn_cohort(self, spec: CohortSpec) -> None:
        publish_cohort_spec(self.roster_dir, spec)
        self._pretouch_compile_cache(spec.generation)
        self._children = {}
        # The supervisor only owns the world-size env when it actually manages
        # a multi-process cohort; with ONE supervised child (single-host
        # elastic launch, possibly of a multi-host worker) the launcher's own
        # ACCELERATE_NUM_PROCESSES/PROCESS_ID must survive untouched.
        manages_world = len(self.commands) > 1
        for new_rank, prev_rank in enumerate(spec.members):
            child_env = dict(self.env)
            child_env.update(
                spec.to_env(
                    new_rank=new_rank if manages_world else None,
                    include_world=manages_world,
                )
            )
            child_env[GENERATION_ENV_VAR] = str(spec.generation)
            # workers announce into the SAME roster dir the supervisor reads
            child_env["ACCELERATE_COHORT_DIR"] = self.roster_dir
            hb = self._heartbeat_file(new_rank)
            # a stale mtime from the PREVIOUS generation must not instantly
            # re-trip the gap watch before the new child can arm its watchdog
            try:
                os.unlink(hb)
            except OSError:
                pass
            child_env[HEARTBEAT_FILE_ENV_VAR] = hb
            proc = self._spawn_fn(self.commands[prev_rank], env=child_env)
            self._children[new_rank] = proc
        self._spawned_at = time.monotonic()
        logger.info(
            f"spawned cohort generation {spec.generation}: "
            f"{len(self._children)} process(es)"
        )

    def _teardown(self) -> None:
        """Stop every still-running child: SIGTERM (flight recorder dumps on
        it), grace period, then SIGKILL."""
        live = [p for p in self._children.values() if p.poll() is None]
        for p in live:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + self.policy.grace_period_s
        for p in live:
            try:
                p.wait(timeout=max(0.05, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                    p.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass

    # ------------------------------------------------------------- forensics ----
    def _latest_dump(self) -> "tuple[Optional[str], Optional[int]]":
        """Newest flight dump this incarnation produced (path, step) — the
        restart record links it, and the step feeds poison detection."""
        try:
            candidates = [
                os.path.join(self.telemetry_dir, n)
                for n in os.listdir(self.telemetry_dir)
                if n.startswith("flight-rank") and n.endswith(".json")
            ]
        except OSError:
            return None, None
        def _mtime(p: str) -> float:
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0

        fresh = [
            p for p in candidates
            if _mtime(p) != self._seen_dumps.get(p)
        ]
        if not fresh:
            return None, None
        newest = max(fresh, key=_mtime)
        for p in fresh:
            self._seen_dumps[p] = _mtime(p)
        step = None
        try:
            with open(newest) as f:
                data = json.load(f)
            step = data.get("step")
            if step is None:
                for ev in reversed(data.get("events", [])):
                    if ev.get("step") is not None:
                        step = ev.get("step")
                        break
        except (OSError, ValueError):
            pass
        return newest, step

    def _heartbeat_stale(self) -> "Optional[int]":
        """The new-rank whose heartbeat file is stalest beyond the timeout, or
        None. Ranks whose file never appeared are measured from spawn time —
        the watchdog creates it at start, so a missing file past the timeout
        means the child never even armed its forensics."""
        timeout = self.policy.heartbeat_timeout_s
        if timeout <= 0:
            return None
        now = time.time()
        worst: "tuple[float, Optional[int]]" = (0.0, None)
        for rank, proc in self._children.items():
            if proc.poll() is not None:
                continue  # an exited rank's file goes stale naturally
            path = self._heartbeat_file(rank)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                age = time.monotonic() - self._spawned_at
            if age > timeout and age > worst[0]:
                worst = (age, rank)
        return worst[1]

    def _poisoned(self) -> "Optional[int]":
        """The step the last ``poison_threshold`` incidents all crashed at, or
        None. Transient preemption lands at different steps (or carries no
        step at all); a deterministic bug re-dies at the same one."""
        k = self.policy.poison_threshold
        if k <= 0 or len(self.incidents) < k:
            return None
        tail = self.incidents[-k:]
        steps = {i.step for i in tail}
        if len(steps) == 1 and None not in steps and all(
            i.cause not in ("killed", "terminated", "heartbeat_gap") for i in tail
        ):
            return tail[-1].step
        return None

    # ------------------------------------------------------------------- run ----
    def run(self) -> int:
        """Supervise until the cohort finishes cleanly, the restart budget is
        exhausted, a poison step is diagnosed, or membership cannot be
        renegotiated. Returns the exit code to propagate."""
        # dumps already on disk belong to previous runs: remember their mtimes
        # so only a NEW/rewritten dump gets attributed to this run's incidents
        try:
            for n in os.listdir(self.telemetry_dir):
                if n.startswith("flight-rank") and n.endswith(".json"):
                    p = os.path.join(self.telemetry_dir, n)
                    self._seen_dumps[p] = os.path.getmtime(p)
        except OSError:
            pass
        members = list(range(len(self.commands)))
        spec = CohortSpec(
            generation=0,
            num_processes=len(members),
            members=members,
            dp_replicate_size=self.axis_sizes.get("dp_replicate"),
            axis_sizes={a: s for a, s in self.axis_sizes.items() if a != "dp_replicate"},
        )
        self._emit("elastic", phase="start", processes=len(members),
                   max_restarts=self.policy.max_restarts)
        self._spawn_cohort(spec)
        last_rc = 1
        while True:
            incident = self._watch()
            if incident is None:  # clean finish
                self._emit("elastic", phase="done", generation=self.generation,
                           restarts=self.restarts_used)
                self._goodput_verdict()
                return 0
            failed_at = time.monotonic()
            self._teardown()
            self.incidents.append(incident)
            last_rc = incident.exit_code if incident.exit_code else 1
            poison = self._poisoned()
            if poison is not None:
                diagnosis = (
                    f"poison step: the last {self.policy.poison_threshold} restarts all "
                    f"died at step {poison} (cause {incident.cause}) — this is a "
                    "deterministic failure, not a preemption; restarting again would "
                    "re-die. Inspect the flight dump"
                    + (f": {incident.dump}" if incident.dump else " in the telemetry dir")
                )
                logger.error(diagnosis)
                print(f"[accelerate-tpu elastic] {diagnosis}", file=sys.stderr)
                self._emit("restart", generation=self.generation, cause="poison_step",
                           step=poison, exit_code=incident.exit_code,
                           dump=incident.dump, gave_up=True)
                return last_rc
            if self.restarts_used >= self.policy.max_restarts:
                msg = (
                    f"restart budget exhausted ({self.restarts_used}/"
                    f"{self.policy.max_restarts}); last cause: {incident.cause}"
                    + (f", dump: {incident.dump}" if incident.dump else "")
                )
                logger.error(msg)
                print(f"[accelerate-tpu elastic] {msg}", file=sys.stderr)
                self._emit("restart", generation=self.generation, cause=incident.cause,
                           step=incident.step, exit_code=incident.exit_code,
                           dump=incident.dump, gave_up=True, budget_exhausted=True)
                return last_rc
            self.restarts_used += 1
            delay = self.policy.backoff(self.restarts_used)
            alive = (
                sorted(self.available_fn())
                if self.available_fn is not None
                else list(range(len(self.commands)))
            )
            try:
                spec = negotiate_membership(
                    alive,
                    prev_num_processes=len(self.commands),
                    generation=self.generation + 1,
                    prev_axis_sizes=self.axis_sizes or None,
                )
            except MembershipError as e:
                logger.error(f"cannot renegotiate cohort: {e}")
                self._emit("restart", generation=self.generation, cause="membership",
                           error=str(e), gave_up=True)
                return last_rc
            logger.warning(
                f"cohort gen {self.generation} died ({incident.cause}"
                + (f", step {incident.step}" if incident.step is not None else "")
                + f"); restart {self.restarts_used}/{self.policy.max_restarts} "
                f"as gen {spec.generation} with {spec.num_processes} process(es) "
                f"in {delay:.1f}s"
                + (f" — dump: {incident.dump}" if incident.dump else "")
            )
            time.sleep(delay)
            self.generation = spec.generation
            self._spawn_cohort(spec)
            downtime_s = round(time.monotonic() - failed_at, 3)
            self._emit(
                "restart",
                generation=spec.generation,
                attempt=self.restarts_used,
                cause=incident.cause,
                exit_code=incident.exit_code,
                step=incident.step,
                dump=incident.dump,
                processes=spec.num_processes,
                downtime_s=downtime_s,
            )
            if self._slo_monitor is not None:
                self._slo_monitor.observe("restart_downtime", value=downtime_s)
                for rec in self._slo_monitor.evaluate(emit=False):
                    if rec.get("entered"):
                        # the supervisor writes its own stream (no EventLog
                        # in this process) — same record schema
                        self._emit("slo_violation", generation=spec.generation,
                                   **{k: v for k, v in rec.items() if k != "entered"})

    def _maybe_emit_status(self) -> None:
        """Throttled liveness record for the hub's live plane: the current
        generation, how many children are alive, and the restart budget."""
        now = time.monotonic()
        if now - self._last_status_t < self.status_interval_s:
            return
        self._last_status_t = now
        self._emit(
            "supervisor",
            generation=self.generation,
            processes=sum(1 for p in self._children.values() if p.poll() is None),
            restarts_used=self.restarts_used,
            max_restarts=self.policy.max_restarts,
        )

    def _watch(self) -> "Optional[_Incident]":
        """Block until the cohort finishes (returns None) or something dies /
        goes silent (returns the incident)."""
        while True:
            self._maybe_emit_status()
            for rank, proc in self._children.items():
                rc = proc.poll()
                if rc is None:
                    continue
                if rc == 0:
                    continue
                cause, _ = classify_exit(rc)
                dump, step = self._latest_dump()
                return _Incident(
                    generation=self.generation, cause=cause, exit_code=rc,
                    step=step, dump=dump,
                )
            if all(p.poll() == 0 for p in self._children.values()):
                return None
            stale = self._heartbeat_stale()
            if stale is not None:
                dump, step = self._latest_dump()
                return _Incident(
                    generation=self.generation, cause="heartbeat_gap",
                    exit_code=None, step=step, dump=dump,
                )
            time.sleep(0.05)


def supervise_command(
    cmd: "list[str]",
    env: "Optional[dict[str, str]]" = None,
    policy: Optional[RestartPolicy] = None,
    telemetry_dir: Optional[str] = None,
    axis_sizes: "Optional[dict[str, int]]" = None,
) -> int:
    """Single-host convenience: supervise ONE child command (the
    ``accelerate-tpu launch --elastic`` path on a laptop/single TPU-VM)."""
    sup = Supervisor(
        [cmd], env=env, policy=policy, telemetry_dir=telemetry_dir,
        axis_sizes=axis_sizes,
    )
    return sup.run()
