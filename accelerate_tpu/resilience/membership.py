"""Cohort membership across restarts: generation counters, the roster
handshake, and the shrink-the-mesh arithmetic for elastic resume.

A restarted job is a NEW cohort: possibly fewer hosts (one stayed preempted),
possibly renumbered ranks (contiguity is required by ``jax.distributed``).
This module owns the bookkeeping that makes the regrown cohort coherent
before any jax code runs:

- **Restart generation** — ``ACCELERATE_RESTART_GENERATION`` counts cohort
  incarnations (0 = first launch). Every forensic artifact and telemetry
  stream of a supervised run carries it, chaos faults can pin to it, and the
  roster files are namespaced by it so a stale generation-0 announcement can
  never vote in generation 1's rendezvous.
- **Roster handshake** — each worker :func:`announce_membership` into a
  shared directory (``member-gen<g>-rank<k>.json``, write-then-rename like
  the checkpoint commit markers; the same shared-fs assumption the sharded
  loader already makes). The supervisor reads the roster to learn who is
  actually alive, writes the authoritative :class:`CohortSpec`
  (``cohort-gen<g>.json``), and workers :func:`load_cohort_spec` before
  constructing state — so every rank agrees on the new world size without a
  collective (which a half-dead cohort could not run).
- **Shrink arithmetic** — :func:`negotiate_membership` maps "``m`` of ``n``
  hosts survive" onto the mesh: only ``dp_replicate`` may shrink (model-
  parallel axes are baked into the checkpointed layout); the data-parallel
  width scales by ``m/n`` and must stay integral. Anything else raises
  :class:`MembershipError` with the exact arithmetic that failed — the
  supervisor then waits for the host to return instead of respawning a
  cohort that cannot rendezvous.

``state.process_identity()`` (PR 4) stays the identity source: it answers
from the env protocol without booting jax, so announcements work in the
window before — or instead of — backend init.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Optional

GENERATION_ENV_VAR = "ACCELERATE_RESTART_GENERATION"
ELASTIC_RESUME_ENV_VAR = "ACCELERATE_ELASTIC_RESUME"
_MEMBER_NAME = "member-gen{gen:04d}-rank{rank:05d}.json"
_COHORT_NAME = "cohort-gen{gen:04d}.json"


class MembershipError(RuntimeError):
    """The surviving host set cannot form a valid cohort (non-integral
    data-parallel shrink, or a model-parallel axis would have to change)."""


def current_generation() -> int:
    """This process's restart generation (0 outside supervised runs; malformed
    env degrades to 0 — the identity path must never raise)."""
    raw = os.environ.get(GENERATION_ENV_VAR, "").strip()
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


@dataclass
class CohortSpec:
    """The authoritative description of one cohort incarnation."""

    generation: int
    num_processes: int
    members: "list[int]"  # PREVIOUS ranks, in new-rank order (index = new rank)
    dp_replicate_size: Optional[int] = None  # None = not mesh-managed
    axis_sizes: "dict[str, int]" = field(default_factory=dict)  # full mesh intent

    def to_env(self, new_rank: Optional[int] = None,
               include_world: bool = True) -> "dict[str, str]":
        """The env rewrite a worker of this cohort must see BEFORE state
        construction: world size, (optionally) its new rank, the shrunken
        ``dp_replicate``, generation, and the elastic-resume hints.

        ``include_world=False`` keeps the generation/resume hints but leaves
        ``ACCELERATE_NUM_PROCESSES``/mesh sizes alone — a supervisor managing
        ONE child (which may itself be a rank of a launcher-configured
        multi-host job) must not clobber the launcher's world size."""
        env = {
            GENERATION_ENV_VAR: str(self.generation),
            # legacy spelling the --max_restarts loop already exposes
            "ACCELERATE_RESTART_COUNT": str(self.generation),
        }
        if include_world:
            env["ACCELERATE_NUM_PROCESSES"] = str(self.num_processes)
            if new_rank is not None:
                env["ACCELERATE_PROCESS_ID"] = str(new_rank)
            if self.dp_replicate_size is not None:
                env["PARALLELISM_CONFIG_DP_REPLICATE_SIZE"] = str(self.dp_replicate_size)
            for axis, size in self.axis_sizes.items():
                env[f"PARALLELISM_CONFIG_{axis.upper()}_SIZE"] = str(size)
        if self.generation > 0:
            env[ELASTIC_RESUME_ENV_VAR] = "1"
            env["ACCELERATE_RESUME_FROM_CHECKPOINT"] = "latest"
        return env


def negotiate_membership(
    alive: "list[int]",
    prev_num_processes: int,
    generation: int,
    prev_axis_sizes: "Optional[dict[str, int]]" = None,
) -> CohortSpec:
    """Fit the mesh onto the surviving hosts.

    ``alive`` lists the previous ranks still available (any order); the new
    cohort renumbers them contiguously in ascending previous-rank order.
    Only ``dp_replicate`` scales: ``new_dp = old_dp * len(alive) /
    prev_num_processes`` must be a positive integer, and every other axis is
    carried over unchanged. With no axis intent recorded (single-host runs,
    tests) the spec only rewrites the world size.
    """
    if not alive:
        raise MembershipError("no surviving members to form a cohort from")
    members = sorted(set(int(r) for r in alive))
    new_world = len(members)
    axis_sizes = dict(prev_axis_sizes or {})
    dp = axis_sizes.pop("dp_replicate", None)
    new_dp = None
    if dp is not None and prev_num_processes > 0 and new_world != prev_num_processes:
        scaled = dp * new_world
        if scaled % prev_num_processes != 0 or scaled // prev_num_processes < 1:
            raise MembershipError(
                f"cannot shrink dp_replicate={dp} from {prev_num_processes} to "
                f"{new_world} host(s): {dp}*{new_world}/{prev_num_processes} is not a "
                "positive integer — wait for the host to return or relaunch with an "
                "explicit smaller topology"
            )
        new_dp = scaled // prev_num_processes
    elif dp is not None:
        new_dp = dp
    fixed = {a: s for a, s in axis_sizes.items() if s and s > 1}
    if fixed and new_world != prev_num_processes:
        # model-parallel axes are frozen into the checkpoint layout; a shrink
        # can only come out of the replicate axis
        if new_dp is None:
            raise MembershipError(
                f"cohort shrank {prev_num_processes}->{new_world} but the mesh has no "
                f"dp_replicate axis to absorb it (fixed axes: {fixed})"
            )
    return CohortSpec(
        generation=generation,
        num_processes=new_world,
        members=members,
        dp_replicate_size=new_dp,
        axis_sizes=fixed,
    )


# ---------------------------------------------------------------------------
# roster handshake (shared-fs, write-then-rename — no collectives)


def announce_membership(roster_dir: str, generation: Optional[int] = None) -> str:
    """Drop this process's membership announcement for ``generation`` (default:
    :func:`current_generation`). Returns the file path. Never raises on
    identity problems — a worker that cannot announce is simply absent from
    the roster, which is the failure the roster exists to surface."""
    from ..state import process_identity

    gen = current_generation() if generation is None else int(generation)
    ident = process_identity()
    rank = int(ident.get("process_index", 0))
    os.makedirs(roster_dir, exist_ok=True)
    path = os.path.join(roster_dir, _MEMBER_NAME.format(gen=gen, rank=rank))
    payload = {
        "generation": gen,
        "rank": rank,
        "announced_at_unix": round(time.time(), 3),
        **{k: ident.get(k) for k in ("hostname", "pid", "num_processes", "run_id")},
    }
    with open(path + ".tmp", "w") as f:
        json.dump(payload, f)
    os.replace(path + ".tmp", path)
    return path


def read_roster(roster_dir: str, generation: int) -> "dict[int, dict]":
    """All announcements for ``generation``: ``{rank: payload}``."""
    roster: "dict[int, dict]" = {}
    if not os.path.isdir(roster_dir):
        return roster
    prefix = f"member-gen{generation:04d}-rank"
    for name in sorted(os.listdir(roster_dir)):
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(roster_dir, name)) as f:
                payload = json.load(f)
            roster[int(payload["rank"])] = payload
        except (OSError, ValueError, KeyError):
            continue  # a torn announcement is an absent member
    return roster


def publish_cohort_spec(roster_dir: str, spec: CohortSpec) -> str:
    """Supervisor-side: make ``spec`` the authoritative cohort description."""
    os.makedirs(roster_dir, exist_ok=True)
    path = os.path.join(roster_dir, _COHORT_NAME.format(gen=spec.generation))
    with open(path + ".tmp", "w") as f:
        json.dump(asdict(spec), f)
    os.replace(path + ".tmp", path)
    return path


def load_cohort_spec(roster_dir: str, generation: Optional[int] = None) -> Optional[CohortSpec]:
    """Worker-side: the published spec for ``generation`` (default: this
    process's), or None when the run is not supervised/elastic."""
    gen = current_generation() if generation is None else int(generation)
    path = os.path.join(roster_dir, _COHORT_NAME.format(gen=gen))
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            data = json.load(f)
        return CohortSpec(**data)
    except (OSError, ValueError, TypeError):
        return None


def await_roster(
    roster_dir: str, generation: int, expected: int, timeout: float = 60.0
) -> "dict[int, dict]":
    """Block until ``expected`` members announced for ``generation`` (or the
    timeout passes — returning whoever did show up, so the caller can decide
    to shrink around the missing)."""
    deadline = time.monotonic() + timeout
    while True:
        roster = read_roster(roster_dir, generation)
        if len(roster) >= expected or time.monotonic() > deadline:
            return roster
        time.sleep(0.05)
