"""Elastic, preemption-tolerant training (ROADMAP item 5).

PR 4 made failures diagnosable (watchdog, flight recorder, exit-101 abort)
and PR 5 made them survivable (crash-consistent ``_COMMITTED`` checkpoints).
This package makes them *routine*: a supervised cohort that detects a dead or
stalled rank, tears the job down, and regrows it — possibly on fewer hosts —
resuming from the last committed checkpoint without a human in the loop.

- :mod:`.supervisor` — the ``accelerate-tpu launch --elastic`` loop: exit-code
  classification (101 = stall abort, signals = preemption), heartbeat-file
  gap detection, bounded-backoff restarts under a budget, poison-step
  diagnosis, restart telemetry.
- :mod:`.membership` — restart generations and the cohort roster handshake:
  who survived, how the world renumbers, how ``dp_replicate`` rescales.
- :mod:`.reshard` — cross-topology resume: mesh-shape guards
  (``CheckpointTopologyError``) and fused-ZeRO-1 bucket re-padding so a dp=N
  checkpoint restores onto dp=M.
- :mod:`.chaos` — the deterministic fault-injection harness (``make chaos``)
  that proves all of the above under seeded SIGKILL/hang/straggler schedules,
  plus the straggler-mitigation replanner.

See ``docs/resilience.md``.
"""

from .chaos import (
    ChaosFaultError,
    ChaosSchedule,
    Fault,
    maybe_arm_from_env,
    maybe_inject,
    replan_data_assignment,
)
from .membership import (
    CohortSpec,
    MembershipError,
    announce_membership,
    current_generation,
    load_cohort_spec,
    negotiate_membership,
)
from .reshard import (
    CheckpointTopologyError,
    check_topology,
    is_elastic_compatible,
    mesh_shape_dict,
    saved_topology,
    topology_matches,
)
from .supervisor import (
    RestartPolicy,
    Supervisor,
    classify_exit,
    supervise_command,
)

__all__ = [
    "ChaosFaultError",
    "ChaosSchedule",
    "CheckpointTopologyError",
    "CohortSpec",
    "Fault",
    "MembershipError",
    "RestartPolicy",
    "Supervisor",
    "announce_membership",
    "check_topology",
    "classify_exit",
    "current_generation",
    "is_elastic_compatible",
    "load_cohort_spec",
    "maybe_arm_from_env",
    "maybe_inject",
    "mesh_shape_dict",
    "negotiate_membership",
    "replan_data_assignment",
    "saved_topology",
    "supervise_command",
    "topology_matches",
]
