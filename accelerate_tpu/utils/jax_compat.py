"""Version-bridging shims over renamed jax APIs.

The package targets the modern spelling ``jax.shard_map(..., check_vma=...,
axis_names=...)``; jax < 0.6 ships the same functionality as
``jax.experimental.shard_map.shard_map(..., check_rep=..., auto=...)``.
One shim keeps every call site on the modern spelling and translates for
older installs, so kernels and parallel schedules run unmodified on both.
"""

from __future__ import annotations

from typing import Optional


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` on any installed jax (the public
    predicate only exists from 0.4.38; older installs expose the same fact as
    a live coordinator client on the internal global state)."""
    import jax

    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:  # pragma: no cover - exotic jax builds
        return False


def enable_cpu_multiprocess_collectives() -> None:
    """Turn on cross-process collectives for the CPU backend (gloo).

    The CPU backend refuses multi-process computations unless its collectives
    implementation is selected; the flag spelling changed across jax versions.
    Must run before the backend initializes — the multi-host bootstrap calls
    it right before ``jax.distributed.initialize``. A no-op when neither flag
    exists (ancient jax) — the subsequent collective raises its own error.
    """
    import jax

    for flag, value in (
        ("jax_cpu_collectives_implementation", "gloo"),
        ("jax_cpu_enable_gloo_collectives", True),
    ):
        try:
            jax.config.update(flag, value)
            return
        except (AttributeError, ValueError):
            continue


def broadcast_one_to_all(x, is_source: bool):
    """``multihost_utils.broadcast_one_to_all`` that preserves the input dtype
    (old-jax gloo CPU collectives upcast sub-int32 payloads to int32 in the
    underlying psum, mangling raw-bytes broadcasts)."""
    import numpy as np
    from jax.experimental import multihost_utils

    from ..telemetry import flight_recorder as _flight

    x = np.asarray(x)
    # every wire collective feeds the per-rank schedule fingerprint (the
    # jaxlint R4 runtime cross-check) — here, not only in operations.py,
    # because data_loader and friends call these wrappers directly. The
    # "wire:" prefix separates leaf-level entries from op-level ones (an
    # operations.py gather logs both; the sequence stays rank-consistent).
    _flight.record_collective("wire:broadcast_one_to_all", f"{x.shape}/{x.dtype}")
    out = np.asarray(multihost_utils.broadcast_one_to_all(x, is_source=is_source))
    if out.dtype != x.dtype:
        out = out.astype(x.dtype)
    return out


def process_allgather(x, tiled: bool = False):
    """``multihost_utils.process_allgather`` preserving the input dtype (same
    old-jax gloo upcast as :func:`broadcast_one_to_all`)."""
    import numpy as np
    from jax.experimental import multihost_utils

    from ..telemetry import flight_recorder as _flight

    in_dtype = np.asarray(x).dtype if not hasattr(x, "dtype") else x.dtype
    _flight.record_collective(
        "wire:process_allgather", f"{getattr(x, 'shape', ())}/{in_dtype}"
    )
    out = np.asarray(multihost_utils.process_allgather(x, tiled=tiled))
    if out.dtype != in_dtype:
        out = out.astype(in_dtype)
    return out


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    check_vma: Optional[bool] = None,
    axis_names=None,
):
    """``jax.shard_map`` on any installed jax.

    ``check_vma`` maps to the pre-0.6 ``check_rep``; ``axis_names`` (the axes
    manual inside the body) maps to the pre-0.6 ``auto`` (its complement over
    the mesh axes — partial-manual mode, which old jax only supports with
    replication checking off).
    """
    try:
        from jax import shard_map as _new  # jax >= 0.6 spelling
    except ImportError:
        _new = None
    if _new is not None:
        kwargs = {}
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return _new(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)

    from jax.experimental.shard_map import shard_map as _old

    kwargs = {}
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None and frozenset(mesh.axis_names) - frozenset(axis_names):
        # the modern partial-manual mode (auto axes) lowers to a PartitionId
        # instruction old XLA's SPMD partitioner rejects; run fully manual
        # instead — axes unmentioned by the specs replicate their operands, so
        # the body computes identically on every auto-axis slice and the
        # result matches (at the cost of redundant compute on those slices).
        # Replication checking cannot see that equivalence: off.
        kwargs["check_rep"] = False
    return _old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
