"""Seeding and cross-process RNG synchronization.

TPU-native counterpart of the reference's ``utils/random.py``
(``/root/reference/src/accelerate/utils/random.py`` — ``set_seed:39``,
``synchronize_rng_state:78``, ``synchronize_rng_states:154``).

JAX's explicit ``PRNGKey`` makes most of this trivial: device RNG is a value you
hold, fork, and checkpoint. What still needs care is the *host-side* RNG used by
samplers/shuffles (python/numpy/torch), which must agree across processes so every
host draws the same permutation — the reference broadcasts rank-0 state per epoch
(``data_loader.py:559-560``).
"""

from __future__ import annotations

import random
from typing import Iterable, Optional

import numpy as np

from ..state import PartialState
from .dataclasses import RNGType
from .imports import is_torch_available
from .operations import broadcast_object_list


_GLOBAL_KEY = None  # module-level default jax PRNG key set by set_seed


def get_rng_key():
    """The framework-global jax PRNG key (set by :func:`set_seed`), or None."""
    return _GLOBAL_KEY


def next_rng_key():
    """Split the global key and return a fresh subkey."""
    global _GLOBAL_KEY
    import jax

    if _GLOBAL_KEY is None:
        set_seed(0)
    _GLOBAL_KEY, sub = jax.random.split(_GLOBAL_KEY)
    return sub


def set_seed(seed: int, device_specific: bool = False, deterministic: bool = False) -> None:
    """Seed python/numpy/torch(host)/jax (reference ``set_seed:39``).

    ``device_specific`` offsets the seed by process index so each host draws
    different data-augmentation randomness while model init stays synced.
    """
    global _GLOBAL_KEY
    import jax

    if device_specific:
        seed = seed + PartialState().process_index
    random.seed(seed)
    np.random.seed(seed % (2**32))
    if is_torch_available():
        import torch

        torch.manual_seed(seed)
    _GLOBAL_KEY = jax.random.PRNGKey(seed)


def synchronize_rng_state(rng_type: Optional[RNGType] = None, generator=None) -> None:
    """Broadcast rank-0's RNG state for one stream to all processes
    (reference ``synchronize_rng_state:78``)."""
    state = PartialState()
    if state.num_processes == 1:
        return
    rng_type = RNGType(str(rng_type)) if rng_type is not None else RNGType.NUMPY
    if rng_type == RNGType.PYTHON:
        payload = [random.getstate()]
        payload = broadcast_object_list(payload)
        random.setstate(payload[0])
    elif rng_type == RNGType.NUMPY:
        payload = [np.random.get_state()]
        payload = broadcast_object_list(payload)
        np.random.set_state(payload[0])
    elif rng_type == RNGType.TORCH and is_torch_available():
        import torch

        payload = [torch.get_rng_state()]
        payload = broadcast_object_list(payload)
        torch.set_rng_state(payload[0])
    elif rng_type == RNGType.GENERATOR and generator is not None:
        payload = [generator.get_state() if hasattr(generator, "get_state") else None]
        payload = broadcast_object_list(payload)
        if payload[0] is not None:
            generator.set_state(payload[0])
    elif rng_type == RNGType.JAX:
        global _GLOBAL_KEY
        payload = [None if _GLOBAL_KEY is None else np.asarray(_GLOBAL_KEY)]
        payload = broadcast_object_list(payload)
        if payload[0] is not None:
            import jax

            _GLOBAL_KEY = jax.numpy.asarray(payload[0])


def synchronize_rng_states(rng_types: Iterable[str | RNGType], generator=None) -> None:
    """Synchronize several streams at once (reference ``synchronize_rng_states:154``)."""
    for rng_type in rng_types:
        synchronize_rng_state(RNGType(str(rng_type)), generator=generator)


def capture_rng_states(include_torch: bool = True) -> dict:
    """Snapshot all host RNG streams + the global jax key, for checkpointing
    (reference ``checkpointing.py:153-176``)."""
    states = {
        "python": random.getstate(),
        "numpy": np.random.get_state(),
        "jax_key": None if _GLOBAL_KEY is None else np.asarray(_GLOBAL_KEY),
    }
    if include_torch and is_torch_available():
        import torch

        states["torch"] = torch.get_rng_state()
    return states


def restore_rng_states(states: dict) -> None:
    """Inverse of :func:`capture_rng_states` (reference ``checkpointing.py:287-309``)."""
    global _GLOBAL_KEY
    random.setstate(states["python"])
    np.random.set_state(states["numpy"])
    if states.get("jax_key") is not None:
        import jax.numpy as jnp

        _GLOBAL_KEY = jnp.asarray(states["jax_key"])
    if "torch" in states and is_torch_available():
        import torch

        torch.set_rng_state(states["torch"])
