"""Core enums and configuration dataclasses.

TPU-native counterpart of the reference's ``utils/dataclasses.py``
(``/root/reference/src/accelerate/utils/dataclasses.py`` — ``DistributedType:600``,
``PrecisionType:765``, ``RNGType:781``, ``DataLoaderConfiguration:814``,
``ProjectConfiguration:909``, ``GradientAccumulationPlugin:972``,
``ProfileKwargs:484``, ``LoggerType:737``). Engine-specific plugins (DeepSpeed /
Megatron / FSDP-torch) collapse into sharding configuration — see
``accelerate_tpu/parallel/`` and ``parallelism_config.py``.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Callable, Optional

from .environment import (
    parse_flag_from_env,
    parse_int_from_env,
    parse_optional_int_from_env,
    parse_seconds_from_env,
)


class BaseEnum(str, enum.Enum):
    def __str__(self) -> str:  # so f-strings print the bare value
        return self.value

    @classmethod
    def list(cls) -> list[str]:
        return [v.value for v in cls]


class DistributedType(BaseEnum):
    """How this process participates in distributed execution.

    Unlike the reference (``utils/dataclasses.py:600`` — one value per engine:
    MULTI_GPU / DEEPSPEED / FSDP / MEGATRON_LM / XLA), a JAX program has exactly one
    execution model: SPMD over a device mesh. The interesting structure (dp/fsdp/tp/
    cp/sp sizes) lives in :class:`~accelerate_tpu.parallelism_config.ParallelismConfig`.
    """

    NO = "NO"  # single device
    SPMD = "SPMD"  # >1 device, single- or multi-host, via mesh + GSPMD
    MULTI_HOST = "MULTI_HOST"  # SPMD spanning multiple processes/hosts


class PrecisionType(BaseEnum):
    """Mixed-precision modes (reference ``utils/dataclasses.py:765``).

    On TPU bf16 needs no loss scaling (MXU-native); fp16 is supported for parity but
    bf16 is the recommended mode. fp8 uses XLA fp8 dot_general / Pallas kernels.
    """

    NO = "no"
    FP16 = "fp16"
    BF16 = "bf16"
    FP8 = "fp8"


class RNGType(BaseEnum):
    """RNG streams that can be synchronized/checkpointed (reference ``:781``)."""

    JAX = "jax"  # explicit jax.random key held by the Accelerator
    NUMPY = "numpy"
    PYTHON = "python"
    TORCH = "torch"  # host-side torch generators used by interop dataloaders
    GENERATOR = "generator"


class LoggerType(BaseEnum):
    ALL = "all"
    TENSORBOARD = "tensorboard"
    WANDB = "wandb"
    MLFLOW = "mlflow"
    COMETML = "comet_ml"
    AIM = "aim"
    CLEARML = "clearml"
    DVCLIVE = "dvclive"
    SWANLAB = "swanlab"
    TRACKIO = "trackio"
    JSONL = "jsonl"  # built-in dependency-free tracker


class SaveFormat(BaseEnum):
    MSGPACK = "msgpack"  # flax serialization
    SAFETENSORS = "safetensors"
    NUMPY = "npz"
    ORBAX = "orbax"


@dataclass
class KwargsHandler:
    """Base for kwargs passthrough dataclasses (reference ``:68``)."""

    def to_dict(self) -> dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()}

    def to_kwargs(self) -> dict[str, Any]:
        from dataclasses import fields

        default = self.__class__()
        return {
            f.name: getattr(self, f.name)
            for f in fields(self)
            if getattr(self, f.name) != getattr(default, f.name)
        }


@dataclass
class InitProcessGroupKwargs(KwargsHandler):
    """Options for ``jax.distributed.initialize`` (reference ``:273`` wraps
    ``torch.distributed.init_process_group``)."""

    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    local_device_ids: Optional[list[int]] = None
    initialization_timeout: timedelta = field(default_factory=lambda: timedelta(seconds=300))


@dataclass
class GradientAccumulationPlugin(KwargsHandler):
    """Reference ``utils/dataclasses.py:972``. ``adjust_scheduler`` multiplies
    scheduler steps; ``sync_with_dataloader`` forces a sync step at end-of-epoch."""

    num_steps: int = 1
    adjust_scheduler: bool = True
    sync_with_dataloader: bool = True
    sync_each_batch: bool = False

    def __post_init__(self):
        if self.num_steps < 1:
            raise ValueError(f"gradient accumulation steps must be >= 1, got {self.num_steps}")


@dataclass
class DataLoaderConfiguration(KwargsHandler):
    """Reference ``utils/dataclasses.py:814``.

    ``dispatch_batches``: process 0 reads batches and broadcasts (DataLoaderDispatcher,
    reference ``data_loader.py:704``); default per-process sharded reads.
    ``even_batches``: wrap around to equalize final batches (static shapes make this
    the strongly-recommended default under XLA).
    ``prefetch_depth``: how many batches the background producer may fetch,
    host-process and transfer to device ahead of the consuming step (no
    reference counterpart — TPU-native async input pipeline, see
    ``docs/data_pipeline.md``). ``0`` disables prefetching and restores fully
    synchronous iteration.
    """

    split_batches: bool = False
    dispatch_batches: Optional[bool] = None
    even_batches: bool = True
    use_seedable_sampler: bool = True
    non_blocking: bool = True
    use_stateful_dataloader: bool = False
    data_seed: Optional[int] = None
    prefetch_depth: int = 2


@dataclass
class ProjectConfiguration(KwargsHandler):
    """Checkpoint/artifact layout (reference ``utils/dataclasses.py:909``)."""

    project_dir: Optional[str] = None
    logging_dir: Optional[str] = None
    automatic_checkpoint_naming: bool = False
    total_limit: Optional[int] = None
    iteration: int = 0
    save_on_each_node: bool = False

    def set_directories(self, project_dir: Optional[str] = None) -> None:
        self.project_dir = project_dir
        if self.logging_dir is None:
            self.logging_dir = project_dir

    def __post_init__(self):
        if self.logging_dir is None:
            self.logging_dir = self.project_dir


@dataclass
class JitConfig(KwargsHandler):
    """Compilation options — the moral twin of ``TorchDynamoPlugin`` (reference
    ``utils/dataclasses.py:1024``). Under JAX, jit is default-on; these knobs tune it.

    ``donate_params``: donate param/opt-state buffers to the train step (halves HBM
    for the update). ``persistent_cache_dir`` enables the XLA compilation cache so the
    reference's "regional compilation" compile-latency win (``benchmarks/torch.compile``)
    is matched by cache reuse. ``remat_policy`` names a jax.checkpoint policy for
    activation rematerialisation.
    """

    disable_jit: bool = field(
        default_factory=lambda: parse_flag_from_env("ACCELERATE_TPU_DISABLE_JIT", False)
    )
    donate_params: bool = True
    persistent_cache_dir: Optional[str] = field(
        default_factory=lambda: os.environ.get("ACCELERATE_TPU_COMPILE_CACHE")
    )
    remat_policy: Optional[str] = None  # e.g. "nothing_saveable", "dots_saveable"

    def apply(self) -> None:
        import jax

        if self.persistent_cache_dir:
            jax.config.update("jax_compilation_cache_dir", self.persistent_cache_dir)
        if self.disable_jit:
            jax.config.update("jax_disable_jit", True)


@dataclass
class ProfileConfig(KwargsHandler):
    """``jax.profiler`` trace configuration — counterpart of ``ProfileKwargs``
    (reference ``utils/dataclasses.py:484-599`` builds ``torch.profiler.profile``).

    ``output_trace_dir`` receives a TensorBoard/Perfetto-compatible trace; the
    reference exports per-rank Chrome traces (``accelerator.py:4148-4205``).

    Two complementary mechanisms:

    - the ``accelerator.profile(...)`` *context* (whole-block, or the
      reference-style ``wait/warmup/active/repeat`` step schedule below);
    - **automatic trace windows** on the tracked train step (no context
      needed): every ``trace_every`` steps — or one-shot at step
      ``trace_at`` — a window of ``trace_steps`` steps is traced, parsed
      (top-k ops, compute/collective/idle split, comms-overlap ratio — see
      ``telemetry/xplane.py``) and emitted as a ``trace`` telemetry record.
      Env-seeded (``ACCELERATE_TRACE_EVERY`` / ``ACCELERATE_TRACE_STEPS`` /
      ``ACCELERATE_TRACE_AT`` / ``ACCELERATE_TRACE_DIR``) so a launcher can
      arm profiling with zero code changes.
    """

    output_trace_dir: Optional[str] = field(
        default_factory=lambda: os.environ.get("ACCELERATE_TRACE_DIR") or None
    )
    create_perfetto_link: bool = False
    create_perfetto_trace: bool = True
    host_tracer_level: int = 2
    python_tracer_level: int = 0
    device_tracer_level: int = 1
    # step-windowed schedule (reference ProfileKwargs wait/warmup/active/
    # repeat/skip_first, ``utils/dataclasses.py:484-599``): when ``active > 0``
    # the profile context traces only the active window of each cycle, driven
    # by ``prof.step()`` calls; ``repeat=0`` cycles until the context exits
    skip_first: int = 0
    wait: int = 0
    warmup: int = 0
    active: int = 0
    repeat: int = 0
    # automatic trace windows on the tracked step (telemetry/xplane.py):
    # every Nth step / a one-shot step index, window length in steps
    trace_every: int = field(
        default_factory=lambda: parse_int_from_env("ACCELERATE_TRACE_EVERY", 0)
    )
    trace_steps: int = field(
        default_factory=lambda: max(1, parse_int_from_env("ACCELERATE_TRACE_STEPS", 1))
    )
    trace_at: Optional[int] = field(
        default_factory=lambda: parse_optional_int_from_env("ACCELERATE_TRACE_AT")
    )

    @property
    def schedule_enabled(self) -> bool:
        return self.active > 0

    @property
    def windows_enabled(self) -> bool:
        """True when automatic trace windows should drive the tracked step."""
        return self.trace_every > 0 or self.trace_at is not None

    def build_options(self):
        import jax

        options = jax.profiler.ProfileOptions()
        for attr in ("host_tracer_level", "python_tracer_level", "device_tracer_level"):
            value = getattr(self, attr)
            try:
                setattr(options, attr, value)
            except (AttributeError, ValueError):  # older jax ProfileOptions surface
                pass
        return options


@dataclass
class AutocastConfig(KwargsHandler):
    """Scoped opt-out of the bf16 compute policy (reference ``AutocastKwargs:113``)."""

    enabled: bool = True
    cache_enabled: bool = True


@dataclass
class WatchdogConfig(KwargsHandler):
    """Hang/straggler forensics (no reference counterpart — pod-scale TPU runs
    need hang *attribution*, see ``telemetry/watchdog.py`` and
    ``docs/troubleshooting.md``).

    ``timeout`` seconds without a heartbeat (train step, prefetch producer) or
    with one blocking phase held open (a collective, backend init) before the
    watchdog dumps ``flight-rank<k>.json`` — all-thread stacks, the event ring,
    and the name of the phase the rank is blocked in. ``0`` (the default)
    disables the watchdog entirely: no thread is started and no file is
    opened. Defaults seed from ``ACCELERATE_WATCHDOG_TIMEOUT`` /
    ``ACCELERATE_WATCHDOG_INTERVAL`` / ``ACCELERATE_WATCHDOG_ABORT`` /
    ``ACCELERATE_FLIGHT_DIR`` so a launcher can arm forensics without code
    changes. ``abort_on_stall`` exits the process (code 101) after dumping so
    an orchestrator restarts the rank instead of wedging the pod. Size the
    timeout above your longest legitimate gap between steps (checkpointing,
    eval) — a stall dump is cheap but noisy.
    """

    timeout: float = field(
        default_factory=lambda: parse_seconds_from_env("ACCELERATE_WATCHDOG_TIMEOUT")
    )
    interval: Optional[float] = None
    abort_on_stall: bool = field(
        default_factory=lambda: parse_flag_from_env("ACCELERATE_WATCHDOG_ABORT")
    )
    flight_dir: Optional[str] = field(
        default_factory=lambda: os.environ.get("ACCELERATE_FLIGHT_DIR")
    )

    @property
    def enabled(self) -> bool:
        """True when a positive timeout arms the watchdog."""
        return self.timeout > 0


@dataclass
class CheckpointConfig(KwargsHandler):
    """Asynchronous zero-stall checkpointing (no reference counterpart — the
    reference's ``save_state`` blocks for the full serialize+write; see
    ``docs/checkpointing.md`` "Async saves and crash consistency").

    ``async_save``: default for ``Accelerator.save_state`` — when True, saves
    run ``blocking=False``: the train loop only pays the device→host snapshot
    (milliseconds) and a single daemon writer serializes, fsyncs and commits
    in the background. Per-call ``save_state(..., blocking=...)`` overrides.
    ``max_in_flight``: how many snapshots may be queued/writing at once;
    an additional ``save_state`` blocks (back-pressure) until a slot frees —
    the default of 1 bounds host RAM to one extra state copy.
    ``save_on_each_node``: default for the same-named ``save_state`` kwarg
    (reference ``save_state:3529``): every node writes a full copy to its
    node-local dir instead of only the main process writing one.
    Seeds from ``ACCELERATE_ASYNC_CHECKPOINT`` so a launcher can flip saves
    async without code changes.
    """

    async_save: bool = field(
        default_factory=lambda: parse_flag_from_env("ACCELERATE_ASYNC_CHECKPOINT", False)
    )
    max_in_flight: int = 1
    save_on_each_node: bool = False

    def __post_init__(self):
        if self.max_in_flight < 1:
            raise ValueError(f"max_in_flight must be >= 1, got {self.max_in_flight}")


@dataclass
class GradScalerConfig(KwargsHandler):
    """fp16 loss-scaling settings (reference ``GradScalerKwargs:241``). Only used for
    ``mixed_precision="fp16"``; bf16 on TPU needs no scaler. Implemented with a
    DynamicScale-style state threaded through the train step."""

    init_scale: float = 2.0**15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    enabled: bool = True


# ---------------------------------------------------------------------------
# Mixed-precision policy


@dataclass(frozen=True)
class MixedPrecisionPolicy:
    """dtype policy for params / compute / output, jmp-style.

    The reference wraps forward in ``torch.autocast`` + ``convert_outputs_to_fp32``
    (``accelerator.py:1778-1789``); under JAX we cast inputs/params at well-defined
    boundaries instead, which XLA then fuses.
    """

    param_dtype: Any = None  # jnp dtype or None = float32
    compute_dtype: Any = None
    output_dtype: Any = None

    @classmethod
    def from_precision(cls, precision: str | PrecisionType) -> "MixedPrecisionPolicy":
        import jax.numpy as jnp

        precision = PrecisionType(str(precision))
        if precision == PrecisionType.NO:
            return cls(None, None, None)  # "no" = never touch dtypes
        if precision == PrecisionType.BF16:
            return cls(jnp.float32, jnp.bfloat16, jnp.float32)
        if precision == PrecisionType.FP16:
            return cls(jnp.float32, jnp.float16, jnp.float32)
        if precision == PrecisionType.FP8:
            # fp8 applies per-matmul via Pallas/XLA recipes; activations stay bf16.
            return cls(jnp.float32, jnp.bfloat16, jnp.float32)
        raise ValueError(f"unknown precision {precision}")

    def cast_to_compute(self, tree):
        import jax
        import jax.numpy as jnp

        if self.compute_dtype is None:
            return tree

        from ..ops.quantization import QuantizedArray

        def _cast(path, x):
            # quantized leaves (int8 codes + f32 scales) and fp8 delayed-scaling
            # meta must pass through untouched — casting their f32 scales to
            # bf16 silently degrades accuracy
            if isinstance(x, QuantizedArray):
                return x
            if any(getattr(k, "key", None) == "fp8_meta" for k in path):
                return x
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(self.compute_dtype)
            return x

        return jax.tree_util.tree_map_with_path(
            _cast, tree, is_leaf=lambda x: isinstance(x, QuantizedArray)
        )

    def cast_to_param(self, tree):
        import jax
        import jax.numpy as jnp

        if self.param_dtype is None:
            return tree

        def _cast(x):
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(self.param_dtype)
            return x

        return jax.tree_util.tree_map(_cast, tree)

    def cast_to_output(self, tree):
        import jax
        import jax.numpy as jnp

        if self.output_dtype is None:
            return tree

        def _cast(x):
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(self.output_dtype)
            return x

        return jax.tree_util.tree_map(_cast, tree)


# ---------------------------------------------------------------------------
# Reference-compat plugin/kwargs spellings.
#
# The reference steers torch engines (DDP buckets, torch FSDP wrappers, the
# DeepSpeed runtime) through these objects. On TPU the same intents are
# sharding assignments and dtype policies, so each shim translates its knobs
# into the native configuration (and warns about knobs with no XLA meaning)
# rather than mirroring engine internals.


class DDPCommunicationHookType(BaseEnum):
    """Gradient-compression choices (reference ``DDPCommunicationHookType``,
    ``utils/dataclasses.py:134``). The allreduce itself is GSPMD-inserted on
    TPU; the hook's wire-compression half maps to casting the gradient signal
    (see ``examples/by_feature/gradient_compression.py``)."""

    NO = "no"
    FP16 = "fp16"
    BF16 = "bf16"
    POWER_SGD = "power_sgd"
    BATCHED_POWER_SGD = "batched_power_sgd"


@dataclass
class DistributedDataParallelKwargs(KwargsHandler):
    """Reference ``DistributedDataParallelKwargs`` (``utils/dataclasses.py:155``)
    compat. Bucketing/graph knobs steer torch DDP's NCCL schedule and have no
    GSPMD counterpart (XLA schedules grad collectives itself); they are accepted
    so reference configs parse. ``comm_hook`` is honored: it selects the dtype
    returned by :meth:`gradient_compression_dtype`, which
    ``Accelerator.prepare_train_step`` applies to the gradient signal."""

    bucket_cap_mb: int = 25
    find_unused_parameters: bool = False
    gradient_as_bucket_view: bool = False
    static_graph: bool = False
    comm_hook: DDPCommunicationHookType = DDPCommunicationHookType.NO

    def __post_init__(self):
        self.comm_hook = DDPCommunicationHookType(str(self.comm_hook))

    def gradient_compression_dtype(self) -> Optional[str]:
        """dtype name the gradient signal is bounded to, or None."""
        if self.comm_hook == DDPCommunicationHookType.FP16:
            return "float16"
        if self.comm_hook == DDPCommunicationHookType.BF16:
            return "bfloat16"
        if self.comm_hook in (
            DDPCommunicationHookType.POWER_SGD,
            DDPCommunicationHookType.BATCHED_POWER_SGD,
        ):
            import warnings

            warnings.warn(
                "PowerSGD low-rank gradient compression has no XLA counterpart; "
                "falling back to a bf16 cast of the gradient signal."
            )
            return "bfloat16"
        return None


@dataclass
class FullyShardedDataParallelPlugin(KwargsHandler):
    """Migration shim for reference ``FullyShardedDataParallelPlugin``
    (``utils/dataclasses.py:1566``). FSDP on TPU is not a module wrapper — it is
    a ``NamedSharding`` assignment over the ``dp_shard`` mesh axis — so this
    object's one real job is :meth:`to_parallelism_config`. Wrapper-scheduling
    knobs (auto-wrap policy, backward prefetch, ``use_orig_params``) have no
    XLA meaning: GSPMD decides gather/reshard scheduling.

    ``sharding_strategy`` accepts the reference spellings (``FULL_SHARD``,
    ``SHARD_GRAD_OP``, ``NO_SHARD``, ``HYBRID_SHARD``, or their 1-4 codes).
    ``FULL_SHARD`` and ``SHARD_GRAD_OP`` collapse: under GSPMD, params are
    gathered on demand either way, so ZeRO-2 vs ZeRO-3 is a scheduling detail
    the compiler owns."""

    sharding_strategy: Any = "FULL_SHARD"
    cpu_offload: bool = False
    activation_checkpointing: bool = False
    state_dict_type: str = "SHARDED_STATE_DICT"
    # None = unset: the FSDP_CPU_RAM_EFFICIENT_LOADING env flag (written by
    # enable/disable_fsdp_ram_efficient_loading) supplies the default, True
    # absent that; an EXPLICIT constructor value always wins over the env
    cpu_ram_efficient_loading: Optional[bool] = None

    _STRATEGIES = {1: "FULL_SHARD", 2: "SHARD_GRAD_OP", 3: "NO_SHARD", 4: "HYBRID_SHARD"}

    def __post_init__(self):
        if self.cpu_ram_efficient_loading is None:
            env_flag = os.environ.get("FSDP_CPU_RAM_EFFICIENT_LOADING", "true")
            self.cpu_ram_efficient_loading = env_flag.strip().lower() in ("1", "true", "yes")
        s = self.sharding_strategy
        if isinstance(s, int):
            if s not in self._STRATEGIES:
                raise ValueError(
                    f"unknown sharding_strategy code {s} (valid: {sorted(self._STRATEGIES)})"
                )
            s = self._STRATEGIES[s]
        s = str(s).rsplit(".", 1)[-1].upper()  # accept "ShardingStrategy.FULL_SHARD"
        if s not in self._STRATEGIES.values():
            raise ValueError(f"unknown sharding_strategy {self.sharding_strategy!r}")
        self.sharding_strategy = s

    @property
    def remat(self) -> "bool | str":
        """The ``activation_checkpointing`` knob in native form: pass this as
        the model forward's ``remat=`` argument (e.g.
        ``llama_loss(..., remat=plugin.remat)``). Maps to the
        ``"dots_no_batch"`` policy — the transformer sweet spot — rather than
        full recompute, matching torch FSDP's per-block checkpointing cost."""
        return "dots_no_batch" if self.activation_checkpointing else False

    def to_parallelism_config(
        self, num_devices: Optional[int] = None, dp_replicate_size: int = 1
    ):
        """Translate to the native mesh config. ``HYBRID_SHARD`` needs
        ``dp_replicate_size`` (the outer replica count; reference HSDP)."""
        from ..parallelism_config import ParallelismConfig

        if self.sharding_strategy == "NO_SHARD":
            if num_devices is None:
                import jax

                num_devices = len(jax.devices())
            return ParallelismConfig(dp_replicate_size=num_devices)
        if self.sharding_strategy == "HYBRID_SHARD" and dp_replicate_size == 1:
            raise ValueError("HYBRID_SHARD requires dp_replicate_size > 1")
        return ParallelismConfig(dp_replicate_size=dp_replicate_size, dp_shard_size=-1)


@dataclass
class DeepSpeedPlugin(KwargsHandler):
    """Migration shim for reference ``DeepSpeedPlugin`` (``utils/dataclasses.py:1113``).
    ZeRO stages are shardings here: stage 0 → pure replication; stage 1 →
    params replicated with the OPTIMIZER STATE sharded across replicas
    (``parallel.sharding.zero1_state_specs``); stages 2-3 → the ``dp_shard``
    FSDP NamedSharding (grad/param sharding collapse under GSPMD's
    compiler-scheduled gathers). A reference ``hf_ds_config`` dict is accepted
    and mined for the fields that still mean something here (stage,
    accumulation, clipping, offload)."""

    zero_stage: int = 2
    gradient_accumulation_steps: int = 1
    gradient_clipping: Optional[float] = None
    offload_optimizer_device: Optional[str] = None
    offload_param_device: Optional[str] = None
    zero3_init_flag: bool = False
    zero3_save_16bit_model: bool = False
    hf_ds_config: Optional[dict] = None

    def __post_init__(self):
        cfg = self.hf_ds_config or {}
        zero = cfg.get("zero_optimization", {})

        def _fill(attr, value, cast):
            """ds_config fills fields still at their DEFAULT; an explicit
            constructor value wins (with a warning on disagreement — the
            reference errors on flag/config mismatches, ``fill_match``)."""
            if value is None or _is_auto(value):
                return
            value = cast(value)
            current = getattr(self, attr)
            default = type(self).__dataclass_fields__[attr].default
            if current == default:
                setattr(self, attr, value)
            elif current != value:
                import warnings

                warnings.warn(
                    f"DeepSpeedPlugin.{attr}={current!r} (explicit) disagrees with "
                    f"hf_ds_config value {value!r}; keeping the explicit value"
                )

        _fill("zero_stage", zero.get("stage"), int)
        _fill("gradient_accumulation_steps", cfg.get("gradient_accumulation_steps"), int)
        _fill("gradient_clipping", cfg.get("gradient_clipping"), float)
        for src, attr in (("offload_optimizer", "offload_optimizer_device"),
                          ("offload_param", "offload_param_device")):
            dev = zero.get(src, {}).get("device")
            if dev and dev != "none":
                _fill(attr, dev, str)
        if not 0 <= self.zero_stage <= 3:
            raise ValueError(f"zero_stage must be 0-3, got {self.zero_stage}")

    @classmethod
    def from_env(cls) -> "DeepSpeedPlugin":
        """Build from the launcher's env protocol (reference
        ``utils/launch.py:557-577`` writer / ``utils/dataclasses.py:1225-1232``
        reader): ``ACCELERATE_DEEPSPEED_ZERO_STAGE``, offload devices,
        ``ACCELERATE_GRADIENT_CLIPPING``, ``ACCELERATE_DEEPSPEED_CONFIG_FILE``
        (json loaded into ``hf_ds_config``)."""
        kwargs: dict[str, Any] = {}
        stage = os.environ.get("ACCELERATE_DEEPSPEED_ZERO_STAGE")
        if stage is not None and not _is_auto(stage):
            kwargs["zero_stage"] = int(stage)
        clip = os.environ.get("ACCELERATE_GRADIENT_CLIPPING")
        if clip is not None and not _is_auto(clip):
            kwargs["gradient_clipping"] = float(clip)
        for env_name, attr in (
            ("ACCELERATE_DEEPSPEED_OFFLOAD_OPTIMIZER_DEVICE", "offload_optimizer_device"),
            ("ACCELERATE_DEEPSPEED_OFFLOAD_PARAM_DEVICE", "offload_param_device"),
        ):
            dev = os.environ.get(env_name)
            if dev and dev != "none":
                kwargs[attr] = dev
        config_file = os.environ.get("ACCELERATE_DEEPSPEED_CONFIG_FILE")
        if config_file:
            import json

            with open(config_file) as f:
                kwargs["hf_ds_config"] = json.load(f)
        accum = os.environ.get("ACCELERATE_GRADIENT_ACCUMULATION_STEPS")
        if accum is not None and not _is_auto(accum):
            kwargs["gradient_accumulation_steps"] = int(accum)
        return cls(**kwargs)

    def to_parallelism_config(self, num_devices: Optional[int] = None):
        from ..parallelism_config import ParallelismConfig

        if self.zero_stage in (0, 1):
            # stage 1 keeps params replicated (the optimizer-state sharding is
            # applied separately over the dp_replicate axis)
            if num_devices is None:
                import jax

                num_devices = len(jax.devices())
            return ParallelismConfig(dp_replicate_size=num_devices)
        return ParallelismConfig(dp_shard_size=-1)

    @property
    def mixed_precision(self) -> Optional[str]:
        """Precision requested by the ds config's ``bf16``/``fp16`` sections
        (None when absent — the Accelerator's own setting then applies)."""
        cfg = self.hf_ds_config or {}
        if cfg.get("bf16", {}).get("enabled") is True:
            return "bf16"
        if cfg.get("fp16", {}).get("enabled") is True:
            return "fp16"
        return None

    def dummy_optim_kwargs(self) -> dict:
        """Hyperparameters for a :class:`DummyOptim` from the ds config's
        ``optimizer`` section (the reference's config-is-source-of-truth flow:
        ``examples/by_feature/deepspeed_with_config_support.py``). ``auto``
        values are omitted so the placeholder's own values fill them."""
        params = (self.hf_ds_config or {}).get("optimizer", {}).get("params", {})
        out: dict = {}
        for src, dst, cast in (
            ("lr", "lr", float),
            ("weight_decay", "weight_decay", float),
            ("betas", "betas", tuple),
            ("eps", "eps", float),
        ):
            v = params.get(src)
            if v is not None and not _is_auto(v):
                out[dst] = cast(v)
        return out

    def dummy_scheduler_kwargs(self) -> dict:
        """``DummyScheduler`` fields from the ds config's ``scheduler`` section
        (WarmupLR / WarmupDecayLR shapes)."""
        params = (self.hf_ds_config or {}).get("scheduler", {}).get("params", {})
        out: dict = {}
        total = params.get("total_num_steps")
        if total is not None and not _is_auto(total):
            out["total_num_steps"] = int(total)
        warm = params.get("warmup_num_steps")
        if warm is not None and not _is_auto(warm):
            out["warmup_num_steps"] = int(warm)
        return out


def _is_auto(v) -> bool:
    return isinstance(v, str) and v == "auto"


# Reference names for config objects that already exist natively (the reference
# calls every kwargs-handler "...Kwargs"; our spellings say what they configure).
AutocastKwargs = AutocastConfig
GradScalerKwargs = GradScalerConfig
ProfileKwargs = ProfileConfig


class CustomDtype(BaseEnum):
    """reference ``CustomDtype`` — sub-byte / fp8 markers for memory-size
    accounting (``dtype_byte_size``/``infer_auto_device_map``): these have no
    numpy dtype, so device-map math names them explicitly."""

    FP8 = "fp8"
    INT4 = "int4"
    INT2 = "int2"


class ComputeEnvironment(BaseEnum):
    """reference ``utils/dataclasses.py`` — config-file field; SageMaker
    clusters are not a TPU deployment target but configs naming them parse."""

    LOCAL_MACHINE = "LOCAL_MACHINE"
    AMAZON_SAGEMAKER = "AMAZON_SAGEMAKER"


class SageMakerDistributedType(BaseEnum):
    """reference config-file enum (parsed, not acted on — no SageMaker on TPU)."""

    NO = "NO"
    DATA_PARALLEL = "DATA_PARALLEL"
    MODEL_PARALLEL = "MODEL_PARALLEL"


class DynamoBackend(BaseEnum):
    """reference ``DynamoBackend:684``. On TPU there is exactly one compiler —
    XLA via jit, on by default — so these values only steer :class:`JitConfig`:
    ``EAGER`` disables jit (debugging), everything else keeps it on."""

    NO = "NO"
    EAGER = "EAGER"
    AOT_EAGER = "AOT_EAGER"
    INDUCTOR = "INDUCTOR"
    AOT_TS_NVFUSER = "AOT_TS_NVFUSER"
    NVPRIMS_NVFUSER = "NVPRIMS_NVFUSER"
    CUDAGRAPHS = "CUDAGRAPHS"
    OFI = "OFI"
    FX2TRT = "FX2TRT"
    ONNXRT = "ONNXRT"
    TENSORRT = "TENSORRT"
    IPEX = "IPEX"
    TVM = "TVM"


@dataclass
class TorchDynamoPlugin(KwargsHandler):
    """Migration shim for reference ``TorchDynamoPlugin:1024``. XLA compilation
    is default-on; the one actionable knob is ``backend=EAGER`` → run eager
    (:class:`JitConfig` ``disable_jit``). ``mode``/``fullgraph``/``dynamic``
    have no XLA meaning (jit always captures the full graph with static
    shapes) and are accepted for config compatibility."""

    backend: Any = DynamoBackend.NO
    mode: str = "default"
    fullgraph: bool = False
    dynamic: Optional[bool] = None
    options: Optional[dict] = None
    disable: bool = False

    def to_jit_config(self) -> JitConfig:
        backend = str(self.backend).rsplit(".", 1)[-1].upper()
        return JitConfig(disable_jit=(backend == "EAGER"))


@dataclass
class TorchContextParallelConfig(KwargsHandler):
    """Migration shim for reference ``TorchContextParallelConfig:2186``:
    ``cp_comm_strategy`` maps onto the native ``cp_rotate_method`` —
    ``allgather`` → allgather rotation, ``alltoall`` → the zig-zag
    load-balanced ring (the rotation-style strategy here)."""

    cp_comm_strategy: Optional[str] = None

    def __post_init__(self):
        if self.cp_comm_strategy is None:
            self.cp_comm_strategy = os.environ.get(
                "PARALLELISM_CONFIG_CP_COMM_STRATEGY", "allgather"
            )
        if self.cp_comm_strategy not in ("allgather", "alltoall"):
            raise ValueError(
                f"cp_comm_strategy must be 'allgather' or 'alltoall', got "
                f"{self.cp_comm_strategy!r}"
            )

    @property
    def cp_rotate_method(self) -> str:
        return "allgather" if self.cp_comm_strategy == "allgather" else "zigzag"


@dataclass
class TorchTensorParallelConfig(KwargsHandler):
    """Migration shim for reference ``TorchTensorParallelConfig:2264``.
    ``enable_async_tp`` is accepted and ignored with the same warning the
    reference emits — XLA already overlaps TP collectives with compute."""

    enable_async_tp: bool = False

    def __post_init__(self):
        if self.enable_async_tp:
            import warnings

            warnings.warn(
                "async tensor parallelism is not a knob under XLA (collective "
                "overlap is compiler-scheduled); ignoring enable_async_tp",
                stacklevel=2,
            )


@dataclass
class TorchTensorParallelPlugin(KwargsHandler):
    """Migration shim: reference TP plugin → ``tp`` mesh axis size."""

    tp_size: int = 1
    torch_device_mesh: Any = None  # accepted for signature parity

    def to_parallelism_config(self):
        from ..parallelism_config import ParallelismConfig

        return ParallelismConfig(tp_size=self.tp_size, dp_shard_size=-1)


@dataclass
class DeepSpeedSequenceParallelConfig(KwargsHandler):
    """Migration shim for reference ``DeepSpeedSequenceParallelConfig:2214``
    (Ulysses/ALST). Sequence-length knobs are accepted (our Ulysses works at
    any length divisible by ``sp``); ``sp_attn_implementation`` maps onto the
    native ``attention_impl``."""

    sp_seq_length: Optional[int] = None
    sp_seq_length_is_variable: Optional[bool] = None
    sp_attn_implementation: Optional[str] = None

    def __post_init__(self):
        if self.sp_seq_length_is_variable is None:
            self.sp_seq_length_is_variable = (
                os.environ.get("PARALLELISM_CONFIG_SP_SEQ_LENGTH_IS_VARIABLE", "true").lower()
                == "true"
            )
        if self.sp_attn_implementation is None:
            self.sp_attn_implementation = os.environ.get(
                "PARALLELISM_CONFIG_SP_ATTN_IMPLEMENTATION", None
            )
        if self.sp_attn_implementation is not None and self.sp_attn_implementation not in (
            "flash_attention_2", "flash_attention_3", "sdpa"
        ):
            raise ValueError(
                f"invalid sp_attn_implementation {self.sp_attn_implementation!r}"
            )

    @property
    def attention_impl(self) -> str:
        """Native ``attention_impl`` for the model forward."""
        if self.sp_attn_implementation in ("flash_attention_2", "flash_attention_3"):
            return "flash"
        return "xla"


class DummyOptim:
    """Placeholder optimizer (reference ``utils/deepspeed.py`` ``DummyOptim``):
    in the reference the real optimizer comes from the DeepSpeed config; here
    ``Accelerator.prepare`` materializes an optax AdamW from the recorded
    hyperparameters — user scripts written against the reference's
    DummyOptim/prepare flow run unchanged."""

    def __init__(self, params=None, lr: float = 1e-3, weight_decay: float = 0.0, **kwargs):
        self.params = params
        self.lr = lr
        self.weight_decay = weight_decay
        self.kwargs = kwargs

    def to_optax(self, learning_rate=None):
        """Materialize as optax AdamW. ``learning_rate`` (a schedule fn)
        overrides the constant ``lr`` — the paired-DummyScheduler case.
        Recorded betas/eps hyperparameters carry over; other kwargs warn."""
        import optax

        kwargs = dict(self.kwargs)
        b1, b2 = kwargs.pop("betas", (0.9, 0.999))
        eps = kwargs.pop("eps", 1e-8)
        kwargs.pop("params", None)
        if kwargs:
            import warnings

            warnings.warn(
                f"DummyOptim: ignoring unsupported hyperparameters {sorted(kwargs)}",
                stacklevel=2,
            )
        return optax.adamw(
            learning_rate if learning_rate is not None else self.lr,
            b1=b1, b2=b2, eps=eps, weight_decay=self.weight_decay,
        )


class DummyScheduler:
    """Placeholder scheduler (reference ``DummyScheduler``): ``prepare`` turns
    it into a linear warmup→decay optax schedule over ``total_num_steps`` with
    ``warmup_num_steps`` of warmup applied to the paired optimizer's LR."""

    def __init__(self, optimizer=None, total_num_steps: Optional[int] = None,
                 warmup_num_steps: int = 0, lr_scheduler_callable=None, **kwargs):
        self.optimizer = optimizer
        self.total_num_steps = total_num_steps
        self.warmup_num_steps = warmup_num_steps
        self.lr_scheduler_callable = lr_scheduler_callable
        self.kwargs = kwargs


def add_model_config_to_megatron_parser(*args, **kwargs):  # pragma: no cover
    raise NotImplementedError(
        "Megatron-LM is a CUDA engine; its TP/PP/EP capabilities are provided natively "
        "via ParallelismConfig mesh axes on TPU."
    )


# --------------------------------------------------- fp8 recipe kwargs shims --
@dataclass
class FP8RecipeKwargs(KwargsHandler):
    """Migration shim for reference ``FP8RecipeKwargs`` (``utils/dataclasses.py:455``,
    deprecated there in favor of backend-specific kwargs). Every backend maps to
    the ONE native fp8 path: XLA fp8 ``dot_general`` with delayed scaling
    (``ops/fp8.py``); :meth:`to_native` yields that recipe."""

    backend: Optional[str] = None
    margin: int = 0
    interval: int = 1  # accepted: native scaling re-derives per step
    fp8_format: str = "HYBRID"
    amax_history_len: int = 16
    amax_compute_algo: str = "max"
    override_linear_precision: Any = None  # TE triple; see filter_first_and_last_linear_layers
    use_autocast_during_eval: bool = False

    def __post_init__(self):
        if self.backend is not None:
            self.backend = str(self.backend).upper()
            if self.backend not in ("TE", "MSAMP", "AO"):
                raise ValueError(f"unknown fp8 backend {self.backend!r}")
        self.fp8_format = str(self.fp8_format).upper()
        if self.fp8_format not in ("HYBRID", "E4M3"):
            # same validation as the native FP8Recipe this builds — silently
            # coercing would mask exactly the misconfigurations it rejects
            raise ValueError(
                f"unknown fp8_format {self.fp8_format!r} (valid: HYBRID, E4M3)"
            )

    def to_native(self):
        from ..ops.fp8 import FP8Recipe

        return FP8Recipe(
            margin=self.margin,
            amax_history_len=self.amax_history_len,
            amax_compute_algo=self.amax_compute_algo,
            fp8_format=self.fp8_format,
        )


@dataclass
class TERecipeKwargs(FP8RecipeKwargs):
    """TransformerEngine recipe spelling (reference ``utils/dataclasses.py:359``)."""

    def __post_init__(self):
        self.backend = "TE"
        super().__post_init__()


@dataclass
class AORecipeKwargs(FP8RecipeKwargs):
    """torchao Float8 recipe spelling (reference ``utils/dataclasses.py:311``).
    ``config``/``module_filter_func`` accepted for signature parity."""

    config: Any = None
    module_filter_func: Any = None

    def __post_init__(self):
        self.backend = "AO"
        super().__post_init__()


@dataclass
class MSAMPRecipeKwargs(FP8RecipeKwargs):
    """MS-AMP recipe spelling (reference ``utils/dataclasses.py:438``).
    ``opt_level`` accepted: optimizer-state precision is governed natively by
    the optax transform chain."""

    opt_level: str = "O2"

    def __post_init__(self):
        self.backend = "MSAMP"
        super().__post_init__()


# ------------------------------------------------------- Megatron-LM shim ----
@dataclass
class MegatronLMPlugin(KwargsHandler):
    """Migration shim for reference ``MegatronLMPlugin`` (``utils/dataclasses.py:2286``).

    The Megatron ENGINE (CUDA kernels, fused softmax, its own runtime) is not
    ported — its capabilities are native here: TP/PP/EP/SP are mesh axes and
    GSPMD shardings. This shim maps the plugin's parallelism degrees onto
    :class:`~accelerate_tpu.parallelism_config.ParallelismConfig` so a script
    that passes ``megatron_lm_plugin=MegatronLMPlugin(tp_degree=2, ...)``
    configures the same mesh. Engine-tuning knobs (fused kernels, selective
    recompute spellings) are accepted and ignored; XLA owns those decisions.
    """

    tp_degree: int = 1
    pp_degree: int = 1
    num_micro_batches: int = 1
    expert_model_parallel_size: int = 1
    context_parallel_size: int = 1
    sequence_parallelism: bool = False
    gradient_clipping: Optional[float] = None
    use_distributed_optimizer: bool = False  # ZeRO-style: opt state sharded anyway
    recompute_activations: bool = False
    other_megatron_args: Optional[dict] = None

    @property
    def remat(self) -> "bool | str":
        return "dots_no_batch" if self.recompute_activations else False

    def to_parallelism_config(self):
        from ..parallelism_config import ParallelismConfig

        # NOTE: Megatron's sequence_parallelism is a FLAG on the tp group
        # (norm/dropout activations sharded along the existing tp axis, no
        # extra devices) — NOT a Ulysses sp mesh axis. Under GSPMD the
        # activation sharding it buys is compiler-inserted from the tp param
        # specs, so the flag maps to nothing; mapping it to sp_size would
        # demand tp*2 devices and build a different topology than asked for.
        return ParallelismConfig(
            tp_size=self.tp_degree,
            pp_size=self.pp_degree,
            ep_size=self.expert_model_parallel_size,
            cp_size=self.context_parallel_size,
            dp_shard_size=-1,
        )


# ------------------------------------------------ DeepSpeed-surface spellings --
class HfDeepSpeedConfig:
    """Thin holder for a ds_config dict/file (reference ``utils/deepspeed.py``
    ``HfDeepSpeedConfig``): dotted-path access + stage probes. The values feed
    :class:`DeepSpeedPlugin`'s config-file mapping; there is no engine to hand
    the dict to."""

    def __init__(self, config_file_or_dict):
        import json as _json

        if isinstance(config_file_or_dict, dict):
            self.config = dict(config_file_or_dict)
        else:
            with open(config_file_or_dict) as f:
                self.config = _json.load(f)

    def get_value(self, ds_key_long: str, default=None):
        node = self.config
        for part in ds_key_long.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def is_true(self, ds_key_long: str) -> bool:
        return bool(self.get_value(ds_key_long))

    def is_false(self, ds_key_long: str) -> bool:
        value = self.get_value(ds_key_long)
        return value is not None and not bool(value)

    def is_zero2(self) -> bool:
        return self.get_value("zero_optimization.stage") == 2

    def is_zero3(self) -> bool:
        return self.get_value("zero_optimization.stage") == 3

    def is_offload(self) -> bool:
        for key in ("offload_optimizer", "offload_param"):
            device = self.get_value(f"zero_optimization.{key}.device")
            if device not in (None, "none"):
                return True
        return False


def get_active_deepspeed_plugin(state_or_accelerator):
    """The active :class:`DeepSpeedPlugin` (reference ``utils/deepspeed.py``
    spelling). Accepts an ``Accelerator`` or anything exposing
    ``deepspeed_plugin``; raises when no plugin is configured."""
    plugin = getattr(state_or_accelerator, "deepspeed_plugin", None)
    if isinstance(plugin, dict):  # reference multi-plugin dict: the selected one
        for p in plugin.values():
            if getattr(p, "selected", False):
                return p
        raise ValueError("no DeepSpeedPlugin in the dict is selected")
    if plugin is None:
        raise ValueError(
            "no DeepSpeedPlugin is active; pass deepspeed_plugin= to Accelerator"
        )
    return plugin


def deepspeed_required(func):
    """Decorator: the wrapped method requires an active DeepSpeedPlugin
    (reference ``utils/deepspeed.py`` spelling)."""
    import functools as _functools

    @_functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        get_active_deepspeed_plugin(self)  # raises with the actionable message
        return func(self, *args, **kwargs)

    return wrapper


# --------------------------------------------- fsdp ram-efficient toggles ----
def enable_fsdp_ram_efficient_loading() -> None:
    """Set the env flag that makes :class:`FullyShardedDataParallelPlugin`
    default to cpu-ram-efficient loading (reference ``utils/fsdp_utils.py``
    spelling; the native mechanism is abstract init via ``jax.eval_shape`` +
    per-shard reads in ``sharded_checkpoint``)."""
    os.environ["FSDP_CPU_RAM_EFFICIENT_LOADING"] = "true"


def disable_fsdp_ram_efficient_loading() -> None:
    os.environ["FSDP_CPU_RAM_EFFICIENT_LOADING"] = "false"
