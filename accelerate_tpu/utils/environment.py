"""Environment-variable parsing and process-environment helpers.

TPU-native counterpart of the reference's ``utils/environment.py``
(``/root/reference/src/accelerate/utils/environment.py:83`` ``parse_flag_from_env``,
``:376`` ``patch_environment``). All framework configuration flows through
``ACCELERATE_*`` env vars written by the launcher and read by dataclass defaults,
mirroring the reference's env-var channel (``utils/launch.py:197-420``).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any

_TRUE = {"1", "true", "yes", "y", "on"}
_FALSE = {"0", "false", "no", "n", "off", ""}


def str_to_bool(value: str) -> int:
    """Convert a string to 1/0, raising on unrecognized values."""
    value = value.lower().strip()
    if value in _TRUE:
        return 1
    if value in _FALSE:
        return 0
    raise ValueError(f"invalid truth value {value!r}")


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key, None)
    if value is None:
        return default
    try:
        return bool(str_to_bool(value))
    except ValueError:
        raise ValueError(f"If set, {key} must be yes or no, got {value!r}.")


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, str(default))


def parse_seconds_from_env(key: str, default: float = 0.0) -> float:
    """A duration env var as non-negative seconds; ``default`` when unset,
    blank, or malformed (forensics config must never crash on a bad env)."""
    raw = os.environ.get(key, "").strip()
    if not raw:
        return default
    try:
        return max(0.0, float(raw))
    except ValueError:
        return default


def parse_int_from_env(key: str, default: int = 0) -> int:
    """An integer env var; ``default`` when unset, blank, or malformed
    (telemetry/profiling config must never crash on a bad env)."""
    return parse_optional_int_from_env(key, default)


def parse_optional_int_from_env(key: str, default: "int | None" = None) -> "int | None":
    """Like :func:`parse_int_from_env` but the default may be ``None``
    ("feature not triggered") — unset/blank/malformed values yield it."""
    raw = os.environ.get(key, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def parse_optional_float_from_env(key: str, default: "float | None" = None) -> "float | None":
    """A float env var (scientific notation welcome, e.g. peak-FLOPs
    overrides); unset/blank/malformed yields ``default``."""
    raw = os.environ.get(key, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def get_int_from_env(keys: list[str] | tuple[str, ...], default: int) -> int:
    """Return the first env var among ``keys`` that is set, as an int."""
    if isinstance(keys, str):
        keys = [keys]
    for key in keys:
        value = os.environ.get(key, None)
        if value is not None:
            return int(value)
    return default


@contextmanager
def patch_environment(**kwargs: Any):
    """Temporarily set env vars (upper-cased keys), restoring previous values on exit.

    Mirrors reference ``utils/environment.py:376``. ``None`` values unset the var.
    """
    saved: dict[str, str | None] = {}
    for key, value in kwargs.items():
        key = key.upper()
        saved[key] = os.environ.get(key)
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = str(value)
    try:
        yield
    finally:
        for key, old in saved.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


def are_libraries_initialized(*library_names: str) -> list[str]:
    """Return the subset of ``library_names`` already imported in this process."""
    import sys

    return [name for name in library_names if name in sys.modules]


def convert_dict_to_env_variables(current_env: dict[str, Any]) -> list[str]:
    """``{k: v}`` → ``["k=v", ...]`` suitable for a spawned process's env block
    (reference ``utils/environment.py:34`` — the launcher's env-injection
    sanitizer). Key case is preserved (env names are case-sensitive:
    ``http_proxy`` ≠ ``HTTP_PROXY``); keys may not contain ``=``/newlines/
    ``;`` and values may not contain newlines/``;``."""
    bad_keys = {
        str(k) for k in current_env if any(ch in str(k) for ch in ("=", "\n", ";"))
    }
    bad_vals = {
        str(k) for k, v in current_env.items() if any(ch in str(v) for ch in ("\n", ";"))
    }
    if bad_keys or bad_vals:
        raise ValueError(
            "malformed env entries (shell-injection guard): "
            f"keys={sorted(bad_keys)} values-of={sorted(bad_vals)}"
        )
    return [f"{k}={v}" for k, v in current_env.items()]


@contextmanager
def clear_environment():
    """Run with a COMPLETELY empty ``os.environ``, restored (same mapping
    object, contents back) on exit — even on exception (reference
    ``clear_environment:341``)."""
    saved = dict(os.environ)
    os.environ.clear()
    try:
        yield
    finally:
        os.environ.clear()
        os.environ.update(saved)


def purge_accelerate_environment(func_or_cls):
    """Decorator: run the function (or every test method of a class) with all
    ``ACCELERATE_*`` / ``PARALLELISM_CONFIG_*`` vars removed, restoring them
    afterwards (reference ``purge_accelerate_environment:412`` — keeps env
    state from one test leaking into the next)."""
    import functools
    import inspect

    def _wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            saved = {
                k: os.environ.pop(k)
                for k in list(os.environ)
                if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_"))
            }
            try:
                return fn(*args, **kwargs)
            finally:
                for k in list(os.environ):
                    if k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_")):
                        del os.environ[k]
                os.environ.update(saved)

        return inner

    if inspect.isclass(func_or_cls):
        # dir() (not vars()) so test methods INHERITED from a base class are
        # wrapped too — the wrapper lands on the decorated subclass, leaving
        # the base untouched (reference covers inherited members as well).
        # getattr_static preserves classmethod/staticmethod descriptors, which
        # must be re-wrapped as the SAME descriptor kind.
        for name in dir(func_or_cls):
            if not (name.startswith("test") or name in ("setUp", "tearDown")):
                continue
            try:
                raw = inspect.getattr_static(func_or_cls, name)
            except AttributeError:
                continue
            inner_fn = raw.__func__ if isinstance(raw, (classmethod, staticmethod)) else raw
            if not callable(inner_fn) or getattr(inner_fn, "_accelerate_env_purged", False):
                continue
            wrapped = _wrap(inner_fn)
            wrapped._accelerate_env_purged = True
            if isinstance(raw, classmethod):
                wrapped = classmethod(wrapped)
            elif isinstance(raw, staticmethod):
                wrapped = staticmethod(wrapped)
            setattr(func_or_cls, name, wrapped)
        return func_or_cls
    return _wrap(func_or_cls)


def get_current_device_type() -> str:
    """Active accelerator platform string (reference ``utils/environment.py``
    spelling, which maps torch device modules): ``"tpu"`` / ``"gpu"`` /
    ``"cpu"`` from the live JAX backend."""
    import jax

    return jax.default_backend()


def get_cpu_distributed_information() -> dict:
    """Host-side process topology (reference ``utils/environment.py``
    ``get_cpu_distributed_information`` reads MPI/torchrun env): rank / world
    size / local counterparts from the launcher env protocol, falling back to
    the live ``PartialState`` when one exists."""
    info = {
        "rank": get_int_from_env(("ACCELERATE_PROCESS_ID", "RANK"), 0),
        "world_size": get_int_from_env(("ACCELERATE_NUM_PROCESSES", "WORLD_SIZE"), 1),
        # the launcher/state spelling is ACCELERATE_LOCAL_PROCESS_INDEX
        # (state.py consumes it); LOCAL_RANK covers torchrun-style callers
        "local_rank": get_int_from_env(("ACCELERATE_LOCAL_PROCESS_INDEX", "LOCAL_RANK"), 0),
        "local_world_size": get_int_from_env(("LOCAL_WORLD_SIZE",), 1),
    }
    from ..state import PartialState

    if PartialState._shared_state:
        state = PartialState()
        info["rank"] = state.process_index
        info["world_size"] = state.num_processes
        info["local_rank"] = state.local_process_index
        # local_world_size must agree with the live topology (ADVICE round 5):
        # a stale or missing LOCAL_WORLD_SIZE would otherwise hand
        # set_numa_affinity an inconsistent process count and mis-slice the
        # CPUs. Only rank-INDEPENDENT corrections are applied (every rank must
        # compute the same count or affinity slices overlap): a single-process
        # state is exactly 1, and a declared count is bounded by the live
        # world size. An undeclared count under multi-process stays at the env
        # default of 1, where set_numa_affinity degrades to a neutral
        # full-affinity no-op — declare LOCAL_WORLD_SIZE for exact pinning.
        if state.num_processes == 1:
            info["local_world_size"] = 1
        else:
            info["local_world_size"] = min(info["local_world_size"], state.num_processes)
    return info


def set_numa_affinity(local_process_index: int, verbose: bool = False) -> None:
    """Pin this process to an equal slice of the host's CPUs (reference
    ``utils/environment.py`` ``set_numa_affinity`` pins to the GPU's NUMA
    node via pynvml; TPU VMs expose no such mapping, so the slice is computed
    from the local process count). No-op on platforms without
    ``sched_setaffinity``."""
    if not hasattr(os, "sched_getaffinity"):
        return
    try:
        cpus = sorted(os.sched_getaffinity(0))
        local_world = get_cpu_distributed_information()["local_world_size"]
        per = max(len(cpus) // max(local_world, 1), 1)
        start = (local_process_index * per) % len(cpus)
        slice_ = cpus[start:start + per] or cpus
        os.sched_setaffinity(0, slice_)
        if verbose:
            print(f"process {local_process_index}: CPU affinity {slice_}")
    except OSError:
        pass
