"""Environment-variable parsing and process-environment helpers.

TPU-native counterpart of the reference's ``utils/environment.py``
(``/root/reference/src/accelerate/utils/environment.py:83`` ``parse_flag_from_env``,
``:376`` ``patch_environment``). All framework configuration flows through
``ACCELERATE_*`` env vars written by the launcher and read by dataclass defaults,
mirroring the reference's env-var channel (``utils/launch.py:197-420``).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any

_TRUE = {"1", "true", "yes", "y", "on"}
_FALSE = {"0", "false", "no", "n", "off", ""}


def str_to_bool(value: str) -> int:
    """Convert a string to 1/0, raising on unrecognized values."""
    value = value.lower().strip()
    if value in _TRUE:
        return 1
    if value in _FALSE:
        return 0
    raise ValueError(f"invalid truth value {value!r}")


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key, None)
    if value is None:
        return default
    try:
        return bool(str_to_bool(value))
    except ValueError:
        raise ValueError(f"If set, {key} must be yes or no, got {value!r}.")


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, str(default))


def get_int_from_env(keys: list[str] | tuple[str, ...], default: int) -> int:
    """Return the first env var among ``keys`` that is set, as an int."""
    if isinstance(keys, str):
        keys = [keys]
    for key in keys:
        value = os.environ.get(key, None)
        if value is not None:
            return int(value)
    return default


@contextmanager
def patch_environment(**kwargs: Any):
    """Temporarily set env vars (upper-cased keys), restoring previous values on exit.

    Mirrors reference ``utils/environment.py:376``. ``None`` values unset the var.
    """
    saved: dict[str, str | None] = {}
    for key, value in kwargs.items():
        key = key.upper()
        saved[key] = os.environ.get(key)
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = str(value)
    try:
        yield
    finally:
        for key, old in saved.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


def are_libraries_initialized(*library_names: str) -> list[str]:
    """Return the subset of ``library_names`` already imported in this process."""
    import sys

    return [name for name in library_names if name in sys.modules]
