"""Rank-aware rich console (reference ``utils/rich.py``: a shared ``Console``
singleton gated on the extra being installed).

Here the console is additionally main-process-only by default — N hosts
printing N copies of a table is the multihost analogue of N progress bars
(see ``utils/tqdm.py``).
"""

from __future__ import annotations

from .imports import is_rich_available

_console = None


def get_console():
    """Shared ``rich.console.Console`` (created on first use)."""
    if not is_rich_available():
        raise ImportError("rich is not installed; pip install rich")
    global _console
    if _console is None:
        from rich.console import Console

        _console = Console()
    return _console


def rich_print(*args, main_process_only: bool = True, **kwargs):
    """``console.print`` that renders only on the main process by default."""
    from ..state import PartialState

    if not is_rich_available():  # check on EVERY rank, before the gate: a
        # missing dep must fail symmetrically, not strand non-main processes
        # at the next collective (same order as utils/tqdm.py)
        raise ImportError("rich is not installed; pip install rich")
    if main_process_only and not PartialState().is_main_process:
        return
    get_console().print(*args, **kwargs)
