"""The pinned API-parity boundary against the reference's ``accelerate.utils``.

The reference (``/root/reference/src/accelerate/utils/__init__.py``) exports
~260 names. Every one either RESOLVES from ``accelerate_tpu.utils`` or appears
here with the reason it deliberately does not. ``tests/test_api_parity.py``
asserts ``resolved ∪ excluded == reference`` with no overlap, so a name can
never be silently dropped: adding one to the reference-tracking set without
implementing it or registering it here fails CI.

Exclusion policy: a name is excluded only when it is bound to an engine or
vendor mechanism that does not exist in this stack (CUDA engines, torch
wrapper machinery, torchrun). Capabilities are never excluded — each reason
names the native counterpart that provides the capability.
"""

from __future__ import annotations

_MEGATRON = (
    "Megatron-LM engine internal: TP/PP/EP/SP are native mesh axes "
    "(ParallelismConfig; MegatronLMPlugin maps degrees onto them); there is "
    "no engine to drive"
)
_DEEPSPEED = (
    "DeepSpeed engine internal: ZeRO staging is native GSPMD sharding "
    "(DeepSpeedPlugin maps config onto it); there is no engine object to wrap"
)
_FSDP2 = (
    "torch-FSDP2 wrapper machinery (DTensor/meta-device surgery): FSDP here "
    "is a NamedSharding assignment — prepare()/infer_param_specs and "
    "save_fsdp_model/load_fsdp_model cover the capability"
)
_FP8_ENGINE = (
    "TE/torchao/MS-AMP CUDA module surgery: fp8 is native XLA fp8 dot_general "
    "with delayed scaling (ops/fp8.py; FP8RecipeKwargs/TERecipeKwargs map the "
    "recipes)"
)
_CUDA = "CUDA/GPU-vendor specific; no TPU meaning"
_TORCHRUN = (
    "torchrun/torch.distributed launcher internals: launching is one process "
    "per host over jax.distributed (commands/launch.py env protocol)"
)

#: name -> reason it is deliberately not provided
EXCLUDED_REFERENCE_UTILS: "dict[str, str]" = {
    # ---- Megatron-LM engine internals -----------------------------------
    "AbstractTrainStep": _MEGATRON,
    "BertTrainStep": _MEGATRON,
    "GPTTrainStep": _MEGATRON,
    "T5TrainStep": _MEGATRON,
    "MegatronEngine": _MEGATRON,
    "MegatronLMDummyDataLoader": _MEGATRON,
    "MegatronLMDummyScheduler": _MEGATRON,
    "MegatronLMOptimizerWrapper": _MEGATRON,
    "MegatronLMSchedulerWrapper": _MEGATRON,
    "megatron_lm_initialize": _MEGATRON,
    "megatron_lm_prepare_data_loader": _MEGATRON,
    "megatron_lm_prepare_model_optimizer_scheduler": _MEGATRON,
    "megatron_lm_prepare_optimizer": _MEGATRON,
    "megatron_lm_prepare_scheduler": _MEGATRON,
    # ---- DeepSpeed engine internals -------------------------------------
    "DeepSpeedEngineWrapper": _DEEPSPEED,
    "DeepSpeedOptimizerWrapper": _DEEPSPEED,
    "DeepSpeedSchedulerWrapper": _DEEPSPEED,
    "GatheredParameters": (
        "ZeRO-3 param-gather context: GSPMD gathers sharded params on demand "
        "inside the compiled step; a host-side gather is jax.device_get"
    ),
    "map_pytorch_optim_to_deepspeed": (
        "swaps torch optims for DeepSpeed fused-CUDA optims; torch optimizers "
        "are bridged to optax automatically in prepare()"
    ),
    "compile_regions_deepspeed": _DEEPSPEED,
    "prepare_deepspeed_cmd_env": (
        "PDSH/OpenMPI DeepSpeed launcher env; pod launching is native "
        "(commands/launch.py gcloud fan-out + jax.distributed)"
    ),
    # ---- fp8 CUDA engine module surgery ---------------------------------
    "apply_fp8_autowrap": _FP8_ENGINE,
    "contextual_fp8_autocast": _FP8_ENGINE,
    "convert_model": _FP8_ENGINE,
    "convert_model_to_fp8_ao": _FP8_ENGINE,
    "check_cuda_fp8_capability": _CUDA,
    # ---- torch-FSDP2 wrapper machinery ----------------------------------
    "fsdp2_apply_ac": _FSDP2 + "; activation checkpointing is jax.checkpoint "
                               "(FullyShardedDataParallelPlugin.remat)",
    "fsdp2_canonicalize_names": _FSDP2,
    "fsdp2_load_full_state_dict": _FSDP2,
    "fsdp2_prepare_model": _FSDP2,
    "fsdp2_switch_optimizer_parameters": _FSDP2,
    # ---- CUDA / other-vendor probes and tools ---------------------------
    "check_cuda_p2p_ib_support": _CUDA,
    "get_gpu_info": _CUDA,
    "install_xla": "installs torch_xla wheels; this framework IS the XLA path",
    # ---- torch-version pins / torchrun registries -----------------------
    "MITA_PROFILING_AVAILABLE_PYTORCH_VERSION": "torch-version pin for a torch profiler feature",
    "XPU_PROFILING_AVAILABLE_PYTORCH_VERSION": "torch-version pin for a torch profiler feature",
    "TORCH_DISTRIBUTED_OPERATION_TYPES": "torch.distributed op-name registry; collectives are jax.lax primitives",
    "TORCH_LAUNCH_PARAMS": _TORCHRUN,
    "_filter_args": _TORCHRUN + " (private helper)",
    # ---- SageMaker ------------------------------------------------------
    "prepare_sagemager_args_inputs": (
        "SageMaker launch route is deliberately out of scope for a TPU "
        "framework (GCP TPU-VM pods are the deployment target); documented "
        "in docs/launching.md and asserted by tests/test_api_parity.py"
    ),
}
