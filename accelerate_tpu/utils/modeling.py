"""Modeling utils: abstract params, size accounting, device-map inference.

TPU-native counterpart of the reference's ``utils/modeling.py``
(``/root/reference/src/accelerate/utils/modeling.py`` — ``compute_module_sizes:651``,
``get_max_memory:744``, ``get_balanced_memory:918``, ``infer_auto_device_map:1278``,
``find_tied_parameters:554``, ``load_state_dict:1620``,
``load_checkpoint_in_model:1788``, ``dtype_byte_size``/``convert_file_size_to_int``).

Architecture shift: the reference analyzes ``nn.Module`` trees on the meta
device; here a "model" is a nested param pytree and the zero-RAM analogue of the
meta device is a tree of ``jax.ShapeDtypeStruct`` obtained from ``jax.eval_shape``
(:func:`abstract_params`). A *module* is a subtree (a '/'-joined path prefix);
device-map inference walks top-level subtrees and splits them when they do not
fit — the same greedy algorithm, guarantees included (largest-layer reserve on
the main device so offloaded layers can always be paged back in).

Device-map values: ``int`` (index into ``jax.local_devices()``), ``"cpu"``
(host RAM, paged to HBM per forward), ``"disk"`` (memmap spill via
``utils/offload.py``).
"""

from __future__ import annotations

import os
import re
from collections import OrderedDict, defaultdict
from typing import Any, Mapping, Optional, Union

import numpy as np

from .offload import load_offload_index, offload_weight, save_offload_index

WEIGHTS_NAME = "model.safetensors"
WEIGHTS_INDEX_NAME = "model.safetensors.index.json"


# ------------------------------------------------------------------ pytrees --
def named_parameters(tree, prefix: str = "", sep: str = "/") -> "OrderedDict[str, Any]":
    """Flatten a nested param pytree to ``{'a/b/c': leaf}`` (insertion order)."""
    out: OrderedDict[str, Any] = OrderedDict()

    def _walk(node, path):
        if isinstance(node, Mapping):
            for k, v in node.items():
                _walk(v, f"{path}{sep}{k}" if path else str(k))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                _walk(v, f"{path}{sep}{i}" if path else str(i))
        else:
            out[path] = node

    _walk(tree, prefix)
    return out


def unflatten_parameters(flat: Mapping[str, Any], sep: str = "/") -> dict:
    """Inverse of :func:`named_parameters` (list/tuple structure becomes dicts
    with stringified integer keys — device maps only need subtree grouping)."""
    root: dict = {}
    for path, leaf in flat.items():
        parts = path.split(sep)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return root


def abstract_params(init_fn, *args, **kwargs):
    """Zero-memory model "construction": shapes/dtypes only, no allocation
    (reference ``init_empty_weights`` ``big_modeling.py:61`` monkeypatches
    meta-device registration; ``jax.eval_shape`` is the native primitive)."""
    import jax

    return jax.eval_shape(init_fn, *args, **kwargs)


# -------------------------------------------------------------------- sizes --
def dtype_byte_size(dtype) -> float:
    """Bytes per element, fractional for sub-byte dtypes (reference
    ``dtype_byte_size`` handles int4/fp8 the same way)."""
    if dtype.__class__.__name__ == "CustomDtype":  # enum marker (fp8/int4/int2)
        dtype = dtype.value
    name = str(np.dtype(dtype)) if not isinstance(dtype, str) else dtype
    name = name.replace("jax.numpy.", "")
    if name == "int2":
        return 0.25
    if name in ("int4", "uint4"):
        return 0.5
    if name == "fp8":
        return 1
    if "float8" in name or name in ("int8", "uint8", "bool"):
        return 1
    bits = re.search(r"[^\d](\d+)(_.*)?$", name)
    if bits is None:
        # e.g. 'bfloat16' via ml_dtypes
        try:
            import ml_dtypes  # noqa: F401

            return np.dtype(name).itemsize
        except Exception as e:
            raise ValueError(f"`dtype` is not a valid dtype: {name}") from e
    return int(bits.group(1)) // 8


def convert_file_size_to_int(size: Union[int, str]) -> int:
    """``"6GB"``/``"200MiB"``/int → bytes (reference ``convert_file_size_to_int``)."""
    if isinstance(size, int):
        return size
    mem_size = str(size).upper().strip()
    units = [("GIB", 2**30), ("MIB", 2**20), ("KIB", 2**10), ("GB", 10**9), ("MB", 10**6), ("KB", 10**3)]
    for suffix, mult in units:
        if mem_size.endswith(suffix):
            return int(float(mem_size[: -len(suffix)]) * mult)
    if mem_size.isdigit():
        return int(mem_size)
    raise ValueError(f"size {size!r} is not in a valid format (e.g. '6GB', '200MiB', 4096)")


def _leaf_size(leaf, dtype=None, path: str = "", special_dtypes: Optional[dict] = None) -> int:
    shape = getattr(leaf, "shape", ())
    numel = int(np.prod(shape)) if shape else 1
    leaf_dtype = getattr(leaf, "dtype", np.float32)
    if special_dtypes is not None and path in special_dtypes:
        leaf_dtype = special_dtypes[path]
    elif dtype is not None:
        # reference: loading dtype never upcasts storage (modeling.py:672-678)
        leaf_dtype = dtype if dtype_byte_size(dtype) < dtype_byte_size(leaf_dtype) else leaf_dtype
    return int(np.ceil(numel * dtype_byte_size(leaf_dtype)))


def compute_parameter_sizes(tree, dtype=None, special_dtypes=None) -> "OrderedDict[str, int]":
    return OrderedDict(
        (path, _leaf_size(leaf, dtype, path, special_dtypes))
        for path, leaf in named_parameters(tree).items()
    )


def compute_module_sizes(tree, dtype=None, special_dtypes=None) -> dict[str, int]:
    """Size of every subtree prefix, '' = whole model (reference
    ``compute_module_sizes:651``)."""
    sizes: dict[str, int] = defaultdict(int)
    for path, size in compute_parameter_sizes(tree, dtype, special_dtypes).items():
        parts = path.split("/")
        for i in range(len(parts) + 1):
            sizes["/".join(parts[:i])] += size
    return dict(sizes)


def total_byte_size(tree, dtype=None) -> int:
    return compute_module_sizes(tree, dtype)[""]


def find_tied_parameters(tree) -> list[list[str]]:
    """Groups of param paths sharing the SAME underlying array (reference
    ``find_tied_parameters:554``; torch ties by object identity — jax arrays tie
    the same way when a model reuses e.g. the embedding table as lm head)."""
    by_id: dict[int, list[str]] = defaultdict(list)
    for path, leaf in named_parameters(tree).items():
        if leaf is not None and not np.isscalar(leaf):
            by_id[id(leaf)].append(path)
    return sorted(group for group in by_id.values() if len(group) > 1)


def retie_parameters(tree, tied_groups: list[list[str]]):
    """Point every path in a tied group at one shared array (reference
    ``retie_parameters:609``). Returns a new tree (pytrees are immutable-ish)."""
    flat = named_parameters(tree)
    for group in tied_groups:
        sources = [p for p in group if flat.get(p) is not None]
        if not sources:
            continue
        src = flat[sources[0]]
        for path in group:
            flat[path] = src
    return unflatten_parameters(flat)


# ------------------------------------------------------------------- memory --
def get_max_memory(max_memory: Optional[dict] = None) -> "OrderedDict[Union[int, str], int]":
    """Per-accelerator HBM + host RAM budget (reference ``get_max_memory:744``
    probes CUDA/XPU/NPU; here: ``device.memory_stats()['bytes_limit']`` for each
    local TPU/accelerator, /proc/meminfo for the host)."""
    import jax

    if max_memory is not None:
        out: OrderedDict = OrderedDict()
        for key, val in max_memory.items():
            out[key] = convert_file_size_to_int(val) if not isinstance(val, int) else val
        return out

    out = OrderedDict()
    accel = [d for d in jax.local_devices() if d.platform != "cpu"]
    for i, dev in enumerate(accel):
        stats = {}
        try:
            stats = dev.memory_stats() or {}
        except Exception:
            pass
        limit = stats.get("bytes_limit")
        if limit is None:
            limit = 16 * 2**30  # conservative HBM default when stats are absent
        out[i] = int(0.9 * (limit - stats.get("bytes_in_use", 0)))
    if not accel:
        # CPU backend: each "device" is the host; expose one budget slot
        out[0] = _host_ram_bytes() // 2
    out["cpu"] = _host_ram_bytes()
    return out


def _host_ram_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 * 2**30


def get_balanced_memory(
    tree,
    max_memory: Optional[dict] = None,
    no_split_module_patterns: Optional[list[str]] = None,
    dtype=None,
    special_dtypes=None,
    low_zero: bool = False,
) -> "OrderedDict[Union[int, str], int]":
    """Cap per-device budgets so layers spread evenly instead of filling device
    0 first (reference ``get_balanced_memory:918``; ``low_zero`` leaves room on
    device 0 for generate-time buffers)."""
    max_memory = get_max_memory(max_memory)
    num_devices = len([d for d in max_memory if isinstance(d, int) and max_memory[d] > 0])
    if num_devices == 0:
        return max_memory
    if num_devices == 1:
        if low_zero:
            raise ValueError("low_zero requires at least 2 accelerator devices")
        return max_memory

    module_sizes = compute_module_sizes(tree, dtype, special_dtypes)
    per_device = module_sizes[""] // (num_devices - 1 if low_zero else num_devices)

    # Buffer: mean + stddev of the leaf-module sizes (reference :975-991) so the
    # last device absorbs rounding without spilling to cpu.
    leaves = [
        size
        for name, size in module_sizes.items()
        if name and not any(other.startswith(name + "/") for other in module_sizes)
    ]
    buffer = int(np.mean(leaves) + np.std(leaves)) if leaves else 0
    no_split = no_split_module_patterns or []
    if no_split:
        split_caps = [
            size for name, size in module_sizes.items() if name and _matches_any(name, no_split)
        ]
        buffer = max(buffer, max(split_caps) if split_caps else 0)
    per_device += buffer

    out = OrderedDict()
    for key, val in max_memory.items():
        if isinstance(key, int):
            cap = per_device if not (low_zero and key == 0) else per_device // 4
            out[key] = min(val, cap)
        else:
            out[key] = val
    return out


def _matches_any(name: str, patterns: list[str]) -> bool:
    tail = name.split("/")[-1]
    return any(re.search(p, name) or re.search(p, tail) for p in patterns)


# ------------------------------------------------------- device-map inference --
def infer_auto_device_map(
    tree,
    max_memory: Optional[dict] = None,
    no_split_module_patterns: Optional[list[str]] = None,
    dtype=None,
    special_dtypes=None,
    clean_result: bool = True,
    verbose: bool = False,
) -> "OrderedDict[str, Union[int, str]]":
    """Greedy module→device allocation, accelerators first then cpu then disk
    (reference ``infer_auto_device_map:1278``). Invariants preserved:

    - never exceed any device budget;
    - on *main* devices keep headroom for the largest unsplittable layer so an
      offloaded layer can always be paged back in for compute;
    - modules holding tied weights are placed together;
    - a module that doesn't fit is split into its children unless it matches
      ``no_split_module_patterns``.
    """
    max_memory = get_max_memory(max_memory)
    # a user map that omits "cpu" must still cap the host tier at real RAM so
    # oversized models spill to disk instead of exhausting memory
    max_memory.setdefault("cpu", _host_ram_bytes())
    no_split = no_split_module_patterns or []
    devices = [d for d in max_memory if isinstance(d, int)] + ["cpu", "disk"]
    main_devices = [devices[0]] if devices else []
    if "cpu" in max_memory and devices[0] != "cpu":
        main_devices.append("cpu")

    module_sizes = compute_module_sizes(tree, dtype, special_dtypes)
    tied_parameters = find_tied_parameters(tree)

    if not isinstance(tree, Mapping):
        raise TypeError("infer_auto_device_map expects a nested dict param pytree")
    modules_to_treat: list[str] = list(tree.keys())
    flat_tree = named_parameters(tree)
    children_of: dict[str, list[str]] = defaultdict(list)
    for name in module_sizes:
        if name:
            parent = "/".join(name.split("/")[:-1])
            children_of[parent].append(name)

    def _is_leaf_module(name: str) -> bool:
        return name in flat_tree or not children_of.get(name)

    def _max_layer_size(queue: list[str]) -> int:
        """Largest unsplittable unit still to place (reference
        ``get_max_layer_size``)."""
        best = 0
        for name in queue:
            if _is_leaf_module(name) or _matches_any(name, no_split):
                best = max(best, module_sizes[name])
            else:
                best = max(best, _max_layer_size(children_of[name]))
        return best

    device_map: OrderedDict[str, Union[int, str]] = OrderedDict()
    current_device = 0
    used = {device: 0 for device in devices}

    def _tied_companions(name: str) -> list[str]:
        """Unplaced top-level queue entries tied to params inside ``name``."""
        inside = {p for p in flat_tree if p == name or p.startswith(name + "/")}
        out = []
        for group in tied_parameters:
            group_in = [p for p in group if p in inside]
            group_out = [p for p in group if p not in inside]
            if group_in and group_out:
                for p in group_out:
                    for queued in modules_to_treat:
                        if (p == queued or p.startswith(queued + "/")) and queued not in out:
                            out.append(queued)
        return out

    while modules_to_treat:
        name = modules_to_treat.pop(0)
        module_size = module_sizes[name]
        device = devices[current_device]
        budget = max_memory.get(device) if device != "disk" else None

        reserve = _max_layer_size(modules_to_treat) if device in main_devices else 0
        companions = _tied_companions(name)
        size_with_ties = module_size + sum(module_sizes[c] for c in companions)

        fits = budget is None or used[device] + size_with_ties + reserve <= budget
        if fits:
            if verbose:
                print(f"putting {name} (+{companions}) size={size_with_ties} on {device}")
            device_map[name] = device
            used[device] += size_with_ties
            for c in companions:
                device_map[c] = device
                modules_to_treat.remove(c)
            continue

        kids = children_of.get(name, [])
        splittable = kids and not _matches_any(name, no_split) and not companions
        if splittable:
            if verbose:
                print(f"splitting {name} into {len(kids)} children")
            modules_to_treat[0:0] = kids
        else:
            if verbose:
                print(f"{name} does not fit on {device}, advancing")
            modules_to_treat.insert(0, name)
            current_device += 1
            if current_device >= len(devices):
                raise RuntimeError(f"module {name} fits nowhere — even disk failed?")

    if clean_result:
        device_map = clean_device_map(device_map)
    return device_map


def clean_device_map(device_map: "OrderedDict[str, Union[int, str]]", module_prefix: str = "") -> OrderedDict:
    """Collapse children that share a device onto their parent prefix
    (reference ``clean_device_map``)."""
    prefixes = sorted({k.split("/")[0] if not module_prefix else k for k in device_map})
    values = set(device_map.values())
    if module_prefix == "" and len(values) == 1:
        return OrderedDict({"": device_map[next(iter(device_map))]})
    out: OrderedDict = OrderedDict()
    for prefix in prefixes:
        sub = OrderedDict(
            (k, v) for k, v in device_map.items() if k == prefix or k.startswith(prefix + "/")
        )
        if len(set(sub.values())) == 1:
            out[prefix] = next(iter(sub.values()))
        else:
            out.update(sub)
    return out


def lookup_device(device_map: Mapping[str, Any], path: str):
    """Most-specific device-map entry covering ``path``."""
    if path in device_map:
        return device_map[path]
    parts = path.split("/")
    for i in range(len(parts) - 1, -1, -1):
        prefix = "/".join(parts[:i])
        if prefix in device_map:
            return device_map[prefix]
    raise KeyError(f"{path} not covered by device_map (keys={list(device_map)[:8]}…)")


# -------------------------------------------------------- checkpoint loading --
def load_state_dict(checkpoint_file: str, device_map: Optional[dict] = None) -> dict:
    """Load a safetensors/npz file as flat ``{name: np.ndarray}``, lazily
    (reference ``load_state_dict:1620`` — safetensors framework='numpy')."""
    if checkpoint_file.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return load_file(checkpoint_file)
    if checkpoint_file.endswith((".npz", ".npy")):
        with np.load(checkpoint_file, allow_pickle=False) as data:
            return {k: data[k] for k in data.files}
    raise ValueError(f"unsupported checkpoint format: {checkpoint_file}")


def load_checkpoint_in_params(
    abstract_tree,
    checkpoint: str,
    device_map: Optional[Mapping[str, Any]] = None,
    offload_folder: Optional[str] = None,
    dtype=None,
    strict: bool = True,
):
    """Stream a (possibly sharded) checkpoint into a placed param tree
    (reference ``load_checkpoint_in_model:1788``): each tensor goes straight to
    its mapped device — HBM ``device_put``, host numpy, or disk memmap — without
    ever materializing the whole model in host RAM.

    ``checkpoint`` is a safetensors file, an index json, or a directory holding
    either. Returns ``(tree, offload_index)``.
    """
    import jax

    shard_files = _resolve_checkpoint_files(checkpoint)
    expected = named_parameters(abstract_tree)
    device_map = device_map or {"": 0}
    disk_index: dict = {}
    accel = [d for d in jax.local_devices() if d.platform != "cpu"] or jax.local_devices()

    flat_out: dict[str, Any] = {}
    for shard in shard_files:
        state = load_state_dict(shard)
        for name, value in state.items():
            if name not in expected:
                if strict:
                    raise KeyError(f"checkpoint tensor {name!r} not in model")
                continue
            if dtype is not None:
                value = value.astype(dtype)
            target = lookup_device(device_map, name)
            if target == "disk":
                if offload_folder is None:
                    raise ValueError("device_map contains 'disk' but no offload_folder given")
                os.makedirs(offload_folder, exist_ok=True)
                disk_index = offload_weight(value, name, offload_folder, disk_index)
                flat_out[name] = None
            elif target == "cpu":
                flat_out[name] = value
            else:
                if int(target) >= len(accel):
                    raise ValueError(
                        f"device_map places {name!r} on device {target} but only "
                        f"{len(accel)} local devices exist"
                    )
                flat_out[name] = jax.device_put(value, accel[int(target)])
    if offload_folder and disk_index:
        save_offload_index(disk_index, offload_folder)
    missing = [k for k in expected if k not in flat_out]
    if missing and strict:
        raise KeyError(f"checkpoint is missing tensors: {missing[:5]}…")
    return unflatten_parameters(flat_out), (load_offload_index(offload_folder) if offload_folder else {})


def _resolve_checkpoint_files(checkpoint: str) -> list[str]:
    import json as _json

    if os.path.isdir(checkpoint):
        index = os.path.join(checkpoint, WEIGHTS_INDEX_NAME)
        single = os.path.join(checkpoint, WEIGHTS_NAME)
        if os.path.isfile(index):
            checkpoint = index
        elif os.path.isfile(single):
            return [single]
        else:
            shards = sorted(
                os.path.join(checkpoint, f)
                for f in os.listdir(checkpoint)
                if f.endswith((".safetensors", ".npz"))
            )
            if not shards:
                raise FileNotFoundError(f"no checkpoint files under {checkpoint}")
            return shards
    if checkpoint.endswith(".index.json") or checkpoint.endswith("index.json"):
        folder = os.path.dirname(checkpoint)
        with open(checkpoint) as f:
            index_data = _json.load(f)
        files = sorted(set(index_data["weight_map"].values()))
        return [os.path.join(folder, f) for f in files]
    return [checkpoint]


# ---------------------------------------------------------------------------
# torch-module helpers (reference utils/modeling.py spellings) — the bridge
# story accepts nn.Modules, so the reference's module-walking utilities exist
# here too, operating on torch objects directly


def named_module_tensors(module, include_buffers: bool = True, recurse: bool = False,
                         remove_non_persistent: bool = False):
    """reference ``named_module_tensors``: yield (name, tensor) for params and
    (optionally) buffers of ``module``."""
    yield from module.named_parameters(recurse=recurse)
    if include_buffers:
        non_persistent: set = set()
        if remove_non_persistent:
            # collect with DOTTED prefixes so submodule buffers filter too
            submods = module.named_modules() if recurse else [("", module)]
            for prefix, sub in submods:
                for bname in getattr(sub, "_non_persistent_buffers_set", set()):
                    non_persistent.add(f"{prefix}.{bname}" if prefix else bname)
        for name, buf in module.named_buffers(recurse=recurse):
            if name not in non_persistent:
                yield name, buf


def set_module_tensor_to_device(module, tensor_name: str, device, value=None, dtype=None,
                                **kwargs):
    """reference ``set_module_tensor_to_device:217``: (re)place one named
    param/buffer of a torch module, optionally with a new value/dtype."""
    import torch

    if "." in tensor_name:
        splits = tensor_name.split(".")
        for split in splits[:-1]:
            module = getattr(module, split)
        tensor_name = splits[-1]
    is_buffer = tensor_name in getattr(module, "_buffers", {})
    if not is_buffer and tensor_name not in getattr(module, "_parameters", {}):
        # unknown name must fail LOUDLY (reference raises too) — silently
        # attaching a fresh Parameter would leave the real weight untrained
        raise ValueError(f"{tensor_name} is not a parameter or buffer of {module}")
    old = module._buffers[tensor_name] if is_buffer else module._parameters.get(tensor_name)
    if old is None and value is None:
        raise ValueError(f"{tensor_name} has no existing value; pass value=")
    with torch.no_grad():
        if value is not None:
            t = torch.as_tensor(value)
        else:
            t = old
        if dtype is not None:
            t = t.to(dtype)
        t = t.to(device)
        if is_buffer:
            module._buffers[tensor_name] = t
        else:
            requires_grad = old.requires_grad if old is not None else False
            module._parameters[tensor_name] = torch.nn.Parameter(t, requires_grad=requires_grad)


def id_tensor_storage(tensor):
    """reference ``id_tensor_storage``: a (device, storage-ptr, nbytes) key that
    identifies shared storage across tensor views (tied-weight detection)."""
    try:
        storage = tensor.untyped_storage()
        return tensor.device, storage.data_ptr(), storage.nbytes()
    except Exception:
        return tensor.device, id(tensor), tensor.numel() * tensor.element_size()


def has_offloaded_params(module) -> bool:
    """reference ``has_offloaded_params``: True when the module's weights are
    managed by an offload hook (paged in per forward)."""
    hook = getattr(module, "_hf_hook", None) or getattr(module, "_accelerate_hook", None)
    return bool(hook is not None and getattr(hook, "offload", False))


class align_module_device:
    """reference ``align_module_device:2151``: context manager moving a torch
    module's tensors to ``execution_device`` for the duration of the block,
    restoring original devices afterwards."""

    def __init__(self, module, execution_device=None):
        self.module = module
        self.execution_device = execution_device
        self._orig = {}

    def __enter__(self):
        if self.execution_device is None:
            return self.module
        for name, t in named_module_tensors(self.module, recurse=True):
            self._orig[name] = t.device
            set_module_tensor_to_device(self.module, name, self.execution_device)
        return self.module

    def __exit__(self, *exc):
        for name, dev in self._orig.items():
            set_module_tensor_to_device(self.module, name, dev)
        self._orig.clear()
        return False


def load_offloaded_weights(model, index: dict, offload_folder: str) -> None:
    """reference ``load_offloaded_weights``: page every weight recorded in an
    offload ``index`` back into a torch module (bridge interop; the pytree
    path uses :class:`~accelerate_tpu.utils.offload.OffloadedWeightsLoader`)."""
    import os

    from .offload import load_offloaded_weight

    if not index:
        return
    for name, meta in index.items():
        tensor_file = os.path.join(offload_folder, f"{name}.dat")
        value = load_offloaded_weight(tensor_file, meta)
        set_module_tensor_to_device(model, name, "cpu", value=value)


# ------------------------------------------- reference sizing/check spellings --
def get_max_layer_size(
    tree, no_split_module_patterns: Optional[list[str]] = None
) -> "tuple[int, list[str]]":
    """``(size_bytes, [names])`` of the largest unsplittable "layer" (reference
    ``utils/modeling.py`` ``get_max_layer_size``). A layer is a depth-1 subtree,
    except stacked scan layers (a leading axis of length L shared by every leaf
    under a subtree, as ``init_llama``/``init_bert`` produce) count per-slice —
    one scan layer, not the whole stack. ``no_split_module_patterns`` forces
    matching subtrees to be counted whole."""
    no_split = no_split_module_patterns or []
    sizes = compute_module_sizes(tree)
    flat = named_parameters(tree)
    best, names = 0, []

    def _stack_depth(prefix: str) -> int:
        """Leading-axis length if every leaf under prefix shares one, else 0.
        A scan stack has MANY leaves sharing the axis; a single matrix trivially
        "shares" its own first dim and must not count as stacked."""
        leaves = [
            leaf for path, leaf in flat.items()
            if path.startswith(prefix + "/") or path == prefix
        ]
        if len(leaves) < 2:
            return 0
        dims = {
            getattr(leaf, "shape", (0,))[0] if getattr(leaf, "ndim", 0) > 0 else 0
            for leaf in leaves
        }
        return dims.pop() if len(dims) == 1 and 0 not in dims else 0

    top_level = {path.split("/")[0] for path in flat}
    for name in sorted(top_level):
        size = sizes.get(name, 0)
        stack = 0 if _matches_any(name, no_split) else _stack_depth(name)
        if stack > 1:
            size //= stack
        if size > best:
            best, names = size, [name]
        elif size == best and size > 0:
            names.append(name)
    return best, names


def calculate_maximum_sizes(tree) -> "tuple[int, tuple[int, list[str]]]":
    """``(total_bytes, (largest_layer_bytes, [names]))`` — reference
    ``utils/modeling.py`` ``calculate_maximum_sizes``, the pair
    ``estimate-memory`` prints per dtype."""
    return total_byte_size(tree), get_max_layer_size(tree)


def check_device_map(tree, device_map: Mapping[str, Any]) -> None:
    """Every parameter must be covered by some device-map prefix (reference
    ``utils/modeling.py`` ``check_device_map``); raises ``ValueError`` listing
    the uncovered paths otherwise."""
    if "" in device_map:
        return
    uncovered = [
        path
        for path in named_parameters(tree)
        if not any(path == k or path.startswith(k + "/") for k in device_map)
    ]
    if uncovered:
        raise ValueError(
            f"device_map does not cover these parameters: {uncovered[:10]}"
            + (f" (+{len(uncovered) - 10} more)" if len(uncovered) > 10 else "")
        )


def check_tied_parameters_in_config(model) -> list[list[str]]:
    """Tied-weight groups DECLARED by the model's config (reference
    ``utils/modeling.py`` spelling: trusts ``tie_word_embeddings``-style flags
    over runtime identity). Accepts a transformers-style object with
    ``.config`` or a config itself; falls back to runtime identity for plain
    pytrees via :func:`find_tied_parameters`."""
    config = getattr(model, "config", model)
    tie = getattr(config, "tie_word_embeddings", None)
    if tie is None and isinstance(config, Mapping):
        tie = config.get("tie_word_embeddings")
    if tie:
        return [["embed_tokens", "lm_head"]]
    if hasattr(model, "items") or not hasattr(model, "config"):
        try:
            return find_tied_parameters(model)
        except Exception:
            return []
    return []


def check_tied_parameters_on_same_device(
    tied_groups: list[list[str]], device_map: Mapping[str, Any]
) -> None:
    """Warn when a tied group is split across devices (reference
    ``utils/modeling.py`` spelling) — offload would then break the tie."""
    import warnings

    for group in tied_groups:
        devices = {lookup_device(device_map, path) for path in group}
        devices.discard(None)
        if len(devices) > 1:
            warnings.warn(
                f"tied parameters {group} are placed on multiple devices "
                f"{sorted(map(str, devices))}; they will be materialized as "
                "separate arrays and silently un-tied"
            )


def ensure_weights_retied(tree, tied_groups: Optional[list[list[str]]] = None):
    """Re-point tied groups at one shared array after any per-leaf transform
    that may have broken identity (reference ``fsdp_utils.py``
    ``ensure_weights_retied``). Groups default to the runtime-detected ones."""
    return retie_parameters(tree, tied_groups or find_tied_parameters(tree))


def extract_submodules_state_dict(state_dict: Mapping[str, Any], submodule_names: list[str]) -> dict:
    """Subset of ``state_dict`` under any of ``submodule_names`` (reference
    ``utils/modeling.py`` spelling), keys re-rooted at the submodule."""
    out = {}
    for name in submodule_names:
        for key, value in state_dict.items():
            for sep in ("/", "."):
                if key.startswith(name + sep):
                    out[key[len(name + sep):]] = value
    return out


def get_module_children_bottom_up(model, return_fqns: bool = False) -> list:
    """Torch-module children deepest-first, the whole model last (reference
    ``utils/modeling.py`` spelling, used for bottom-up wrapping policies).
    Accepts a torch ``nn.Module`` or our ``BridgedModule`` wrapper."""
    module = getattr(model, "torch_module", model)
    ordered: list = []
    for name, child in getattr(module, "named_children", lambda: [])():
        for sub_name, sub in _children_bottom_up_inner(child, name):
            ordered.append((sub_name, sub))
    ordered.append(("", module))
    return [(n, m) for n, m in ordered] if return_fqns else [m for _, m in ordered]


def _children_bottom_up_inner(module, prefix: str):
    for name, child in module.named_children():
        yield from _children_bottom_up_inner(child, f"{prefix}.{name}")
    yield prefix, module


def copy_tensor_to_devices(tensor):
    """Replicate a host/device array onto every local device (reference
    ``inference.py`` ``copy_tensor_to_devices``, used to broadcast the PP
    output). GSPMD spelling: a fully-replicated ``NamedSharding``."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("_replica",))
    return jax.device_put(tensor, NamedSharding(mesh, PartitionSpec()))


def get_mixed_precision_context_manager(native_amp: bool = True, autocast_kwargs=None):
    """Context manager matching the reference spelling
    (``utils/modeling.py:2049`` returns ``torch.autocast`` per device). JAX has
    no ambient autocast — precision is a compile-time dtype policy baked into
    the jitted step — so the ambient context is a nullcontext; the policy-aware
    equivalent is ``Accelerator.autocast`` (which governs steps *built* inside
    it)."""
    import contextlib

    return contextlib.nullcontext()


def get_grad_scaler(distributed_type=None, **kwargs):
    """fp16 dynamic loss-scaling config (reference ``utils/modeling.py:2092``
    returns a ``torch.amp.GradScaler``). Scaling here lives IN-GRAPH (scale +
    growth-counter carried in the optimizer state, applied inside the jitted
    step), so the config object is the scaler."""
    from .dataclasses import GradScalerConfig

    return GradScalerConfig(**kwargs)


def get_fsdp2_grad_scaler(**kwargs):
    """Reference returns a DTensor-aware GradScaler (``fsdp_utils.py:778``);
    under GSPMD the in-graph scaler is already sharding-transparent."""
    return get_grad_scaler(**kwargs)


def has_ao_layers(model) -> bool:
    """torchao fp8-layer probe (reference ``utils/ao.py``). Bridge-routed
    models never hold torchao modules; a torch model is inspected directly."""
    try:
        from torchao.float8.float8_linear import Float8Linear  # type: ignore
    except Exception:
        return False
    module = getattr(model, "torch_module", model)
    return any(isinstance(m, Float8Linear) for m in getattr(module, "modules", lambda: [])())


def has_transformer_engine_layers(model) -> bool:
    """TransformerEngine layer probe (reference ``utils/transformer_engine.py``)."""
    try:
        import transformer_engine.pytorch as te  # type: ignore
    except Exception:
        return False
    module = getattr(model, "torch_module", model)
    return any(isinstance(m, te.module.base.TransformerEngineBaseModule)
               for m in getattr(module, "modules", lambda: [])())


def filter_first_and_last_linear_layers(model) -> list[str]:
    """Names of every Linear EXCEPT the first and last (reference
    ``utils/transformer_engine.py`` spelling) — the standard fp8 recipe keeps
    the embedding-adjacent and head projections in high precision. Works on a
    torch module or our ``BridgedModule``."""
    module = getattr(model, "torch_module", model)
    try:
        import torch.nn as nn
    except Exception:
        return []
    linears = [n for n, m in module.named_modules() if isinstance(m, nn.Linear)]
    return linears[1:-1] if len(linears) > 2 else []


def has_4bit_bnb_layers(model) -> bool:
    """bitsandbytes Linear4bit probe (reference ``utils/bnb.py``). Native 4-bit
    lives in ``ops/quantization.py`` (NF4 ``QuantizedArray``), not as module
    types; a torch model is inspected directly."""
    try:
        from bitsandbytes.nn import Linear4bit  # type: ignore
    except Exception:
        return False
    module = getattr(model, "torch_module", model)
    return any(isinstance(m, Linear4bit) for m in getattr(module, "modules", lambda: [])())
