"""Capability probes: which optional libraries / hardware are available.

TPU-native counterpart of the reference's ``utils/imports.py``
(``/root/reference/src/accelerate/utils/imports.py:62-426`` — ~50 ``is_*_available``
probes). Here the compute stack is always JAX; probes cover optional integrations
(trackers, orbax, flax, torch-interop) and the accelerator platform itself.
"""

from __future__ import annotations

import functools
import importlib.util


@functools.lru_cache(maxsize=None)
def _package_available(name: str) -> bool:
    return importlib.util.find_spec(name) is not None


def is_flax_available() -> bool:
    return _package_available("flax")


def is_optax_available() -> bool:
    return _package_available("optax")


def is_orbax_available() -> bool:
    return _package_available("orbax")


def is_chex_available() -> bool:
    return _package_available("chex")


def is_torch_available() -> bool:
    return _package_available("torch")


def is_transformers_available() -> bool:
    return _package_available("transformers")


def is_datasets_available() -> bool:
    return _package_available("datasets")


def is_safetensors_available() -> bool:
    return _package_available("safetensors")


def is_tensorboard_available() -> bool:
    return _package_available("tensorboard") or _package_available("tensorboardX")


def is_comet_ml_available() -> bool:
    return _package_available("comet_ml")


def is_aim_available() -> bool:
    return _package_available("aim")


def is_clearml_available() -> bool:
    return _package_available("clearml")


def is_dvclive_available() -> bool:
    return _package_available("dvclive")


def is_swanlab_available() -> bool:
    return _package_available("swanlab")


def is_trackio_available() -> bool:
    return _package_available("trackio")


def is_wandb_available() -> bool:
    return _package_available("wandb")


def is_mlflow_available() -> bool:
    return _package_available("mlflow")


def is_rich_available() -> bool:
    return _package_available("rich")


def is_tqdm_available() -> bool:
    return _package_available("tqdm")


def is_pandas_available() -> bool:
    return _package_available("pandas")


def is_pytest_available() -> bool:
    return _package_available("pytest")


@functools.lru_cache(maxsize=None)
def is_tpu_available() -> bool:
    """True when the default JAX backend is a TPU (incl. tunneled/virtual TPUs)."""
    import jax

    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def is_gpu_available() -> bool:
    import jax

    try:
        return jax.default_backend() == "gpu"
    except Exception:
        return False


def is_cpu_only() -> bool:
    import jax

    return jax.default_backend() == "cpu"


def is_pallas_available() -> bool:
    """Pallas ships with jax; TPU lowering needs a TPU backend, CPU uses interpret mode."""
    return _package_available("jax")


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1


# --------------------------------------------------------------------------
# Reference-spelling probes migrated user code calls
# (reference utils/imports.py:62-426). Answers reflect THIS stack honestly:
# precision probes describe what the jitted step supports; torch-engine
# probes are plain package probes and stay False in a TPU image.


def is_bf16_available(ignore_tpu: bool = False) -> bool:
    """bf16 is the native TPU matmul dtype; supported everywhere here
    (reference checks CUDA capability; its ``ignore_tpu`` flag is accepted
    for signature parity)."""
    return True


def is_fp16_available() -> bool:
    """fp16 compute with in-graph dynamic loss scaling is always available."""
    return True


@functools.lru_cache(maxsize=None)
def is_fp8_available() -> bool:
    """True when jax exposes float8 dtypes (XLA fp8 dot support)."""
    try:
        import jax.numpy as jnp

        return hasattr(jnp, "float8_e4m3fn")
    except Exception:
        return False


def is_cuda_available() -> bool:
    return is_gpu_available()


def is_mps_available(min_version: str | None = None) -> bool:
    return False


def is_peft_available() -> bool:
    return _package_available("peft")


def is_timm_available() -> bool:
    return _package_available("timm")


def is_torchvision_available() -> bool:
    return _package_available("torchvision")


def is_matplotlib_available() -> bool:
    return _package_available("matplotlib")


def is_deepspeed_available() -> bool:
    """Plain package probe; ZeRO capabilities are provided natively via
    sharding (DeepSpeedPlugin shim), so this is False in a TPU image."""
    return _package_available("deepspeed")


def is_megatron_lm_available() -> bool:
    return _package_available("megatron")


def is_bnb_available() -> bool:
    """bitsandbytes (CUDA); int8/NF4 quantization is native here
    (``ops/quantization.py``)."""
    return _package_available("bitsandbytes")


def is_torch_xla_available(check_is_tpu: bool = False, check_is_gpu: bool = False) -> bool:
    """The reference gates its TPU path on torch_xla; this framework IS the
    TPU path, so the probe only reports whether the package exists for
    interop purposes."""
    return _package_available("torch_xla")


# -- remaining reference probe spellings (utils/imports.py:62-426): plain
# package probes so reference-written capability gates evaluate honestly on a
# TPU image (most are CUDA/torch-ecosystem packages and report False here)
def is_boto3_available() -> bool:
    return _package_available("boto3")


def is_sagemaker_available() -> bool:
    return _package_available("sagemaker")


def is_triton_available() -> bool:
    return _package_available("triton")


def is_schedulefree_available() -> bool:
    return _package_available("schedulefree")


def is_lomo_available() -> bool:
    """LOMO's fused update is native here (``Accelerator.lomo_backward``);
    the probe reports the torch package for interop parity."""
    return _package_available("lomo_optim")


def is_pynvml_available() -> bool:
    return _package_available("pynvml")


def is_import_timer_available() -> bool:
    return _package_available("import_timer")


def is_torchdata_available() -> bool:
    return _package_available("torchdata")


def is_torchdata_stateful_dataloader_available() -> bool:
    if not _package_available("torchdata"):
        return False
    try:
        from torchdata.stateful_dataloader import StatefulDataLoader  # noqa: F401

        return True
    except ImportError:
        return False


def is_pippy_available() -> bool:
    """Pipeline parallelism is native (``parallel/pipeline.py``, trainable);
    reference gates on torch.distributed.pipelining instead."""
    try:
        import torch.distributed.pipelining  # noqa: F401

        return True
    except ImportError:
        return False


def is_xccl_available() -> bool:
    try:
        import torch

        return hasattr(torch.distributed, "is_xccl_available") and torch.distributed.is_xccl_available()
    except ImportError:
        return False


def is_weights_only_available() -> bool:
    """torch.load(weights_only=...) support probe (reference gates torch>=2.4)."""
    try:
        import torch

        from .versions import compare_versions

        return compare_versions(torch.__version__, ">=", "2.4.0")
    except ImportError:
        return False


# -- device-vendor probes (reference utils/imports.py:62-426): each reports
# whether that accelerator stack is importable. On a TPU image none are, so
# reference-written gates like ``if is_xpu_available(): ...`` fall through
# honestly rather than raising ImportError at the import site.
def is_xpu_available(check_device: bool = False) -> bool:
    return _package_available("intel_extension_for_pytorch")


def is_npu_available(check_device: bool = False) -> bool:
    return _package_available("torch_npu")


def is_mlu_available(check_device: bool = False) -> bool:
    return _package_available("torch_mlu")


def is_musa_available(check_device: bool = False) -> bool:
    return _package_available("torch_musa")


def is_sdaa_available(check_device: bool = False) -> bool:
    return _package_available("torch_sdaa")


def is_hpu_available(init_hccl: bool = False) -> bool:
    return _package_available("habana_frameworks")


def is_habana_gaudi1() -> bool:
    """Gaudi1 detection requires the habana stack; absent it, not Gaudi1."""
    return False


# -- quantization/fp8 engine probes: the capabilities exist natively
# (``ops/quantization.py`` int8/NF4 kernels, ``ops/fp8.py`` delayed-scaling
# fp8 dot); these report whether the CUDA engines the reference delegates to
# are importable, for scripts that branch on the engine rather than the
# capability.
def is_4bit_bnb_available() -> bool:
    return is_bnb_available()


def is_8bit_bnb_available() -> bool:
    return is_bnb_available()


def is_bitsandbytes_multi_backend_available() -> bool:
    return is_bnb_available()


def is_torchao_available() -> bool:
    return _package_available("torchao")


def is_msamp_available() -> bool:
    return _package_available("msamp")


def is_transformer_engine_available() -> bool:
    return _package_available("transformer_engine")


def is_transformer_engine_mxfp8_available() -> bool:
    """MXFP8 needs TE + Blackwell-class hardware; without TE it is False."""
    return False


def is_peft_model(model) -> bool:
    """True iff ``model`` is a PEFT-wrapped torch model (reference
    ``utils/other.py`` spelling). Works through our torch bridge: unwraps
    ``BridgedModule`` to the underlying torch module first."""
    inner = getattr(model, "torch_module", model)
    if not is_peft_available():
        return False
    try:
        from peft import PeftModel  # type: ignore

        return isinstance(inner, PeftModel)
    except Exception:
        return False


def model_has_dtensor(model) -> bool:
    """torch DTensor probe (reference ``utils/modeling.py``). Sharding here is
    GSPMD ``jax.Array`` — a torch model routed through the bridge never holds
    DTensors, and a plain torch model is checked directly."""
    try:
        from torch.distributed.tensor import DTensor  # type: ignore
    except Exception:
        return False
    params = getattr(model, "parameters", None)
    if params is None:
        return False
    return any(isinstance(p, DTensor) for p in model.parameters())


def torchao_required(func):
    """Decorator guard (reference ``utils/ao.py``): the wrapped function needs
    the torchao CUDA engine, which has no TPU meaning — the native fp8 path is
    ``ops/fp8.py``. Raises with that pointer when called without torchao."""
    import functools as _functools

    @_functools.wraps(func)
    def wrapper(*args, **kwargs):
        if not is_torchao_available():
            raise ImportError(
                f"{func.__name__} requires torchao (CUDA fp8 engine). On TPU use "
                "the native fp8 path: ops/fp8.py (fp8_dot / make_fp8_optimizer) "
                "with FP8RecipeKwargs/AORecipeKwargs."
            )
        return func(*args, **kwargs)

    return wrapper
