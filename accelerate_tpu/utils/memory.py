"""OOM-recovery utilities.

TPU-native counterpart of the reference's ``utils/memory.py``
(``/root/reference/src/accelerate/utils/memory.py`` — ``release_memory:66``,
``should_reduce_batch_size:96``, ``find_executable_batch_size:115``,
``clear_device_cache:39``).

On TPU an OOM surfaces as an ``XlaRuntimeError`` whose message carries
``RESOURCE_EXHAUSTED`` (HBM) — usually at compile/first-execute time of the
jitted step, which makes the retry loop *cheaper* than on CUDA: the failed
allocation aborts before any training state is touched.
"""

from __future__ import annotations

import functools
import gc
import inspect
from typing import Callable, Optional

_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "OOM",
    "Attempting to reserve",  # XLA allocator message
)


def clear_device_cache(garbage_collection: bool = False) -> None:
    """Drop compiled-executable and array caches (reference
    ``clear_device_cache:39`` — there: ``torch.cuda.empty_cache`` per backend)."""
    if garbage_collection:
        gc.collect()
    import jax

    try:
        jax.clear_caches()
    except Exception:
        pass


def release_memory(*objects):
    """Release references + caches; returns ``None`` placeholders so callers can
    rebind (reference ``release_memory:66``: ``a, b = release_memory(a, b)``)."""
    if not isinstance(objects, list):
        objects = list(objects)
    for i in range(len(objects)):
        objects[i] = None
    gc.collect()
    clear_device_cache()
    return objects


def should_reduce_batch_size(exception: Exception) -> bool:
    """Heuristic: does this exception mean the device ran out of memory?
    (reference ``should_reduce_batch_size:96`` checks CUDA/CUDNN/CPU OOM
    statuses; on TPU the signal is XLA's RESOURCE_EXHAUSTED.)"""
    if isinstance(exception, MemoryError):
        return True
    msg = str(exception)
    return any(marker in msg for marker in _OOM_MARKERS)


def find_executable_batch_size(
    function: Optional[Callable] = None,
    starting_batch_size: int = 128,
    reduce_batch_size_fn: Optional[Callable[[int], int]] = None,
):
    """Decorator: call ``function(batch_size, *args, **kwargs)``, halving
    ``batch_size`` on OOM until it fits (reference
    ``find_executable_batch_size:115``). Caches are cleared between attempts so
    a failed compilation doesn't poison the next one.

    Example::

        @find_executable_batch_size(starting_batch_size=512)
        def train(batch_size):
            ...
        train()
    """
    if function is None:
        return functools.partial(
            find_executable_batch_size,
            starting_batch_size=starting_batch_size,
            reduce_batch_size_fn=reduce_batch_size_fn,
        )
    reduce_fn = reduce_batch_size_fn or (lambda b: b // 2)

    @functools.wraps(function)
    def wrapper(*args, **kwargs):
        batch_size = starting_batch_size
        params = list(inspect.signature(function).parameters.keys())
        if len(params) < 1 or params[0] != "batch_size":
            raise TypeError(
                f"Batch size was passed into `{function.__name__}` as the first argument, "
                f"but its signature is ({', '.join(params)}) — it must accept `batch_size` first."
            )
        while True:
            if batch_size == 0:
                raise RuntimeError("No executable batch size found, reached zero.")
            try:
                return function(batch_size, *args, **kwargs)
            except Exception as e:
                if should_reduce_batch_size(e):
                    clear_device_cache(garbage_collection=True)
                    batch_size = reduce_fn(batch_size)
                else:
                    raise

    return wrapper
